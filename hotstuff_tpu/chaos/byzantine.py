"""Byzantine adversary policies: wire-level misbehaviour for a real node.

A Byzantine chaos node runs the UNMODIFIED consensus stack (so it forms
QCs, rotates leadership and keeps protocol state like any replica) while
an AdversaryPolicy attached to its transport edges mutates, suppresses,
or fabricates its wire traffic. The adversary legitimately owns the
node's signing seed, so equivocating proposals are properly signed — the
attack is on protocol semantics, not on the signature scheme — while the
forgery policies deliberately emit garbage signatures to exercise the
verification rejection lanes (and prove the dedup cache never caches a
rejected triple).

Policies work on the consensus-plane codec (decode_consensus_message /
encode_consensus_message); frames they cannot decode (another plane, or
future message types) pass through untouched.
"""

from __future__ import annotations

import logging

from ..consensus.messages import (
    QC,
    TC,
    Block,
    Timeout,
    TimeoutBundle,
    Vote,
    VoteBundle,
    decode_consensus_message,
    encode_consensus_message,
)
from ..crypto.primitives import Digest, PublicKey, Signature
from ..crypto import pysigner
from ..utils import metrics

log = logging.getLogger("hotstuff.chaos")

_M_FORGED_VOTES = metrics.counter("chaos.forged_votes")
_M_FORGED_TIMEOUTS = metrics.counter("chaos.forged_timeouts")
_M_EQUIVOCATIONS = metrics.counter("chaos.equivocations")
_M_STALE_REPLAYS = metrics.counter("chaos.stale_replays")
_M_WITHHELD = metrics.counter("chaos.withheld_votes")


class AdversaryPolicy:
    """Base policy: observe/forward everything unchanged.

    `on_send(src, dst, data)` returns a list of unframed payloads to send
    in place of `data` (empty = suppress, None = pass through unchanged);
    `on_receive(src, dst, data)` observes inbound traffic to the Byzantine
    node. `attach(transport)` hands the policy its injection handle."""

    def __init__(self, node: int, seed: bytes, committee, rng) -> None:
        self.node = node
        self.seed = seed
        self.committee = committee
        self.rng = rng
        self.transport = None
        self.names = sorted(committee.authorities.keys())
        self.pk = self.names[node]

    def attach(self, transport) -> None:
        self.transport = transport

    def on_send(self, src: int, dst: int, data: bytes):
        return None

    def on_receive(self, src: int | None, dst: int, data: bytes) -> None:
        return None

    # -- helpers -------------------------------------------------------------

    def _decode(self, data: bytes):
        try:
            return decode_consensus_message(data)
        except Exception:
            return None  # not consensus-plane traffic; leave it alone

    def _broadcast_honest(self, msg) -> None:
        data = encode_consensus_message(msg)
        for i in range(len(self.names)):
            if i != self.node:
                self.transport.inject(i, data)


class Equivocator(AdversaryPolicy):
    """Equivocating leader: when this node broadcasts its own proposal,
    each recipient gets one of TWO conflicting, correctly signed blocks
    for the same round (split by destination parity). Safety must hold:
    at most one branch can gather a quorum."""

    def on_send(self, src: int, dst: int, data: bytes):
        msg = self._decode(data)
        if not isinstance(msg, Block) or msg.author != self.pk:
            return None
        variant = dst % 2
        payload = [Digest.of(f"equivocation-{msg.round}-{variant}".encode())]
        digest = Block.make_digest(self.pk, msg.round, payload, msg.qc)
        twin = Block(
            msg.qc,
            msg.tc,
            self.pk,
            msg.round,
            tuple(payload),
            Signature(pysigner.sign(self.seed, digest.data)),
        )
        _M_EQUIVOCATIONS.inc()
        log.debug(
            "equivocating leader: round %d variant %d -> node %d",
            msg.round,
            variant,
            dst,
        )
        return [encode_consensus_message(twin)]


class SigForger(AdversaryPolicy):
    """Forged-signature flood: every proposal this node observes triggers
    a burst of votes and timeouts with garbage signatures, claiming BOTH
    its own and honest authorities as authors. Every one of them must die
    in the verification rejection lanes — zero false accepts, zero dedup
    cache entries."""

    def __init__(self, node, seed, committee, rng, burst: int = 2) -> None:
        super().__init__(node, seed, committee, rng)
        self.burst = burst
        self.forged: list[tuple[bytes, PublicKey, Signature]] = []

    def on_receive(self, src, dst, data) -> None:
        msg = self._decode(data)
        if not isinstance(msg, Block):
            return
        for author in self.names[: self.burst + 1]:
            sig = Signature(self.rng.randbytes(64))
            vote = Vote(msg.digest(), msg.round, author, sig)
            self.forged.append((vote.signed_digest().data, author, sig))
            _M_FORGED_VOTES.inc()
            self._broadcast_honest(vote)
        # A forged timeout (garbage signature over the timeout digest) with
        # a replayed-but-valid high_qc: the timeout signature must reject.
        tsig = Signature(self.rng.randbytes(64))
        timeout = Timeout(msg.qc, msg.round, self.pk, tsig)
        self.forged.append((timeout.signed_digest().data, self.pk, tsig))
        _M_FORGED_TIMEOUTS.inc()
        self._broadcast_honest(timeout)


class StaleReplayer(AdversaryPolicy):
    """Stale-QC replay: remembers blocks and TCs it sees, and re-broadcasts
    old ones whenever a newer proposal arrives. Honest nodes must discard
    stale rounds without state damage or double commits."""

    KEEP = 16

    def __init__(self, node, seed, committee, rng) -> None:
        super().__init__(node, seed, committee, rng)
        self._old: list = []

    def on_receive(self, src, dst, data) -> None:
        msg = self._decode(data)
        if isinstance(msg, (Block, TC)):
            if self._old and self.rng.random() < 0.5:
                stale = self._old[self.rng.randrange(len(self._old))]
                _M_STALE_REPLAYS.inc()
                self._broadcast_honest(stale)
            self._old.append(msg)
            del self._old[: -self.KEEP]


class VoteWithholder(AdversaryPolicy):
    """Withholds every vote and timeout this node would have sent. With
    n = 3f+1 the remaining 2f+1 honest replicas must keep committing
    (at timeout pace through the Byzantine node's leader rounds)."""

    def on_send(self, src: int, dst: int, data: bytes):
        msg = self._decode(data)
        if isinstance(msg, (Vote, Timeout)):
            _M_WITHHELD.inc()
            return []
        return None


class BundlePoisoner(AdversaryPolicy):
    """Byzantine aggregator for the overlay plane (consensus/overlay.py):
    POISONS every outbound partial bundle with a forged entry claiming an
    honest authority (garbage signature — it must reject alone, without
    suppressing the honest entries it rides beside), and WITHHOLDS a
    fraction of the bundles it should have forwarded up the tree (the
    silent-aggregator shape the gossip fallback exists to bound). The
    node legitimately signs its own entries — the attack is on the
    aggregation relay, not the signature scheme.

    Deterministic by COUNT, not probability: every WITHHOLD_EVERY-th
    bundle is dropped, every other one is poisoned — a short run (the
    tier-1 sweep early-stops on its commit floor) still exercises both
    behaviours as soon as a handful of bundles flow."""

    WITHHOLD_EVERY = 3

    def __init__(self, node, seed, committee, rng) -> None:
        super().__init__(node, seed, committee, rng)
        self.forged: list[tuple[bytes, PublicKey, Signature]] = []
        self._bundles_seen = 0

    def on_send(self, src: int, dst: int, data: bytes):
        from ..consensus.messages import _timeout_digest, _vote_digest

        msg = self._decode(data)
        if not isinstance(msg, (VoteBundle, TimeoutBundle)):
            return None
        self._bundles_seen += 1
        if self._bundles_seen % self.WITHHOLD_EVERY == 0:
            _M_WITHHELD.inc()
            return []
        author = self.names[(self.node + 1) % len(self.names)]
        sig = Signature(self.rng.randbytes(64))
        if isinstance(msg, VoteBundle):
            self.forged.append(
                (_vote_digest(msg.hash, msg.round).data, author, sig)
            )
            _M_FORGED_VOTES.inc()
            poisoned = VoteBundle(
                msg.round, msg.hash, msg.votes + ((author, sig),)
            )
        else:
            # Two attack classes per timeout bundle: (a) a garbage
            # signature under an honest authority (dies in signature
            # verification), and (b) the TC-poisoning shape
            # overlay.filter_backed exists for — this node's OWN entry
            # re-signed with a LEGITIMATE signature over an absurd
            # high_qc_round claim the carried QC cannot back. Honest
            # receivers must drop (b) unmerged (agg.invalid_entries), or
            # any TC including it would fail every future proposal's
            # justification check: permanent liveness loss.
            fake_hqr = msg.round + 1_000_000
            fake_sig = Signature(
                pysigner.sign(
                    self.seed, _timeout_digest(msg.round, fake_hqr).data
                )
            )
            entries = tuple(
                (self.pk, fake_sig, fake_hqr) if pk == self.pk else (pk, s, hqr)
                for pk, s, hqr in msg.timeouts
            )
            hqr = msg.high_qc.round
            self.forged.append(
                (_timeout_digest(msg.round, hqr).data, author, sig)
            )
            _M_FORGED_TIMEOUTS.inc()
            poisoned = TimeoutBundle(
                msg.round, msg.high_qc, entries + ((author, sig, hqr),)
            )
        return [encode_consensus_message(poisoned)]
