"""Named chaos scenarios: the library `tools/chaos_run.py --scenario`
selects from. Each scenario is a declarative recipe — node count, fault
plan, Byzantine policies, run bounds, heal point, and extra expectations
evaluated against the finished report — executed by `run_scenario()` on a
VirtualTimeLoop for deterministic replay.

Link delays are deliberately nonzero everywhere: on the virtual clock a
zero-latency network would let rounds complete in zero virtual time and a
bounded-duration scenario would run unbounded rounds. 10-20 ms links keep
round costs realistic AND bound the work per virtual second.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from ..consensus.config import Parameters
from ..crypto.scheduler import SchedulerConfig
from ..ingress.admission import IngressConfig, LaneSpec
from ..ingress.loadgen import ArrivalCurve, IngressLoad
from ..utils import metrics
from ..utils.telemetry import (
    TelemetryConfig,
    infer_fleet_regions,
    peer_latency_map,
)
from . import vtime
from .byzantine import (
    BundlePoisoner,
    Equivocator,
    SigForger,
    StaleReplayer,
    VoteWithholder,
)
from .orchestrator import (
    BoundaryCrash,
    BulkFlood,
    ChaosOrchestrator,
    ReconfigDirective,
)
from .plan import (
    CrashWindow,
    DelayedBoot,
    FaultPlan,
    LinkFaults,
    Partition,
    WanMatrix,
)

# Bounds on one scenario run. VIRTUAL_TIMEOUT_S catches a stop condition
# that never fires (virtual time races ahead forever); WALL_TIMEOUT_S is a
# real-clock watchdog for the opposite failure — a frozen virtual clock
# (livelock), which no virtual deadline can interrupt.
VIRTUAL_TIMEOUT_S = 600.0
WALL_TIMEOUT_S = 300.0

_LINK = LinkFaults(delay=0.01)  # healthy-but-realistic 10 ms links


def _params(timeout_ms: int = 1_000) -> Parameters:
    return Parameters(
        timeout_delay=timeout_ms,
        sync_retry_delay=1_000,
        timeout_backoff=2.0,
        max_timeout_delay=8_000,
    )


@dataclass
class Scenario:
    name: str
    description: str
    n: int = 4
    plan: Callable[[], FaultPlan] = FaultPlan
    # Size-parameterized plan factory (receives the EFFECTIVE committee
    # size, after any matrix `n` override): the way a grid scenario
    # expresses faults that must scale with n — e.g. the timeout_storm's
    # half|half no-quorum partition — without pinning node indices.
    # Takes precedence over `plan` when set.
    plan_n: Callable[[int], FaultPlan] | None = None
    byzantine: dict[int, object] = field(default_factory=dict)
    parameters: Callable[[], Parameters] = _params
    duration: float = 30.0  # virtual seconds (upper bound)
    min_commits: int = 4  # per-honest-node early-stop / liveness floor
    heal_t: float | None = None  # liveness must show progress past this
    expect: Callable[[dict, dict], list[str]] | None = None  # (report, metric deltas)
    slow: bool = False  # excluded from the tier-1 short sweep
    # Open-loop client traffic (ingress/loadgen.IngressLoad factory): the
    # orchestrator attaches one in-process ingress pipeline + generator
    # per target node, riding each node's real verification service.
    ingress: Callable[[], IngressLoad] | None = None
    # Open-loop bulk-verification flood (orchestrator.BulkFlood factory)
    # and per-node scheduler knobs (crypto/scheduler.SchedulerConfig
    # factory, e.g. the virtual device-occupancy pace that makes bulk
    # queueing observable under the virtual clock).
    flood: Callable[[], BulkFlood] | None = None
    scheduler: Callable[[], SchedulerConfig] | None = None
    # Live telemetry plane (utils/telemetry.TelemetryConfig factory): one
    # per-node snapshot ring + SLO burn evaluator on the virtual clock,
    # embedded in the report's `telemetry` section.
    telemetry: Callable[[], TelemetryConfig] | None = None
    # Genesis committee as node indices (None = every node): nodes outside
    # it run the full stack as JOIN candidates, admitted only by a
    # committed EpochChange (consensus/reconfig.py).
    committee: tuple[int, ...] | None = None
    # Size-parameterized genesis committee (receives the EFFECTIVE node
    # count, after any matrix `n` override) — the committee-free form a
    # grid reconfig scenario must use: membership derives from n instead
    # of pinning indices, so cells can scale it. Takes precedence over
    # `committee` when set.
    committee_n: Callable[[int], tuple[int, ...]] | None = None
    # Epoch-reconfiguration directives (orchestrator.ReconfigDirective
    # factory): a signed committee change injected mid-run, or a LIST of
    # chained directives (rolling churn — each waits for the previous
    # boundary to be committed-past before building).
    reconfig: Callable[[], "ReconfigDirective | list[ReconfigDirective]"] | None = None
    # Size-parameterized directive factory (receives the effective n) —
    # the committee-free form grid reconfig cells use; precedence over
    # `reconfig` when set.
    reconfig_n: Callable[[int], "list[ReconfigDirective]"] | None = None
    # Quorum-crash-at-the-boundary machinery (orchestrator.BoundaryCrash
    # factory list): crash nodes the instant an epoch switch lands.
    boundary_crashes: Callable[[], list[BoundaryCrash]] | None = None
    # Matrix-cell virtual-second budget override: None = the grid's
    # MATRIX_CELL_DURATION_S cap (which bounds a REGRESSED cell's wall
    # cost). Only a scenario whose CONTRACT structurally needs longer —
    # rolling_churn's three progress-gated boundaries — declares one;
    # everything else stays capped so cells remain comparable across
    # matrix revisions.
    cell_duration: float | None = None
    # Scenario REQUIRES the trusted-crypto stub at every size (not just
    # from TRUSTED_CRYPTO_MIN_N up): the aggregate-certificate cells,
    # whose exact-BLS pairing (~0.4 s per verification) is unrunnable in
    # a virtual-time fleet at ANY committee size. Read the trust model
    # in chaos/trusted_crypto.py before setting this.
    trusted_crypto: bool = False
    # Per-scenario matrix-size override (None = the grid's MATRIX_SIZES):
    # how the aggregate cells extend the grid to n=128 — the committee
    # size the constant-size-certificate claim is about — without
    # tripling every legacy scenario's cell count.
    matrix_sizes: tuple[int, ...] | None = None
    # Commit-proof serving plane (§5.5q): boot a ProofRegistry +
    # ProofService per node, feed admitted ingress tx digests into that
    # node's proposals, and attach one subscribe-until-commit proof
    # client per ACCEPTED transaction — outcomes land in the report's
    # `proofs` section (requires `ingress`).
    proofs: bool = False
    # Byzantine nonce-squatting driver: never-admitted MODE_SUBSCRIBE
    # queries/s per target node (0 = off); outcomes in `proof_squat`.
    proof_squat_rate: float = 0.0
    # Scenario-declared per-SLO burn budget (seconds-in-violation the run
    # may spend per SLO row, utils/incidents.py §5.5r): judged in the
    # report's `health` block; rows not named here are reported unjudged.
    burn_budget: Callable[[], dict[str, float]] | None = None


def _expect_counter(deltas: dict, name: str, minimum: int = 1) -> list[str]:
    if deltas.get(name, 0) < minimum:
        return [f"expected {name} >= {minimum}, saw {deltas.get(name, 0)}"]
    return []


def _expect_forgery(report: dict, deltas: dict) -> list[str]:
    problems = _expect_counter(deltas, "chaos.forged_votes")
    problems += _expect_counter(deltas, "verifier.rejected_sigs")
    if report.get("forged_triples_cached", 0) != 0:
        problems.append(
            f"{report['forged_triples_cached']} forged triples found in a "
            "VerifiedSigCache (rejected signatures must never be cached)"
        )
    return problems


SCENARIOS: dict[str, Scenario] = {}


def _register(s: Scenario) -> Scenario:
    SCENARIOS[s.name] = s
    return s


_register(
    Scenario(
        name="baseline",
        description="No faults: 4 honest nodes on healthy 10 ms links must "
        "commit one common chain (the chaos plane's own sanity check).",
        plan=lambda: FaultPlan(default_link=_LINK),
        # The scenario-registry lint requires every scenario to assert
        # something beyond not-crashing: the baseline pins real traffic
        # and the per-node commit floor (4 nodes x min_commits).
        expect=lambda report, deltas: _expect_counter(deltas, "chaos.frames")
        + _expect_counter(deltas, "consensus.commits", minimum=16),
    )
)

_register(
    Scenario(
        name="lossy_links",
        description="Every directed link drops 8%, duplicates 3%, reorders "
        "8%, and jitters up to 20 ms; sync retries must keep the chain "
        "growing with no safety damage.",
        plan=lambda: FaultPlan(
            default_link=LinkFaults(
                drop=0.08, duplicate=0.03, reorder=0.08, delay=0.01, jitter=0.02
            )
        ),
        duration=60.0,
        min_commits=8,
        expect=lambda report, deltas: _expect_counter(deltas, "chaos.drops")
        + _expect_counter(deltas, "chaos.duplicates")
        + _expect_counter(deltas, "chaos.reorders"),
    )
)

_register(
    Scenario(
        name="partition_heal",
        description="A 2|2 partition (no quorum on either side) from t=1 to "
        "t=4, then heal: commits must stop during the partition and resume "
        "after — the liveness checker gates on post-heal progress.",
        plan=lambda: FaultPlan(
            default_link=_LINK,
            partitions=[Partition(start=1.0, end=4.0, groups=((0, 1), (2, 3)))],
        ),
        duration=40.0,
        min_commits=2,
        heal_t=4.0,
        expect=lambda report, deltas: _expect_counter(
            deltas, "chaos.partition_drops"
        ),
    )
)

_register(
    Scenario(
        name="leader_crash",
        description="Node 1 crashes at t=1 and restarts at t=4 against its "
        "persisted store: progress continues through its leader rounds via "
        "TCs, and the restarted node may not double-vote (safety state "
        "reload).",
        plan=lambda: FaultPlan(
            default_link=_LINK,
            crashes=[CrashWindow(node=1, at=1.0, restart=4.0)],
        ),
        duration=40.0,
        min_commits=3,
        heal_t=4.0,
        expect=lambda report, deltas: _expect_counter(deltas, "chaos.crashes")
        + _expect_counter(deltas, "chaos.restarts"),
    )
)

_register(
    Scenario(
        name="equivocating_leader",
        description="Node 1 sends conflicting, correctly signed proposals to "
        "different peers whenever it leads: neither twin may gather a "
        "quorum, so its rounds fall to the pacemaker and safety holds.",
        plan=lambda: FaultPlan(default_link=_LINK),
        byzantine={1: Equivocator},
        duration=60.0,
        min_commits=3,
        expect=lambda report, deltas: _expect_counter(
            deltas, "chaos.equivocations"
        ),
    )
)

_register(
    Scenario(
        name="forged_signatures",
        description="Node 1 floods votes/timeouts carrying garbage "
        "signatures under both its own and honest authorities: the "
        "verifier must reject every one (nonzero rejections, zero false "
        "accepts in committed QCs, zero dedup-cache entries for forged "
        "triples).",
        plan=lambda: FaultPlan(default_link=_LINK),
        byzantine={1: SigForger},
        duration=60.0,
        min_commits=3,
        expect=_expect_forgery,
    )
)

def _expect_stale_replay(report: dict, deltas: dict) -> list[str]:
    """Gate the replay-counter expectation on a replay actually having
    been injected: the StaleReplayer needs to SEE at least two
    blocks/TCs before it has stale material, and at some seeds the run
    early-stops (min_commits reached) first — previously an EXPECT
    failure with nothing wrong (the stale_qc_replay@seed2 flake). A full-
    duration run with zero replays is still a failure: the adversary had
    the whole window and injected nothing, so the scenario tested
    nothing."""
    replays = deltas.get("chaos.stale_replays", 0)
    early_stop = report["virtual_seconds"] < report["duration_requested"]
    if replays == 0 and early_stop:
        return []
    return _expect_counter(deltas, "chaos.stale_replays")


_register(
    Scenario(
        name="stale_qc_replay",
        description="Node 1 re-broadcasts old proposals and TCs on every new "
        "round: honest replicas must discard stale rounds without state "
        "damage or re-commits.",
        plan=lambda: FaultPlan(default_link=_LINK),
        byzantine={1: StaleReplayer},
        duration=60.0,
        # 5 (not 3): long enough that the replayer has stale material
        # before the early-stop at almost any seed; the expectation above
        # stays gated for the residue.
        min_commits=5,
        expect=_expect_stale_replay,
    )
)

_register(
    Scenario(
        name="vote_withholding",
        description="Node 1 withholds every vote and timeout: the remaining "
        "2f+1 honest replicas keep committing, at pacemaker pace through "
        "the silent node's leader rounds.",
        plan=lambda: FaultPlan(default_link=_LINK),
        byzantine={1: VoteWithholder},
        duration=60.0,
        min_commits=3,
        expect=lambda report, deltas: _expect_counter(
            deltas, "chaos.withheld_votes"
        ),
    )
)

# Flash-crowd ingress: deliberately small lanes + a paced drain (40 tx/s
# capacity per node) so a 60 tx/s spike demonstrably overloads admission
# under the virtual clock, where Python work costs zero virtual time and
# an unpaced drain could never saturate.
_FLASH_SPIKE = (5.0, 7.0)  # virtual-second spike window (see expectations)


def _flash_ingress_config() -> IngressConfig:
    return IngressConfig(
        lanes=(
            LaneSpec("priority", min_fee=1_000, capacity=8),
            LaneSpec("standard", min_fee=1, capacity=16),
            LaneSpec("bulk", min_fee=0, capacity=16),
        ),
        verify_batch=4,
        verify_interval=0.1,
    )


def _commit_rate(report: dict, t0: float, t1: float) -> float:
    """Aggregate honest commits/sec inside [t0, t1) from commit_times."""
    n = sum(
        1
        for times in report.get("commit_times", {}).values()
        for t in times
        if t0 <= t < t1
    )
    return n / max(t1 - t0, 1e-9)


def _expect_flash_crowd(report: dict, deltas: dict) -> list[str]:
    problems = _expect_counter(deltas, "ingress.shed")
    problems += _expect_counter(deltas, "ingress.verified_sigs", minimum=20)
    totals = {"offered": 0, "accepted": 0, "shed": 0, "retry_hints": 0}
    for summary in report.get("ingress", {}).values():
        for k in totals:
            totals[k] += summary.get(k, 0)
    if totals["shed"] and totals["retry_hints"] != totals["shed"]:
        problems.append(
            f"{totals['shed']} sheds but only {totals['retry_hints']} carried "
            "a retry-after hint (backpressure contract: every shed names a "
            "retry window)"
        )
    if not totals["accepted"]:
        problems.append("no client transaction was accepted end-to-end")
    # Commit throughput must hold its pre-overload plateau through the
    # spike: overload lands on the ingress lanes (shed with backpressure),
    # never on consensus. 0.75 here is the any-seed structural guard;
    # tests/test_chaos.py pins the 10%-band acceptance figure at seed 11.
    t0, t1 = _FLASH_SPIKE
    pre = _commit_rate(report, 2.0, t0)
    spike = _commit_rate(report, t0, t1)
    if pre <= 0:
        problems.append("no commits in the pre-overload window")
    elif spike < 0.75 * pre:
        problems.append(
            f"committed throughput collapsed under the flash crowd: "
            f"{spike:.2f}/s in the spike vs {pre:.2f}/s before"
        )
    return problems


_register(
    Scenario(
        name="flash_crowd_ingress",
        description="An open-loop flash crowd (4 -> 60 tx/s per node) hits "
        "every node's authenticated ingress while consensus runs: admission "
        "sheds with retry-after backpressure, ingress signatures ride each "
        "node's real BatchVerificationService, and committed throughput "
        "holds its pre-overload plateau.",
        # 150 ms links: rounds stay realistic-paced, which bounds the
        # PYTHON work 11 virtual seconds cost (every commit is ~a dozen
        # pure-python signature ops — wall time, not virtual time).
        plan=lambda: FaultPlan(default_link=LinkFaults(delay=0.15)),
        duration=11.0,
        min_commits=0,  # no early stop: the spike window must play out
        ingress=lambda: IngressLoad(
            curve=ArrivalCurve(
                kind="flash",
                rate=4,
                peak=60,
                t_start=_FLASH_SPIKE[0],
                t_end=_FLASH_SPIKE[1],
            ),
            duration=10.0,
            clients=3,
            tx_bytes=32,
            config=_flash_ingress_config,
        ),
        expect=_expect_flash_crowd,
    )
)

# Bulk-flood priority: the continuous-batching scheduler's acceptance
# scenario (ISSUE 7). A mempool-class verification flood OVERLOADS the
# bulk pipeline (pace: 2 ms of virtual device time per signature; 40
# groups/s/node of 16 sigs offers ~128% device utilization, so the bulk
# backlog grows without bound for the whole window) while consensus runs
# its QC/TC checks through the SAME per-node scheduler. The critical
# lane must preempt: its p99 queueing delay stays bounded at
# milliseconds while bulk's grows to virtual SECONDS (bulk waits — the
# lane contract), and commits continue through the flood window.
_FLOOD_PACE_S_PER_SIG = 0.002
_FLOOD_GROUP_SIZE = 16
_FLOOD_WINDOW = (1.0, 7.0)  # virtual-second flood span
# One initial bulk bucket occupies group_size * pace = 32 ms of virtual
# device time (coalesced backlog buckets occupy far more); preemption is
# proven if critical p99 stays well under even the smallest bucket.
_CRITICAL_P99_BOUND_MS = 10.0


def _expect_bulk_flood(report: dict, deltas: dict) -> list[str]:
    problems = _expect_counter(deltas, "scheduler.critical_dispatches")
    problems += _expect_counter(deltas, "scheduler.buckets")
    flood_verified = sum(
        s.get("verified", 0) for s in report.get("flood", {}).values()
    )
    if flood_verified < 100:
        problems.append(
            f"bulk flood barely ran: {flood_verified} signatures verified"
        )
    bulk_queued = False
    for label, s in sorted(report.get("scheduler", {}).items()):
        qd = s.get("queue_delay", {})
        crit, bulk = qd.get("consensus"), qd.get("mempool")
        if not crit or crit["count"] < 3:
            problems.append(
                f"node {label}: too little critical-lane traffic to judge "
                f"({0 if not crit else crit['count']} groups)"
            )
            continue
        if crit["p99_ms"] > _CRITICAL_P99_BOUND_MS:
            problems.append(
                f"node {label}: critical-lane p99 queueing "
                f"{crit['p99_ms']:.1f} ms exceeds {_CRITICAL_P99_BOUND_MS} ms "
                "(commit-critical work queued behind the bulk flood)"
            )
        if bulk and bulk["p99_ms"] > _CRITICAL_P99_BOUND_MS:
            bulk_queued = True
    if not bulk_queued:
        problems.append(
            "the flood produced no bulk-lane queueing anywhere — the "
            "scenario did not actually contend the device (pace/rate too "
            "low?), so the critical-lane bound proves nothing"
        )
    # Commits must not stall: a floor overall AND progress INSIDE the
    # overload window on every node (the flood spans almost the whole
    # run, so a stalled scheduler would show up here, not in min_commits).
    t0, t1 = _FLOOD_WINDOW
    for label, times in sorted(report.get("commit_times", {}).items()):
        if len(times) < 3:
            problems.append(f"node {label}: only {len(times)} commits")
        elif not any(t0 + 2.0 <= t < t1 for t in times):
            problems.append(
                f"node {label}: no commit inside the flood window "
                f"[{t0 + 2.0}, {t1}) — consensus stalled behind bulk"
            )
    return problems


_register(
    Scenario(
        name="bulk_flood_priority",
        description="A mempool bulk-verification flood overloads every "
        "node's device scheduler (virtual occupancy pacing, ~128% "
        "utilization) while consensus runs: the preemptive critical lane "
        "keeps QC/TC verification p99 queueing bounded at milliseconds "
        "while bulk's backlog grows to seconds, and commits continue "
        "through the whole flood window.",
        # 150 ms links: realistic round pacing bounds the pure-python
        # signature work per virtual second (flash_crowd rationale).
        plan=lambda: FaultPlan(default_link=LinkFaults(delay=0.15)),
        duration=8.0,
        min_commits=0,  # no early stop: the flood window must play out
        flood=lambda: BulkFlood(
            rate=40.0,
            group_size=_FLOOD_GROUP_SIZE,
            duration=_FLOOD_WINDOW[1] - _FLOOD_WINDOW[0],
            t_start=_FLOOD_WINDOW[0],
            pool=8,
        ),
        scheduler=lambda: SchedulerConfig(
            pace_s_per_sig=_FLOOD_PACE_S_PER_SIG
        ),
        expect=_expect_bulk_flood,
    )
)

# SLO-burn telemetry: the live-telemetry plane's acceptance scenario
# (ISSUE 8). A mempool bulk flood overdrives the virtual device-occupancy
# model (pace 2.2 ms/sig x 40 groups/s x 16 sigs ~= 141% utilization), so
# bulk queueing delay climbs past the mempool lane's published 500 ms SLO
# during the flood window; the per-node telemetry planes (0.5 s snapshot
# interval, 1 s short / 3 s long burn windows) must FIRE the lane.mempool
# burn alert while the fault is active and CLEAR it after the flood stops
# and the backlog drains — with the critical lane never burning (the
# scheduler lane contract, now judged by the evaluator instead of an
# advisory string).
_SLO_FLOOD_WINDOW = (1.0, 4.0)
_SLO_PACE_S_PER_SIG = 0.0022


def _slo_telemetry_config() -> TelemetryConfig:
    return TelemetryConfig(
        interval_s=0.5,
        short_window=2,
        long_window=6,
        burn_factor=2.0,
    )


def _expect_slo_burn(report: dict, deltas: dict) -> list[str]:
    problems = _expect_counter(deltas, "telemetry.snapshots")
    problems += _expect_counter(deltas, "telemetry.slo_burn_fired")
    problems += _expect_counter(deltas, "telemetry.slo_burn_cleared")
    t0, t1 = _SLO_FLOOD_WINDOW
    if not any(
        t["reason"] == "slo_burn" for t in report.get("watchdog_triggers", ())
    ):
        problems.append(
            "no slo_burn watchdog trigger (the alert never reached the "
            "auto-dump path)"
        )
    telem = report.get("telemetry", {})
    if not telem:
        problems.append("report carries no telemetry section")
    for label, node in sorted(telem.items()):
        fired = [
            a
            for a in node.get("alerts", ())
            if a["slo"] == "lane.mempool" and a["event"] == "fired"
        ]
        cleared = [
            a
            for a in node.get("alerts", ())
            if a["slo"] == "lane.mempool" and a["event"] == "cleared"
        ]
        if not fired:
            problems.append(
                f"node {label}: mempool-lane SLO burn never fired under a "
                "flood that exceeds the lane's 500 ms objective"
            )
            continue
        if not (t0 <= fired[0]["t"] <= t1 + 1.0):
            problems.append(
                f"node {label}: burn fired at t={fired[0]['t']}, outside "
                f"the injected fault window [{t0}, {t1}]"
            )
        if not cleared:
            problems.append(
                f"node {label}: burn alert never cleared after the flood "
                "stopped (heal not observed)"
            )
        elif cleared[0]["t"] <= t1:
            problems.append(
                f"node {label}: burn cleared at t={cleared[0]['t']}, "
                "before the fault even ended"
            )
        if node.get("active_alerts"):
            problems.append(
                f"node {label}: alerts still active at run end: "
                f"{node['active_alerts']}"
            )
        # the critical lane must never burn — preemption holds its SLO
        if any(a["slo"] == "lane.consensus" for a in node.get("alerts", ())):
            problems.append(
                f"node {label}: the consensus lane burned its SLO under a "
                "mempool flood (preemption failed)"
            )
    return problems


_register(
    Scenario(
        name="slo_burn_bulk",
        description="A mempool bulk flood (~141% virtual device "
        "utilization) drives bulk queueing past its 500 ms SLO while "
        "per-node telemetry planes snapshot on the virtual clock: the "
        "mempool-lane burn-rate alert fires during the flood, the "
        "consensus lane never burns, and the alert clears after the "
        "backlog drains — the scrapeable alert surface end to end.",
        plan=lambda: FaultPlan(default_link=LinkFaults(delay=0.15)),
        duration=8.0,
        min_commits=0,  # no early stop: fire AND clear must both play out
        flood=lambda: BulkFlood(
            rate=40.0,
            group_size=16,
            duration=_SLO_FLOOD_WINDOW[1] - _SLO_FLOOD_WINDOW[0],
            t_start=_SLO_FLOOD_WINDOW[0],
            pool=8,
        ),
        scheduler=lambda: SchedulerConfig(pace_s_per_sig=_SLO_PACE_S_PER_SIG),
        telemetry=_slo_telemetry_config,
        expect=_expect_slo_burn,
    )
)

# ---------------------------------------------------------------------------
# Incident-ledger scenarios (§5.5r, ISSUE 20): the fault→alert→recovery
# attribution plane's own acceptance runs. incident_smoke is the tier-1
# regression pin (tests/test_incidents.py replays it twice and requires a
# bit-identical ledger); operations_day is the slow-tier game day ROADMAP
# item 4 sketched — rolling restarts across an epoch boundary under
# sustained ingress, judged by the health verdict instead of counters.

_SMOKE_FLOOD_WINDOW = (1.0, 4.0)  # slo_burn_bulk's proven burn recipe
_SMOKE_CRASH = (6.8, 7.8)  # after the burn clears (~t=6), before run end


def _smoke_ingress_config() -> IngressConfig:
    # Default (deep) lanes + a mild drain pacer: light traffic admits
    # cleanly — the smoke's ingress is background load, not the fault.
    return IngressConfig(verify_batch=4, verify_interval=0.1)


def _expect_incident_smoke(report: dict, deltas: dict) -> list[str]:
    problems = _expect_counter(deltas, "chaos.crashes")
    problems += _expect_counter(deltas, "chaos.restarts")
    problems += _expect_counter(deltas, "telemetry.slo_burn_fired")
    problems += _expect_counter(deltas, "incident.opened", minimum=3)
    problems += _expect_counter(deltas, "incident.attributed")
    ledger = report.get("incidents") or {}
    health = report.get("health") or {}
    kinds = {r["kind"] for r in ledger.get("incidents", ())}
    for want in ("flood", "crash", "link_fault"):
        if want not in kinds:
            problems.append(
                f"no {want} incident in the ledger (saw {sorted(kinds)})"
            )
    if health.get("alerts_attributed", 0) < 1:
        problems.append("no alert attributed to any injected fault")
    if health.get("alerts_unattributed", 0):
        problems.append(
            f"{health['alerts_unattributed']} unattributed alert(s): "
            f"{ledger.get('unattributed')}"
        )
    if health.get("residual", 0):
        problems.append("alert span(s) still open at run end (residual)")
    if health.get("burn_budget_ok") is not True:
        problems.append(f"burn budget violated: {health.get('burn')}")
    if not health.get("ok"):
        problems.append("health verdict is not green")
    flood_rows = [
        r for r in ledger.get("incidents", ()) if r["kind"] == "flood"
    ]
    if flood_rows and (
        flood_rows[0]["mttd_s"] is None or flood_rows[0]["mttr_s"] is None
    ):
        problems.append("flood incident carries no MTTD/MTTR")
    return problems


_register(
    Scenario(
        name="incident_smoke",
        description="Leader crash + a lossy link under light ingress while "
        "a short mempool flood drives one SLO burn fire/clear cycle: the "
        "incident ledger must attribute every alert to an injected fault "
        "window (unattributed == 0), carry MTTD/MTTR for the flood, stay "
        "within the declared burn budget, and replay bit-identically at "
        "the same seed — the incident plane's tier-1 regression pin.",
        plan=lambda: FaultPlan(
            # 150 ms links bound the pure-python wall cost per virtual
            # second (flash_crowd rationale); the 2<->3 pair additionally
            # drops 5% — a node-scoped link_fault window in the ledger.
            default_link=LinkFaults(delay=0.15),
            links={
                (2, 3): LinkFaults(delay=0.15, drop=0.05),
                (3, 2): LinkFaults(delay=0.15, drop=0.05),
            },
            crashes=[
                CrashWindow(
                    node=1, at=_SMOKE_CRASH[0], restart=_SMOKE_CRASH[1]
                )
            ],
        ),
        duration=10.0,
        min_commits=0,  # no early stop: fire, clear, crash must all play
        heal_t=_SMOKE_CRASH[1],
        ingress=lambda: IngressLoad(
            curve=ArrivalCurve(kind="sustained", rate=3.0),
            duration=9.0,
            clients=1,
            tx_bytes=32,
            config=_smoke_ingress_config,
        ),
        flood=lambda: BulkFlood(
            rate=40.0,
            group_size=16,
            duration=_SMOKE_FLOOD_WINDOW[1] - _SMOKE_FLOOD_WINDOW[0],
            t_start=_SMOKE_FLOOD_WINDOW[0],
            pool=8,
        ),
        scheduler=lambda: SchedulerConfig(pace_s_per_sig=_SLO_PACE_S_PER_SIG),
        telemetry=_slo_telemetry_config,
        burn_budget=lambda: {"lane.mempool": 30.0},
        expect=_expect_incident_smoke,
    )
)

# Operations day (ROADMAP item 4's stretch, scoped to the virtual plane):
# every node rolling-restarts once, one at a time, across a committed
# epoch boundary, under sustained ingress plus a mid-day mempool surge —
# pass/fail is the incident plane's verdict (burn budget respected,
# unattributed == 0, MTTD/MTTR ceilings), not a pile of counters. Runs
# on the trusted-crypto stub: membership/timing is at stake, not forgery.
_OPS_CRASH_START = 3.0
_OPS_CRASH_SPACING = 2.0
_OPS_CRASH_DOWN = 1.2
_OPS_SURGE_WINDOW = (8.0, 10.5)  # the mid-day mempool surge (burn source)
_OPS_MTTD_CEILING_MS = 6_000.0
_OPS_MTTR_CEILING_MS = 15_000.0


def _ops_committee(n: int) -> tuple[int, ...]:
    """Genesis committee with two join candidates held back: n-2 members
    keeps quorum with any single member down (the rolling-restart
    invariant) and leaves candidates for the boundary rotation."""
    return tuple(range(max(3, n - 2)))


def _ops_plan(n: int) -> FaultPlan:
    return FaultPlan(
        default_link=LinkFaults(delay=0.1),
        crashes=[
            CrashWindow(
                node=i,
                at=_OPS_CRASH_START + _OPS_CRASH_SPACING * i,
                restart=_OPS_CRASH_START + _OPS_CRASH_SPACING * i
                + _OPS_CRASH_DOWN,
            )
            for i in range(n)
        ],
    )


def _ops_directives(n: int) -> list[ReconfigDirective]:
    return [ReconfigDirective(at=2.0, rotate=2, activation_margin=_CHURN_MARGIN)]


def _expect_operations_day(report: dict, deltas: dict) -> list[str]:
    n = report["nodes"]
    problems = _expect_no_handoff_violation(deltas)
    problems += _expect_counter(deltas, "reconfig.epoch_switches")
    problems += _expect_counter(deltas, "chaos.crashes", minimum=n)
    problems += _expect_counter(deltas, "chaos.restarts", minimum=n)
    # Rotated-out genesis members legitimately stop committing at the
    # boundary, so the generic heal_t progress gate can't apply fleet-wide
    # — instead every FINAL-committee member must commit after the LAST
    # rolling restart: the day ends with the whole committee working.
    last_restart = max(
        (e["t"] for e in report["events"] if e["event"] == "restart"),
        default=0.0,
    )
    disagreements, memberships = _switch_memberships(report)
    problems += disagreements
    if memberships:
        _act, final_members = memberships[max(memberships)]
        for i in sorted(final_members):
            times = report.get("commit_times", {}).get(str(i), [])
            if not any(t > last_restart for t in times):
                problems.append(
                    f"final-committee node {i} never committed after the "
                    f"last rolling restart at t={last_restart}"
                )
    else:
        problems.append("no epoch-switch memberships recorded")
    problems += _expect_counter(deltas, "telemetry.slo_burn_fired")
    problems += _expect_counter(deltas, "incident.opened", minimum=n + 1)
    totals = {"offered": 0, "accepted": 0}
    for summary in report.get("ingress", {}).values():
        for k in totals:
            totals[k] += summary.get(k, 0)
    if not totals["accepted"]:
        problems.append("sustained ingress admitted nothing all day")
    ledger = report.get("incidents") or {}
    health = report.get("health") or {}
    kinds = [r["kind"] for r in ledger.get("incidents", ())]
    if kinds.count("crash") < n:
        problems.append(
            f"expected {n} crash incidents (one rolling restart per "
            f"node), saw {kinds.count('crash')}"
        )
    if "epoch_switch" not in kinds:
        problems.append("no epoch_switch incident — the boundary never ran")
    # The game-day verdict: every alert explained, burn inside budget,
    # nothing left burning, detection/recovery inside the ceilings.
    if health.get("alerts_attributed", 0) < 3:
        problems.append(
            f"only {health.get('alerts_attributed', 0)} alert(s) "
            "attributed — the surge never exercised the alert plane"
        )
    if health.get("alerts_unattributed", 0):
        problems.append(
            f"{health['alerts_unattributed']} unattributed alert(s): "
            f"{ledger.get('unattributed')}"
        )
    if health.get("residual", 0):
        problems.append("alert span(s) still open at run end (residual)")
    if health.get("burn_budget_ok") is not True:
        problems.append(f"burn budget violated: {health.get('burn')}")
    for kind, s in sorted((health.get("mttd") or {}).items()):
        if s["p99_ms"] > _OPS_MTTD_CEILING_MS:
            problems.append(
                f"{kind} detection p99 {s['p99_ms']:.0f} ms exceeds the "
                f"{_OPS_MTTD_CEILING_MS:.0f} ms ceiling"
            )
    for kind, s in sorted((health.get("mttr") or {}).items()):
        if s["p99_ms"] > _OPS_MTTR_CEILING_MS:
            problems.append(
                f"{kind} recovery p99 {s['p99_ms']:.0f} ms exceeds the "
                f"{_OPS_MTTR_CEILING_MS:.0f} ms ceiling"
            )
    if not health.get("ok"):
        problems.append("health verdict is not green")
    return problems


_register(
    Scenario(
        name="operations_day",
        description="A production game day on the virtual clock: all "
        "seven nodes rolling-restart one at a time across a committed "
        "epoch boundary (two members rotate at the boundary) under "
        "sustained client ingress, with a mid-day mempool surge driving "
        "the SLO burn plane — pass/fail is the incident ledger's health "
        "verdict: every alert attributed to an injected fault, the "
        "declared burn budget respected, no residual alerts, and "
        "MTTD/MTTR p99 inside the ceilings.",
        n=7,
        committee_n=_ops_committee,
        plan_n=_ops_plan,
        reconfig_n=_ops_directives,
        duration=22.0,
        min_commits=0,  # no early stop: the whole day must play out
        # No heal_t: nodes rotated out at the boundary stop committing by
        # design; the expectation pins final-committee progress instead.
        slow=True,
        trusted_crypto=True,
        ingress=lambda: IngressLoad(
            curve=ArrivalCurve(kind="sustained", rate=4.0),
            duration=20.0,
            clients=2,
            tx_bytes=32,
        ),
        flood=lambda: BulkFlood(
            rate=40.0,
            group_size=16,
            duration=_OPS_SURGE_WINDOW[1] - _OPS_SURGE_WINDOW[0],
            t_start=_OPS_SURGE_WINDOW[0],
            pool=8,
        ),
        scheduler=lambda: SchedulerConfig(pace_s_per_sig=_SLO_PACE_S_PER_SIG),
        telemetry=_slo_telemetry_config,
        burn_budget=lambda: {
            "lane.mempool": 60.0,
            "lane.consensus": 2.0,
        },
        expect=_expect_operations_day,
    )
)


def _expect_flood_cell(report: dict, deltas: dict) -> list[str]:
    """flash_crowd's contract, size-parameterized for the matrix grid:
    shed>0 with a retry hint on every shed, the commit plateau held
    through the spike, no node starved outright, and the ledger carries
    the spike window with zero unattributed alerts."""
    problems = _expect_flash_crowd(report, deltas)
    starved = [
        int(i)
        for i, rounds in sorted(
            report.get("commits", {}).items(), key=lambda kv: int(kv[0])
        )
        if not rounds
    ]
    if starved:
        problems.append(f"nodes with zero commits under the flood: {starved}")
    ledger = report.get("incidents") or {}
    health = report.get("health") or {}
    if "ingress_spike" not in {
        r["kind"] for r in ledger.get("incidents", ())
    }:
        problems.append("no ingress_spike incident in the ledger")
    if health.get("alerts_unattributed", 0):
        problems.append(
            f"{health['alerts_unattributed']} unattributed alert(s) in a "
            f"flood cell: {ledger.get('unattributed')}"
        )
    return problems


_register(
    Scenario(
        name="flood",
        description="flash_crowd_ingress, grid-shaped (ROADMAP item 3's "
        "flood-cell residue): the identical open-loop 4 -> 60 tx/s flash "
        "crowd per node, with the expectations size-parameterized — shed "
        "with retry hints, plateau held, no starved node at any committee "
        "size — and the spike window pinned in the incident ledger. Slow "
        "tier standalone (the tier-1 copy of this machinery is "
        "flash_crowd_ingress); its home is the matrix grid.",
        plan=lambda: FaultPlan(default_link=LinkFaults(delay=0.15)),
        duration=11.0,
        # The spike machinery ends at t=10; running a cell to the 30 s
        # grid cap would soak 19 empty virtual seconds per cell.
        cell_duration=11.0,
        min_commits=0,  # no early stop: the spike window must play out
        slow=True,
        ingress=lambda: IngressLoad(
            curve=ArrivalCurve(
                kind="flash",
                rate=4,
                peak=60,
                t_start=_FLASH_SPIKE[0],
                t_end=_FLASH_SPIKE[1],
            ),
            duration=10.0,
            clients=3,
            tx_bytes=32,
            config=_flash_ingress_config,
        ),
        expect=_expect_flood_cell,
    )
)

_register(
    Scenario(
        name="saturation_lossy",
        description="Long lossy-link soak (15% drop, heavy jitter, 7 nodes, "
        "f=2 margin) — the extended-tier variant of lossy_links.",
        n=7,
        plan=lambda: FaultPlan(
            default_link=LinkFaults(
                drop=0.15, duplicate=0.05, reorder=0.10, delay=0.01, jitter=0.04
            )
        ),
        duration=240.0,
        min_commits=5,
        slow=True,
        expect=lambda report, deltas: _expect_counter(deltas, "chaos.drops")
        + _expect_counter(deltas, "consensus.sync_requests"),
    )
)

# ---------------------------------------------------------------------------
# Reconfiguration + catch-up scenarios (ROADMAP item 5, ISSUE 10). All three
# use 150 ms links: realistic round pacing bounds the pure-python signature
# work per virtual second (flash_crowd rationale), and a catch-up node's
# chain replay is the dominant wall cost.

_CATCHUP_LINK = LinkFaults(delay=0.15)

# The acceptance bound: a catch-up node must end within this many committed
# rounds of the live tip (commits lag the tip uniformly across nodes, so
# committed-round lag measures tip lag without racing in-flight messages).
MAX_TIP_LAG_ROUNDS = 4


def _max_commit_round(report: dict, node: int) -> int:
    return max(
        (r for r, _d in report["commits"].get(str(node), [])), default=0
    )


def _tip_round(report: dict) -> int:
    return max(
        (
            r
            for commits in report["commits"].values()
            for r, _d in commits
        ),
        default=0,
    )


def _expect_catchup(report: dict, deltas: dict, node: int) -> list[str]:
    """Shared catch-up assertions: the node range-synced (not one digest
    at a time) and ended within MAX_TIP_LAG_ROUNDS of the live tip."""
    problems = _expect_counter(deltas, "sync.range_requests")
    problems += _expect_counter(deltas, "sync.range_replies")
    # Rounds outnumber blocks: the absent node's leader rounds fall to
    # TCs, so a "9 rounds behind" gap may be only ~4 blocks of ancestry.
    problems += _expect_counter(deltas, "sync.range_blocks", minimum=3)
    if not report["commits"].get(str(node)):
        problems.append(f"catch-up node {node} never committed")
        return problems
    tip = _tip_round(report)
    mine = _max_commit_round(report, node)
    if tip - mine > MAX_TIP_LAG_ROUNDS:
        problems.append(
            f"catch-up node {node} ended {tip - mine} rounds behind the "
            f"tip (round {mine} vs {tip}; bound {MAX_TIP_LAG_ROUNDS})"
        )
    return problems


def _expect_epoch_reconfig(report: dict, deltas: dict) -> list[str]:
    problems = _expect_counter(deltas, "reconfig.epoch_switches", minimum=4)
    problems += _expect_counter(deltas, "reconfig.proposed")
    switches = report.get("epoch_switches", {})
    if not switches:
        return problems + ["no node recorded an epoch switch"]
    acts = {e["activation_round"] for evs in switches.values() for e in evs}
    epochs_seen = {e["epoch"] for evs in switches.values() for e in evs}
    if len(acts) != 1:
        problems.append(f"nodes disagree on the activation round: {sorted(acts)}")
        return problems
    if epochs_seen != {2}:
        problems.append(f"expected exactly epoch 2, saw {sorted(epochs_seen)}")
    act = next(iter(acts))
    # The original quorum members (0-2) must have switched...
    for i in (0, 1, 2):
        if str(i) not in switches:
            problems.append(f"node {i} never applied the epoch switch")
    # ...and committed on BOTH sides of the boundary: the safety checker
    # verified those QCs against epoch 1 and epoch 2 committees
    # respectively (run_scenario already folds its violations into ok).
    for i in (0, 1, 2):
        rounds = [r for r, _d in report["commits"].get(str(i), [])]
        if not any(r < act for r in rounds):
            problems.append(f"node {i} has no pre-boundary commit")
        if not any(r > act for r in rounds):
            problems.append(f"node {i} has no post-boundary commit")
    # The JOINED validator caught up from genesis (range sync) and
    # commits past the boundary...
    problems += _expect_catchup(report, deltas, node=4)
    if _max_commit_round(report, 4) <= act:
        problems.append(
            "joined node 4 never committed past the activation boundary"
        )
    # ...while the DEPARTED one stops at it (the new committee neither
    # serves it blocks nor counts its votes; +2 covers in-flight frames).
    left_max = _max_commit_round(report, 3)
    if left_max > act + 2:
        problems.append(
            f"departed node 3 kept committing past the boundary "
            f"(round {left_max} > activation {act})"
        )
    problems += _expect_counter(deltas, "chaos.invariant_checks")
    return problems


def _expect_genesis_catchup(report: dict, deltas: dict) -> list[str]:
    problems = _expect_catchup(report, deltas, node=3)
    boots = [e for e in report["events"] if e["event"] == "boot"]
    if [e["node"] for e in boots] != [3]:
        problems.append(f"expected one late boot of node 3, saw {boots}")
    return problems


def _expect_long_offline(report: dict, deltas: dict) -> list[str]:
    problems = _expect_counter(deltas, "chaos.crashes")
    problems += _expect_counter(deltas, "chaos.restarts")
    problems += _expect_catchup(report, deltas, node=2)
    return problems


_register(
    Scenario(
        name="epoch_reconfig",
        description="Validator join+leave at a committed epoch boundary "
        "under load: a signed EpochChange rides the chain (epoch-commit "
        "rule), nodes 0-3 hand the committee to {0,1,2,4} at the "
        "activation round, the joining node 4 range-syncs from genesis "
        "and commits past the boundary, the departing node 3 stops at "
        "it, and every committed QC re-verifies against the committee of "
        "its own epoch on both sides.",
        n=5,
        committee=(0, 1, 2, 3),
        plan=lambda: FaultPlan(default_link=_CATCHUP_LINK),
        reconfig=lambda: ReconfigDirective(
            at=2.0, add=(4,), remove=(3,), activation_margin=10
        ),
        duration=12.0,
        min_commits=0,  # no early stop: the boundary must play out
        expect=_expect_epoch_reconfig,
    )
)

_register(
    Scenario(
        name="genesis_catchup",
        description="A committee validator boots for the first time at "
        "t=6 with an EMPTY store while the chain runs: batched range "
        "sync fetches and fully re-verifies the ancestor chain from "
        "genesis, and the node ends within 4 committed rounds of the "
        "live tip.",
        plan=lambda: FaultPlan(
            default_link=_CATCHUP_LINK,
            boots=[DelayedBoot(node=3, at=6.0)],
        ),
        duration=11.0,
        min_commits=0,  # no early stop: the catch-up window must play out
        expect=_expect_genesis_catchup,
    )
)

_register(
    Scenario(
        name="long_offline_catchup",
        description="Node 2 crashes at t=1 and stays down for most of the "
        "run; on restart against its persisted store it is dozens of "
        "rounds behind and must range-sync to the tip (per-digest sync "
        "would crawl at one block per retry), ending within 4 committed "
        "rounds of the live tip with the double-vote guard intact.",
        plan=lambda: FaultPlan(
            default_link=_CATCHUP_LINK,
            crashes=[CrashWindow(node=2, at=1.0, restart=9.0)],
        ),
        duration=12.0,
        min_commits=0,  # no early stop: the offline window must play out
        heal_t=9.0,
        expect=_expect_long_offline,
    )
)

# ---------------------------------------------------------------------------
# Aggregation-overlay scenarios (ISSUE 13 / ROADMAP item 2): the region-aware
# vote/timeout aggregation tree (consensus/overlay.py), its failure modes, and
# the timeout_storm matrix cells that pin the O(n²) -> O(n·fanout) win.

# The storm window: a half|half partition leaves NO quorum on either side,
# so every round inside it stalls to the pacemaker on every node — the
# deterministic, committee-size-invariant timeout storm (the organic
# version was the 64-node lossy@seed2 multi-round stall, CHAOS_MATRIX_r01).
_STORM_WINDOW = (1.0, 5.0)

# Overlay bound on timeout-plane frames per LOCAL TIMEOUT event: one
# upward bundle + at most `agg_fanout` gossip-fallback frames + the
# bounded merged re-forwards, amortized over the fleet's timeout events.
# O(fanout), committee-size-free — the legacy all-to-all plane pays
# exactly n-1 per event (frames-per-stalled-round = n times these).
AGG_STORM_FRAMES_PER_TIMEOUT = 10.0


def _agg_params(timeout_ms: int = 1_000) -> Parameters:
    return Parameters(
        timeout_delay=timeout_ms,
        sync_retry_delay=1_000,
        timeout_backoff=2.0,
        max_timeout_delay=8_000,
        aggregation_overlay=True,
        agg_fanout=4,
        agg_hold_ms=40,
        # Below the 1 s pacemaker: a genuinely stalled round (dead
        # aggregator, partition) always reaches the gossip fallback
        # before the next local timeout re-arms it.
        agg_fallback_ms=400,
    )


def _storm_plan(n: int) -> FaultPlan:
    half = max(1, n // 2)
    return FaultPlan(
        default_link=LinkFaults(drop=0.03, delay=0.02, jitter=0.01),
        partitions=[
            Partition(
                start=_STORM_WINDOW[0],
                end=_STORM_WINDOW[1],
                groups=(tuple(range(half)), tuple(range(half, n))),
            )
        ],
        # Regions always present: the tree's region-aware placement (and
        # the wan.cross_region_frames accounting) is part of what the
        # storm cells pin.
        wan=WanMatrix(),
    )


def _storm_metrics(deltas: dict) -> tuple[int, int]:
    return (
        deltas.get("consensus.timeouts", 0),
        deltas.get("agg.timeout_frames", 0),
    )


def _expect_timeout_storm(report: dict, deltas: dict) -> list[str]:
    n = report["nodes"]
    problems = _expect_counter(deltas, "chaos.partition_drops")
    timeouts, frames = _storm_metrics(deltas)
    if timeouts < n:
        problems.append(
            f"storm never fired: {timeouts} local timeouts across {n} nodes"
        )
        return problems
    fpt = frames / timeouts
    if fpt > AGG_STORM_FRAMES_PER_TIMEOUT:
        problems.append(
            f"timeout-plane frames per local timeout {fpt:.1f} exceeds the "
            f"overlay bound {AGG_STORM_FRAMES_PER_TIMEOUT} — the O(n) "
            "per-event storm is back"
        )
    problems += _expect_counter(deltas, "agg.bundles_sent")
    # No quorum exists inside the window, so every armed fallback fires:
    # the crashed-aggregator degradation path is structurally exercised.
    problems += _expect_counter(deltas, "agg.fallbacks")
    return problems


def _expect_timeout_storm_legacy(report: dict, deltas: dict) -> list[str]:
    n = report["nodes"]
    problems = _expect_counter(deltas, "chaos.partition_drops")
    timeouts, frames = _storm_metrics(deltas)
    if timeouts < n:
        problems.append(
            f"storm never fired: {timeouts} local timeouts across {n} nodes"
        )
        return problems
    fpt = frames / timeouts
    if fpt < 0.8 * (n - 1):
        problems.append(
            f"legacy baseline frames per timeout {fpt:.1f} is below "
            f"0.8*(n-1)={0.8 * (n - 1):.1f} — the committed baseline is "
            "not measuring the all-to-all storm"
        )
    if deltas.get("agg.bundles_sent", 0):
        problems.append("overlay bundles observed in the legacy cell")
    return problems


_register(
    Scenario(
        name="timeout_storm",
        description="Half|half no-quorum partition stalls every round in "
        "[1,5) on every node — the deterministic O(n²) timeout storm — "
        "with the aggregation overlay ON: timeouts merge up the "
        "region-aware tree as partial bundles (one frame per node per "
        "event plus bounded gossip fallback), frames-per-timeout stays "
        "O(fanout) regardless of committee size, and the fleet heals "
        "cleanly after the window.",
        plan_n=_storm_plan,
        parameters=_agg_params,
        duration=30.0,
        min_commits=4,
        heal_t=_STORM_WINDOW[1],
        expect=_expect_timeout_storm,
    )
)

_register(
    Scenario(
        name="timeout_storm_legacy",
        description="The SAME storm with the overlay OFF — the committed "
        "pre-overlay baseline cell: every node broadcasts every Timeout "
        "(n-1 frames per local timeout, O(n²) per stalled round), the "
        "number the timeout_storm cells are diffed against in "
        "CHAOS_MATRIX_rN.json.",
        plan_n=_storm_plan,
        duration=30.0,
        min_commits=4,
        heal_t=_STORM_WINDOW[1],
        expect=_expect_timeout_storm_legacy,
        # Matrix-only: the baseline number is pinned by the committed
        # artifact (and the slow-tier test), not the tier-1 sweep.
        slow=True,
    )
)


def _agg_cert_params(timeout_ms: int = 1_000) -> Parameters:
    p = _agg_params(timeout_ms)
    p.aggregate_certs = True
    return p


# Upper bound on committed certificate bytes per commit EVENT in an
# aggregate cell: one AggQC (172 B under the 64-byte trusted-agg stub
# signature) plus headroom for a stall round's AggTC, both n-independent
# EXCEPT the committee bitmap (ceil(n/8) bytes per certificate — the only
# size-dependent term an aggregate certificate carries, and exactly the
# term `_agg_cert_bytes_bound` prices). Legacy cells at n=64 run ~4.3 KB
# per QC — the O(1)-modulo-bitmap claim is asserted per cell up to n=256.
AGG_CERT_BYTES_PER_COMMIT = 400


def _agg_cert_bytes_bound(n: int) -> int:
    """Size-parameterized form of the per-commit certificate budget: the
    flat two-certificate core plus two bitmaps' worth of growth."""
    return AGG_CERT_BYTES_PER_COMMIT + 2 * ((n + 7) // 8)


def _expect_agg_certs(report: dict, deltas: dict) -> list[str]:
    problems = _expect_counter(deltas, "agg.qcs_formed", minimum=4)
    problems += _expect_counter(deltas, "agg.cert_bytes_committed")
    problems += _expect_counter(deltas, "chaos.stub_agg_verifies")
    if deltas.get("agg.partial_rejects", 0):
        problems.append(
            f"fault-free aggregate fleet rejected "
            f"{deltas['agg.partial_rejects']} partials"
        )
    commits = deltas.get("consensus.commits", 0)
    if commits:
        bound = _agg_cert_bytes_bound(report["nodes"])
        per = deltas.get("agg.cert_bytes_committed", 0) / commits
        if per > bound:
            problems.append(
                f"certificate bytes per committed round {per:.0f} exceeds "
                f"the bitmap-parameterized bound {bound} at "
                f"n={report['nodes']} — the constant-size claim regressed"
            )
    return problems


_register(
    Scenario(
        name="agg_certs",
        description="Constant-size certificates (§5.5o): every vote and "
        "timeout rides as a singleton aggregate partial, interior overlay "
        "nodes merge bitmap-disjoint partials Handel-style, and committed "
        "blocks carry AggQC/AggTC — one aggregate signature plus a "
        "committee bitmap — so certificate bytes per committed round stay "
        "flat (modulo the ceil(n/8)-byte bitmap) from n=4 to n=256, the "
        "matrix column the O(1) claim is pinned by. Runs the trusted-agg "
        "stub at every size: the exact BLS pairing is for unit tests and "
        "the A/B bench, not fleets.",
        plan=lambda: FaultPlan(default_link=_LINK, wan=WanMatrix()),
        parameters=_agg_cert_params,
        trusted_crypto=True,
        matrix_sizes=(4, 64, 128, 256),
        min_commits=4,
        expect=_expect_agg_certs,
    )
)


# Commit-proof serving (§5.5q): worst-case CommitProof wire size for a
# single-payload block — version byte, 32 B author, u64 round, one-digest
# payload seq, 32 B parent hash + u64 parent round, epoch flag, and the
# aggregate certificate (flat core + the ceil(n/8)-byte committee
# bitmap). Size-parameterized like the certificate bound: the O(1)
# claim is "flat modulo the bitmap", not "flat including it".
PROOF_BYTES_CORE = 310


def _proof_bytes_bound(n: int) -> int:
    return PROOF_BYTES_CORE + ((n + 7) // 8)


def _proof_totals(report: dict) -> dict:
    totals = {
        "tracked": 0, "served": 0, "verified_ok": 0, "verify_failed": 0,
        "unproved_committed": 0, "proof_bytes_max": 0,
    }
    for summary in report.get("proofs", {}).values():
        for k in totals:
            if k == "proof_bytes_max":
                totals[k] = max(totals[k], summary.get(k, 0))
            else:
                totals[k] += summary.get(k, 0)
    return totals


def _expect_ingress_proofs(report: dict, deltas: dict) -> list[str]:
    problems = _expect_counter(deltas, "proofs.indexed")
    problems += _expect_counter(deltas, "proofs.resolved")
    problems += _expect_counter(deltas, "proofs.served", minimum=4)
    if deltas.get("proofs.cert_mismatch", 0):
        problems.append(
            f"{deltas['proofs.cert_mismatch']} commit notes carried a "
            "certificate that did not certify the committed block"
        )
    totals = _proof_totals(report)
    if not totals["tracked"]:
        problems.append("no admitted transaction entered the proof loop")
    if totals["served"] < 4:
        problems.append(
            f"only {totals['served']} proofs reached a client in hand "
            "(floor 4) — the submit→commit→proof loop barely closed"
        )
    # EVERY served proof must verify statelessly at the client; a
    # committed-and-indexed tx whose key never resolved would be an
    # admitted-and-committed tx its client cannot prove.
    if totals["verify_failed"]:
        problems.append(
            f"{totals['verify_failed']} served proofs FAILED stateless "
            "client verification"
        )
    if totals["verified_ok"] != totals["served"]:
        problems.append(
            f"{totals['verified_ok']} verified of {totals['served']} served"
        )
    if totals["unproved_committed"]:
        problems.append(
            f"{totals['unproved_committed']} committed transactions are "
            "not provable by their client (registry resolution hole)"
        )
    bound = _proof_bytes_bound(report["nodes"])
    if totals["proof_bytes_max"] > bound:
        problems.append(
            f"worst served proof {totals['proof_bytes_max']} B exceeds the "
            f"O(1) bound {bound} B at n={report['nodes']}"
        )
    return problems


def _proofs_ingress_config() -> IngressConfig:
    # Generous default lanes + a fast verify tick: this scenario pins the
    # proof loop, not admission overload (flash_crowd_ingress owns that).
    return IngressConfig(verify_batch=4, verify_interval=0.05)


def _proofs_ingress_load() -> IngressLoad:
    return IngressLoad(
        curve=ArrivalCurve(kind="sustained", rate=2),
        duration=10.0,
        clients=2,
        tx_bytes=32,
        config=_proofs_ingress_config,
    )


_register(
    Scenario(
        name="ingress_proofs",
        description="Commit-proof serving plane (§5.5q): open-loop clients "
        "submit through every node's authenticated ingress, each ACCEPTED "
        "digest rides that node's next proposal, and a proof client "
        "subscribes until commit — every served CommitProof must verify "
        "STATELESSLY against the committee keys alone, stay within the "
        "bitmap-parameterized O(1) byte bound, and no admitted-and-"
        "committed transaction may end the run unprovable.",
        plan=lambda: FaultPlan(default_link=_LINK),
        parameters=_agg_cert_params,
        trusted_crypto=True,
        duration=14.0,
        cell_duration=14.0,  # the loop plays out in 14 s at every size
        min_commits=0,  # no early stop: the 4 s post-load tail must play out
        ingress=_proofs_ingress_load,
        proofs=True,
        expect=_expect_ingress_proofs,
    )
)


def _expect_proof_squatter(report: dict, deltas: dict) -> list[str]:
    problems = _expect_counter(deltas, "proofs.subs_shed", minimum=200)
    sent = shed = 0
    for s in report.get("proof_squat", {}).values():
        sent += s.get("sent", 0)
        shed += s.get("shed", 0)
    if sent < 200:
        problems.append(f"squat driver barely ran: {sent} subscriptions")
    if shed != sent:
        problems.append(
            f"only {shed} of {sent} never-admitted subscriptions were shed "
            "(a squatter must never park a waiter or earn a proof)"
        )
    # The registry stays bounded under the flood: squat traffic allocates
    # NOTHING, so total indexed state tracks honest traffic + the ring
    # capacity, orders of magnitude under the squat volume.
    for label, s in sorted(report.get("proofs", {}).items()):
        if s.get("registry_size", 0) > 3_000:
            problems.append(
                f"node {label}: registry size {s['registry_size']} — "
                "squat subscriptions appear to allocate state"
            )
    # Honest clients still get verified proofs THROUGH the squat flood.
    totals = _proof_totals(report)
    if totals["served"] < 4:
        problems.append(
            f"only {totals['served']} honest proofs served under squatting"
        )
    if totals["verify_failed"]:
        problems.append(
            f"{totals['verify_failed']} served proofs failed verification"
        )
    return problems


_register(
    Scenario(
        name="proof_squatter",
        description="Byzantine nonce-squatting clients flood every node's "
        "proof port with subscribe-until-commit queries for (client, nonce) "
        "pairs that were never admitted: each one must be SHED with a retry "
        "hint and allocate NOTHING (proofs.subs_shed pins the count, the "
        "registry size stays bounded by honest traffic), while honest "
        "clients keep receiving verified proofs through the flood.",
        plan=lambda: FaultPlan(default_link=_LINK),
        parameters=_agg_cert_params,
        trusted_crypto=True,
        duration=12.0,
        min_commits=0,
        ingress=_proofs_ingress_load,
        proofs=True,
        proof_squat_rate=25.0,
        expect=_expect_proof_squatter,
    )
)


def _expect_agg_crash(report: dict, deltas: dict) -> list[str]:
    problems = _expect_counter(deltas, "chaos.crashes")
    problems += _expect_counter(deltas, "chaos.restarts")
    problems += _expect_counter(deltas, "agg.bundles_sent")
    problems += _expect_counter(deltas, "agg.entries_merged")
    problems += _expect_counter(deltas, "consensus.timeouts")
    # The crashed node's leader/aggregator rounds stall past
    # agg_fallback_ms, so the bounded gossip fallback must engage —
    # degradation, not silence.
    problems += _expect_counter(deltas, "agg.fallbacks")
    return problems


_register(
    Scenario(
        name="agg_collector_crash",
        description="An overlay aggregator crashes mid-run (node 1 down "
        "t=1..6 of a 7-node committee): rounds where it was the leader, "
        "a subtree parent, or the timeout collector stall to the "
        "pacemaker, the gossip fallback engages (bounded fan-out instead "
        "of silence), and liveness is clean after the restart.",
        n=7,
        plan=lambda: FaultPlan(
            default_link=_LINK,
            wan=WanMatrix(),
            crashes=[CrashWindow(node=1, at=1.0, restart=6.0)],
        ),
        parameters=_agg_params,
        duration=40.0,
        min_commits=4,
        heal_t=6.0,
        expect=_expect_agg_crash,
    )
)


def _expect_agg_byzantine(report: dict, deltas: dict) -> list[str]:
    problems = _expect_counter(deltas, "chaos.forged_votes")
    # chaos.forged_timeouts is deliberately NOT required here: an
    # early-stopping seed can reach its commit floor before any timeout
    # round, and even in a stalled round node 1 may be that round's
    # collector (it then relays no timeout bundle to poison). The
    # timeout-plane poisoning coverage is pinned at the deterministic
    # tier-1 seed in tests/test_overlay.py.
    problems += _expect_counter(deltas, "chaos.withheld_votes")
    problems += _expect_counter(deltas, "agg.invalid_entries")
    problems += _expect_counter(deltas, "verifier.rejected_sigs")
    problems += _expect_counter(deltas, "agg.entries_merged")
    problems += _expect_counter(deltas, "consensus.commits", minimum=8)
    if report.get("forged_triples_cached", 0) != 0:
        problems.append(
            f"{report['forged_triples_cached']} forged bundle entries found "
            "in a VerifiedSigCache (rejected signatures must never be cached)"
        )
    return problems


_register(
    Scenario(
        name="agg_byzantine_bundles",
        description="Byzantine aggregator on the overlay plane: node 1 "
        "poisons every partial bundle it relays — a garbage-signature "
        "entry under an honest authority, plus its own timeout entry "
        "re-signed over an ABSURD high_qc_round the carried QC cannot "
        "back (the TC-poisoning shape) — and withholds every third "
        "bundle outright. A crash window forces timeout rounds so the "
        "timeout plane is exercised: every poisoned entry must reject "
        "ALONE (the honest entries beside it still merge, real RFC 8032 "
        "verification at n=4), nothing forged is ever cached, no TC "
        "becomes unjustifiable, and commits continue.",
        plan=lambda: FaultPlan(
            default_link=_LINK,
            wan=WanMatrix(),
            crashes=[CrashWindow(node=2, at=1.0, restart=4.0)],
        ),
        byzantine={1: BundlePoisoner},
        parameters=_agg_params,
        duration=60.0,
        min_commits=3,
        heal_t=4.0,
        expect=_expect_agg_byzantine,
    )
)


def _expect_agg_epoch(report: dict, deltas: dict) -> list[str]:
    problems = _expect_counter(deltas, "reconfig.epoch_switches", minimum=3)
    problems += _expect_counter(deltas, "reconfig.proposed")
    problems += _expect_counter(deltas, "agg.bundles_sent")
    problems += _expect_counter(deltas, "agg.entries_merged")
    switches = report.get("epoch_switches", {})
    if not switches:
        return problems + ["no node recorded an epoch switch"]
    acts = {e["activation_round"] for evs in switches.values() for e in evs}
    if len(acts) != 1:
        problems.append(f"nodes disagree on the activation round: {sorted(acts)}")
        return problems
    act = next(iter(acts))
    # The original quorum committed on BOTH sides of the boundary: the
    # pre-boundary commits rode epoch 1's tree, the post-boundary ones
    # epoch 2's (node 3 out, node 4 in) — the per-round committee
    # resolution is what rotates the tree at the seam.
    for i in (0, 1, 2):
        rounds = [r for r, _d in report["commits"].get(str(i), [])]
        if not any(r < act for r in rounds):
            problems.append(f"node {i} has no pre-boundary commit")
        if not any(r > act for r in rounds):
            problems.append(f"node {i} has no post-boundary commit")
    return problems


_register(
    Scenario(
        name="agg_epoch_boundary",
        description="An epoch boundary crosses the aggregation tree: the "
        "committee hands {0,1,2,3} -> {0,1,2,4} at a committed activation "
        "round with the overlay ON — vote/timeout bundles route on epoch "
        "1's tree before the boundary and epoch 2's after (the departed "
        "node drops out of the tree, the joiner enters it), with commits "
        "on both sides and one unanimous activation round.",
        n=5,
        committee=(0, 1, 2, 3),
        plan=lambda: FaultPlan(default_link=_CATCHUP_LINK, wan=WanMatrix()),
        parameters=_agg_params,
        reconfig=lambda: ReconfigDirective(
            at=2.0, add=(4,), remove=(3,), activation_margin=10
        ),
        duration=12.0,
        min_commits=0,  # no early stop: the boundary must play out
        expect=_expect_agg_epoch,
    )
)


def _observatory_params() -> Parameters:
    """The probe opt-in (Parameters.probe_interval_ms): probe frames
    share the transport's per-link fault streams with protocol traffic,
    so only the observatory scenarios — whose pins were minted WITH
    probes on — enable them. 250 ms gives every directed link several
    closed probe loops even on an early-stopping seed."""
    return Parameters(
        timeout_delay=1_000,
        sync_retry_delay=1_000,
        timeout_backoff=2.0,
        max_timeout_delay=8_000,
        probe_interval_ms=250,
    )


def _partition_of(regions: dict) -> set[frozenset]:
    """Label-free form of a node->region map: the set of region member
    sets, so synthetic `rtt-k` labels compare against seeded geography."""
    groups: dict[str, set] = {}
    for node, region in regions.items():
        groups.setdefault(region, set()).add(str(node))
    return {frozenset(g) for g in groups.values()}


def _expect_wan_observatory(report: dict, deltas: dict) -> list[str]:
    problems = _expect_counter(deltas, "net.peer.probes_sent")
    problems += _expect_counter(deltas, "net.peer.pongs_received")
    n = report["nodes"]
    latency = peer_latency_map(report.get("peers") or {})
    missing = [
        (a, b)
        for a in (str(i) for i in range(n))
        for b in (str(j) for j in range(n))
        if a != b and (latency.get(a) or {}).get(b) is None
    ]
    if missing:
        problems.append(
            f"{len(missing)} directed link(s) never closed a probe loop "
            f"(first: {missing[:3]})"
        )
        return problems
    inferred = infer_fleet_regions(latency)
    truth = report.get("wan_regions") or {}
    if not truth:
        return problems + ["no seeded WAN regions in the report"]
    if _partition_of(inferred) != _partition_of(truth):
        problems.append(
            "measured RTT classes do not recover the seeded WAN geometry: "
            f"inferred {sorted(inferred.items())} vs seeded "
            f"{sorted(truth.items())}"
        )
    return problems


_register(
    Scenario(
        name="wan_observatory",
        description="Network observatory under the seeded 4-region WAN "
        "matrix: RTT probes on (Parameters.probe_interval_ms), clean "
        "links — every directed link must close probe loops, and the "
        "measured per-peer RTT EWMAs must recover the seeded region "
        "geometry exactly (fleet union-find under the 30 ms threshold "
        "matches the plan's region partition). Same seed, same ledger, "
        "bit for bit — the measurement substrate for region-aware "
        "leader election (ROADMAP item 5).",
        plan=lambda: FaultPlan(wan=WanMatrix()),
        parameters=_observatory_params,
        duration=30.0,
        min_commits=8,
        expect=_expect_wan_observatory,
    )
)


def _election_params(region_aware: bool) -> Parameters:
    """Overlay on (the co-location story needs the vote tree), probes
    OFF — the cells elect from the seeded WanMatrix region map, the
    same map the overlay trees by, so the region-aware and region-blind
    twins differ in exactly one bit: Parameters.region_aware_election.
    Leader-collector rooting is on in BOTH arms: with votes flowing to
    the NEXT leader, the vote trip pipelines into the next broadcast
    and no placement can shorten it — the certificate must form at the
    CURRENT leader and hand off explicitly for the pivot to be a real
    frame election placement controls."""
    p = _agg_params()
    p.region_aware_election = region_aware
    p.leader_collector = True
    return p


# The election cells' fleet is SKEWED (40/30/20/10 across the default
# four regions): under balanced occupancy a 2f+1 quorum must span three
# of four regions, and a quorum-spanning vote path actually pipelines
# better through a MOVING leader (leader->voter->collector is a one-way
# tour) — co-location cannot win there, and plurality is a tie-break
# artifact anyway. With a genuine plurality, the plurality + runner-up
# regions alone reach quorum, so a co-located plurality leader commits
# in one near-region RTT. That is the geometry region-aware election is
# FOR, and the one the cells pin.
ELECTION_WEIGHTS = (0.4, 0.3, 0.2, 0.1)


def _election_plan() -> FaultPlan:
    return FaultPlan(
        default_link=_LINK, wan=WanMatrix(weights=ELECTION_WEIGHTS)
    )


# Floor on the pivot-hop reduction the region-aware schedule must hold at
# fleet scale (n >= TRUSTED_CRYPTO_MIN_N): at least this many times fewer
# cross-region propose->certify pivots per committed round than the
# round-robin twin. The schedule arithmetic predicts ~#regions/n vs
# ~(1 - 1/#regions) — about 12x at n=64 over 4 balanced regions — so 2x
# is a conservative, size-robust pin (the ISSUE's "~2x fewer" floor).
ELECTION_HOP_RATIO = 2.0


def _overall_commit_rate(report: dict) -> float:
    """Fleet commit events per virtual second over the WHOLE run (the
    windowed `_commit_rate` above serves the overload plateaus) — with
    both twins early-stopping at the same min_commits floor, the
    inverse of virtual time-to-floor, i.e. the commit-latency yardstick
    on the virtual clock."""
    commits = sum(len(v) for v in (report.get("commits") or {}).values())
    span = float(report.get("virtual_seconds") or 0.0)
    return commits / span if span else 0.0


def _expect_wan_election_blind(report: dict, deltas: dict) -> list[str]:
    """The region-blind twin's own gate: the election attribution must
    accrue (the counters are elector-mode-independent — that is what
    makes the A/B comparable) and matches + hops must partition the
    committed rounds."""
    problems = _expect_counter(deltas, "elect.rounds", minimum=4)
    rounds = deltas.get("elect.rounds", 0)
    matches = deltas.get("elect.leader_region_matches", 0)
    hops = deltas.get("elect.cross_region_hops", 0)
    if rounds and matches + hops != rounds:
        problems.append(
            f"election attribution does not partition: {matches} co-located "
            f"+ {hops} cross-region pivots != {rounds} committed rounds"
        )
    return problems


def _expect_wan_election(report: dict, deltas: dict) -> list[str]:
    """The region-aware cell is a one-cell A/B: after its own run, it
    REPLAYS the identical (seed, n, virtual window, WanMatrix) with the
    region-blind twin — run_scenario re-enters cleanly here because
    expectations evaluate after the virtual loop has fully drained —
    and pins both deltas: cross-region pivot hops per committed round
    drop by ELECTION_HOP_RATIO at fleet scale (never rise at any size),
    and the fleet commits strictly faster on the virtual clock. The
    in-run round-robin counterfactual (elect.cross_region_hops_blind)
    must agree with the twin's direction, so the artifact carries the
    reduction twice: priced inside one run and measured across two."""
    problems = _expect_wan_election_blind(report, deltas)
    rounds = deltas.get("elect.rounds", 0)
    if not rounds:
        return problems
    n = report["nodes"]
    aware = deltas.get("elect.cross_region_hops", 0) / rounds
    counterfactual = deltas.get("elect.cross_region_hops_blind", 0) / rounds
    if aware > counterfactual:
        problems.append(
            f"in-run counterfactual inverted: region-aware pivots cross "
            f"{aware:.3f}/commit vs {counterfactual:.3f} under round-robin "
            "placement of the same rounds"
        )
    blind = run_scenario(
        "wan_election_blind",
        report["seed"],
        duration=report["duration_requested"],
        n=n,
        trusted_crypto=report.get("crypto_mode") != "exact",
    )
    if not blind["ok"]:
        problems.append(
            "region-blind twin failed its own run: "
            + "; ".join(
                blind.get("safety_violations", [])[:2]
                + blind.get("liveness_violations", [])[:2]
                + blind.get("expectation_failures", [])[:2]
            )
        )
        return problems
    b_rounds = blind["metrics"].get("elect.rounds", 0)
    if not b_rounds:
        return problems + ["region-blind twin accrued no election rounds"]
    b_hops = blind["metrics"].get("elect.cross_region_hops", 0) / b_rounds
    if n >= TRUSTED_CRYPTO_MIN_N:
        if aware * ELECTION_HOP_RATIO > b_hops:
            problems.append(
                f"cross-region pivot hops per commit: region-aware "
                f"{aware:.3f} vs region-blind {b_hops:.3f} — less than the "
                f"pinned {ELECTION_HOP_RATIO:.0f}x reduction at n={n}"
            )
        aware_rate = _overall_commit_rate(report)
        blind_rate = _overall_commit_rate(blind)
        if aware_rate <= blind_rate:
            problems.append(
                f"virtual-clock commit latency did not improve: "
                f"{aware_rate:.3f} commits/s region-aware vs "
                f"{blind_rate:.3f} region-blind at n={n}"
            )
    elif aware > b_hops:
        problems.append(
            f"cross-region pivot hops per commit rose under the "
            f"region-aware schedule at n={n}: {aware:.3f} vs {b_hops:.3f}"
        )
    return problems


_register(
    Scenario(
        name="wan_election",
        description="Region-aware leader election under the seeded "
        "4-region WAN matrix with 40/30/20/10 skewed occupancy (§5.5p): "
        "the plurality + runner-up regions alone reach quorum, and "
        "region-block rotation keeps the "
        "propose->certify pivot — leader of round r handing to the vote "
        "collector, who IS round r+1's leader — inside one region except "
        "at the #regions block seams, so cross-region pivot hops per "
        "committed round drop and commits land faster on the virtual "
        "clock. The expectation replays the identical seed/size/window "
        "with the region-blind twin in the same cell: the artifact pins "
        "the A/B, not just the treated arm. The commit floor is one full "
        "rotation cycle at n=64 (and a whole multiple at n=4), so both "
        "arms average over EVERY region's geometry — a shorter window "
        "would sample only the plurality block's links.",
        plan=_election_plan,
        parameters=lambda: _election_params(True),
        duration=30.0,
        min_commits=64,
        matrix_sizes=(4, 64),
        expect=_expect_wan_election,
    )
)


_register(
    Scenario(
        name="wan_election_blind",
        description="The region-blind control arm of the wan_election "
        "A/B: identical overlay, WanMatrix, and parameters except "
        "region_aware_election=False (legacy round-robin). Never swept "
        "standalone in the matrix — wan_election's expectation replays "
        "it in-cell at the treated arm's exact seed/size/window.",
        plan=_election_plan,
        parameters=lambda: _election_params(False),
        duration=30.0,
        min_commits=64,
        expect=_expect_wan_election_blind,
        slow=True,
    )
)


# ---------------------------------------------------------------------------
# Production-grade succession (ISSUE 15 / ROADMAP item 4): rolling committee
# churn under the epoch-final handoff, quorum crashing at the activation
# boundary, and a joiner range-syncing across several boundaries mid-batch.
# All three are membership/topology/timing scenarios, so their tier-1 tests
# run under the trusted-crypto stub (the PR 12 trust model: forgery is not
# at stake here and exact pysigner dominates wall time); the matrix carries
# an exact-crypto rolling_churn cell at n=4.

_CHURN_EPOCHS = 3  # boundaries the committee rotates through
_CHURN_MARGIN = 8  # activation margin per directive (rounds)


def _churn_committee(n: int) -> tuple[int, ...]:
    """Genesis committee for a size-n fleet: the first max(3, n//2)
    indices — the rest are join candidates the rotation admits."""
    return tuple(range(max(3, n // 2)))


def _churn_rotate(n: int) -> int:
    """Members replaced per boundary: a third of the committee (rounded
    up), so _CHURN_EPOCHS boundaries replace every genesis member."""
    c = len(_churn_committee(n))
    return max(1, (c + 2) // 3)


def _churn_directives(n: int) -> list[ReconfigDirective]:
    k = _churn_rotate(n)
    # `at` times are lower bounds only: each directive additionally waits
    # for the previous boundary to be committed-past (the orchestrator's
    # progress gate), so churn paces itself off real chain progress.
    return [
        ReconfigDirective(at=t, rotate=k, activation_margin=_CHURN_MARGIN)
        for t in (1.5, 2.5, 3.5)
    ]


def _switch_memberships(report: dict) -> tuple[list[str], dict]:
    """Fold per-node epoch-switch events into epoch -> (activation,
    members), flagging any disagreement (the unanimity contract)."""
    problems: list[str] = []
    by_epoch: dict[int, set] = {}
    for evs in report.get("epoch_switches", {}).values():
        for e in evs:
            by_epoch.setdefault(e["epoch"], set()).add(
                (e["activation_round"], tuple(e.get("members", ())))
            )
    folded = {}
    for epoch in sorted(by_epoch):
        if len(by_epoch[epoch]) != 1:
            problems.append(
                f"nodes disagree on epoch {epoch}'s boundary/membership: "
                f"{sorted(by_epoch[epoch])}"
            )
        else:
            act, members = next(iter(by_epoch[epoch]))
            folded[epoch] = (act, members)
    return problems, folded


def _expect_no_handoff_violation(deltas: dict) -> list[str]:
    """The hard invariant the epoch-final handoff establishes: a commit
    may never land past its declared activation round."""
    late = deltas.get("reconfig.late_applies", 0)
    if late:
        return [
            f"epoch handoff violated: reconfig.late_applies = {late} "
            "(a commit landed at/past its declared activation round)"
        ]
    return []


def _expect_rolling_churn(report: dict, deltas: dict) -> list[str]:
    n = report["nodes"]
    genesis = set(_churn_committee(n))
    problems = _expect_no_handoff_violation(deltas)
    problems += _expect_counter(
        deltas, "reconfig.proposed", minimum=_CHURN_EPOCHS
    )
    problems += _expect_counter(
        deltas, "reconfig.epoch_switches", minimum=_CHURN_EPOCHS
    )
    disagreements, memberships = _switch_memberships(report)
    problems += disagreements
    expected = set(range(2, 2 + _CHURN_EPOCHS))
    if not expected <= set(memberships):
        problems.append(
            f"committee did not rotate through epochs {sorted(expected)}: "
            f"saw {sorted(memberships)}"
        )
        return problems
    if disagreements:
        return problems
    # FULL rotation: every genesis member rotated out at some boundary.
    for g in sorted(genesis):
        if all(g in members for _act, members in memberships.values()):
            problems.append(f"genesis member {g} never rotated out")
    # Per-node commit floors, scaled by the committee geometry: every
    # FINAL-committee member holds a participation floor, and members
    # past the last boundary must carry QUORUM weight of the final
    # committee — the committee demonstrably works as a committee. (Not
    # every-member: at fleet sizes a few joiners can still be mid
    # catch-up at cutoff without any liveness defect; at the default
    # n=6 the final committee is 3-of-3, so quorum = everyone and the
    # tier-1 pin stays maximal.)
    final_act, final_members = memberships[max(expected)]
    past_boundary = 0
    for i in sorted(final_members):
        rounds = [r for r, _d in report["commits"].get(str(i), [])]
        if len(rounds) < 3:
            problems.append(
                f"final-committee node {i} committed {len(rounds)} blocks (< 3)"
            )
        elif max(rounds) > final_act:
            past_boundary += 1
    quorum = 2 * len(final_members) // 3 + 1
    if past_boundary < quorum:
        problems.append(
            f"only {past_boundary} of {len(final_members)} final-committee "
            f"members committed past the last boundary {final_act} "
            f"(quorum {quorum})"
        )
    # Joiners demonstrably used batched range sync, and the safety
    # checker audited the run (its own epoch-final schedule included).
    problems += _expect_counter(deltas, "sync.range_requests")
    problems += _expect_counter(deltas, "sync.range_blocks", minimum=3)
    problems += _expect_counter(deltas, "chaos.invariant_checks")
    return problems


_register(
    Scenario(
        name="rolling_churn",
        description="The committee FULLY rotates over three committed "
        "epoch boundaries while traffic runs: chained committee-free "
        "rotation directives (a third of the committee per boundary, "
        "paced off real chain progress), every genesis member departs, "
        "every joiner range-syncs across the prior boundaries and "
        "commits past the last one, all under the epoch-final handoff — "
        "reconfig.late_applies must stay ZERO and the SafetyChecker's "
        "independently derived epoch schedule must agree at every step.",
        n=6,
        committee_n=_churn_committee,
        plan=lambda: FaultPlan(default_link=LinkFaults(delay=0.1)),
        reconfig_n=_churn_directives,
        # Three progress-gated boundaries + a joiner catch-up stall per
        # boundary (small committees need every member, so each admission
        # costs a few pacemaker rounds) + post-final-boundary traffic.
        duration=45.0,
        cell_duration=45.0,  # the matrix cell needs the full contract too
        min_commits=0,  # no early stop: all three boundaries must play out
        expect=_expect_rolling_churn,
    )
)


def _expect_boundary_quorum_crash(report: dict, deltas: dict) -> list[str]:
    problems = _expect_no_handoff_violation(deltas)
    problems += _expect_counter(deltas, "chaos.crashes", minimum=3)
    problems += _expect_counter(deltas, "chaos.restarts", minimum=3)
    problems += _expect_counter(deltas, "reconfig.epoch_switches")
    disagreements, memberships = _switch_memberships(report)
    problems += disagreements
    if 2 not in memberships:
        return problems + ["the epoch-2 boundary never landed"]
    act, _members = memberships[2]
    # The crashed quorum must come back on epoch 2 (persisted epoch-final
    # state reloaded — or the pending handoff replayed to completion) and
    # commit PAST the boundary it crashed at.
    finals = report.get("final_epochs", {})
    for i in ("0", "1", "2"):
        if finals.get(i) != 2:
            problems.append(
                f"restarted node {i} ended on epoch {finals.get(i)}, not 2 "
                "(persisted epoch-final state not recovered)"
            )
        rounds = [r for r, _d in report["commits"].get(i, [])]
        if not any(r > act for r in rounds):
            problems.append(
                f"restarted node {i} never committed past the boundary {act}"
            )
    # Progress resumed AFTER the restarts (the boundary crash healed).
    restarts = [
        e["t"] for e in report["events"] if e["event"] == "restart"
    ]
    if restarts:
        heal = max(restarts)
        resumed = any(
            t > heal
            for times in report.get("commit_times", {}).values()
            for t in times
        )
        if not resumed:
            problems.append(
                f"no commit after the last restart at t={heal} — the "
                "boundary crash never healed"
            )
    return problems


_register(
    Scenario(
        name="boundary_quorum_crash",
        description="A quorum of the old committee (nodes 0-2 of "
        "{0,1,2,3}) crashes the INSTANT the first epoch-2 switch lands — "
        "the worst place to die: some victims have applied and persisted "
        "the boundary, some still hold only the pending handoff. On "
        "restart every victim must reload its epoch-final state (schedule "
        "+ pending wall), never re-judge rounds its crashed incarnation "
        "certified, and the fleet must commit past the boundary with "
        "reconfig.late_applies still zero.",
        n=5,
        committee=(0, 1, 2, 3),
        plan=lambda: FaultPlan(default_link=_CATCHUP_LINK),
        reconfig=lambda: ReconfigDirective(
            at=2.0, add=(4,), remove=(3,), activation_margin=10
        ),
        boundary_crashes=lambda: [
            BoundaryCrash(epoch=2, nodes=(0, 1, 2), down_s=3.0)
        ],
        duration=25.0,
        min_commits=0,  # no early stop: crash + recovery must play out
        expect=_expect_boundary_quorum_crash,
    )
)


def _expect_multi_epoch_catchup(report: dict, deltas: dict) -> list[str]:
    problems = _expect_no_handoff_violation(deltas)
    problems += _expect_counter(deltas, "reconfig.epoch_switches")
    disagreements, memberships = _switch_memberships(report)
    problems += disagreements
    if not {2, 3} <= set(memberships):
        return problems + [
            f"both boundaries must land: saw epochs {sorted(memberships)}"
        ]
    boots = [e for e in report["events"] if e["event"] == "boot"]
    if [e["node"] for e in boots] != [5]:
        problems.append(f"expected one late boot of node 5, saw {boots}")
    # The late joiner crossed BOTH boundaries inside its range-synced
    # batches (its store was empty at boot) and ended on the live epoch,
    # near the live tip.
    if report.get("final_epochs", {}).get("5") != 3:
        problems.append(
            f"late joiner ended on epoch "
            f"{report.get('final_epochs', {}).get('5')}, not 3"
        )
    problems += _expect_catchup(report, deltas, node=5)
    return problems


_register(
    Scenario(
        name="multi_epoch_catchup",
        description="Two chained epoch boundaries land ({0,1,2,3} -> "
        "{1,2,3,4} -> {2,3,4,5}) and THEN node 5 — admitted by the second "
        "change — boots for the first time with an EMPTY store: one "
        "genesis range sync must replay the chain THROUGH both committed "
        "boundaries (epoch switches committed mid-batch govern the blocks "
        "after them), leaving the joiner on the live epoch within the "
        "tip-lag bound.",
        n=6,
        committee=(0, 1, 2, 3),
        plan=lambda: FaultPlan(
            default_link=_CATCHUP_LINK,
            boots=[DelayedBoot(node=5, at=10.0)],
        ),
        reconfig=lambda: [
            ReconfigDirective(at=1.5, add=(4,), remove=(0,), activation_margin=10),
            ReconfigDirective(at=2.5, add=(5,), remove=(1,), activation_margin=10),
        ],
        duration=18.0,
        min_commits=0,  # no early stop: both boundaries + the boot play out
        expect=_expect_multi_epoch_catchup,
    )
)


# The short sweep tier-1 runs (and the CLI's --scenario all default).
SHORT_SCENARIOS = [name for name, s in SCENARIOS.items() if not s.slow]

# ---------------------------------------------------------------------------
# Scenario-matrix grid (tools/chaos_run.py --matrix): the default sweep of
# scenarios x seeds x committee sizes whose consolidated report
# (CHAOS_MATRIX_rN.json) is the regression harness for every scale claim
# the ROADMAP makes. Grid scenarios must be COMMITTEE-SIZE-INVARIANT:
# faults expressed as per-link defaults or single-node crash windows, no
# hardcoded committee subsets (the graftlint `matrix` pass enforces
# both that every name resolves here and that none pins a committee).
# timeout_storm / timeout_storm_legacy are ISSUE 13's storm cells: the
# same size-parameterized half|half stall with the overlay on vs off, so
# the artifact carries BOTH frames-per-stalled-round numbers (the
# `timeout_plane` block per cell) and the O(n²) -> O(n·fanout) win is a
# committed, regression-tracked delta.
# rolling_churn is the grid's reconfig cell (ISSUE 15): committee-free
# by construction (committee_n + rotation directives derive membership
# from n), exact crypto at n=4, trusted-stub at n=64, with per-node
# commit floors scaled by the committee geometry in its expectation.
# wan_observatory is ISSUE 16's measurement cell: probes on, clean
# links — asserts the MEASURED per-peer RTT classes recover the seeded
# WanMatrix geometry at every grid size (committee-free by construction;
# the probe plane is size-agnostic).
MATRIX_SCENARIOS = (
    "baseline",
    "lossy_links",
    "leader_crash",
    "timeout_storm",
    "timeout_storm_legacy",
    "rolling_churn",
    "wan_observatory",
    # ISSUE 17's constant-size-certificate cells: aggregate QC/TC under
    # the trusted-agg stub, extended to n=256 via its matrix_sizes
    # override (the committee sizes the O(1) bytes-per-committed-round
    # claim is about).
    "agg_certs",
    # ISSUE 18's election cells (§5.5p): region-aware vs region-blind
    # A/B inside one cell — the expectation replays the blind twin at
    # the identical seed/size/window and pins the cross-region pivot-hop
    # reduction plus the virtual-clock commit-latency win.
    "wan_election",
    # ISSUE 19's commit-proof serving cells (§5.5q): the full
    # submit→commit→proof loop at n=4 and n=64 — every served proof
    # client-verified, none of the committed admissions unprovable.
    "ingress_proofs",
    # ISSUE 20's flood cells (ROADMAP item 3's flash-crowd residue):
    # flash_crowd_ingress grid-shaped — shed with retry hints, plateau
    # held, no starved node, the spike window pinned in the incident
    # ledger — at n=4 and (trusted-stub) n=64.
    "flood",
)
MATRIX_SEEDS = (1, 2)
MATRIX_SIZES = (4, 64)
# Cells at/above this committee size run the trusted-crypto stub
# (chaos/trusted_crypto.py): exact-int pysigner at 64 nodes costs ~minutes
# of wall time PER ROUND, which is exactly what the stub exists to remove.
TRUSTED_CRYPTO_MIN_N = 16
# Virtual-seconds cap per matrix cell: grid scenarios early-stop on their
# commit floors well before this; the cap bounds a regressed cell's wall
# cost instead of letting it soak its full scenario duration. 30 (not
# 15): lossy links at 64 nodes can cost a multi-round pacemaker stall
# with backed-off 8 s timeouts before healing (observed at seed 2 —
# rounds 8-12, ~10 virtual seconds), and the cap must leave room for the
# slowest node to reach the scenario's commit floor AFTER such a stall.
MATRIX_CELL_DURATION_S = 30.0


def matrix_telemetry_config() -> TelemetryConfig:
    """Per-node telemetry for matrix cells: snapshots fast enough that a
    short early-stopping cell still fills a few windows (the fleet rollup
    merges these rings), rings small enough that a 100-node cell's report
    stays tractable."""
    return TelemetryConfig(interval_s=0.5, ring=64, dump_snapshots=4)


def cell_name(scenario: str, seed: int, n: int) -> str:
    """The stable cell key regression diffs join on."""
    return f"{scenario}@s{seed}/n{n}"


def run_matrix_cell(
    scenario: str,
    seed: int,
    n: int,
    trusted: str = "auto",
    wan: bool = True,
    duration: float | None = None,
) -> dict:
    """Execute one matrix cell and distill it to the committed record:
    verdict + fleet telemetry rollup (utils/telemetry.fleet_rollup), with
    the heavy per-scenario sections (fault trace, flight recorders, raw
    telemetry rings) dropped — a 12-cell matrix with 64-node cells must
    stay a reviewable artifact. `trusted` is auto|on|off; auto stubs
    crypto from TRUSTED_CRYPTO_MIN_N nodes up (the committee size where
    exact-int pysigner stops being runnable on one box)."""
    import time as _time

    from ..utils.telemetry import fleet_rollup
    from .plan import WanMatrix

    if trusted not in ("auto", "on", "off"):
        raise ValueError(f"trusted must be auto|on|off, got {trusted!r}")
    trusted_crypto = (
        trusted == "on"
        or (trusted == "auto" and n >= TRUSTED_CRYPTO_MIN_N)
        or SCENARIOS[scenario].trusted_crypto
    )
    if duration is None:
        # The cell cap bounds a REGRESSED cell's wall cost; only a
        # scenario that declares a cell_duration (rolling_churn's three
        # progress-gated boundaries) gets a longer budget — truncating
        # it would fail the cell for want of virtual time, not health,
        # while un-capping every long scenario would make legacy cells
        # non-comparable across matrix revisions.
        duration = SCENARIOS[scenario].cell_duration or MATRIX_CELL_DURATION_S
    t0 = _time.perf_counter()
    report = run_scenario(
        scenario,
        seed,
        duration=duration,
        n=n,
        trusted_crypto=trusted_crypto,
        wan=WanMatrix() if wan else None,
        telemetry=matrix_telemetry_config(),
    )
    wall = _time.perf_counter() - t0
    # Timeout-plane storm accounting (ISSUE 13): whenever the cell saw
    # local timeouts, commit the frames-per-timeout ratio (and its
    # per-stalled-round form, n× that) so the overlay-vs-legacy delta is
    # diffable straight from the artifact.
    cell_metrics = report.get("metrics", {})
    timeouts = cell_metrics.get("consensus.timeouts", 0)
    frames = cell_metrics.get("agg.timeout_frames", 0)
    timeout_plane = None
    if timeouts:
        timeout_plane = {
            "local_timeouts": timeouts,
            "frames": frames,
            "frames_per_timeout": round(frames / timeouts, 2),
            "frames_per_stalled_round": round(n * frames / timeouts, 1),
        }
    return {
        "cell": cell_name(scenario, seed, n),
        "timeout_plane": timeout_plane,
        "scenario": scenario,
        "seed": seed,
        "n": n,
        "crypto_mode": report["crypto_mode"],
        "wan": wan,
        "green": bool(report["ok"]),
        "wall_seconds": round(wall, 3),
        "virtual_seconds": report["virtual_seconds"],
        "violations": {
            "safety": report["safety_violations"][:5],
            "liveness": report["liveness_violations"][:5],
            "expectations": report.get("expectation_failures", [])[:5],
        },
        "rollup": fleet_rollup(report),
    }

_DELTA_PREFIXES = (
    "chaos.", "verifier.", "consensus.", "net.", "ingress.", "scheduler.",
    "telemetry.", "sync.", "reconfig.", "wan.", "agg.", "elect.", "proofs.",
    "incident.",
)


def _counter_snapshot() -> dict:
    return {
        k: v
        for k, v in metrics.dump(include_buckets=False)["counters"].items()
        if k.startswith(_DELTA_PREFIXES)
    }


def run_scenario(
    name: str,
    seed: int,
    duration: float | None = None,
    n: int | None = None,
    trusted_crypto: bool = False,
    wan: "object | None" = None,
    telemetry: TelemetryConfig | None = None,
) -> dict:
    """Execute one named scenario on a fresh VirtualTimeLoop; returns the
    report dict (see ChaosOrchestrator._report) extended with the scenario
    name, metric deltas, and expectation failures folded into `ok`.

    The fleet overrides (all default-off, so committed determinism pins
    replay unchanged): `n` scales the committee — only valid for
    scenarios without a pinned committee subset; `trusted_crypto` swaps
    signatures for the keyed-hash stub (chaos/trusted_crypto.py — read
    its trust model first); `wan` attaches a plan.WanMatrix of per-region
    RTT classes; `telemetry` forces a per-node TelemetryPlane config (the
    matrix runner's rollup source) over the scenario's own."""
    scenario = SCENARIOS[name]
    if n is not None and scenario.committee is not None:
        raise ValueError(
            f"scenario {name!r} pins committee indices "
            f"{scenario.committee}; its node count cannot be overridden"
        )
    effective_n = n if n is not None else scenario.n
    committee_indices = (
        list(scenario.committee_n(effective_n))
        if scenario.committee_n is not None
        else (list(scenario.committee) if scenario.committee is not None else None)
    )
    reconfig = (
        scenario.reconfig_n(effective_n)
        if scenario.reconfig_n is not None
        else (scenario.reconfig() if scenario.reconfig else None)
    )
    plan = (
        scenario.plan_n(effective_n)
        if scenario.plan_n is not None
        else scenario.plan()
    )
    if wan is not None and plan.wan is None:
        # A scenario whose plan PINS its own matrix (the wan_election
        # cells' weighted-occupancy geometry) keeps it; the override
        # only attaches a matrix to plans that have none. Every grid
        # scenario that pins one pins the default WanMatrix(), so this
        # is not a behavior change for any committed cell.
        plan.wan = wan
    telemetry_config = (
        telemetry
        if telemetry is not None
        else (scenario.telemetry() if scenario.telemetry else None)
    )
    before = _counter_snapshot()

    async def body() -> dict:
        orch = ChaosOrchestrator(
            seed=seed,
            n=effective_n,
            plan=plan,
            byzantine=dict(scenario.byzantine),
            parameters=scenario.parameters(),
            ingress=scenario.ingress() if scenario.ingress else None,
            flood=scenario.flood() if scenario.flood else None,
            scheduler_config=scenario.scheduler() if scenario.scheduler else None,
            telemetry_config=telemetry_config,
            committee_indices=committee_indices,
            reconfig=reconfig,
            boundary_crashes=(
                scenario.boundary_crashes() if scenario.boundary_crashes else None
            ),
            trusted_crypto=trusted_crypto or scenario.trusted_crypto,
            proofs=scenario.proofs,
            proof_squat_rate=scenario.proof_squat_rate,
            burn_budget=scenario.burn_budget() if scenario.burn_budget else None,
        )
        report = await orch.run(
            duration if duration is not None else scenario.duration,
            min_commits=scenario.min_commits,
            heal_t=scenario.heal_t,
        )
        if scenario.heal_t is not None:
            orch.liveness.require_progress(scenario.heal_t, orch.honest)
            report["liveness_violations"] = orch.liveness.violations
            report["ok"] = report["ok"] and orch.liveness.ok()
        if scenario.byzantine:
            report["forged_triples_cached"] = orch.forged_triples_cached()
        return report

    report = vtime.run(
        body(), timeout=VIRTUAL_TIMEOUT_S, wall_timeout=WALL_TIMEOUT_S
    )
    after = _counter_snapshot()
    deltas = {k: after.get(k, 0) - before.get(k, 0) for k in after}
    report["scenario"] = name
    report["description"] = scenario.description
    # What the run was ASKED to last: expectations that gate on an early
    # stop (min_commits reached) compare virtual_seconds against this.
    report["duration_requested"] = (
        duration if duration is not None else scenario.duration
    )
    report["metrics"] = {k: v for k, v in sorted(deltas.items()) if v}
    if scenario.expect is not None:
        failures = scenario.expect(report, deltas)
        report["expectation_failures"] = failures
        report["ok"] = report["ok"] and not failures
    return report
