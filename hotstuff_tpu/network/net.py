"""Point-to-point network plane: fire-and-forget sender + framed receiver.

Capability parity with the reference `network` crate (network/src/lib.rs):
  * NetMessage(bytes, [addr..]) -- one payload, a list of recipients
    (network/src/lib.rs:27)
  * NetSender -- one worker task + bounded queue per peer, lazy connect,
    drop-on-failure (reliability is the protocol's job via sync retries)
    (network/src/lib.rs:29-87)
  * NetReceiver -- TCP accept loop, one worker per inbound connection, reads
    length-delimited frames, decodes, forwards to a delivery channel
    (network/src/lib.rs:89-144)

Wire format: 4-byte big-endian length prefix (tokio LengthDelimitedCodec
default) followed by the codec payload. Properties the protocol relies on:
per-peer per-LANE FIFO (one ordered TCP stream; urgent recovery traffic
may overtake bulk gossip — see NetSender), at-most-once, NO delivery
guarantee. This is the control plane and deliberately stays on host
CPU/TCP; ICI collectives appear only inside the TPU crypto step.

Urgent-lane users (NetMessage.urgent=True): mempool payload sync
requests/replies, and the consensus synchronizer's recovery traffic —
per-digest SyncRequests, batched catch-up SyncRangeRequest/Reply
(consensus/messages.py) and the blocks served for them. Recovery frames
un-stall consensus; queueing them behind megabytes of bulk gossip is
exactly the stall they exist to clear. The aggregation overlay's TIMEOUT
bundles (TAG_TIMEOUT_BUNDLE, consensus/messages.py + overlay.py) ride
the same hot lane — a stalled round's partial quorum IS recovery
traffic — while vote bundles stay on the cold lane with the votes they
replace.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import random
import struct
from dataclasses import dataclass
from typing import Callable

from ..utils import metrics, tracing
from ..utils.actors import Selector, channel, spawn

log = logging.getLogger("hotstuff.network")

Address = tuple[str, int]

_M_FRAMES_TAGGED = metrics.counter("trace.frames_tagged")

_M_BYTES_SENT = metrics.counter("net.bytes_sent")
_M_FRAMES_SENT = metrics.counter("net.frames_sent")
_M_BYTES_RECEIVED = metrics.counter("net.bytes_received")
_M_FRAMES_RECEIVED = metrics.counter("net.frames_received")
_M_SEND_FAILURES = metrics.counter("net.send_failures")
_M_RECONNECTS = metrics.counter("net.reconnects")
_M_DROPPED_FULL = metrics.counter("net.dropped_full")
_M_DECODE_ERRORS = metrics.counter("net.decode_errors")
_M_BACKOFF_SECONDS = metrics.counter("net.backoff_seconds")
_M_BACKOFF_DROPS = metrics.counter("net.backoff_drops")

# Per-peer observatory aggregates (the per-link detail lives in the
# PeerLink ledger below; these are the process-global roll-ups).
_M_PEER_LINKS = metrics.counter("net.peer.links")
_M_PEER_PROBES_SENT = metrics.counter("net.peer.probes_sent")
_M_PEER_PINGS_RECEIVED = metrics.counter("net.peer.pings_received")
_M_PEER_PONGS_RECEIVED = metrics.counter("net.peer.pongs_received")
_M_PEER_RTT_SAMPLES = metrics.counter("net.peer.rtt_samples")

MAX_FRAME = 64 * 1024 * 1024  # defensive cap against Byzantine length prefixes


def backoff_jitter_rng(node: object, sender: str, addr: Address) -> random.Random:
    """Per-(node, sender, peer) seeded jitter stream for connect backoff —
    the chaos `SeededRng.stream` idiom (hash a stable name, seed a
    Random). `node` is the tracing NODE_LABEL (the chaos runner's node
    index; the store name in a real node process — node/main.py sets
    it), NOT just the sender's role name: every node names its sender
    "consensus-sender", so a role-only seed would hand all n-1 nodes
    retrying one recovering peer the SAME jitter sequence — a lockstep
    reconnect stampede, the exact failure jitter exists to prevent.
    With node identity in the seed every draw stays a pure function of
    stable identity (bit-identical under chaos replay) while distinct
    nodes keep decorrelated retry clocks."""
    digest = hashlib.sha256(
        f"net-backoff:{node}:{sender}:{addr[0]}:{addr[1]}".encode()
    ).digest()
    return random.Random(int.from_bytes(digest[:8], "big"))


# ---------------------------------------------------------------------------
# Per-peer link observatory (`net.peer.*`).
#
# One PeerLink per DIRECTED (node, peer address) pair, attributed the
# same way frames and backoff streams are: by the tracing NODE_LABEL
# contextvar the node's construction scope set. Sender paths (both the
# TCP workers and the chaos-transport branch) account frames/bytes/
# drops/backoffs; the consensus probe handlers (consensus/core.py
# Ping/Pong) feed RTT samples. Everything here is pure bookkeeping
# driven by loop-clock durations, so under the chaos virtual clock the
# whole ledger — EWMAs included — replays bit-identically.

# EWMA weight for new RTT samples. 0.2 converges within ~10 probes while
# still smoothing per-frame chaos jitter; the raw p50 rides alongside so
# the dash can show both.
RTT_EWMA_ALPHA = 0.2
# Bounded raw-sample ring per link (p50 source). Small on purpose: the
# observatory is always-on bookkeeping, not a histogram service.
RTT_SAMPLE_CAP = 256
# Gap threshold (ms) for per-vantage RTT classing: consecutive sorted
# EWMAs further apart than this start a new class. The chaos WanMatrix's
# closest inter-region spacing is 20 ms (us-west 62 vs eu-west 82 from
# us-east), so 15 ms splits every seeded geometry while absorbing EWMA
# residue from per-frame latency jitter.
RTT_CLASS_GAP_MS = 15.0


class PeerLink:
    """Per-directed-peer accounting: link counters + RTT estimators."""

    __slots__ = (
        "frames_sent", "bytes_sent", "drops_full", "backoff_drops",
        "connects", "reconnects", "send_failures", "probes_sent",
        "pings_received", "pongs_received", "rtt_ewma_ms", "_rtt_samples",
    )

    def __init__(self) -> None:
        self.frames_sent = 0
        self.bytes_sent = 0
        self.drops_full = 0
        self.backoff_drops = 0
        self.connects = 0
        self.reconnects = 0
        self.send_failures = 0
        self.probes_sent = 0
        self.pings_received = 0
        self.pongs_received = 0
        self.rtt_ewma_ms: float | None = None
        self._rtt_samples: list[float] = []

    def note_sent(self, nbytes: int) -> None:
        self.frames_sent += 1
        self.bytes_sent += nbytes

    def note_rtt(self, rtt_ms: float) -> None:
        if self.rtt_ewma_ms is None:
            self.rtt_ewma_ms = rtt_ms
        else:
            self.rtt_ewma_ms = (
                RTT_EWMA_ALPHA * rtt_ms
                + (1.0 - RTT_EWMA_ALPHA) * self.rtt_ewma_ms
            )
        self._rtt_samples.append(rtt_ms)
        if len(self._rtt_samples) > RTT_SAMPLE_CAP:
            del self._rtt_samples[0]
        _M_PEER_RTT_SAMPLES.inc()

    def rtt_p50_ms(self) -> float | None:
        if not self._rtt_samples:
            return None
        ordered = sorted(self._rtt_samples)
        # Nearest-rank p50, mirroring utils/metrics.percentile.
        return ordered[max(0, -(-len(ordered) // 2) - 1)]

    def snapshot(self) -> dict:
        return {
            "frames_sent": self.frames_sent,
            "bytes_sent": self.bytes_sent,
            "drops_full": self.drops_full,
            "backoff_drops": self.backoff_drops,
            "connects": self.connects,
            "reconnects": self.reconnects,
            "send_failures": self.send_failures,
            "probes_sent": self.probes_sent,
            "pings_received": self.pings_received,
            "pongs_received": self.pongs_received,
            "rtt_ewma_ms": (
                round(self.rtt_ewma_ms, 6)
                if self.rtt_ewma_ms is not None
                else None
            ),
            "rtt_p50_ms": (
                round(self.rtt_p50_ms(), 6)
                if self._rtt_samples
                else None
            ),
            "rtt_samples": len(self._rtt_samples),
        }


# node label -> "host:port" -> PeerLink
_peer_links: dict[object, dict[str, PeerLink]] = {}


def _addr_key(addr: Address) -> str:
    return f"{addr[0]}:{addr[1]}"


def peer_link(addr: Address, node: object | None = None) -> PeerLink:
    """The (create-on-first-touch) ledger entry for `addr` as seen from
    `node` (default: the calling task's tracing NODE_LABEL)."""
    if node is None:
        node = tracing.NODE_LABEL.get()
    links = _peer_links.setdefault(node, {})
    key = _addr_key(addr)
    link = links.get(key)
    if link is None:
        link = links[key] = PeerLink()
        _M_PEER_LINKS.inc()
    return link


def peer_snapshot(node: object | None = None) -> dict[str, dict]:
    """JSON-ready per-peer view for one node, sorted by peer key so the
    serialized form is bit-stable across same-seed replays."""
    if node is None:
        node = tracing.NODE_LABEL.get()
    links = _peer_links.get(node) or {}
    return {key: links[key].snapshot() for key in sorted(links)}


def reset_peers() -> None:
    """Drop every ledger entry (chaos runs start from a clean slate so
    back-to-back scenarios in one process cannot bleed into each other)."""
    _peer_links.clear()


def note_probe_sent(addr: Address) -> None:
    """A Ping left for `addr` (consensus/core.py probe ticker)."""
    peer_link(addr).probes_sent += 1
    _M_PEER_PROBES_SENT.inc()


def note_ping_received(addr: Address) -> None:
    """A Ping arrived from the peer listening at `addr`."""
    peer_link(addr).pings_received += 1
    _M_PEER_PINGS_RECEIVED.inc()


def note_pong_rtt(addr: Address, rtt_s: float) -> None:
    """A Pong closed the loop for `addr`: fold the measured round trip
    (loop-clock seconds) into the link's EWMA/p50 estimators."""
    link = peer_link(addr)
    link.pongs_received += 1
    link.note_rtt(rtt_s * 1000.0)
    _M_PEER_PONGS_RECEIVED.inc()


def rtt_classes(
    rtts: dict[str, float], gap_ms: float = RTT_CLASS_GAP_MS
) -> dict[str, int]:
    """Cluster peers into RTT classes from ONE vantage: sort by
    (RTT, peer) and start a new class at every gap wider than `gap_ms`.
    Class 0 is the nearest band (same-region peers under the chaos
    WanMatrix). Pure and order-stable — same inputs, same classes."""
    classes: dict[str, int] = {}
    cls = -1
    prev: float | None = None
    for peer, rtt in sorted(rtts.items(), key=lambda kv: (kv[1], kv[0])):
        if prev is None or rtt - prev > gap_ms:
            cls += 1
        classes[peer] = cls
        prev = rtt
    return classes


# ---------------------------------------------------------------------------
# Pluggable transport (the chaos subsystem's fault-injection seam).
#
# When a transport is installed, NetSender/NetReceiver keep their public
# contract (NetMessage in, decoded messages out, identical framing and
# codec calls) but hand the socket layer to the transport: senders submit
# framed payloads per destination, receivers register (port, deliver,
# decode) bindings. hotstuff_tpu/chaos/transport.py installs a seeded
# FaultyTransport here to drop/delay/duplicate/reorder/partition traffic
# deterministically; production code never installs one and takes the TCP
# paths below.

_transport = None


def install_transport(transport) -> object | None:
    """Install (or, with None, remove) the process-wide transport override;
    returns the previous one. Affects NetSender/NetReceiver instances
    created AFTERWARDS — install before booting nodes (instances snapshot
    the transport at construction)."""
    global _transport
    prev, _transport = _transport, transport
    return prev


@dataclass(slots=True)
class NetMessage:
    """(serialized bytes, recipient addresses) -- network/src/lib.rs:27.

    `urgent` selects the hot egress lane: protocol-critical recovery
    traffic (payload sync requests/replies) that must not queue behind
    bulk gossip. See NetSender.

    `trace` is an optional causal trace context (utils/tracing.py):
    when set, the sender appends its 22-byte trailer INSIDE the frame
    (counted by the length prefix, stripped by the receiver before the
    codec) so the block's journey can be stitched across nodes.
    Trailer-less peers and trailer-less frames interoperate unchanged."""

    data: bytes
    addresses: list[Address]
    urgent: bool = False
    trace: "tracing.TraceContext | None" = None


def frame(data: bytes, trace: "tracing.TraceContext | None" = None) -> bytes:
    if trace is not None:
        trailer = trace.trailer()
        return struct.pack(">I", len(data) + len(trailer)) + data + trailer
    return struct.pack(">I", len(data)) + data


class FrameReader:
    """Bulk-buffered frame reader: one stream read yields every complete
    frame already in the TCP buffer, so the per-frame event-loop cost is
    amortized. The previous per-frame readexactly pair costs two awaits
    PER FRAME — at a 30k tx/s ingress the saturated-node profile showed
    those awaits as ~15% of node CPU (data/profiles/). Returns None on
    EOF (clean or mid-frame); raises ConnectionError on a frame whose
    declared length exceeds the Byzantine MAX_FRAME cap."""

    __slots__ = ("_reader", "_buf", "_off")

    READ_SIZE = 256 * 1024

    def __init__(self, reader: asyncio.StreamReader) -> None:
        self._reader = reader
        # bytearray: += grows in place (amortized O(chunk)); an immutable-
        # bytes rebuild per refill would be O(buffer) per read — quadratic
        # for any frame larger than READ_SIZE, and a CPU-DoS lever for a
        # peer trickling a MAX_FRAME-sized declaration in small segments.
        self._buf = bytearray()
        self._off = 0

    def _try_parse(self) -> bytes | None:
        """Pop one complete frame from the buffer, or None if incomplete."""
        have = len(self._buf) - self._off
        if have < 4:
            return None
        length = int.from_bytes(self._buf[self._off : self._off + 4], "big")
        if length > MAX_FRAME:
            raise ConnectionError(f"frame too large: {length}")
        if have < 4 + length:
            return None
        start = self._off + 4
        data = bytes(self._buf[start : start + length])
        self._off = start + length
        return data

    async def next_frame(self) -> bytes | None:
        while True:
            data = self._try_parse()
            if data is not None:
                return data
            if self._off:  # compact consumed prefix before refilling
                del self._buf[: self._off]
                self._off = 0
            try:
                chunk = await self._reader.read(self.READ_SIZE)
            except (ConnectionError, OSError):
                return None
            if not chunk:
                return None
            self._buf += chunk



class NetSender:
    """Receives NetMessage from a channel; maintains one worker per peer
    address so a slow peer never blocks broadcast
    (network/src/lib.rs:44-57,60-86).

    Each peer has TWO bounded lanes: `urgent` messages (payload sync
    requests/replies — the recovery path that un-stalls consensus) ride a
    hot lane the worker drains first; everything else (bulk payload
    gossip) rides the cold lane. A single FIFO let megabytes of gossip
    backlog starve the kilobyte-scale recovery replies that would have
    cleared it — the round-5 300 s saturation runs stalled exactly this
    way (sync retries re-broadcast for minutes while replies sat behind
    gossip and dropped). FIFO order is preserved WITHIN each lane; no
    protocol message relies on cross-lane ordering (payload delivery and
    sync replies are idempotent at the receiver)."""

    PEER_QUEUE = 1_000

    # Connect-failure backoff (per peer, jittered exponential). Without it
    # every frame queued for an unreachable peer retries open_connection
    # immediately: a partitioned peer with a full cold lane hot-loops
    # SYN attempts (one per queued frame) for the whole partition.
    BACKOFF_BASE_S = 0.05
    BACKOFF_MAX_S = 5.0

    def __init__(self, rx: asyncio.Queue, name: str = "net-sender") -> None:
        self._rx = rx
        self._name = name
        # Captured at construction: chaos installs its transport before
        # booting nodes, and a mid-flight install must not tear an active
        # sender between two planes.
        self._transport = _transport
        # addr -> (hot, cold) queues
        self._peers: dict[Address, tuple[asyncio.Queue, asyncio.Queue]] = {}
        self._task = spawn(self._run(), name=name)

    def egress_backlogged(self, frac: float = 0.5) -> bool:
        """True while MORE THAN HALF of the peers' COLD lanes sit above
        `frac` of capacity — i.e. gossip fan-out can no longer reach a
        majority without dropping. Producers (the payload maker) use this
        as a high-water backpressure signal: without it, sustained
        overload fills the per-peer queues, payload gossip drops
        silently, replicas stall on payload availability, and consensus
        crawls at timeout pace while the producer keeps flooding.
        Majority (not any) so one slow/Byzantine peer whose queue sits
        full cannot throttle our payload production. The hot lane is
        excluded: recovery traffic must never feed back into shedding."""
        if not self._peers:
            return False
        high_water = int(self.PEER_QUEUE * frac)
        over = sum(
            1 for _, cold in self._peers.values() if cold.qsize() > high_water
        )
        return over * 2 > len(self._peers)

    async def _run(self) -> None:
        while True:
            msg: NetMessage = await self._rx.get()
            payload = frame(msg.data, msg.trace)
            if msg.trace is not None:
                _M_FRAMES_TAGGED.inc()
                tracing.event(
                    "net.send", msg.trace.trace_id,
                    hop=msg.trace.hop, peers=len(msg.addresses),
                    bytes=len(msg.data), urgent=msg.urgent,
                )
            if self._transport is not None:
                # Chaos seam: the transport owns delivery (and the faults).
                for addr in msg.addresses:
                    peer_link(addr).note_sent(len(payload))
                    await self._transport.send(addr, payload, urgent=msg.urgent)
                continue
            for addr in msg.addresses:
                lanes = self._peers.get(addr)
                if lanes is None:
                    lanes = (
                        asyncio.Queue(self.PEER_QUEUE),
                        asyncio.Queue(self.PEER_QUEUE),
                    )
                    self._peers[addr] = lanes
                    spawn(
                        self._worker(addr, *lanes),
                        name=f"{self._name}-{addr}",
                    )
                q = lanes[0] if msg.urgent else lanes[1]
                try:
                    q.put_nowait(payload)
                except asyncio.QueueFull:
                    # Fire-and-forget: drop rather than block the fan-out.
                    _M_DROPPED_FULL.inc()
                    peer_link(addr).drops_full += 1
                    log.debug("dropping message to %s: peer queue full", addr)

    async def _worker(
        self, addr: Address, hot: asyncio.Queue, cold: asyncio.Queue
    ) -> None:
        """Per-peer worker: lazily connects, writes frames in per-lane FIFO
        order (hot lane first), drops messages while the peer is
        unreachable. The Selector's anti-starvation bound means a saturated
        hot lane still lets the occasional cold frame through rather than
        parking gossip forever."""
        # Selector serves LOWER priority numbers first (the pacemaker's
        # must-lose timer branch gets priority=1 in consensus/core.py):
        # hot keeps the winning default 0, cold must lose ties.
        selector = Selector()
        selector.add("hot", hot.get)
        selector.add("cold", cold.get, priority=1)
        # The worker task inherits the node's NODE_LABEL contextvar
        # (orchestrator sets an index per in-process node; node/main.py
        # sets the store name per process).
        jitter = backoff_jitter_rng(tracing.NODE_LABEL.get(), self._name, addr)
        link = peer_link(addr)
        writer: asyncio.StreamWriter | None = None
        connected_before = False  # reconnects = churn, not initial connects
        backoff = 0.0  # current backoff window (s); 0 = healthy
        next_attempt = 0.0  # loop time before which connects are suppressed
        loop = asyncio.get_running_loop()
        while True:
            _branch, payload = await selector.next()
            if writer is None:
                if loop.time() < next_attempt:
                    # Inside the backoff window: drop without a SYN. The
                    # fire-and-forget contract already allows the drop;
                    # what backoff buys is not hot-looping connect attempts
                    # (one per queued frame) against a partitioned peer.
                    _M_BACKOFF_DROPS.inc()
                    link.backoff_drops += 1
                    continue
                try:
                    _, writer = await asyncio.open_connection(addr[0], addr[1])
                    if connected_before:
                        _M_RECONNECTS.inc()
                        link.reconnects += 1
                    connected_before = True
                    link.connects += 1
                    backoff = 0.0
                except OSError as e:
                    _M_SEND_FAILURES.inc()
                    link.send_failures += 1
                    # Jittered exponential growth, capped AFTER the jitter so
                    # BACKOFF_MAX_S is a true bound: jitter decorrelates the
                    # retry clocks of many senders all aimed at one
                    # recovering peer (no reconnect stampede at heal time).
                    # Drawn from the per-(sender, peer) seeded stream, not
                    # the ambient `random` module, so a chaos replay sees
                    # the identical backoff schedule.
                    backoff = min(
                        max(2 * backoff, self.BACKOFF_BASE_S)
                        * (0.5 + jitter.random()),
                        self.BACKOFF_MAX_S,
                    )
                    next_attempt = loop.time() + backoff
                    _M_BACKOFF_SECONDS.inc(backoff)
                    log.debug(
                        "failed to connect to %s: %s (backing off %.2fs)",
                        addr,
                        e,
                        backoff,
                    )
                    continue  # drop this message
            try:
                writer.write(payload)
                await writer.drain()
                _M_FRAMES_SENT.inc()
                _M_BYTES_SENT.inc(len(payload))
                link.note_sent(len(payload))
            except (ConnectionError, OSError) as e:
                _M_SEND_FAILURES.inc()
                link.send_failures += 1
                log.debug("failed to send to %s: %s", addr, e)
                try:
                    writer.close()
                except Exception:
                    pass
                writer = None  # reconnect lazily on next message


class NetReceiver:
    """Binds a listener; every inbound connection gets a worker that decodes
    frames and forwards them into the delivery channel
    (network/src/lib.rs:89-144)."""

    def __init__(
        self,
        address: Address,
        deliver: asyncio.Queue,
        decode: Callable[[bytes], object],
        name: str = "net-receiver",
    ) -> None:
        self._address = address
        self._deliver = deliver
        self._decode = decode
        self._name = name
        self._transport = _transport  # captured like NetSender's
        self._server: asyncio.AbstractServer | None = None
        self._task = spawn(self._run(), name=name)

    async def _run(self) -> None:
        if self._transport is not None:
            # Chaos seam: register the binding instead of a TCP listener;
            # park until cancelled (a chaos crash), then unbind so the
            # restarted node can re-register the port.
            self._transport.bind(self._address, self._deliver, self._decode)
            log.debug("%s bound on chaos transport %s", self._name, self._address)
            try:
                await asyncio.Event().wait()
            finally:
                self._transport.unbind(self._address)
            return
        self._server = await asyncio.start_server(
            self._handle, host=self._address[0], port=self._address[1]
        )
        log.debug("%s listening on %s", self._name, self._address)
        async with self._server:
            await self._server.serve_forever()

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        frames = FrameReader(reader)
        while True:
            try:
                data = await frames.next_frame()
            except ConnectionError as e:
                log.warning("%s: dropping connection from %s: %s", self._name, peer, e)
                break
            if data is None:
                break
            _M_FRAMES_RECEIVED.inc()
            _M_BYTES_RECEIVED.inc(len(data) + 4)  # + length prefix
            data, ctx = tracing.strip_trailer(data)
            if ctx is not None:
                tracing.note_received(ctx)
                tracing.event(
                    "net.recv", ctx.trace_id, hop=ctx.hop, bytes=len(data)
                )
            try:
                message = self._decode(data)
            except Exception as e:
                _M_DECODE_ERRORS.inc()
                log.warning("%s: undecodable frame from %s: %r", self._name, peer, e)
                continue
            await self._deliver.put(message)
        try:
            writer.close()
        except Exception:
            pass


class SimpleSender:
    """Convenience owner of a NetSender: exposes send/broadcast coroutines.
    Plays the role of Synchronizer::transmit's shared send path
    (consensus/src/synchronizer.rs:109-129)."""

    def __init__(self, name: str = "sender") -> None:
        self._tx = channel()
        self._sender = NetSender(self._tx, name=name)

    async def send(self, data: bytes, address: Address) -> None:
        await self._tx.put(NetMessage(data, [address]))

    async def broadcast(self, data: bytes, addresses: list[Address]) -> None:
        await self._tx.put(NetMessage(data, list(addresses)))
