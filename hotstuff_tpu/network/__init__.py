from .net import NetMessage, NetReceiver, NetSender

__all__ = ["NetMessage", "NetReceiver", "NetSender"]
