"""Pluggable crypto execution backends.

This is the `CryptoBackend` seam called for by the north star: the reference
hard-wires ed25519_dalek's `verify_batch` (crypto/src/lib.rs:194-220); here
every batch verification dispatches through an interchangeable backend so the
hot path can run either on host CPU (baseline) or as a vmapped JAX kernel on
TPU (hotstuff_tpu.ops.ed25519), sharded over a device mesh at scale.
"""

from __future__ import annotations

import abc
import threading
from typing import Sequence

from .primitives import InvalidSignature, PublicKey, Signature


class CryptoBackend(abc.ABC):
    """Batch signature verification engine.

    Contract (matching ed25519_dalek `verify_batch`): returns True iff ALL
    (message, key, signature) triples verify. `verify_batch_mask` additionally
    reports per-item validity (needed to avoid re-verifying a whole QC when
    one Byzantine vote is bad)."""

    name: str = "abstract"

    @abc.abstractmethod
    def verify_batch_mask(
        self,
        messages: Sequence[bytes],
        keys: Sequence[PublicKey],
        signatures: Sequence[Signature],
    ) -> list[bool]: ...

    def verify_batch(
        self,
        messages: Sequence[bytes],
        keys: Sequence[PublicKey],
        signatures: Sequence[Signature],
    ) -> bool:
        if not messages:
            return True
        return all(self.verify_batch_mask(messages, keys, signatures))


class CpuBackend(CryptoBackend):
    """Host ed25519 via OpenSSL (`cryptography`) -- the parity baseline,
    equivalent to the reference's ed25519_dalek CPU path."""

    name = "cpu"

    def verify_batch_mask(
        self,
        messages: Sequence[bytes],
        keys: Sequence[PublicKey],
        signatures: Sequence[Signature],
    ) -> list[bool]:
        out = []
        for msg, pk, sig in zip(messages, keys, signatures, strict=True):
            try:
                pk.to_crypto().verify(sig.data, msg)
                out.append(True)
            except (InvalidSignature, ValueError):
                out.append(False)
        return out


_lock = threading.Lock()
_backend: CryptoBackend = CpuBackend()


def get_backend() -> CryptoBackend:
    return _backend


def set_backend(backend: CryptoBackend) -> CryptoBackend:
    """Install the active backend (e.g. TpuBackend); returns the previous one."""
    global _backend
    with _lock:
        prev, _backend = _backend, backend
    return prev


def make_backend(kind: str, **kwargs) -> CryptoBackend:
    """Factory used by the node CLI's --crypto flag (cpu | tpu | remote)."""
    if kind == "cpu":
        return CpuBackend()
    if kind == "tpu":
        from .tpu_backend import TpuBackend

        return TpuBackend(**kwargs)
    if kind == "remote":
        from .remote import RemoteBackend

        return RemoteBackend(**kwargs)
    raise ValueError(f"unknown crypto backend {kind!r}")
