from .primitives import (
    Digest,
    Hashable,
    KeyPair,
    PublicKey,
    SecretKey,
    Signature,
    generate_keypair,
    generate_production_keypair,
    sha512_32,
)
from .backend import CryptoBackend, CpuBackend, get_backend, set_backend
from .service import SignatureService

__all__ = [
    "Digest",
    "Hashable",
    "KeyPair",
    "PublicKey",
    "SecretKey",
    "Signature",
    "generate_keypair",
    "generate_production_keypair",
    "sha512_32",
    "CryptoBackend",
    "CpuBackend",
    "get_backend",
    "set_backend",
    "SignatureService",
]
