"""BatchVerificationService: deadline-flushed signature-verification actor.

The north-star constraint (BASELINE.json): TPU batch verification must not
regress consensus latency — QC formation blocks round advancement, so
per-vote verification cannot wait for a large batch to fill. This actor
generalises the reference's SignatureService request/oneshot seam
(crypto/src/lib.rs:226-252) to verification: callers submit GROUPS of
(message, key, signature) triples (a QC's votes, one synthetic payload
batch, or a single vote) and await a per-item validity mask. The actor
concatenates pending groups and flushes to the active CryptoBackend when

  * the pending total reaches `max_batch` (size flush, TPU-efficient),
  * the oldest group is `max_delay` seconds old (deadline flush, keeps
    p99 latency bounded at low rates — SURVEY.md §7 "hard parts" item 1), or
  * an URGENT group is pending (consensus-critical: QC/TC/vote checks gate
    round advancement, so they flush after an opportunistic drain instead
    of waiting out the deadline).

The backend call runs in a worker thread so the TPU dispatch never blocks
the event loop (the mempool/consensus cores keep processing while a batch
is in flight — the same pipelining the reference gets from tokio). Groups
are enqueued whole (one queue item, one future per group), so per-item
asyncio overhead is O(1) per group, not O(n) — at 100k+ sigs/s the Python
queue would otherwise dominate the TPU kernel.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Sequence

from .backend import CryptoBackend, get_backend
from .primitives import PublicKey, Signature

log = logging.getLogger("hotstuff.crypto")


@dataclass
class _Group:
    messages: list[bytes]
    keys: list[PublicKey]
    signatures: list[Signature]
    urgent: bool
    future: asyncio.Future = field(default_factory=lambda: asyncio.get_running_loop().create_future())

    def __len__(self) -> int:
        return len(self.messages)


class BatchVerificationService:
    def __init__(
        self,
        backend: CryptoBackend | None = None,
        max_batch: int = 8192,
        max_delay: float = 0.002,
        max_concurrent_dispatches: int = 4,
    ) -> None:
        self._backend = backend
        self.max_batch = max_batch
        self.max_delay = max_delay
        self._queue: asyncio.Queue[_Group] = asyncio.Queue()
        self._task: asyncio.Task | None = None
        # Flushes dispatch CONCURRENTLY (bounded): an urgent 3-signature QC
        # check must not wait out a multi-thousand-signature workload batch
        # already in flight on the device (backends route small batches to
        # the CPU fast path, so the urgent flush completes in microseconds
        # while the big dispatch is still on the wire).
        self._dispatch_sem = asyncio.Semaphore(max_concurrent_dispatches)
        self._dispatches: set[asyncio.Task] = set()
        self.stats = {
            "flushes": 0,
            "size_flushes": 0,
            "urgent_flushes": 0,
            "verified": 0,
        }

    def _ensure_task(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="batch-verification-service"
            )

    @property
    def backend(self) -> CryptoBackend:
        return self._backend or get_backend()

    # -- submission API ------------------------------------------------------

    async def verify_group(
        self,
        messages: Sequence[bytes],
        pairs: Sequence[tuple[PublicKey, Signature]],
        urgent: bool = False,
    ) -> list[bool]:
        """Submit a correlated group (e.g. one QC's votes or one synthetic
        payload batch); resolves to the per-item validity mask once the
        group's flush completes."""
        if not messages:
            return []
        self._ensure_task()
        group = _Group(
            list(messages),
            [pk for pk, _ in pairs],
            [sig for _, sig in pairs],
            urgent,
        )
        await self._queue.put(group)
        return await group.future

    async def verify(
        self,
        message: bytes,
        key: PublicKey,
        signature: Signature,
        urgent: bool = True,
    ) -> bool:
        """Await a single verification (batched under the hood)."""
        mask = await self.verify_group([message], [(key, signature)], urgent)
        return mask[0]

    # -- flush loop ----------------------------------------------------------

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            first = await self._queue.get()
            groups = [first]
            total = len(first)
            urgent = first.urgent
            deadline = loop.time() + self.max_delay
            while total < self.max_batch:
                # Opportunistic drain of whatever is already enqueued.
                while not self._queue.empty() and total < self.max_batch:
                    g = self._queue.get_nowait()
                    groups.append(g)
                    total += len(g)
                    urgent |= g.urgent
                if urgent or total >= self.max_batch:
                    break
                timeout = deadline - loop.time()
                if timeout <= 0:
                    break
                try:
                    g = await asyncio.wait_for(self._queue.get(), timeout)
                except asyncio.TimeoutError:
                    break
                groups.append(g)
                total += len(g)
                urgent |= g.urgent

            # Urgent groups dispatch in their OWN flush, immediately: a
            # 3-signature QC check must neither ride a multi-thousand-
            # signature workload batch down the device path nor wait for a
            # dispatch slot held by one (backends send small batches down
            # the CPU fast path, so unbounded urgent dispatches are bounded
            # in practice by the consensus message rate). Workload groups
            # coalesced in the same pass flush separately, gated by the
            # dispatch bound — acquired inside _dispatch so this loop keeps
            # draining the queue while every slot is in flight.
            if urgent:
                hot = [g for g in groups if g.urgent]
                cold = [g for g in groups if not g.urgent]
                self._spawn_dispatch(hot, sum(len(g) for g in hot), True)
                if cold:
                    self._spawn_dispatch(cold, sum(len(g) for g in cold), False)
            else:
                self._spawn_dispatch(groups, total, False)

    def _spawn_dispatch(self, groups: list[_Group], total: int, urgent: bool) -> None:
        task = asyncio.get_running_loop().create_task(
            self._dispatch(groups, total, urgent), name="verify-dispatch"
        )
        self._dispatches.add(task)
        task.add_done_callback(self._dispatches.discard)

    async def _dispatch(self, groups: list[_Group], total: int, urgent: bool) -> None:
        if not urgent:
            await self._dispatch_sem.acquire()
        try:
            msgs = [m for g in groups for m in g.messages]
            keys = [k for g in groups for k in g.keys]
            sigs = [s for g in groups for s in g.signatures]
            backend = self.backend
            try:
                mask = await asyncio.to_thread(
                    backend.verify_batch_mask, msgs, keys, sigs
                )
            except Exception as exc:  # backend failure must not hang callers
                for g in groups:
                    if not g.future.done():
                        g.future.set_exception(exc)
                return
            self.stats["flushes"] += 1
            self.stats["size_flushes"] += total >= self.max_batch
            self.stats["urgent_flushes"] += urgent
            self.stats["verified"] += total
            lo = 0
            for g in groups:
                hi = lo + len(g)
                if not g.future.cancelled():
                    g.future.set_result([bool(b) for b in mask[lo:hi]])
                lo = hi
        finally:
            if not urgent:
                self._dispatch_sem.release()
