"""BatchVerificationService: the verification façade over the device
scheduler.

The north-star constraint (BASELINE.json): TPU batch verification must not
regress consensus latency — QC formation blocks round advancement, so
per-vote verification cannot wait for a large batch to fill. This service
generalises the reference's SignatureService request/oneshot seam
(crypto/src/lib.rs:226-252) to verification: callers submit GROUPS of
(message, key, signature) triples (a QC's votes, one synthetic payload
batch, or a single vote), DECLARE their source class (`source=`:
consensus-critical / sync / ingress / mempool-bulk — crypto/scheduler.py),
and await a per-item validity mask.

Batching policy lives in the continuous-batching DeviceScheduler
(crypto/scheduler.py): typed priority lanes, a preemptive critical lane,
alignment-grid bucket sizing, continuous refill. This class remains the
DISPATCH EXECUTOR — dedup cache, committee tagging, the backend call,
future resolution — and the thin source-registration façade callers see.
The pre-scheduler single-queue flush heuristics survive as
`use_scheduler=False` (`_run_legacy`), kept as the measured baseline for
`bench.py --scheduler-ab`.

The backend call runs in a worker thread so the TPU dispatch never blocks
the event loop (the mempool/consensus cores keep processing while a batch
is in flight — the same pipelining the reference gets from tokio). Groups
are enqueued whole (one lane entry, one future per group), so per-item
asyncio overhead is O(1) per group, not O(n) — at 100k+ sigs/s the Python
queue would otherwise dominate the TPU kernel.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Sequence

from ..utils import metrics, tracing
from .backend import CryptoBackend, get_backend
from .primitives import PublicKey, Signature
from .scheduler import (
    DeviceScheduler,
    LaneStats,
    SchedulerConfig,
    note_queue_delay,
    resolve_source,
)

log = logging.getLogger("hotstuff.crypto")

_M_DEDUP_HITS = metrics.counter("verifier.dedup_hits")
_M_DEDUP_MISSES = metrics.counter("verifier.dedup_misses")
_M_DEDUP_INSERTS = metrics.counter("verifier.dedup_inserts")
_M_DEDUP_EVICTIONS = metrics.counter("verifier.dedup_evictions")


class VerifiedSigCache:
    """Bounded LRU of (message, pk, sig) triples that VERIFIED.

    Every vote signature is checked 2-3x over its lifetime: once on vote
    arrival, again inside every QC that carries it (`QC.verify`), and again
    when that QC rides a Block/Timeout. A hit here short-circuits the
    backend call entirely. Only successes are cached (a miss proves
    nothing), and the triple is the full (message, key, signature) — a
    forged signature over the same digest can never alias a cached entry.

    Thread-safe: the consensus event loop seeds it while backend dispatch
    worker threads look entries up.
    """

    __slots__ = ("maxsize", "_entries", "_lock")

    def __init__(self, maxsize: int = 65536) -> None:
        if maxsize <= 0:
            raise ValueError("dedup cache needs maxsize >= 1")
        self.maxsize = maxsize
        self._entries: OrderedDict[tuple[bytes, bytes, bytes], None] = (
            OrderedDict()
        )
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._entries)

    def hit(self, message: bytes, key: PublicKey, sig: Signature) -> bool:
        """True iff this exact triple previously verified (refreshes LRU
        recency); counts into verifier.dedup_hits/misses."""
        k = (message, key.data, sig.data)
        with self._lock:
            if k in self._entries:
                self._entries.move_to_end(k)
                _M_DEDUP_HITS.inc()
                return True
        _M_DEDUP_MISSES.inc()
        return False

    def add(self, message: bytes, key: PublicKey, sig: Signature) -> None:
        """Record a VERIFIED triple; evicts least-recently-used past
        maxsize (memory stays bounded at ~128 B/entry)."""
        k = (message, key.data, sig.data)
        with self._lock:
            if k in self._entries:
                self._entries.move_to_end(k)
                return
            self._entries[k] = None
            _M_DEDUP_INSERTS.inc()
            while len(self._entries) > self.maxsize:
                self._entries.popitem(last=False)
                _M_DEDUP_EVICTIONS.inc()


@dataclass
class _Group:
    messages: list[bytes]
    keys: list[PublicKey]
    signatures: list[Signature]
    urgent: bool
    committee: bool = False
    # dedup=False opts the group out of the verified-signature cache: the
    # mempool's SYNTHETIC workload draws cyclically from a fixed pool of
    # pre-signed triples, and caching those would make the benchmark
    # measure the cache instead of the backend.
    dedup: bool = True
    # Causal trace id (utils/tracing.py): set for consensus groups so the
    # flight recorder can attribute this batch's verification cost to the
    # block whose QC/vote/proposal it checks.
    trace: str | None = None
    # Source class (crypto/scheduler.py) + queueing timestamps: t_submit is
    # stamped at admission, t_dequeue when a bucket (or legacy flush) takes
    # the group — their difference is the per-lane queueing delay the
    # scheduler metrics and verify.batch trace events attribute.
    source: str = "mempool"
    t_submit: float = 0.0
    t_dequeue: float = 0.0
    future: asyncio.Future = field(default_factory=lambda: asyncio.get_running_loop().create_future())

    def __len__(self) -> int:
        return len(self.messages)


class BatchVerificationService:
    def __init__(
        self,
        backend: CryptoBackend | None = None,
        max_batch: int = 8192,
        max_delay: float = 0.002,
        max_concurrent_dispatches: int = 4,
        dedup_cache_size: int = 65536,
        inline: bool = False,
        use_scheduler: bool = True,
        scheduler_config: SchedulerConfig | None = None,
        steal_backends: Sequence[CryptoBackend] | None = None,
    ) -> None:
        self._backend = backend
        self.max_batch = max_batch
        self.max_delay = max_delay
        # Cross-chip work stealing (crypto/scheduler.py): sibling shard
        # backends bulk buckets may be stolen to when the home backend's
        # pipeline window is full. Backend 0 (the `backend` arg) stays
        # home for every critical dispatch and all legacy-loop flushes.
        # inline=True (the chaos virtual-time mode) FORCES stealing off:
        # which backend a bucket lands on must not depend on wall-clock
        # thread timing when a scenario replays bit-for-bit (§5.5i).
        self._steal_backends: list[CryptoBackend] = (
            [] if inline else list(steal_backends or ())
        )
        # inline=True runs the backend call ON the event loop instead of a
        # worker thread. Production keeps the thread (a TPU dispatch must
        # not block consensus timers); the chaos runner opts in because its
        # pure-python backend is millisecond-cheap and thread scheduling is
        # the one nondeterminism its virtual-time replay cannot control.
        self.inline = inline
        # Verified-signature dedup: set dedup_cache_size=0 to disable
        # (the bench A/B switch and the uncached-baseline tests).
        self.dedup: VerifiedSigCache | None = (
            VerifiedSigCache(dedup_cache_size) if dedup_cache_size else None
        )
        self._queue: asyncio.Queue[_Group] = asyncio.Queue()
        self._task: asyncio.Task | None = None
        # Per-lane queueing-delay reservoir, fed by BOTH flush paths (the
        # scheduler's dequeue and the legacy loop) — the bench A/B and the
        # chaos scheduler expectations read per-service p50/p99 from here.
        self.lane_stats = LaneStats()
        # The continuous-batching device scheduler (crypto/scheduler.py) is
        # the default flush policy; use_scheduler=False keeps the legacy
        # single-queue heuristics as the measured A/B baseline.
        self.scheduler: DeviceScheduler | None = (
            DeviceScheduler(
                self._spawn_dispatch,
                max_batch=max_batch,
                alignment_fn=self._bucket_alignment,
                config=scheduler_config,
                lane_stats=self.lane_stats,
                n_backends=1 + len(self._steal_backends),
            )
            if use_scheduler
            else None
        )
        # Flushes dispatch CONCURRENTLY (bounded): an urgent 3-signature QC
        # check must not wait out a multi-thousand-signature workload batch
        # already in flight on the device (backends route small batches to
        # the CPU fast path, so the urgent flush completes in microseconds
        # while the big dispatch is still on the wire; urgent dispatches
        # never acquire this semaphore). With steal backends configured
        # the bound must cover every backend window the scheduler can
        # legitimately fill (bulk_concurrency per backend) — otherwise
        # the service-global semaphore silently caps stealing below the
        # per-backend accounting that admitted it. Without steal
        # backends the caller's max_concurrent_dispatches stands as-is.
        dispatch_bound = max_concurrent_dispatches
        if self.scheduler is not None and self._steal_backends:
            dispatch_bound = max(
                dispatch_bound,
                self.scheduler.config.bulk_concurrency
                * (1 + len(self._steal_backends)),
            )
        self._dispatch_sem = asyncio.Semaphore(dispatch_bound)
        self._dispatches: set[asyncio.Task] = set()
        self.stats = {
            "flushes": 0,
            "size_flushes": 0,
            "urgent_flushes": 0,
            "verified": 0,
        }

    def _ensure_task(self) -> None:
        if self._task is None or self._task.done():
            # actors.spawn (not bare create_task): the service task then
            # joins the caller's SpawnScope, so a chaos crash-restart of a
            # node tears down its verification flush loop too.
            from ..utils.actors import spawn

            loop = (
                self.scheduler.run()
                if self.scheduler is not None
                else self._run_legacy()
            )
            self._task = spawn(loop, name="batch-verification-service")

    @property
    def backend(self) -> CryptoBackend:
        return self._backend or get_backend()

    def _bucket_alignment(self) -> int:
        """The device bucket grid the scheduler sizes bulk buckets against
        (TpuBackend.bucket_alignment; 0 for gridless backends)."""
        return getattr(self.backend, "bucket_alignment", 0)

    # -- submission API ------------------------------------------------------

    async def verify_group(
        self,
        messages: Sequence[bytes],
        pairs: Sequence[tuple[PublicKey, Signature]],
        urgent: bool = False,
        committee: bool = False,
        dedup: bool = True,
        trace: str | None = None,
        source: str | None = None,
    ) -> list[bool]:
        """Submit a correlated group (e.g. one QC's votes or one synthetic
        payload batch); resolves to the per-item validity mask once the
        group's flush completes. `source` declares the group's scheduler
        class ("consensus" | "sync" | "ingress" | "mempool" —
        crypto/scheduler.py); when omitted, the legacy `urgent` bit maps to
        consensus-critical vs mempool bulk. `committee=True` tags the group
        as signed by registered validator keys, routing it to the backend's
        committee-resident kernel when available; `dedup=False` bypasses
        the verified-signature cache (synthetic benchmark load, where
        repeats are intentional and must pay full verification); `trace`
        tags the group with a causal trace id so the flight recorder can
        attribute the batch's cost to the block it checks."""
        if not messages:
            return []
        self._ensure_task()
        cls = resolve_source(source, urgent)
        group = _Group(
            list(messages),
            [pk for pk, _ in pairs],
            [sig for _, sig in pairs],
            cls.preemptive,
            committee,
            dedup,
            trace,
            cls.name,
            asyncio.get_running_loop().time(),
        )
        if self.scheduler is not None:
            self.scheduler.submit(group)
        else:
            await self._queue.put(group)
        return await group.future

    async def verify(
        self,
        message: bytes,
        key: PublicKey,
        signature: Signature,
        urgent: bool = True,
        committee: bool = False,
        trace: str | None = None,
        source: str | None = None,
    ) -> bool:
        """Await a single verification (batched under the hood)."""
        mask = await self.verify_group(
            [message], [(key, signature)], urgent, committee, trace=trace,
            source=source,
        )
        return mask[0]

    def seed_verified(
        self, message: bytes, key: PublicKey, signature: Signature
    ) -> None:
        """Record an ALREADY-VERIFIED triple into the dedup cache (the
        aggregator seeds vote/timeout signatures on arrival, so the QC/TC
        assembled from them re-verifies zero signatures here)."""
        if self.dedup is not None:
            self.dedup.add(message, key, signature)

    # -- flush loops ---------------------------------------------------------
    #
    # Production rides DeviceScheduler.run() (crypto/scheduler.py). The
    # legacy single-queue heuristics below are retained as the measured
    # baseline for `bench.py --scheduler-ab` (use_scheduler=False): size /
    # deadline / urgent flushing with no lanes, no alignment sizing, no
    # continuous refill.

    async def _run_legacy(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            first = await self._queue.get()
            groups = [first]
            total = len(first)
            urgent = first.urgent
            deadline = loop.time() + self.max_delay
            while total < self.max_batch:
                # Opportunistic drain of whatever is already enqueued.
                while not self._queue.empty() and total < self.max_batch:
                    g = self._queue.get_nowait()
                    groups.append(g)
                    total += len(g)
                    urgent |= g.urgent
                if urgent or total >= self.max_batch:
                    break
                timeout = deadline - loop.time()
                if timeout <= 0:
                    break
                try:
                    g = await asyncio.wait_for(self._queue.get(), timeout)
                except asyncio.TimeoutError:
                    break
                groups.append(g)
                total += len(g)
                urgent |= g.urgent

            # The legacy path stamps dequeue time at flush decision, so the
            # per-lane queue-delay attribution is directly comparable with
            # the scheduler's (same submit -> dequeue definition).
            now = loop.time()
            for g in groups:
                g.t_dequeue = now
                note_queue_delay(self.lane_stats, g.source, max(0.0, now - g.t_submit))

            # Urgent groups dispatch in their OWN flush, immediately: a
            # 3-signature QC check must neither ride a multi-thousand-
            # signature workload batch down the device path nor wait for a
            # dispatch slot held by one (backends send small batches down
            # the CPU fast path, so unbounded urgent dispatches are bounded
            # in practice by the consensus message rate). Workload groups
            # coalesced in the same pass flush separately, gated by the
            # dispatch bound — acquired inside _dispatch so this loop keeps
            # draining the queue while every slot is in flight.
            if urgent:
                hot = [g for g in groups if g.urgent]
                cold = [g for g in groups if not g.urgent]
                self._spawn_dispatch(hot, sum(len(g) for g in hot), True)
                if cold:
                    self._spawn_dispatch(cold, sum(len(g) for g in cold), False)
            else:
                self._spawn_dispatch(groups, total, False)

    def _spawn_dispatch(
        self, groups: list[_Group], total: int, urgent: bool,
        backend_idx: int = 0,
    ) -> asyncio.Task:
        from ..utils.actors import spawn

        task = spawn(
            self._dispatch(groups, total, urgent, backend_idx),
            name="verify-dispatch",
        )
        self._dispatches.add(task)
        task.add_done_callback(self._dispatches.discard)
        return task

    async def _dispatch(
        self, groups: list[_Group], total: int, urgent: bool,
        backend_idx: int = 0,
    ) -> None:
        if not urgent:
            await self._dispatch_sem.acquire()
        try:
            msgs = [m for g in groups for m in g.messages]
            keys = [k for g in groups for k in g.keys]
            sigs = [s for g in groups for s in g.signatures]
            # backend_idx > 0 is a scheduler steal: the bucket rides a
            # sibling shard's pipeline. Committee routing still resolves
            # per backend (an unregistered steal target just takes the
            # generic kernel — correctness never depends on the tag).
            backend = (
                self.backend
                if backend_idx == 0
                else self._steal_backends[backend_idx - 1]
            )

            # Verified-signature dedup: triples the aggregator (or an
            # earlier flush) already validated resolve True without
            # touching the backend; only misses dispatch. Per-item
            # eligibility: a flush may mix dedup-opted-out synthetic
            # groups with consensus traffic. The scan (and the index-
            # gather re-copy) is skipped entirely when no group opted in
            # or nothing hit — the synthetic throughput path pays zero.
            cache = self.dedup if any(g.dedup for g in groups) else None
            mask = [False] * len(msgs)
            miss = range(len(msgs))
            dedupable = None
            if cache is not None:
                dedupable = [g.dedup for g in groups for _ in range(len(g))]
                miss = []
                for i, (m, k, s) in enumerate(zip(msgs, keys, sigs)):
                    if dedupable[i] and cache.hit(m, k, s):
                        mask[i] = True
                    else:
                        miss.append(i)
            if miss:
                full = len(miss) == len(msgs)
                kwargs = {}
                if all(g.committee for g in groups) and getattr(
                    backend, "supports_committee_routing", False
                ):
                    kwargs["committee"] = True
                m = msgs if full else [msgs[i] for i in miss]
                k = keys if full else [keys[i] for i in miss]
                s = sigs if full else [sigs[i] for i in miss]
                t0 = time.perf_counter()
                try:
                    if self.inline:
                        sub = backend.verify_batch_mask(m, k, s, **kwargs)
                    else:
                        sub = await asyncio.to_thread(
                            backend.verify_batch_mask, m, k, s, **kwargs
                        )
                except Exception as exc:  # backend failure must not hang callers
                    for g in groups:
                        if not g.future.done():
                            g.future.set_exception(exc)
                    return
                dur = time.perf_counter() - t0
                if tracing.enabled():
                    # One verify.batch event per traced group in the flush
                    # (batch tags + the group's scheduler lane and queueing
                    # delay, the per-class attribution trace_report.py's
                    # verify-lane table aggregates), plus a watchdog sample
                    # of the flush's per-signature cost.
                    for g in groups:
                        if g.trace is not None:
                            tracing.event(
                                "verify.batch", g.trace, dur,
                                n=len(g), flush=len(miss), lane=g.source,
                                queue_s=round(
                                    max(0.0, g.t_dequeue - g.t_submit), 6
                                ),
                            )
                    tracing.WATCHDOG.note_verify(dur, len(miss))
                for i, ok in zip(miss, sub):
                    mask[i] = bool(ok)
                    if ok and cache is not None and dedupable[i]:
                        cache.add(msgs[i], keys[i], sigs[i])
            self.stats["flushes"] += 1
            self.stats["size_flushes"] += total >= self.max_batch
            self.stats["urgent_flushes"] += urgent
            self.stats["verified"] += total
            lo = 0
            for g in groups:
                hi = lo + len(g)
                if not g.future.cancelled():
                    g.future.set_result([bool(b) for b in mask[lo:hi]])
                lo = hi
        finally:
            if not urgent:
                self._dispatch_sem.release()
