"""BatchVerificationService: deadline-flushed signature-verification actor.

The north-star constraint (BASELINE.json): TPU batch verification must not
regress consensus latency — QC formation blocks round advancement, so
per-vote verification cannot wait for a large batch to fill. This actor
generalises the reference's SignatureService request/oneshot seam
(crypto/src/lib.rs:226-252) to verification: callers await single
(message, key, signature) checks; the actor accumulates concurrent requests
and flushes to the active CryptoBackend when either

  * the pending batch reaches `max_batch` (size flush, TPU-efficient), or
  * the oldest request is `max_delay` seconds old (deadline flush, keeps
    p99 latency bounded at low rates — SURVEY.md §7 "hard parts" item 1).

The backend call runs in a worker thread so the TPU dispatch never blocks
the event loop (the mempool/consensus cores keep processing while a batch
is in flight — the same pipelining the reference gets from tokio).
"""

from __future__ import annotations

import asyncio
from typing import Sequence

from .backend import CryptoBackend, get_backend
from .primitives import PublicKey, Signature


class BatchVerificationService:
    def __init__(
        self,
        backend: CryptoBackend | None = None,
        max_batch: int = 4096,
        max_delay: float = 0.002,
    ) -> None:
        self._backend = backend
        self.max_batch = max_batch
        self.max_delay = max_delay
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: asyncio.Task | None = None
        self.stats = {"flushes": 0, "size_flushes": 0, "verified": 0}

    def _ensure_task(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="batch-verification-service"
            )

    @property
    def backend(self) -> CryptoBackend:
        return self._backend or get_backend()

    async def verify(
        self, message: bytes, key: PublicKey, signature: Signature
    ) -> bool:
        """Await a single verification (batched under the hood)."""
        self._ensure_task()
        fut = asyncio.get_running_loop().create_future()
        await self._queue.put((message, key, signature, fut))
        return await fut

    async def verify_many(
        self,
        messages: Sequence[bytes],
        pairs: Sequence[tuple[PublicKey, Signature]],
    ) -> list[bool]:
        """Submit a correlated group (e.g. one QC's votes); resolves when
        every member's result is in (they may span multiple flushes)."""
        self._ensure_task()
        loop = asyncio.get_running_loop()
        futs = [loop.create_future() for _ in messages]
        for m, (pk, sig), fut in zip(messages, pairs, futs):
            await self._queue.put((m, pk, sig, fut))
        return list(await asyncio.gather(*futs))

    async def _run(self) -> None:
        while True:
            first = await self._queue.get()
            batch = [first]
            deadline = asyncio.get_running_loop().time() + self.max_delay
            while len(batch) < self.max_batch:
                timeout = deadline - asyncio.get_running_loop().time()
                if timeout <= 0:
                    break
                try:
                    batch.append(
                        await asyncio.wait_for(self._queue.get(), timeout)
                    )
                except asyncio.TimeoutError:
                    break
            # opportunistic drain of anything already enqueued
            while len(batch) < self.max_batch and not self._queue.empty():
                batch.append(self._queue.get_nowait())

            msgs = [m for m, _, _, _ in batch]
            keys = [k for _, k, _, _ in batch]
            sigs = [s for _, _, s, _ in batch]
            backend = self.backend
            try:
                mask = await asyncio.to_thread(
                    backend.verify_batch_mask, msgs, keys, sigs
                )
            except Exception as exc:  # backend failure must not hang callers
                for _, _, _, fut in batch:
                    if not fut.done():
                        fut.set_exception(exc)
                continue
            self.stats["flushes"] += 1
            self.stats["size_flushes"] += len(batch) >= self.max_batch
            self.stats["verified"] += len(batch)
            for (_, _, _, fut), ok in zip(batch, mask):
                if not fut.cancelled():
                    fut.set_result(bool(ok))
