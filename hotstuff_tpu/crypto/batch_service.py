"""BatchVerificationService: deadline-flushed signature-verification actor.

The north-star constraint (BASELINE.json): TPU batch verification must not
regress consensus latency — QC formation blocks round advancement, so
per-vote verification cannot wait for a large batch to fill. This actor
generalises the reference's SignatureService request/oneshot seam
(crypto/src/lib.rs:226-252) to verification: callers submit GROUPS of
(message, key, signature) triples (a QC's votes, one synthetic payload
batch, or a single vote) and await a per-item validity mask. The actor
concatenates pending groups and flushes to the active CryptoBackend when

  * the pending total reaches `max_batch` (size flush, TPU-efficient),
  * the oldest group is `max_delay` seconds old (deadline flush, keeps
    p99 latency bounded at low rates — SURVEY.md §7 "hard parts" item 1), or
  * an URGENT group is pending (consensus-critical: QC/TC/vote checks gate
    round advancement, so they flush after an opportunistic drain instead
    of waiting out the deadline).

The backend call runs in a worker thread so the TPU dispatch never blocks
the event loop (the mempool/consensus cores keep processing while a batch
is in flight — the same pipelining the reference gets from tokio). Groups
are enqueued whole (one queue item, one future per group), so per-item
asyncio overhead is O(1) per group, not O(n) — at 100k+ sigs/s the Python
queue would otherwise dominate the TPU kernel.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Sequence

from ..utils import metrics, tracing
from .backend import CryptoBackend, get_backend
from .primitives import PublicKey, Signature

log = logging.getLogger("hotstuff.crypto")

_M_DEDUP_HITS = metrics.counter("verifier.dedup_hits")
_M_DEDUP_MISSES = metrics.counter("verifier.dedup_misses")
_M_DEDUP_INSERTS = metrics.counter("verifier.dedup_inserts")
_M_DEDUP_EVICTIONS = metrics.counter("verifier.dedup_evictions")


class VerifiedSigCache:
    """Bounded LRU of (message, pk, sig) triples that VERIFIED.

    Every vote signature is checked 2-3x over its lifetime: once on vote
    arrival, again inside every QC that carries it (`QC.verify`), and again
    when that QC rides a Block/Timeout. A hit here short-circuits the
    backend call entirely. Only successes are cached (a miss proves
    nothing), and the triple is the full (message, key, signature) — a
    forged signature over the same digest can never alias a cached entry.

    Thread-safe: the consensus event loop seeds it while backend dispatch
    worker threads look entries up.
    """

    __slots__ = ("maxsize", "_entries", "_lock")

    def __init__(self, maxsize: int = 65536) -> None:
        if maxsize <= 0:
            raise ValueError("dedup cache needs maxsize >= 1")
        self.maxsize = maxsize
        self._entries: OrderedDict[tuple[bytes, bytes, bytes], None] = (
            OrderedDict()
        )
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._entries)

    def hit(self, message: bytes, key: PublicKey, sig: Signature) -> bool:
        """True iff this exact triple previously verified (refreshes LRU
        recency); counts into verifier.dedup_hits/misses."""
        k = (message, key.data, sig.data)
        with self._lock:
            if k in self._entries:
                self._entries.move_to_end(k)
                _M_DEDUP_HITS.inc()
                return True
        _M_DEDUP_MISSES.inc()
        return False

    def add(self, message: bytes, key: PublicKey, sig: Signature) -> None:
        """Record a VERIFIED triple; evicts least-recently-used past
        maxsize (memory stays bounded at ~128 B/entry)."""
        k = (message, key.data, sig.data)
        with self._lock:
            if k in self._entries:
                self._entries.move_to_end(k)
                return
            self._entries[k] = None
            _M_DEDUP_INSERTS.inc()
            while len(self._entries) > self.maxsize:
                self._entries.popitem(last=False)
                _M_DEDUP_EVICTIONS.inc()


@dataclass
class _Group:
    messages: list[bytes]
    keys: list[PublicKey]
    signatures: list[Signature]
    urgent: bool
    committee: bool = False
    # dedup=False opts the group out of the verified-signature cache: the
    # mempool's SYNTHETIC workload draws cyclically from a fixed pool of
    # pre-signed triples, and caching those would make the benchmark
    # measure the cache instead of the backend.
    dedup: bool = True
    # Causal trace id (utils/tracing.py): set for consensus groups so the
    # flight recorder can attribute this batch's verification cost to the
    # block whose QC/vote/proposal it checks.
    trace: str | None = None
    future: asyncio.Future = field(default_factory=lambda: asyncio.get_running_loop().create_future())

    def __len__(self) -> int:
        return len(self.messages)


class BatchVerificationService:
    def __init__(
        self,
        backend: CryptoBackend | None = None,
        max_batch: int = 8192,
        max_delay: float = 0.002,
        max_concurrent_dispatches: int = 4,
        dedup_cache_size: int = 65536,
        inline: bool = False,
    ) -> None:
        self._backend = backend
        self.max_batch = max_batch
        self.max_delay = max_delay
        # inline=True runs the backend call ON the event loop instead of a
        # worker thread. Production keeps the thread (a TPU dispatch must
        # not block consensus timers); the chaos runner opts in because its
        # pure-python backend is millisecond-cheap and thread scheduling is
        # the one nondeterminism its virtual-time replay cannot control.
        self.inline = inline
        # Verified-signature dedup: set dedup_cache_size=0 to disable
        # (the bench A/B switch and the uncached-baseline tests).
        self.dedup: VerifiedSigCache | None = (
            VerifiedSigCache(dedup_cache_size) if dedup_cache_size else None
        )
        self._queue: asyncio.Queue[_Group] = asyncio.Queue()
        self._task: asyncio.Task | None = None
        # Flushes dispatch CONCURRENTLY (bounded): an urgent 3-signature QC
        # check must not wait out a multi-thousand-signature workload batch
        # already in flight on the device (backends route small batches to
        # the CPU fast path, so the urgent flush completes in microseconds
        # while the big dispatch is still on the wire).
        self._dispatch_sem = asyncio.Semaphore(max_concurrent_dispatches)
        self._dispatches: set[asyncio.Task] = set()
        self.stats = {
            "flushes": 0,
            "size_flushes": 0,
            "urgent_flushes": 0,
            "verified": 0,
        }

    def _ensure_task(self) -> None:
        if self._task is None or self._task.done():
            # actors.spawn (not bare create_task): the service task then
            # joins the caller's SpawnScope, so a chaos crash-restart of a
            # node tears down its verification flush loop too.
            from ..utils.actors import spawn

            self._task = spawn(self._run(), name="batch-verification-service")

    @property
    def backend(self) -> CryptoBackend:
        return self._backend or get_backend()

    # -- submission API ------------------------------------------------------

    async def verify_group(
        self,
        messages: Sequence[bytes],
        pairs: Sequence[tuple[PublicKey, Signature]],
        urgent: bool = False,
        committee: bool = False,
        dedup: bool = True,
        trace: str | None = None,
    ) -> list[bool]:
        """Submit a correlated group (e.g. one QC's votes or one synthetic
        payload batch); resolves to the per-item validity mask once the
        group's flush completes. `committee=True` tags the group as signed
        by registered validator keys, routing it to the backend's
        committee-resident kernel when available; `dedup=False` bypasses
        the verified-signature cache (synthetic benchmark load, where
        repeats are intentional and must pay full verification); `trace`
        tags the group with a causal trace id so the flight recorder can
        attribute the batch's cost to the block it checks."""
        if not messages:
            return []
        self._ensure_task()
        group = _Group(
            list(messages),
            [pk for pk, _ in pairs],
            [sig for _, sig in pairs],
            urgent,
            committee,
            dedup,
            trace,
        )
        await self._queue.put(group)
        return await group.future

    async def verify(
        self,
        message: bytes,
        key: PublicKey,
        signature: Signature,
        urgent: bool = True,
        committee: bool = False,
        trace: str | None = None,
    ) -> bool:
        """Await a single verification (batched under the hood)."""
        mask = await self.verify_group(
            [message], [(key, signature)], urgent, committee, trace=trace
        )
        return mask[0]

    def seed_verified(
        self, message: bytes, key: PublicKey, signature: Signature
    ) -> None:
        """Record an ALREADY-VERIFIED triple into the dedup cache (the
        aggregator seeds vote/timeout signatures on arrival, so the QC/TC
        assembled from them re-verifies zero signatures here)."""
        if self.dedup is not None:
            self.dedup.add(message, key, signature)

    # -- flush loop ----------------------------------------------------------

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            first = await self._queue.get()
            groups = [first]
            total = len(first)
            urgent = first.urgent
            deadline = loop.time() + self.max_delay
            while total < self.max_batch:
                # Opportunistic drain of whatever is already enqueued.
                while not self._queue.empty() and total < self.max_batch:
                    g = self._queue.get_nowait()
                    groups.append(g)
                    total += len(g)
                    urgent |= g.urgent
                if urgent or total >= self.max_batch:
                    break
                timeout = deadline - loop.time()
                if timeout <= 0:
                    break
                try:
                    g = await asyncio.wait_for(self._queue.get(), timeout)
                except asyncio.TimeoutError:
                    break
                groups.append(g)
                total += len(g)
                urgent |= g.urgent

            # Urgent groups dispatch in their OWN flush, immediately: a
            # 3-signature QC check must neither ride a multi-thousand-
            # signature workload batch down the device path nor wait for a
            # dispatch slot held by one (backends send small batches down
            # the CPU fast path, so unbounded urgent dispatches are bounded
            # in practice by the consensus message rate). Workload groups
            # coalesced in the same pass flush separately, gated by the
            # dispatch bound — acquired inside _dispatch so this loop keeps
            # draining the queue while every slot is in flight.
            if urgent:
                hot = [g for g in groups if g.urgent]
                cold = [g for g in groups if not g.urgent]
                self._spawn_dispatch(hot, sum(len(g) for g in hot), True)
                if cold:
                    self._spawn_dispatch(cold, sum(len(g) for g in cold), False)
            else:
                self._spawn_dispatch(groups, total, False)

    def _spawn_dispatch(self, groups: list[_Group], total: int, urgent: bool) -> None:
        from ..utils.actors import spawn

        task = spawn(self._dispatch(groups, total, urgent), name="verify-dispatch")
        self._dispatches.add(task)
        task.add_done_callback(self._dispatches.discard)

    async def _dispatch(self, groups: list[_Group], total: int, urgent: bool) -> None:
        if not urgent:
            await self._dispatch_sem.acquire()
        try:
            msgs = [m for g in groups for m in g.messages]
            keys = [k for g in groups for k in g.keys]
            sigs = [s for g in groups for s in g.signatures]
            backend = self.backend

            # Verified-signature dedup: triples the aggregator (or an
            # earlier flush) already validated resolve True without
            # touching the backend; only misses dispatch. Per-item
            # eligibility: a flush may mix dedup-opted-out synthetic
            # groups with consensus traffic. The scan (and the index-
            # gather re-copy) is skipped entirely when no group opted in
            # or nothing hit — the synthetic throughput path pays zero.
            cache = self.dedup if any(g.dedup for g in groups) else None
            mask = [False] * len(msgs)
            miss = range(len(msgs))
            dedupable = None
            if cache is not None:
                dedupable = [g.dedup for g in groups for _ in range(len(g))]
                miss = []
                for i, (m, k, s) in enumerate(zip(msgs, keys, sigs)):
                    if dedupable[i] and cache.hit(m, k, s):
                        mask[i] = True
                    else:
                        miss.append(i)
            if miss:
                full = len(miss) == len(msgs)
                kwargs = {}
                if all(g.committee for g in groups) and getattr(
                    backend, "supports_committee_routing", False
                ):
                    kwargs["committee"] = True
                m = msgs if full else [msgs[i] for i in miss]
                k = keys if full else [keys[i] for i in miss]
                s = sigs if full else [sigs[i] for i in miss]
                t0 = time.perf_counter()
                try:
                    if self.inline:
                        sub = backend.verify_batch_mask(m, k, s, **kwargs)
                    else:
                        sub = await asyncio.to_thread(
                            backend.verify_batch_mask, m, k, s, **kwargs
                        )
                except Exception as exc:  # backend failure must not hang callers
                    for g in groups:
                        if not g.future.done():
                            g.future.set_exception(exc)
                    return
                dur = time.perf_counter() - t0
                if tracing.enabled():
                    # One verify.batch event per traced group in the flush
                    # (batch tags), plus a watchdog sample of the flush's
                    # per-signature cost for regression detection.
                    for g in groups:
                        if g.trace is not None:
                            tracing.event(
                                "verify.batch", g.trace, dur,
                                n=len(g), flush=len(miss),
                            )
                    tracing.WATCHDOG.note_verify(dur, len(miss))
                for i, ok in zip(miss, sub):
                    mask[i] = bool(ok)
                    if ok and cache is not None and dedupable[i]:
                        cache.add(msgs[i], keys[i], sigs[i])
            self.stats["flushes"] += 1
            self.stats["size_flushes"] += total >= self.max_batch
            self.stats["urgent_flushes"] += urgent
            self.stats["verified"] += total
            lo = 0
            for g in groups:
                hi = lo + len(g)
                if not g.future.cancelled():
                    g.future.set_result([bool(b) for b in mask[lo:hi]])
                lo = hi
        finally:
            if not urgent:
                self._dispatch_sem.release()
