"""TPU CryptoBackend: the north-star offload.

Routes `Signature::verify_batch` / `verify_batch_alt` equivalents (the
reference's QC::verify path consensus/src/messages.rs:197 and the mempool
batch workload mempool/src/core.rs:135-148) to the JAX ed25519 kernel
(hotstuff_tpu.ops.ed25519), optionally sharded across a device mesh
(hotstuff_tpu.parallel.mesh).

Small batches fall back to the host CPU: the TPU wins only past a crossover
size (dispatch + transfer amortisation — SURVEY.md §7 "hard parts" item 3).
The crossover is configurable and can be measured with bench.py.
"""

from __future__ import annotations

import threading
from typing import Sequence

from ..utils import metrics
from .backend import CpuBackend, CryptoBackend
from .primitives import PublicKey, Signature

# Mirrors the instance-local `stats` dict into the process-global metrics
# registry so backend routing shows up in METRICS snapshots and dumps.
_M_TPU_BATCHES = metrics.counter("crypto.tpu_batches")
_M_TPU_SIGS = metrics.counter("crypto.tpu_sigs")
_M_CPU_BATCHES = metrics.counter("crypto.cpu_batches")
_M_CPU_SIGS = metrics.counter("crypto.cpu_sigs")
_M_BATCH_SIZE = metrics.histogram("crypto.batch_size", metrics.SIZE_BUCKETS)


class TpuBackend(CryptoBackend):
    name = "tpu"

    def __init__(
        self,
        crossover: int = 64,
        max_bucket: int = 8192,
        min_bucket: int = 128,
        mesh=None,
        sharded: bool = False,
        chunk: int | None = None,
    ):
        # import lazily so CPU-only processes never touch jax
        from ..ops import enable_persistent_cache

        enable_persistent_cache()
        if sharded or mesh is not None:
            import jax

            from ..parallel.mesh import ShardedEd25519Verifier

            kernel = "w4" if jax.default_backend() == "cpu" else "pallas"
            self._verifier = ShardedEd25519Verifier(
                mesh=mesh,
                min_bucket=min_bucket,
                max_bucket=max_bucket,
                kernel=kernel,
                chunk=chunk,
            )
        else:
            import jax

            from ..ops.ed25519 import Ed25519TpuVerifier

            # pallas ladder on a real accelerator; the jnp w4 kernel on the
            # CPU interpreter (pallas has no CPU lowering). Packed wire
            # format + threaded upload pipeline either way.
            kernel = "w4" if jax.default_backend() == "cpu" else "pallas"
            self._verifier = Ed25519TpuVerifier(
                min_bucket=min_bucket,
                max_bucket=max_bucket,
                kernel=kernel,
                chunk=chunk,
            )
        self._cpu = CpuBackend()
        self.crossover = crossover
        self._lock = threading.Lock()
        self.stats = {"tpu_batches": 0, "tpu_sigs": 0, "cpu_batches": 0, "cpu_sigs": 0}

    def warmup(self) -> float:
        """Force-compile every device bucket shape the verifier dispatches at
        runtime, BEFORE the node joins consensus. The first dispatch at each
        bucket width triggers XLA compilation (tens of seconds cold); paying
        that lazily inside the protocol stalls rounds past timeout_delay and
        fires the pacemaker (the round-4 saturation runs logged dozens of
        boot-window timeouts). With the persistent compile cache enabled in
        __init__, later processes and runs hit the on-disk cache and this
        costs seconds. Returns wall seconds spent.

        Junk inputs are used on purpose: compilation is shape-dependent
        only, and the masks are discarded. 32-byte messages warm the
        production device-hash path; one 33-byte batch at the largest width
        warms the host-hash variant the failure latch falls back to.
        """
        import os
        import time

        t0 = time.perf_counter()
        v = self._verifier
        widths, w = [], v.min_bucket
        top = min(v.chunk, v.max_bucket) if hasattr(v, "chunk") else v.max_bucket
        while w < top:
            widths.append(w)
            w *= 2
        # The largest shape actually dispatched for a full chunk (bucket
        # rounding may exceed `top` when min_bucket isn't a power of two).
        widths.append(v._bucket(top))
        for width in widths:
            junk_m = [os.urandom(32)] * width
            junk_k = [os.urandom(32)] * width
            junk_s = [os.urandom(64)] * width
            v.verify_batch_mask(junk_m, junk_k, junk_s)
        v.verify_batch_mask(
            [os.urandom(33)] * widths[-1],
            [os.urandom(32)] * widths[-1],
            [os.urandom(64)] * widths[-1],
        )
        return time.perf_counter() - t0

    def verify_batch_mask(
        self,
        messages: Sequence[bytes],
        keys: Sequence[PublicKey],
        signatures: Sequence[Signature],
    ) -> list[bool]:
        n = len(messages)
        if n == 0:
            return []
        _M_BATCH_SIZE.record(n)
        if n < self.crossover:
            with self._lock:
                self.stats["cpu_batches"] += 1
                self.stats["cpu_sigs"] += n
            _M_CPU_BATCHES.inc()
            _M_CPU_SIGS.inc(n)
            return self._cpu.verify_batch_mask(messages, keys, signatures)
        with self._lock:
            self.stats["tpu_batches"] += 1
            self.stats["tpu_sigs"] += n
        _M_TPU_BATCHES.inc()
        _M_TPU_SIGS.inc(n)
        mask = self._verifier.verify_batch_mask(
            list(messages),
            [k.data for k in keys],
            [s.data for s in signatures],
        )
        return mask.tolist()
