"""TPU CryptoBackend: the north-star offload.

Routes `Signature::verify_batch` / `verify_batch_alt` equivalents (the
reference's QC::verify path consensus/src/messages.rs:197 and the mempool
batch workload mempool/src/core.rs:135-148) to the JAX ed25519 kernel
(hotstuff_tpu.ops.ed25519), optionally sharded across a device mesh
(hotstuff_tpu.parallel.mesh).

Small batches fall back to the host CPU: the TPU wins only past a crossover
size (dispatch + transfer amortisation — SURVEY.md §7 "hard parts" item 3).
The crossover is configurable and can be measured with bench.py.

`register_committee()` installs the validator keys as device-resident
precompute (ops.ed25519.CommitteeTable); batches tagged as committee
traffic whose keys all resolve then ride the committee kernel — no
per-batch key decompression or window-table builds. Untagged batches
(mempool synthetic load, client transactions) keep the generic path.
"""

from __future__ import annotations

import logging
import threading
from typing import Sequence

from ..utils import metrics
from .backend import CpuBackend, CryptoBackend
from .primitives import PublicKey, Signature

log = logging.getLogger("hotstuff.crypto")

# Mirrors the instance-local `stats` dict into the process-global metrics
# registry so backend routing shows up in METRICS snapshots and dumps.
_M_TPU_BATCHES = metrics.counter("crypto.tpu_batches")
_M_TPU_SIGS = metrics.counter("crypto.tpu_sigs")
_M_CPU_BATCHES = metrics.counter("crypto.cpu_batches")
_M_CPU_SIGS = metrics.counter("crypto.cpu_sigs")
_M_BATCH_SIZE = metrics.histogram("crypto.batch_size", metrics.SIZE_BUCKETS)
_M_CROSSOVER_FALLBACKS = metrics.counter("verifier.crossover_fallbacks")
_M_COMMITTEE_MISSES = metrics.counter("verifier.committee_misses")
# Adversarial-rejection visibility: forged/garbage signatures reaching the
# backend show up here (split out for committee-tagged traffic, where a
# rejection means a Byzantine vote/timeout hit the committee kernel's
# rejection lanes). The chaos forged-signature scenarios assert on these.
_M_REJECTED = metrics.counter("verifier.rejected_sigs")
_M_COMMITTEE_REJECTED = metrics.counter("verifier.committee_rejected_sigs")


def _is_decade(count: int) -> bool:
    """True on the 1st, 10th, 100th, ... occurrence — the log-throttling
    rule shared by the crossover-fallback and committee-miss warnings."""
    return count >= 1 and count == 10 ** (len(str(count)) - 1)


class TpuBackend(CryptoBackend):
    name = "tpu"
    # BatchVerificationService probes this to tag committee flushes.
    supports_committee_routing = True

    def __init__(
        self,
        crossover: int = 64,
        max_bucket: int = 8192,
        min_bucket: int = 128,
        mesh=None,
        sharded: bool = False,
        chunk: int | None = None,
        committee_crossover: int | None = None,
    ):
        # import lazily so CPU-only processes never touch jax
        from ..ops import enable_persistent_cache

        enable_persistent_cache()
        if sharded or mesh is not None:
            import jax

            from ..parallel.mesh import ShardedEd25519Verifier

            kernel = "w4" if jax.default_backend() == "cpu" else "pallas"
            self._verifier = ShardedEd25519Verifier(
                mesh=mesh,
                min_bucket=min_bucket,
                max_bucket=max_bucket,
                kernel=kernel,
                chunk=chunk,
            )
        else:
            import jax

            from ..ops.ed25519 import Ed25519TpuVerifier

            # pallas ladder on a real accelerator; the jnp w4 kernel on the
            # CPU interpreter (pallas has no CPU lowering). Packed wire
            # format + threaded upload pipeline either way.
            kernel = "w4" if jax.default_backend() == "cpu" else "pallas"
            self._verifier = Ed25519TpuVerifier(
                min_bucket=min_bucket,
                max_bucket=max_bucket,
                kernel=kernel,
                chunk=chunk,
            )
        self._cpu = CpuBackend()
        self.crossover = crossover
        # The committee kernel skips per-batch decompression + window-table
        # builds and ships 96 B + a 4 B index (vs 128 B) per signature, so
        # its device break-even sits well below the generic crossover.
        # Default crossover/4 so quorum-sized QC/TC batches (2f+1 votes)
        # actually ride the device-resident tables instead of falling to
        # the host CPU; tune with bench.py --committee-cache.
        if committee_crossover is not None:
            self.committee_crossover = committee_crossover
        else:
            self.committee_crossover = max(1, crossover // 4)
            # Mesh-aware floor: a sharded verifier's narrowest bucket is
            # lane * ndev (mesh_alignment), so a sub-alignment quorum batch
            # pads up to a FULL mesh bucket — the device pays align lanes
            # regardless of occupancy and the break-even scales with the
            # inflation. Keep the single-chip ratio (crossover/4 = 16
            # against min_bucket 128, i.e. min_bucket/8).
            align = getattr(self._verifier, "mesh_alignment", 0)
            if align:
                self.committee_crossover = max(
                    self.committee_crossover, align // 8
                )
        self._lock = threading.Lock()
        self.stats = {"tpu_batches": 0, "tpu_sigs": 0, "cpu_batches": 0, "cpu_sigs": 0}

    def close(self) -> None:
        """Drain the verifier's dispatch-pipeline workers (ops/pipeline.py).
        Optional — dropped backends are reaped by GC/atexit — but a tidy
        shutdown path for tests and per-shard steal backends."""
        closer = getattr(self._verifier, "close", None)
        if closer is not None:
            closer()

    @property
    def bucket_alignment(self) -> int:
        """The device bucket grid: `lane * ndev` on a mesh
        (parallel/mesh.py `mesh_alignment`), the narrowest bucket width on
        a single chip. The continuous-batching scheduler
        (crypto/scheduler.py) sizes bulk buckets against this so a closed
        bucket pads zero lanes; gridless backends (CPU, pure-python)
        simply lack the attribute."""
        v = self._verifier
        return getattr(v, "mesh_alignment", 0) or getattr(v, "min_bucket", 0)

    # -- committee registration ---------------------------------------------

    def register_committee(
        self, keys: Sequence[PublicKey | bytes], warmup: bool = False
    ) -> int:
        """Install the committee keys as device-resident precompute.

        Idempotent for an identical key sequence; a CHANGED key set (epoch
        reconfiguration) invalidates and rebuilds the table. With `warmup`,
        force-compiles the committee kernel at every bucket width the
        dispatcher uses (same rationale as `warmup()`). Returns the
        committee size."""
        raw = [k.data if isinstance(k, PublicKey) else bytes(k) for k in keys]
        if not getattr(self._verifier, "supports_committee", False):
            log.warning(
                "committee registration skipped: %s has no committee path",
                type(self._verifier).__name__,
            )
            return 0
        table = self._verifier.set_committee(raw)
        log.info(
            "registered %d-key committee for device-resident verification",
            table.size,
        )
        if warmup:
            self._warmup_committee()
        return table.size

    def _warmup_widths(self) -> list[int]:
        """Batch sizes that, fed through the dispatcher, compile every
        bucket width it can dispatch at runtime — shared by warmup() and
        _warmup_committee() so the two kernel families are compiled at
        exactly the same shapes.

        Each candidate size is mapped through the verifier's OWN bucketing
        and deduplicated on the resulting width: mesh alignment
        (min_bucket = lane * ndev, max_bucket rounded to the alignment
        grid) and pallas BLOCK rounding can collapse ladder steps onto one
        dispatched width, and emitting the raw power-of-two ladder would
        compile shapes the sharded verifier re-buckets and never
        dispatches. Sizes are capped at the chunk so every warmup batch
        dispatches as exactly one chunk (no stray split-remainder shapes).
        """
        v = self._verifier
        top = min(v.chunk, v.max_bucket) if hasattr(v, "chunk") else v.max_bucket
        sizes, w = [], v.min_bucket
        while w < top:
            sizes.append(w)
            w *= 2
        # The full-chunk dispatch (its bucket may exceed `top` when
        # min_bucket isn't a power of two).
        sizes.append(top)
        seen, out = set(), []
        for n in sizes:
            width = v._bucket(n)
            if width not in seen:
                seen.add(width)
                out.append(n)
        return out

    def _warmup_committee(self) -> float:
        """Compile the committee kernel family at every dispatch bucket
        width (junk wire bytes; shapes are all that matter — see
        `warmup()`). Returns wall seconds spent."""
        import os
        import time

        t0 = time.perf_counter()
        v = self._verifier
        sizes = self._warmup_widths()
        for n in sizes:
            v.verify_batch_mask_committee(
                [os.urandom(32)] * n, [0] * n, [os.urandom(64)] * n
            )
        # host-hash variant (the device-hash failure latch's fallback)
        v.verify_batch_mask_committee(
            [os.urandom(33)] * sizes[-1],
            [0] * sizes[-1],
            [os.urandom(64)] * sizes[-1],
        )
        secs = time.perf_counter() - t0
        log.info(
            "committee kernel warmup: %d batch sizes (widths %s) in %.1f s",
            len(sizes),
            [v._bucket(n) for n in sizes],
            secs,
        )
        return secs

    def warmup(self) -> float:
        """Force-compile every device bucket shape the verifier dispatches at
        runtime, BEFORE the node joins consensus. The first dispatch at each
        bucket width triggers XLA compilation (tens of seconds cold); paying
        that lazily inside the protocol stalls rounds past timeout_delay and
        fires the pacemaker (the round-4 saturation runs logged dozens of
        boot-window timeouts). With the persistent compile cache enabled in
        __init__, later processes and runs hit the on-disk cache and this
        costs seconds. Returns wall seconds spent.

        Junk inputs are used on purpose: compilation is shape-dependent
        only, and the masks are discarded. 32-byte messages warm the
        production device-hash path; one 33-byte batch at the largest width
        warms the host-hash variant the failure latch falls back to.
        """
        import os
        import time

        t0 = time.perf_counter()
        v = self._verifier
        sizes = self._warmup_widths()
        for n in sizes:
            junk_m = [os.urandom(32)] * n
            junk_k = [os.urandom(32)] * n
            junk_s = [os.urandom(64)] * n
            v.verify_batch_mask(junk_m, junk_k, junk_s)
        v.verify_batch_mask(
            [os.urandom(33)] * sizes[-1],
            [os.urandom(32)] * sizes[-1],
            [os.urandom(64)] * sizes[-1],
        )
        secs = time.perf_counter() - t0
        log.info(
            "generic kernel warmup: %d batch sizes (widths %s) in %.1f s",
            len(sizes),
            [v._bucket(n) for n in sizes],
            secs,
        )
        return secs

    def verify_batch_mask(
        self,
        messages: Sequence[bytes],
        keys: Sequence[PublicKey],
        signatures: Sequence[Signature],
        committee: bool = False,
    ) -> list[bool]:
        """`committee=True` marks the batch as consensus traffic signed by
        registered validator keys: indices are resolved against the
        registered table, the lower `committee_crossover` governs the CPU
        fallback, and the batch rides the committee kernel. Batches with
        any unregistered key (or no registration) fall back to the generic
        path — correctness never depends on the tag."""
        n = len(messages)
        if n == 0:
            return []
        _M_BATCH_SIZE.record(n)
        # Resolve committee routing BEFORE the crossover decision: the
        # committee kernel's cheaper per-batch cost earns it a lower
        # CPU/device break-even than the generic path.
        resolved = self._resolve_committee(keys) if committee else None
        threshold = (
            self.committee_crossover if resolved is not None else self.crossover
        )
        if n < threshold:
            with self._lock:
                self.stats["cpu_batches"] += 1
                self.stats["cpu_sigs"] += n
            _M_CPU_BATCHES.inc()
            _M_CPU_SIGS.inc(n)
            _M_CROSSOVER_FALLBACKS.inc()
            # Log once per decade of fallback count (1st, 10th, 100th, ...)
            # so bench runs show how often the TPU path is bypassed without
            # flooding the log at consensus rates.
            count = _M_CROSSOVER_FALLBACKS.value
            if _is_decade(count):
                log.info(
                    "sub-crossover fallback #%d: batch of %d < crossover %d "
                    "verified on host CPU",
                    count,
                    n,
                    threshold,
                )
            mask = self._cpu.verify_batch_mask(messages, keys, signatures)
            self._count_rejections(mask, resolved is not None)
            return mask
        with self._lock:
            self.stats["tpu_batches"] += 1
            self.stats["tpu_sigs"] += n
        _M_TPU_BATCHES.inc()
        _M_TPU_SIGS.inc(n)
        if resolved is not None:
            indices, table = resolved
            # the table is PINNED through the dispatch: a concurrent
            # re-registration must not swap it under these indices
            mask = self._verifier.verify_batch_mask_committee(
                list(messages),
                indices,
                [s.data for s in signatures],
                table=table,
            ).tolist()
            self._count_rejections(mask, True)
            return mask
        mask = self._verifier.verify_batch_mask(
            list(messages),
            [k.data for k in keys],
            [s.data for s in signatures],
        ).tolist()
        self._count_rejections(mask, False)
        return mask

    @staticmethod
    def _count_rejections(mask: Sequence[bool], committee: bool) -> None:
        bad = sum(1 for ok in mask if not ok)
        if bad:
            _M_REJECTED.inc(bad)
            if committee:
                _M_COMMITTEE_REJECTED.inc(bad)

    def _resolve_committee(self, keys: Sequence[PublicKey]):
        """Map keys to validator indices against ONE table snapshot;
        returns (indices, table), or None when unroutable (no
        registration, or any key outside the registered set)."""
        table = getattr(self._verifier, "committee", None)
        if table is None:
            return None
        try:
            return [table.index[k.data] for k in keys], table
        except KeyError:
            _M_COMMITTEE_MISSES.inc()
            # Once per decade of misses, mirroring crossover_fallbacks:
            # persistent misses mean the registered table is stale (epoch
            # reconfiguration without re-registering) and committee
            # traffic is silently riding the generic kernel.
            count = _M_COMMITTEE_MISSES.value
            if _is_decade(count):
                log.info(
                    "committee miss #%d: tagged batch of %d contains "
                    "unregistered key(s); falling back to the generic "
                    "kernel (re-register after reconfiguration?)",
                    count,
                    len(keys),
                )
            return None
