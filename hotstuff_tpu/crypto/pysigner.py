"""Dependency-free ed25519 (RFC 8032) in exact host integers.

The host OpenSSL wheel (`cryptography`) is optional on this stack
(crypto/primitives.py guards it), and the JAX kernels pay a multi-minute
XLA compile on first use — neither is acceptable inside the chaos
subsystem, whose scenarios must boot real consensus nodes in milliseconds
on any host. This module is the third, always-available implementation:
pure-stdlib signing AND strict verification with the exact-integer
Edwards arithmetic the kernel tests already trust (tests/common.py and
tests/test_mesh_committee.py promote their fixture signer from here).

Semantics match the device kernels' STRICT verification: non-canonical
s (>= L), off-curve keys/R, and wrong-index gathers all reject — the
chaos invariant checkers re-verify committed certificates against this
implementation, so it must agree bit-for-bit with the hot path.

Performance: extended (X:Y:Z:T) coordinates, double-and-add, one field
inversion per compression — ~1 ms per scalar multiplication on a laptop
core. Milliseconds per signature is fine for fault-injection scenarios
(hundreds of signatures); it is never a production verify path.
"""

from __future__ import annotations

import asyncio
import hashlib
from typing import Sequence

from ..utils import metrics
from ..utils.actors import spawn
from .backend import CryptoBackend
from .primitives import Digest, PublicKey, Signature

__all__ = [
    "P",
    "L",
    "D",
    "keypair_from_seed",
    "sign",
    "verify",
    "keypair_exact",
    "sign_exact",
    "verify_exact",
    "install_scheme",
    "active_scheme",
    "PurePythonBackend",
    "PySignatureService",
]

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = -121665 * pow(121666, P - 2, P) % P

_M_REJECTS = metrics.counter("verifier.rejected_sigs")

# Base point (RFC 8032 §5.1): y = 4/5, x recovered with the even root.
_BY = 4 * pow(5, P - 2, P) % P


def _sqrt_mod_p(x2: int) -> int | None:
    """Square root mod P (P ≡ 5 mod 8), or None when x2 is a non-residue."""
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * pow(2, (P - 1) // 4, P) % P
    if (x * x - x2) % P != 0:
        return None
    return x


def _recover_x(y: int, sign_bit: int) -> int | None:
    if y >= P:
        return None
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    x = _sqrt_mod_p(x2)
    if x is None:
        return None
    if x == 0 and sign_bit:
        return None  # -0 is not canonical
    if x & 1 != sign_bit:
        x = P - x
    return x


# Extended homogeneous coordinates (X:Y:Z:T) with x=X/Z, y=Y/Z, xy=T/Z.
_IDENT = (0, 1, 1, 0)
_B_POINT = None  # initialised below once _recover_x exists


def _pt_add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * t2 * D % P
    d = 2 * z1 * z2 % P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _pt_mul(k: int, pt):
    acc = _IDENT
    while k:
        if k & 1:
            acc = _pt_add(acc, pt)
        pt = _pt_add(pt, pt)
        k >>= 1
    return acc


def _pt_compress(pt) -> bytes:
    x, y, z, _ = pt
    zinv = pow(z, P - 2, P)
    x, y = x * zinv % P, y * zinv % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _pt_decompress(data: bytes):
    """Compressed 32 bytes -> extended point, or None (off-curve / non-
    canonical y)."""
    if len(data) != 32:
        return None
    enc = int.from_bytes(data, "little")
    y = enc & ((1 << 255) - 1)
    x = _recover_x(y, enc >> 255)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


_B_POINT = (
    _recover_x(_BY, 0),
    _BY,
    1,
    _recover_x(_BY, 0) * _BY % P,
)


def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


# ---------------------------------------------------------------------------
# Scheme seam. The chaos plane's trusted-crypto mode (chaos/trusted_crypto.py)
# swaps signatures for keyed-hash stubs at hundred-node committee sizes,
# where exact-int ed25519 (~20 ms/sig here) would make a single round cost
# minutes of wall time. Everything that signs or verifies through this
# module — PySignatureService, PurePythonBackend, byzantine policies,
# EpochChange.new_from_seed, the SafetyChecker audit — follows one installed
# scheme, so a run is never half-stubbed. The `*_exact` names below always
# resolve to the real RFC 8032 implementation regardless of any scheme.

_SCHEME = None  # None = exact RFC 8032 (the default, production semantics)


def install_scheme(scheme):
    """Install a signature scheme (or None for exact RFC 8032); returns
    the previously installed scheme so callers can restore it. A scheme
    supplies keypair_from_seed/sign/verify with this module's shapes
    (32-byte seeds and keys, 64-byte signatures)."""
    global _SCHEME
    prev = _SCHEME
    _SCHEME = scheme
    return prev


def active_scheme():
    return _SCHEME


def keypair_from_seed(seed: bytes) -> tuple[bytes, bytes]:
    """32-byte seed -> (public key, seed). The seed IS the secret; signing
    re-derives whatever the active scheme needs from it."""
    if _SCHEME is not None:
        return _SCHEME.keypair_from_seed(seed)
    return keypair_exact(seed)


def sign(seed: bytes, message: bytes) -> bytes:
    """64-byte signature over `message` under the active scheme (exact
    RFC 8032 unless a chaos scheme is installed)."""
    if _SCHEME is not None:
        return _SCHEME.sign(seed, message)
    return sign_exact(seed, message)


def verify(public_key: bytes, message: bytes, signature: bytes) -> bool:
    """Verify under the active scheme. Exact in BOTH modes: the default
    is strict exact-integer RFC 8032; a stub scheme recomputes its keyed
    hash and compares byte-exactly (so corruption always rejects)."""
    if _SCHEME is not None:
        return _SCHEME.verify(public_key, message, signature)
    return verify_exact(public_key, message, signature)


def keypair_exact(seed: bytes) -> tuple[bytes, bytes]:
    """32-byte seed -> (compressed public key, seed). The seed IS the
    secret (RFC 8032 private key); signing re-derives the scalar."""
    if len(seed) != 32:
        raise ValueError("ed25519 seed must be 32 bytes")
    h = hashlib.sha512(seed).digest()
    pk = _pt_compress(_pt_mul(_clamp(h), _B_POINT))
    return pk, seed


def sign_exact(seed: bytes, message: bytes) -> bytes:
    """RFC 8032 Ed25519 signature (64 bytes) over `message`."""
    if len(seed) != 32:
        raise ValueError("ed25519 seed must be 32 bytes")
    h = hashlib.sha512(seed).digest()
    a, prefix = _clamp(h), h[32:]
    pk = _pt_compress(_pt_mul(a, _B_POINT))
    r = int.from_bytes(hashlib.sha512(prefix + message).digest(), "little") % L
    r_enc = _pt_compress(_pt_mul(r, _B_POINT))
    k = (
        int.from_bytes(hashlib.sha512(r_enc + pk + message).digest(), "little")
        % L
    )
    s = (r + k * a) % L
    return r_enc + s.to_bytes(32, "little")


# Decompressed-key memo: committee keys recur on every certificate check,
# and decompression (sqrt + inverse) dominates small verifies. Bounded so
# adversarial key floods cannot grow it.
_KEY_CACHE: dict[bytes, tuple] = {}
_KEY_CACHE_MAX = 4096


def verify_exact(public_key: bytes, message: bytes, signature: bytes) -> bool:
    """STRICT verification: canonical s < L, on-curve canonical A and R,
    full sB == R + hA — the same rejection classes the device kernels
    implement (tests assert mask equality)."""
    if len(signature) != 64 or len(public_key) != 32:
        return False
    a_pt = _KEY_CACHE.get(public_key)
    if a_pt is None:
        a_pt = _pt_decompress(public_key)
        if a_pt is None:
            return False
        if len(_KEY_CACHE) >= _KEY_CACHE_MAX:
            _KEY_CACHE.clear()
        _KEY_CACHE[public_key] = a_pt
    r_enc = signature[:32]
    r_pt = _pt_decompress(r_enc)
    if r_pt is None:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= L:
        return False  # non-canonical s: malleable under cofactored rules
    h = (
        int.from_bytes(
            hashlib.sha512(r_enc + public_key + message).digest(), "little"
        )
        % L
    )
    # Compare sB against R + hA in compressed form (one inversion each).
    lhs = _pt_compress(_pt_mul(s, _B_POINT))
    rhs = _pt_compress(_pt_add(r_pt, _pt_mul(h, a_pt)))
    return lhs == rhs


class PurePythonBackend(CryptoBackend):
    """CryptoBackend over the module-level verifier (exact-integer by
    default; the active scheme under a chaos trusted-crypto run). The
    chaos runner installs this so fault scenarios run the REAL
    verification flow (BatchVerificationService -> backend) on hosts
    with neither the OpenSSL wheel nor a warmed-up accelerator."""

    name = "pure-python"

    def verify_batch_mask(
        self,
        messages: Sequence[bytes],
        keys: Sequence[PublicKey],
        signatures: Sequence[Signature],
    ) -> list[bool]:
        out = []
        for msg, pk, sig in zip(messages, keys, signatures, strict=True):
            ok = verify(pk.data, msg, sig.data)
            if not ok:
                _M_REJECTS.inc()
            out.append(ok)
        return out


class PySignatureService:
    """Drop-in for crypto.service.SignatureService signing with the pure
    signer: same actor shape (queue + oneshot futures), no OpenSSL."""

    def __init__(self, seed: bytes) -> None:
        self._queue: asyncio.Queue = asyncio.Queue(100)
        self._task = spawn(self._run(seed), name="py-signature-service")

    async def _run(self, seed: bytes) -> None:
        while True:
            digest, fut = await self._queue.get()
            if not fut.cancelled():
                fut.set_result(Signature(sign(seed, digest.data)))

    async def request_signature(self, digest: Digest) -> Signature:
        fut = asyncio.get_running_loop().create_future()
        await self._queue.put((digest, fut))
        return await fut
