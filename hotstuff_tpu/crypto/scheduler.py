"""Continuous-batching device scheduler with preemptive priority lanes.

Every device-bound verification — QC/TC-critical consensus checks, mempool
bulk, sync/payload re-verification, and client ingress — used to funnel
through one set of per-service flush heuristics (batch_service._run_legacy):
a single queue, a single deadline, one `urgent` bit. That design has no
vocabulary for "ingress is latency-sensitive but not commit-critical" and
no way to size buckets against the device's alignment grid, so a bulk or
ingress flood and a quorum-sized QC check were fate-shared into the same
coalesced flushes.

This module is the LLM-serving continuous-batching pattern applied to the
verify plane (ROADMAP item 4): typed **sources**, each with a priority
class and latency SLO, feed one admission → bucket → dispatch loop:

  * **Preemptive critical lane.** Consensus-critical groups never wait out
    a lower-class flush timer: any pending critical work is drained and
    dispatched FIRST on every loop pass, bypassing the bulk dispatch bound
    entirely (small quorum batches ride the backend's CPU fast path, so
    unbounded critical dispatches are bounded in practice by the consensus
    message rate). A critical arrival also CLOSES the forming bulk bucket
    early — the formed groups ship right behind it instead of restarting
    their deadline, so preemption never re-delays bulk.
  * **Alignment-grid bucket sizing.** Bulk buckets are sized dynamically
    against the backend's bucket alignment (`TpuBackend.bucket_alignment`:
    `lane × ndev` on a mesh — parallel/mesh.py's `mesh_alignment` — or the
    single-chip `min_bucket`): once a full grid row of work is pending the
    bucket closes, so the device pays its padded lanes with real work in
    them. Backends with no grid (CPU, pure-python) fall back to
    deadline/size flushing alone.
  * **Continuous refill.** Bucket formation runs concurrently with the
    bounded in-flight dispatches: as one bucket dispatches, the next forms
    from whatever sources have work, so the device never idles between
    heterogeneous batches. Buckets are lane-ordered (sync before ingress
    before mempool) but may mix classes — per-group queueing delay is
    attributed to each group's own lane regardless.
  * **Cross-chip work stealing** (`n_backends > 1`). The owning service
    may register sibling shard backends (one TpuBackend per chip/mesh
    leg): each backend gets its own `bulk_concurrency` in-flight account
    mirroring its DispatchPipeline window (ops/pipeline.py), and a bulk
    bucket dispatches to the FIRST backend with a free slot, home (0)
    preferred — one service no longer feeds one backend while sibling
    pipelines idle. A non-home dispatch counts into `pipeline.steals`.
    Critical work always rides home (the committee-registered backend).
    Chaos/virtual-time services run `inline=True`, which forces
    n_backends=1 — bit-identical to the pre-stealing loop.

The scheduler owns admission, per-lane queueing, and bucket formation;
the owning BatchVerificationService stays the dispatch executor (dedup
cache, committee tagging, backend call, future resolution) — its public
`verify_group` API is a thin source-registration façade over `submit()`.

Observability: per-lane queueing-delay histograms (`scheduler.queue_<lane>_s`)
plus bucket/flush counters in the `scheduler.*` namespace, a per-service
`LaneStats` reservoir (the bench A/B and chaos expectations read p50/p99
from it), and `lane=`/`queue_s=` fields on every traced group's
`verify.batch` event so `tools/trace_report.py` attributes queueing delay
per class.

Deterministic by construction: no wall-clock reads (event-loop time only),
no threads of its own — under the chaos VirtualTimeLoop with `inline=True`
dispatch, a scheduled run replays bit-for-bit. `pace_s_per_sig` models
finite device occupancy in VIRTUAL time (a bucket of n signatures holds
the bulk pipeline for n×pace seconds), which is what makes queueing — and
therefore preemption — observable under a clock where Python work costs
zero virtual seconds.

Dependency-free: stdlib + utils.metrics/tracing only (no jax, no crypto).
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from dataclasses import dataclass
from typing import Callable

from ..utils import metrics

log = logging.getLogger("hotstuff.crypto")

__all__ = [
    "SourceClass",
    "SOURCE_CLASSES",
    "CONSENSUS",
    "AGGREGATE",
    "SYNC",
    "INGRESS",
    "MEMPOOL",
    "SchedulerConfig",
    "LaneStats",
    "DeviceScheduler",
    "resolve_source",
    "note_queue_delay",
    "drain_order",
]


@dataclass(frozen=True, slots=True)
class SourceClass:
    """One typed verification source: a priority class + latency SLO.

    `priority` orders lane draining (lower drains first); `slo_s` is the
    published queueing-delay target the per-lane histograms are judged
    against (advisory — reported, never enforced); `max_delay_s` bounds
    how long a forming bucket may wait for more work once this class has
    a group pending; `preemptive` marks the critical lane (immediate
    dispatch, bypasses the bulk bound, closes forming buckets early)."""

    name: str
    priority: int
    slo_s: float
    max_delay_s: float
    preemptive: bool = False


# The five registered sources (ISSUE 7 / ROADMAP item 4; ISSUE 13 filled
# the slot PR 7 left open). QC/TC/vote/proposal checks gate round
# advancement — preemptive, no flush timer. AGGREGATE is the overlay's
# partial-bundle verification (consensus/overlay.py): quorum-forming but
# mergeable-in-batches, so it rides the batched device path at a priority
# strictly between consensus and sync (a stalled round's bundles must not
# queue behind catch-up or ingress floods). Sync/payload re-verification
# un-stalls consensus availability — tight deadline. Ingress is client-
# latency-sensitive bulk; mempool is pure measurement load and starves
# first under pressure (the lane contract, mirroring ingress admission).
CONSENSUS = SourceClass("consensus", 0, slo_s=0.002, max_delay_s=0.0, preemptive=True)
AGGREGATE = SourceClass("aggregate", 1, slo_s=0.010, max_delay_s=0.0005)
SYNC = SourceClass("sync", 2, slo_s=0.020, max_delay_s=0.001)
INGRESS = SourceClass("ingress", 3, slo_s=0.100, max_delay_s=0.002)
MEMPOOL = SourceClass("mempool", 4, slo_s=0.500, max_delay_s=0.004)

SOURCE_CLASSES: dict[str, SourceClass] = {
    c.name: c for c in (CONSENSUS, AGGREGATE, SYNC, INGRESS, MEMPOOL)
}


def resolve_source(source: str | None, urgent: bool) -> SourceClass:
    """Map a verify_group call to its SourceClass. Explicit `source` wins;
    the legacy `urgent` bit keeps un-migrated callers working (urgent ==
    consensus-critical, everything else is mempool bulk)."""
    if source is not None:
        try:
            return SOURCE_CLASSES[source]
        except KeyError:
            raise ValueError(
                f"unknown verification source {source!r}; registered: "
                f"{sorted(SOURCE_CLASSES)}"
            ) from None
    return CONSENSUS if urgent else MEMPOOL


# Sub-resolution deadline guard (the utils/actors.py Timer.RESOLUTION_S
# class of livelock, observed live on the chaos virtual-time loop once
# the overlay's `aggregate` lane made batched deadlines common in
# consensus scenarios): when a pending deadline lands WITHIN the event
# loop clock's resolution of `now` (vtime jumps overshoot by 1e-9), the
# armed wait_for timer fires without the clock advancing, form_bucket
# still judges the deadline "strictly in the future", and the run loop
# re-arms forever at a frozen virtual instant. Deadlines within this
# bound count as DUE — in form_bucket and the run loop alike (the two
# must agree, or the loop waits for a deadline the bucket logic already
# considers expired). One microsecond is far below any max_delay_s.
RESOLUTION_S = 1e-6

_M_SUBMITTED = metrics.counter("scheduler.submitted")
# Cross-chip work stealing (ISSUE 9 / ROADMAP items 1+4): a bulk bucket
# dispatched to any backend other than the home backend 0 counts here —
# the pipeline.* namespace because the free-slot model mirrors each
# backend's DispatchPipeline window (ops/pipeline.py).
_M_STEALS = metrics.counter("pipeline.steals")
_M_DISPATCHED = metrics.counter("scheduler.dispatched_groups")
_M_BUCKETS = metrics.counter("scheduler.buckets")
_M_CRITICAL = metrics.counter("scheduler.critical_dispatches")
_M_SIZE_FLUSHES = metrics.counter("scheduler.size_flushes")
_M_GRID_FLUSHES = metrics.counter("scheduler.grid_flushes")
_M_DEADLINE_FLUSHES = metrics.counter("scheduler.deadline_flushes")
_M_PREEMPT_CLOSES = metrics.counter("scheduler.preempt_closes")
_M_DEPTH = metrics.gauge("scheduler.depth")
_M_BUCKET_SIZE = metrics.histogram("scheduler.bucket_size", metrics.SIZE_BUCKETS)
# Per-lane queueing delay (submit -> dequeue-into-a-bucket). The f-string
# keeps lane names and histogram rows in lockstep; the graftlint
# `scheduler` pass (python -m tools.graftlint)
# separately asserts every registered class has its row in the canonical
# namespace (the starvation lint's schema half).
_QUEUE_HIST = {
    name: metrics.histogram(f"scheduler.queue_{name}_s")
    for name in SOURCE_CLASSES
}


def note_queue_delay(lane_stats: "LaneStats", source: str, queue_s: float) -> None:
    """Record one group's queueing delay into the lane's global histogram
    and the service-local reservoir. Shared by the scheduler's dequeue and
    the legacy flush loop, so before/after attribution is comparable."""
    hist = _QUEUE_HIST.get(source)
    if hist is not None:
        hist.record(queue_s)
    lane_stats.note(source, queue_s)


class LaneStats:
    """Per-service per-lane queueing-delay reservoir.

    The global `scheduler.queue_<lane>_s` histograms aggregate across every
    service in the process; chaos scenarios and the bench A/B need
    PER-SERVICE percentiles (one node's critical lane, one A/B leg), so
    each BatchVerificationService keeps its own bounded sample ring here —
    both the scheduler and the legacy flush loop feed it, which is exactly
    what makes the before/after queueing attribution comparable.

    The ring ROTATES at CAP (oldest evicted) rather than saturating: the
    telemetry plane (utils/telemetry.py) windows per-snapshot deltas off
    `total()`'s monotonic count, and a saturating list would freeze its
    live lane SLOs for the rest of the process once a long-running node
    crossed CAP. `summary()` therefore describes the most recent CAP
    samples — every bench leg and chaos scenario stays well under that."""

    CAP = 65_536  # samples retained per lane (rotating window)

    def __init__(self) -> None:
        self._samples: dict[str, deque] = {
            name: deque(maxlen=self.CAP) for name in SOURCE_CLASSES
        }
        self._total: dict[str, int] = {name: 0 for name in SOURCE_CLASSES}

    def note(self, lane: str, queue_s: float) -> None:
        ring = self._samples.get(lane)
        if ring is None:
            ring = self._samples.setdefault(lane, deque(maxlen=self.CAP))
        ring.append(queue_s)
        self._total[lane] = self._total.get(lane, 0) + 1

    def lanes(self) -> list[str]:
        return list(self._samples)

    def total(self, lane: str) -> int:
        """Monotonic count of samples EVER noted for the lane — the
        telemetry plane's cursor basis, immune to ring rotation."""
        return self._total.get(lane, 0)

    def samples(self, lane: str) -> list[float]:
        """A copy of the lane's retained samples, oldest first (the last
        `total() - cursor` entries are the ones a telemetry window has
        not seen yet)."""
        return list(self._samples.get(lane, ()))

    def tail(self, lane: str, n: int) -> list[float]:
        """The most recent min(n, retained) samples, oldest first —
        O(n), so a telemetry window never pays a full-ring copy just to
        read a few fresh entries."""
        ring = self._samples.get(lane)
        if not ring or n <= 0:
            return []
        if n >= len(ring):
            return list(ring)
        out = [x for _, x in zip(range(n), reversed(ring))]
        out.reverse()
        return out

    def summary(self) -> dict[str, dict]:
        """{lane: {count, p50_ms, p99_ms, max_ms}} for lanes that saw work."""
        out = {}
        for lane, samples in self._samples.items():
            if not samples:
                continue
            ordered = sorted(samples)
            out[lane] = {
                "count": len(ordered),
                "p50_ms": round(metrics.percentile(ordered, 0.50) * 1e3, 3),
                "p99_ms": round(metrics.percentile(ordered, 0.99) * 1e3, 3),
                "max_ms": round(ordered[-1] * 1e3, 3),
            }
        return out


@dataclass(slots=True)
class SchedulerConfig:
    """Knobs beyond what the owning service already carries.

    `bulk_concurrency` bounds in-flight NON-critical buckets (2 = double
    buffering: stage the next bucket while one is on the device; more
    slots only add host-thread contention against the critical lane).
    `pace_s_per_sig` is the virtual device-occupancy model for chaos runs
    (0 = backend-bound, production)."""

    bulk_concurrency: int = 2
    pace_s_per_sig: float = 0.0


class _Lane:
    __slots__ = ("cls", "queue", "enqueued", "dispatched")

    def __init__(self, cls: SourceClass) -> None:
        self.cls = cls
        self.queue: deque = deque()
        self.enqueued = 0
        self.dispatched = 0


class DeviceScheduler:
    """The admission → bucket → dispatch loop.

    `dispatch(groups, total, critical)` is the owning service's executor
    hook (BatchVerificationService._spawn_dispatch): it must return the
    spawned task, whose completion frees a bulk slot. Groups only need
    `.source`, `.t_submit`, `.t_dequeue` and `__len__` — the scheduler
    never looks at messages or futures, which is what keeps the lint's
    drain-order simulation (and unit tests) dependency-free."""

    def __init__(
        self,
        dispatch: Callable[[list, int, bool], "asyncio.Task"],
        *,
        max_batch: int = 8192,
        alignment_fn: Callable[[], int] | None = None,
        config: SchedulerConfig | None = None,
        lane_stats: LaneStats | None = None,
        classes: tuple[SourceClass, ...] | None = None,
        n_backends: int = 1,
    ) -> None:
        self._dispatch = dispatch
        self.max_batch = max_batch
        self._alignment_fn = alignment_fn or (lambda: 0)
        self.config = config or SchedulerConfig()
        self.lane_stats = lane_stats or LaneStats()
        classes = classes or tuple(SOURCE_CLASSES.values())
        ordered = sorted(classes, key=lambda c: c.priority)
        self._critical = [c.name for c in ordered if c.preemptive]
        self._batched = [c.name for c in ordered if not c.preemptive]
        self.lanes: dict[str, _Lane] = {c.name: _Lane(c) for c in ordered}
        # Cross-chip work stealing: one bulk in-flight account per
        # dispatch target. Backend 0 is HOME (the committee-registered
        # primary every critical dispatch rides); targets 1..n-1 are the
        # steal shards — a bulk bucket goes to the first backend with a
        # free slot, home preferred, so one service no longer feeds one
        # backend while sibling pipelines idle. `bulk_concurrency` slots
        # per backend mirror each backend's DispatchPipeline window.
        # With n_backends == 1 the accounting and the dispatch-hook
        # arity are EXACTLY the pre-stealing behavior (the chaos
        # inline/virtual-time determinism contract, §5.5i).
        self.n_backends = max(1, n_backends)
        self._inflight = [0] * self.n_backends
        self._wake: asyncio.Event | None = None  # bound lazily to the loop
        self.stats = {
            "submitted": 0,
            "buckets": 0,
            "critical_dispatches": 0,
            "preempt_closes": 0,
            "steals": 0,
        }

    @property
    def _inflight_bulk(self) -> int:
        """Total bulk dispatches in flight across every backend."""
        return sum(self._inflight)

    def _pick_backend(self) -> int | None:
        """First backend with a free bulk slot, home (0) preferred; None
        while every pipeline window is full (the loop then waits)."""
        for idx in range(self.n_backends):
            if self._inflight[idx] < self.config.bulk_concurrency:
                return idx
        return None

    # -- admission -----------------------------------------------------------

    def submit(self, group) -> None:
        """Admit one group into its lane (synchronous — lanes are unbounded
        like the legacy queue; backpressure stays with the callers, e.g.
        ingress admission and the mempool's verify semaphores)."""
        self.lanes[group.source].queue.append(group)
        self.lanes[group.source].enqueued += 1
        self.stats["submitted"] += 1
        _M_SUBMITTED.inc()
        _M_DEPTH.set(self.depth())
        if self._wake is not None:
            self._wake.set()

    def depth(self) -> int:
        return sum(len(lane.queue) for lane in self.lanes.values())

    # -- bucket formation (pure: unit-testable, reused by the lint) ----------

    def _take(self, group, now: float, bucket: list) -> None:
        group.t_dequeue = now
        lane = self.lanes[group.source]
        lane.dispatched += 1
        note_queue_delay(self.lane_stats, group.source, max(0.0, now - group.t_submit))
        bucket.append(group)

    def drain_critical(self, now: float) -> list:
        """Pop EVERY pending preemptive-lane group (they coalesce into one
        hot bucket — simultaneous QC + vote checks still flush together)."""
        out: list = []
        for name in self._critical:
            queue = self.lanes[name].queue
            while queue:
                self._take(queue.popleft(), now, out)
        return out

    def form_bucket(self, now: float, force: bool = False) -> tuple[list, str] | None:
        """Close and return one batched-lane bucket, or None if the loop
        should keep waiting. Close conditions, in order:

          * `force`   — a critical dispatch just preempted the forming
                        bucket: ship what has accumulated (preempt close).
          * size      — pending work fills max_batch.
          * grid      — a full device alignment row is pending (zero pad
                        waste; alignment 0 disables this trigger).
          * deadline  — the oldest pending group aged past its class's
                        max_delay_s (bounds p99 at low rates, and bounds
                        starvation of the lowest lane: its deadline forces
                        a flush that drains lanes in priority order).

        Groups are indivisible (one future per group), so the last group
        taken may overshoot the grid target; it never overshoots max_batch
        unless it is single-handedly larger than max_batch."""
        pending = sum(
            len(g) for name in self._batched for g in self.lanes[name].queue
        )
        if pending == 0:
            return None
        reason = None
        target = self.max_batch
        if force:
            reason = "preempt"
        elif pending >= self.max_batch:
            reason = "size"
        else:
            align = self._alignment_fn()
            if align > 0 and pending >= align:
                # Close at the largest full grid multiple and leave the
                # remainder forming: the dispatched bucket pads zero lanes,
                # and the residue's own deadline still bounds its wait.
                reason = "grid"
                target = (pending // align) * align
            else:
                deadline = self._next_deadline()
                if deadline is not None and now >= deadline - RESOLUTION_S:
                    reason = "deadline"
        if reason is None:
            return None
        bucket: list = []
        total = 0
        for name in self._batched:
            queue = self.lanes[name].queue
            while queue and (total < target or not bucket):
                g = queue.popleft()
                self._take(g, now, bucket)
                total += len(g)
            if total >= target:
                break
        return bucket, reason

    def _next_deadline(self) -> float | None:
        """Earliest (t_submit + class max_delay) across pending batched
        groups — FIFO lanes mean only each lane's head matters."""
        deadline = None
        for name in self._batched:
            lane = self.lanes[name]
            if lane.queue:
                d = lane.queue[0].t_submit + lane.cls.max_delay_s
                if deadline is None or d < deadline:
                    deadline = d
        return deadline

    # -- dispatch loop -------------------------------------------------------

    def note_bulk_done(self, _task=None, backend: int = 0) -> None:
        """Done-callback for non-critical dispatch tasks: frees the
        backend's bulk slot and wakes the loop so the next bucket can
        ship (continuous refill)."""
        self._inflight[backend] -= 1
        if self._wake is not None:
            self._wake.set()

    def _ship_critical(self, now: float) -> bool:
        hot = self.drain_critical(now)
        if not hot:
            return False
        self.stats["critical_dispatches"] += 1
        _M_CRITICAL.inc()
        _M_DISPATCHED.inc(len(hot))
        _M_DEPTH.set(self.depth())
        # Bypasses the bulk bound AND the pace model: critical work is
        # never delayed by a lower-class flush timer or a busy bulk
        # pipeline (small quorum batches ride the backend's CPU fast path).
        self._dispatch(hot, sum(len(g) for g in hot), True)
        return True

    async def _pace_busy(self, dur: float, loop) -> None:
        """Hold the bulk pipeline busy for `dur` seconds of loop time
        (virtual under chaos) without ever delaying the critical lane:
        wake-ups inside the window ship any pending critical work, then
        the remaining occupancy elapses."""
        end = loop.time() + dur
        while True:
            remaining = end - loop.time()
            if remaining <= RESOLUTION_S:
                return  # sub-resolution remainder: same livelock class
            self._wake.clear()
            try:
                await asyncio.wait_for(self._wake.wait(), remaining)
            except asyncio.TimeoutError:
                return
            self._ship_critical(loop.time())

    async def run(self) -> None:
        """The single admission → bucket → dispatch loop. Spawned by the
        owning service (actors.spawn, so a chaos crash-restart of a node
        tears it down with the rest of the node's task tree)."""
        loop = asyncio.get_running_loop()
        if self._wake is None:
            self._wake = asyncio.Event()
        pace = self.config.pace_s_per_sig
        while True:
            now = loop.time()
            # 1. Critical lane first, always; remember whether it preempted
            #    a forming (non-empty, not-yet-closed) batched backlog.
            preempted = self._ship_critical(now)
            # 2. One batched bucket, if any backend has a free slot and a
            #    close condition holds (a preempt close ships the formed
            #    groups immediately so the critical jump never re-delays
            #    them). Home backend preferred; a bucket shipped to a
            #    sibling shard while home's pipeline window is full is a
            #    STEAL (pipeline.steals).
            target = self._pick_backend()
            if target is not None:
                formed = self.form_bucket(now, force=preempted)
                if formed is not None:
                    bucket, reason = formed
                    total = sum(len(g) for g in bucket)
                    self.stats["buckets"] += 1
                    _M_BUCKETS.inc()
                    _M_DISPATCHED.inc(len(bucket))
                    _M_BUCKET_SIZE.record(total)
                    _M_DEPTH.set(self.depth())
                    if reason == "preempt":
                        self.stats["preempt_closes"] += 1
                        _M_PREEMPT_CLOSES.inc()
                    elif reason == "size":
                        _M_SIZE_FLUSHES.inc()
                    elif reason == "grid":
                        _M_GRID_FLUSHES.inc()
                    else:
                        _M_DEADLINE_FLUSHES.inc()
                    self._inflight[target] += 1
                    if target != 0:
                        self.stats["steals"] += 1
                        _M_STEALS.inc()
                    if self.n_backends == 1:
                        # Pre-stealing arity: single-backend dispatch
                        # hooks (and the lint's drain-order stub) never
                        # see a target index.
                        task = self._dispatch(bucket, total, False)
                        task.add_done_callback(self.note_bulk_done)
                    else:
                        task = self._dispatch(bucket, total, False, target)
                        task.add_done_callback(
                            lambda t, b=target: self.note_bulk_done(t, b)
                        )
                    if pace > 0.0:
                        # Virtual device-occupancy model (chaos): the bulk
                        # pipeline is busy for total*pace seconds — but the
                        # sleep is PREEMPTIBLE: a critical arrival ships
                        # mid-occupancy, then the remainder elapses.
                        await self._pace_busy(total * pace, loop)
                    continue
            # 3. Nothing dispatchable: wait for new work, a freed bulk
            #    slot, or the earliest pending deadline. form_bucket only
            #    returns None while every pending deadline is more than
            #    RESOLUTION_S in the future, so the armed timeout always
            #    exceeds the loop clock's resolution (no sub-resolution
            #    re-arm livelock under the virtual clock — see
            #    RESOLUTION_S above).
            self._wake.clear()
            if self.depth() > 0 and self._ship_critical(loop.time()):
                continue  # raced a critical submit against the clear
            deadline = self._next_deadline()
            waitable = self._pick_backend() is not None
            timeout = None
            if deadline is not None and waitable:
                # form_bucket returned None, so the deadline is more than
                # RESOLUTION_S away; the floor keeps the armed timer past
                # the loop clock's resolution regardless (see RESOLUTION_S).
                timeout = max(deadline - loop.time(), RESOLUTION_S)
            try:
                await asyncio.wait_for(self._wake.wait(), timeout)
            except asyncio.TimeoutError:
                pass

    def summary(self) -> dict:
        """Structured per-lane snapshot (chaos reports embed one per node)."""
        return {
            "backends": self.n_backends,
            "inflight": list(self._inflight),
            "lanes": {
                name: {
                    "priority": lane.cls.priority,
                    "slo_ms": round(lane.cls.slo_s * 1e3, 3),
                    "enqueued": lane.enqueued,
                    "dispatched": lane.dispatched,
                    "depth": len(lane.queue),
                }
                for name, lane in self.lanes.items()
            },
            "queue_delay": self.lane_stats.summary(),
            **self.stats,
        }


# ---------------------------------------------------------------------------
# Starvation lint support (the graftlint `scheduler` pass)


class _StubGroup:
    """Minimal group shape for the drain-order simulation: the scheduler's
    formation logic only reads source/t_submit/len()."""

    __slots__ = ("source", "t_submit", "t_dequeue", "n")

    def __init__(self, source: str, t_submit: float, n: int = 1) -> None:
        self.source = source
        self.t_submit = t_submit
        self.t_dequeue = 0.0
        self.n = n

    def __len__(self) -> int:
        return self.n


def drain_order(classes: tuple[SourceClass, ...] | None = None) -> list[str]:
    """Simulate the loop's selection over one group per registered class
    with NO further arrivals, advancing a synthetic clock past each pending
    deadline, and return the lane names in the order their groups were
    dequeued. A registered class missing from the result can be enqueued
    but never selected — the starvation condition the graftlint
    `scheduler` pass
    fails the build on (rc 1)."""
    sched = DeviceScheduler(lambda groups, total, critical: None)
    classes = classes or tuple(SOURCE_CLASSES.values())
    now = 0.0
    for cls in classes:
        sched.submit(_StubGroup(cls.name, now))
    order: list[str] = []
    for _ in range(4 * len(classes) + 4):  # bounded: no arrivals, must drain
        for g in sched.drain_critical(now):
            order.append(g.source)
        formed = sched.form_bucket(now)
        if formed is not None:
            order.extend(g.source for g in formed[0])
        if sched.depth() == 0:
            break
        deadline = sched._next_deadline()
        now = (deadline if deadline is not None else now) + 1e-6
    return order
