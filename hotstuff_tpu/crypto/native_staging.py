"""ctypes bridge to the C++ batch-staging plane (native/staging.cpp).

Builds the shared library on first use (g++ -O3, cached next to the
source), falling back to the pure-Python staging in ops/ed25519 when a
toolchain is unavailable. This is the native data-plane component the
reference gets from Rust (SURVEY.md §2: each crate maps to a native
equivalent); the control flow stays in Python, the per-byte work in C++.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import pathlib
import subprocess
import threading

import numpy as np

log = logging.getLogger(__name__)

_NATIVE_DIR = pathlib.Path(__file__).resolve().parents[2] / "native"
_SO_PATH = _NATIVE_DIR / "libhotstuff_native.so"
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> pathlib.Path | None:
    srcs = [_NATIVE_DIR / "staging.cpp", _NATIVE_DIR / "store.cpp"]
    hdr = _NATIVE_DIR / "constants.h"
    srcs = [s for s in srcs if s.exists()]
    if not srcs:
        return None
    try:
        if not hdr.exists():
            subprocess.run(
                ["python", str(_NATIVE_DIR / "gen_constants.py")],
                check=True,
                capture_output=True,
            )
        # Gate rebuilds on a content hash of the sources, not mtimes:
        # git checkouts reset mtimes, so an mtime check can silently load
        # a stale artifact that no longer matches the sources.
        digest = hashlib.sha256()
        for s in [*srcs, hdr]:
            digest.update(s.name.encode())
            digest.update(s.read_bytes())
        want = digest.hexdigest()
        stamp = _SO_PATH.with_suffix(".so.hash")
        # Cross-PROCESS lock: a local committee boots N nodes concurrently
        # and each may attempt the build; without it, parallel g++ runs
        # clobber the .so while another process dlopens it.
        import fcntl

        with open(_NATIVE_DIR / ".build.lock", "w") as lock_f:
            fcntl.flock(lock_f, fcntl.LOCK_EX)
            have = stamp.read_text().strip() if stamp.exists() else None
            if not _SO_PATH.exists() or have != want:
                tmp = _SO_PATH.with_suffix(f".so.tmp{os.getpid()}")
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17"]
                    + [str(s) for s in srcs]
                    + ["-o", str(tmp)],
                    check=True,
                    capture_output=True,
                )
                tmp.replace(_SO_PATH)
                stamp.write_text(want + "\n")
        return _SO_PATH
    except (subprocess.CalledProcessError, OSError) as e:
        log.warning("native build failed, using Python path: %s", e)
        return None


def get_lib():
    """The loaded native library, or None (build failure / no toolchain)."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(str(so))
        except OSError as e:  # corrupt/partial artifact must not kill boot
            log.warning("loading native library failed, using Python path: %s", e)
            return None
        lib.hs_stage_batch.restype = ctypes.c_int
        lib.hs_stage_batch_packed.restype = ctypes.c_int
        # store engine (native/store.cpp)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.hs_store_open.restype = ctypes.c_void_p
        lib.hs_store_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.hs_store_write.restype = ctypes.c_int
        lib.hs_store_write.argtypes = [
            ctypes.c_void_p, u8p, ctypes.c_int64, u8p, ctypes.c_int64,
        ]
        lib.hs_store_read.restype = ctypes.c_int64
        lib.hs_store_read.argtypes = [
            ctypes.c_void_p, u8p, ctypes.c_int64, ctypes.POINTER(u8p),
        ]
        lib.hs_store_contains.restype = ctypes.c_int
        lib.hs_store_contains.argtypes = [ctypes.c_void_p, u8p, ctypes.c_int64]
        lib.hs_store_len.restype = ctypes.c_int64
        lib.hs_store_len.argtypes = [ctypes.c_void_p]
        lib.hs_store_compact.restype = ctypes.c_int64
        lib.hs_store_compact.argtypes = [ctypes.c_void_p]
        lib.hs_store_close.restype = None
        lib.hs_store_close.argtypes = [ctypes.c_void_p]
        lib.hs_free.restype = None
        lib.hs_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def stage_batch_packed(messages, keys, signatures) -> dict | None:
    """Native packed staging: one (128, n) u8 wire array (rows 0-31 A,
    32-63 R, 64-95 S, 96-127 h) + host-side s<L mask. 128 B/signature on
    the host->device link vs 772 B for the f32 form (`stage_batch`)."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(messages)
    msg_blob = b"".join(messages)
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum([len(m) for m in messages], out=offsets[1:])
    msgs = np.frombuffer(msg_blob, np.uint8)
    keys_arr = np.frombuffer(b"".join(keys), np.uint8)
    sigs_arr = np.frombuffer(b"".join(signatures), np.uint8)

    packed = np.empty((128, n), np.uint8)
    s_ok = np.empty(n, np.uint8)

    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    rc = lib.hs_stage_batch_packed(
        msgs.ctypes.data_as(u8p),
        offsets.ctypes.data_as(i64p),
        keys_arr.ctypes.data_as(u8p),
        sigs_arr.ctypes.data_as(u8p),
        ctypes.c_int64(n),
        packed.ctypes.data_as(u8p),
        s_ok.ctypes.data_as(u8p),
    )
    if rc != 0:
        return None
    return dict(packed=packed, s_ok=s_ok.astype(bool))


def stage_batch(messages, keys, signatures) -> dict | None:
    """Native equivalent of ops.ed25519.prepare_batch (same dict contract,
    minus the bit arrays used only by the legacy bit-ladder kernel)."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(messages)
    msg_blob = b"".join(messages)
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum([len(m) for m in messages], out=offsets[1:])
    msgs = np.frombuffer(msg_blob, np.uint8)
    keys_arr = np.frombuffer(b"".join(keys), np.uint8)
    sigs_arr = np.frombuffer(b"".join(signatures), np.uint8)

    a_y = np.empty((32, n), np.float32)
    a_sign = np.empty(n, np.float32)
    r_enc = np.empty((32, n), np.float32)
    s_digits = np.empty((64, n), np.float32)
    h_digits = np.empty((64, n), np.float32)
    s_ok = np.empty(n, np.uint8)

    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32p = ctypes.POINTER(ctypes.c_float)

    def p(arr, ty):
        return arr.ctypes.data_as(ty)

    rc = lib.hs_stage_batch(
        p(msgs, u8p),
        p(offsets, i64p),
        p(keys_arr, u8p),
        p(sigs_arr, u8p),
        ctypes.c_int64(n),
        p(a_y, f32p),
        p(a_sign, f32p),
        p(r_enc, f32p),
        p(s_digits, f32p),
        p(h_digits, f32p),
        p(s_ok, u8p),
    )
    if rc != 0:
        return None
    return dict(
        a_y=a_y,
        a_sign=a_sign,
        r_enc=r_enc,
        s_digits=s_digits,
        h_digits=h_digits,
        s_ok=s_ok.astype(bool),
    )
