"""SignatureService: the signing actor.

Mirrors the reference's SignatureService (crypto/src/lib.rs:226-252): an actor
owns the secret key and serves signing requests over a channel with oneshot
replies. The request/reply seam is deliberately async so a remote accelerator
(or a native signer thread) can sit behind the same interface.
"""

from __future__ import annotations

import asyncio

from .primitives import Digest, SecretKey, Signature
from ..utils.actors import spawn


class SignatureService:
    """Clone-able signing handle backed by a single signer task."""

    def __init__(self, secret: SecretKey) -> None:
        self._queue: asyncio.Queue = asyncio.Queue(100)
        self._task = spawn(self._run(secret), name="signature-service")

    async def _run(self, secret: SecretKey) -> None:
        key = secret.to_crypto()
        while True:
            digest, fut = await self._queue.get()
            if not fut.cancelled():
                fut.set_result(Signature(key.sign(digest.data)))

    async def request_signature(self, digest: Digest) -> Signature:
        fut = asyncio.get_running_loop().create_future()
        await self._queue.put((digest, fut))
        return await fut
