"""Core crypto value types: digests, ed25519 keys and signatures.

Capability parity with the reference `crypto` crate (crypto/src/lib.rs:20-224):
  * Digest        -- 32-byte content hash with base64 display   (lib.rs:20-59)
  * PublicKey     -- 32-byte ed25519 public key, base64 serde   (lib.rs:62-108)
  * SecretKey     -- ed25519 secret key, zeroized on drop       (lib.rs:110-164)
  * Signature     -- 64-byte ed25519 signature over a Digest    (lib.rs:166-224)
  * generate_keypair(seeded rng) / generate_production_keypair  (lib.rs:156-164)

Single verification uses the host CPU (OpenSSL via `cryptography`); the batch
paths (`Signature.verify_batch` / `verify_batch_alt`, mirroring lib.rs:194-220)
dispatch through the pluggable CryptoBackend so they can run vmapped on TPU.
"""

from __future__ import annotations

import base64
import hashlib
import os
from dataclasses import dataclass
from typing import Iterable, Protocol, Sequence

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )

    HAVE_CRYPTOGRAPHY = True
except ImportError:  # host OpenSSL wheel absent: value types stay usable
    HAVE_CRYPTOGRAPHY = False
    Ed25519PrivateKey = Ed25519PublicKey = None

    class InvalidSignature(Exception):
        """Stand-in for cryptography.exceptions.InvalidSignature."""


_MISSING_CRYPTOGRAPHY_MSG = (
    "the 'cryptography' package is not installed on this host; "
    "host-side ed25519 signing/verification (SecretKey.to_crypto, "
    "Signature.new/verify, CpuBackend) is unavailable. Install it with "
    "`pip install cryptography`, or route batch verification through a "
    "backend that does not need host OpenSSL (e.g. --crypto tpu|remote)."
)


def require_cryptography() -> None:
    """Raise a clear ImportError when host ed25519 ops are requested on a
    host without the `cryptography` wheel (tests importorskip on this)."""
    if not HAVE_CRYPTOGRAPHY:
        raise ImportError(_MISSING_CRYPTOGRAPHY_MSG)


def sha512_32(data: bytes) -> bytes:
    """SHA-512 truncated to 32 bytes -- the reference's digest function
    (consensus/src/messages.rs digest() impls use Sha512 -> [u8;32])."""
    return hashlib.sha512(data).digest()[:32]


def _b64(data: bytes) -> str:
    return base64.standard_b64encode(data).decode("ascii")


@dataclass(frozen=True, slots=True)
class Digest:
    """32-byte content hash (reference crypto/src/lib.rs:20-59)."""

    data: bytes

    SIZE = 32

    def __post_init__(self) -> None:
        if len(self.data) != self.SIZE:
            raise ValueError(f"Digest must be {self.SIZE} bytes, got {len(self.data)}")

    @staticmethod
    def of(data: bytes) -> "Digest":
        return Digest(sha512_32(data))

    @staticmethod
    def zero() -> "Digest":
        return Digest(bytes(Digest.SIZE))

    def __str__(self) -> str:  # base64 like the reference Display impl
        return _b64(self.data)

    def short(self) -> str:
        """First 8 chars of base64 -- used in log lines for readability."""
        return _b64(self.data)[:8]

    def __repr__(self) -> str:
        return f"Digest({_b64(self.data)})"


class Hashable(Protocol):
    """The reference `Hash` trait (crypto/src/lib.rs:55-59)."""

    def digest(self) -> Digest: ...


@dataclass(frozen=True, slots=True)
class PublicKey:
    """ed25519 public key, 32 bytes (reference crypto/src/lib.rs:62-108)."""

    data: bytes

    SIZE = 32

    def __post_init__(self) -> None:
        if len(self.data) != self.SIZE:
            raise ValueError(f"PublicKey must be {self.SIZE} bytes")

    def encode_base64(self) -> str:
        return _b64(self.data)

    @staticmethod
    def decode_base64(s: str) -> "PublicKey":
        return PublicKey(base64.standard_b64decode(s))

    def __str__(self) -> str:
        return self.encode_base64()

    def short(self) -> str:
        return self.encode_base64()[:8]

    def __lt__(self, other: "PublicKey") -> bool:
        return self.data < other.data

    def to_crypto(self) -> Ed25519PublicKey:
        require_cryptography()
        return Ed25519PublicKey.from_public_bytes(self.data)


class SecretKey:
    """ed25519 secret key (32-byte seed). Best-effort zeroized on drop,
    mirroring the reference's Drop impl (crypto/src/lib.rs:146-153)."""

    SIZE = 32

    def __init__(self, seed: bytes) -> None:
        if len(seed) != self.SIZE:
            raise ValueError(f"SecretKey must be {self.SIZE} bytes")
        self._seed = bytearray(seed)

    @property
    def data(self) -> bytes:
        return bytes(self._seed)

    def encode_base64(self) -> str:
        return _b64(bytes(self._seed))

    @staticmethod
    def decode_base64(s: str) -> "SecretKey":
        return SecretKey(base64.standard_b64decode(s))

    def to_crypto(self) -> Ed25519PrivateKey:
        require_cryptography()
        return Ed25519PrivateKey.from_private_bytes(bytes(self._seed))

    def __del__(self) -> None:
        for i in range(len(self._seed)):
            self._seed[i] = 0


KeyPair = tuple[PublicKey, SecretKey]


def generate_keypair(rng) -> KeyPair:
    """Deterministic keypair from a seeded `random.Random` (or any object with
    `.randbytes`). Mirrors generate_keypair(csprng) (crypto/src/lib.rs:156-158),
    which tests seed with StdRng::from_seed([0;32])."""
    seed = rng.randbytes(32)
    return _keypair_from_seed(seed)


def generate_production_keypair() -> KeyPair:
    """OS-entropy keypair (crypto/src/lib.rs:161-164)."""
    # graftlint: allow[determinism] production entropy by contract; seeded paths use generate_keypair(rng)
    return _keypair_from_seed(os.urandom(32))


def _keypair_from_seed(seed: bytes) -> KeyPair:
    sk = SecretKey(seed)
    pub = sk.to_crypto().public_key().public_bytes_raw()
    return PublicKey(pub), sk


@dataclass(frozen=True, slots=True)
class Signature:
    """ed25519 signature over a Digest's 32 bytes (crypto/src/lib.rs:166-224).

    The reference splits the 64 bytes into two 32-byte halves (part1/part2) for
    serde; we keep the flat 64 bytes and expose `flatten()` for parity.
    """

    data: bytes

    SIZE = 64

    def __post_init__(self) -> None:
        if len(self.data) != self.SIZE:
            raise ValueError(f"Signature must be {self.SIZE} bytes")

    @staticmethod
    def new(digest: Digest, secret: SecretKey) -> "Signature":
        sig = secret.to_crypto().sign(digest.data)
        return Signature(sig)

    def flatten(self) -> bytes:
        return self.data

    def verify(self, digest: Digest, public_key: PublicKey) -> bool:
        """Single strict verification (crypto/src/lib.rs:186-192)."""
        try:
            public_key.to_crypto().verify(self.data, digest.data)
            return True
        except InvalidSignature:
            return False
        except ValueError:
            return False  # malformed public key bytes

    @staticmethod
    def verify_batch(
        digest: Digest, votes: Iterable[tuple[PublicKey, "Signature"]]
    ) -> bool:
        """Many signatures over ONE message -- the QC::verify path
        (crypto/src/lib.rs:194-207, consensus/src/messages.rs:197).
        Dispatches through the active CryptoBackend."""
        from .backend import get_backend

        votes = list(votes)
        return get_backend().verify_batch(
            [digest.data] * len(votes),
            [pk for pk, _ in votes],
            [sig for _, sig in votes],
        )

    @staticmethod
    def verify_batch_alt(
        messages: Sequence[bytes],
        keys_sigs: Sequence[tuple[PublicKey, "Signature"]],
    ) -> bool:
        """Many signatures over DISTINCT messages -- the fork's mempool
        workload (crypto/src/lib.rs:209-220, mempool/src/core.rs:135-148).
        Dispatches through the active CryptoBackend."""
        from .backend import get_backend

        if len(messages) != len(keys_sigs):
            raise ValueError("messages and signatures length mismatch")
        return get_backend().verify_batch(
            list(messages),
            [pk for pk, _ in keys_sigs],
            [sig for _, sig in keys_sigs],
        )
