"""Aggregatable signatures: exact pure-python BLS12-381 (min-pk) plus the
scheme seam the certificate plane verifies through.

Why this exists (ISSUE 17 / ROADMAP item 2): every QC/TC/bundle used to
carry one 96 B Ed25519 entry PER AUTHOR, so certificate bytes and verify
cost both grew O(n) with the committee. BLS signatures add: a partial
quorum is ONE curve point, and a certificate is one aggregate signature
plus a committee bitmap — O(1) bytes at any committee size (the
EdDSA-vs-BLS committee-consensus trade measured in arXiv:2302.00418).

Like `pysigner` for Ed25519, this module is the EXACT, dependency-free
reference implementation: plain-integer BLS12-381 with the optimal ate
pairing, importable on hosts with no jax and no `cryptography` wheel
(the graftlint import-boundary contract for everything chaos-reachable).
It is deliberately slow (~0.1-0.3 s per pairing on one core) — unit
tests and the `bench.py --aggregate-ab` artifact run it; virtual-time
fleets install the trusted-stub aggregate analogue
(chaos/trusted_crypto.TrustedAggScheme) through `install_agg_scheme`,
and the device path (`ops/bls.py`) accelerates the point-aggregation
half over committee-resident tables.

Curve layout (min-pk, the Ethereum/ZCash convention):
  * secret keys are scalars mod r;
  * public keys live in G1 (48 B compressed) — so committee tables on
    the device need only Fp arithmetic;
  * signatures/messages live in G2 (96 B compressed), hashed by
    deterministic try-and-increment + cofactor clearing.

Scheme-interface contract (ExactBlsScheme and every stand-in):
  keypair_from_seed(seed) -> (pk_bytes, sk); sign(sk, msg) -> sig;
  combine(a, b) / aggregate([...]) merge PARTIAL aggregates without any
  secret (public aggregation — what lets overlay interior nodes merge
  in place); verify(pks, msg, sig) checks a same-message aggregate;
  verify_groups([(pks, msg), ...], sig) checks a multi-message
  aggregate (the TC form: one aggregate signature spanning the distinct
  high-qc-round digests).

Trust model note: pk registration (install_agg_registry) is the
proof-of-possession boundary — rogue-key aggregation is prevented by
only ever resolving aggregate keys through the registry that the
deployment populated from its own key ceremony (chaos derives both key
families from the same node seeds).
"""

from __future__ import annotations

import hashlib
import math
import struct

# --------------------------------------------------------------------------
# BLS12-381 parameters (the u-parametrized family; u is the Miller-loop
# count, p and r derive from it — both asserted below so a typo in any
# constant fails at import, not in a wrong-answer pairing).

X_PARAM = -0xD201000000010000  # the BLS12 curve parameter u (negative)

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R_ORDER = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

_U = -X_PARAM
assert R_ORDER == _U**4 - _U**2 + 1, "r != u^4 - u^2 + 1"
assert (
    P == (X_PARAM - 1) ** 2 * R_ORDER // 3 + X_PARAM
), "p != ((u-1)^2 r)/3 + u"
assert P % 4 == 3  # Fp sqrt via the (p+1)/4 exponent

B_G1 = 4  # E:  y^2 = x^3 + 4          over Fp
B_G2 = (4, 4)  # E': y^2 = x^3 + 4(1+i)  over Fp2 (the M-twist)

# Standard generators (ZCash serialization spec test vectors).
G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

KEY_DOMAIN = b"hotstuff-aggsig-key-v1:"  # seed -> scalar derivation
DST_DOMAIN = b"hotstuff-aggsig-g2-v1:"  # hash-to-G2 domain separation

PK_BYTES = 48
SIG_BYTES = 96

# Certificate bitmaps are FIXED 64 bytes on the wire: one bit per member
# of the round's sorted committee, sized for the ROADMAP's 512-node
# stretch goal. Fixed (not length-prefixed by committee size) on
# purpose — it makes the aggregate certificate byte size a constant of
# the protocol, which is exactly the O(1) claim the matrix measures.
AGG_BITMAP_BYTES = 64
MAX_AGG_COMMITTEE = AGG_BITMAP_BYTES * 8


# --------------------------------------------------------------------------
# Fp and Fp2 arithmetic (plain ints / int pairs)


def _inv(a: int) -> int:
    return pow(a, P - 2, P)


def _fp2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def _fp2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def _fp2_neg(a):
    return (-a[0] % P, -a[1] % P)


def _fp2_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = a0 * b0
    t1 = a1 * b1
    return ((t0 - t1) % P, ((a0 + a1) * (b0 + b1) - t0 - t1) % P)


def _fp2_sqr(a):
    a0, a1 = a
    return ((a0 + a1) * (a0 - a1) % P, 2 * a0 * a1 % P)


def _fp2_scalar(a, k: int):
    return (a[0] * k % P, a[1] * k % P)


def _fp2_conj(a):
    return (a[0], -a[1] % P)


def _fp2_inv(a):
    a0, a1 = a
    norm_inv = _inv((a0 * a0 + a1 * a1) % P)
    return (a0 * norm_inv % P, -a1 * norm_inv % P)


def _fp2_pow(a, e: int):
    result = (1, 0)
    base = a
    while e:
        if e & 1:
            result = _fp2_mul(result, base)
        base = _fp2_sqr(base)
        e >>= 1
    return result


FP2_ONE = (1, 0)
FP2_ZERO = (0, 0)
XI = (1, 1)  # the sextic non-residue 1 + i (tower: v^3 = XI, w^2 = v)


def _fp2_sqrt(a):
    """Tonelli-Shanks over Fp2 (group order p^2 - 1 has 2-adicity 3 for
    this p). Returns a square root or None. Deterministic: the
    non-residue is found by a fixed small scan, never sampled."""
    if a == FP2_ZERO:
        return FP2_ZERO
    q = P * P
    # q - 1 = 2^3 * Q with Q odd
    s, Q = 3, (q - 1) >> 3
    z = _FP2_NONRESIDUE
    m = s
    c = _fp2_pow(z, Q)
    t = _fp2_pow(a, Q)
    rt = _fp2_pow(a, (Q + 1) >> 1)
    while t != FP2_ONE:
        # find least i with t^(2^i) == 1
        i, probe = 0, t
        while probe != FP2_ONE:
            probe = _fp2_sqr(probe)
            i += 1
            if i == m:
                return None  # not a square
        b = c
        for _ in range(m - i - 1):
            b = _fp2_sqr(b)
        m = i
        c = _fp2_sqr(b)
        t = _fp2_mul(t, c)
        rt = _fp2_mul(rt, b)
    return rt


def _find_fp2_nonresidue():
    euler = (P * P - 1) >> 1
    for a0, a1 in ((1, 1), (2, 1), (1, 2), (3, 1), (2, 3), (5, 2)):
        if _fp2_pow((a0, a1), euler) != FP2_ONE:
            return (a0, a1)
    raise AssertionError("no small Fp2 non-residue found")


_FP2_NONRESIDUE = _find_fp2_nonresidue()


# --------------------------------------------------------------------------
# Fp6 = Fp2[v]/(v^3 - XI), Fp12 = Fp6[w]/(w^2 - v); elements are nested
# tuples ((c0, c1, c2), ...) of Fp2 pairs.


def _fp6_add(a, b):
    return tuple(_fp2_add(x, y) for x, y in zip(a, b))


def _fp6_sub(a, b):
    return tuple(_fp2_sub(x, y) for x, y in zip(a, b))


def _fp6_neg(a):
    return tuple(_fp2_neg(x) for x in a)


def _fp6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = _fp2_mul(a0, b0)
    t1 = _fp2_mul(a1, b1)
    t2 = _fp2_mul(a2, b2)
    c0 = _fp2_add(
        t0,
        _fp2_mul(
            XI,
            _fp2_sub(
                _fp2_mul(_fp2_add(a1, a2), _fp2_add(b1, b2)), _fp2_add(t1, t2)
            ),
        ),
    )
    c1 = _fp2_add(
        _fp2_sub(
            _fp2_mul(_fp2_add(a0, a1), _fp2_add(b0, b1)), _fp2_add(t0, t1)
        ),
        _fp2_mul(XI, t2),
    )
    c2 = _fp2_add(
        _fp2_sub(
            _fp2_mul(_fp2_add(a0, a2), _fp2_add(b0, b2)), _fp2_add(t0, t2)
        ),
        t1,
    )
    return (c0, c1, c2)


def _fp6_mul_by_v(a):
    # (c0, c1, c2) * v = (XI*c2, c0, c1)
    return (_fp2_mul(XI, a[2]), a[0], a[1])


def _fp6_inv(a):
    a0, a1, a2 = a
    t0 = _fp2_sqr(a0)
    t1 = _fp2_sqr(a1)
    t2 = _fp2_sqr(a2)
    c0 = _fp2_sub(t0, _fp2_mul(XI, _fp2_mul(a1, a2)))
    c1 = _fp2_sub(_fp2_mul(XI, t2), _fp2_mul(a0, a1))
    c2 = _fp2_sub(t1, _fp2_mul(a0, a2))
    norm = _fp2_add(
        _fp2_mul(a0, c0),
        _fp2_mul(XI, _fp2_add(_fp2_mul(a2, c1), _fp2_mul(a1, c2))),
    )
    inv = _fp2_inv(norm)
    return (_fp2_mul(c0, inv), _fp2_mul(c1, inv), _fp2_mul(c2, inv))


FP6_ZERO = (FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE = (FP2_ONE, FP2_ZERO, FP2_ZERO)

FP12_ONE = (FP6_ONE, FP6_ZERO)


def _fp12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = _fp6_mul(a0, b0)
    t1 = _fp6_mul(a1, b1)
    c1 = _fp6_sub(
        _fp6_mul(_fp6_add(a0, a1), _fp6_add(b0, b1)), _fp6_add(t0, t1)
    )
    return (_fp6_add(t0, _fp6_mul_by_v(t1)), c1)


def _fp12_sqr(a):
    return _fp12_mul(a, a)


def _fp12_conj(a):
    # conjugation == the p^6 Frobenius on Fp12
    return (a[0], _fp6_neg(a[1]))


def _fp12_inv(a):
    a0, a1 = a
    norm = _fp6_sub(_fp6_mul(a0, a0), _fp6_mul_by_v(_fp6_mul(a1, a1)))
    inv = _fp6_inv(norm)
    return (_fp6_mul(a0, inv), _fp6_neg(_fp6_mul(a1, inv)))


def _fp12_pow(a, e: int):
    result = FP12_ONE
    base = a
    while e:
        if e & 1:
            result = _fp12_mul(result, base)
        base = _fp12_sqr(base)
        e >>= 1
    return result


# w^(p^2) = gamma * w with gamma = XI^((p^2-1)/6) in Fp2; the p^2
# Frobenius is coefficient-wise multiplication by gamma^k for basis
# element w^k (the towered basis element v^j w^i has k = 2j + i).
_GAMMA_P2 = _fp2_pow(XI, (P * P - 1) // 6)
_GAMMA_P2_POWERS = [FP2_ONE]
for _ in range(5):
    _GAMMA_P2_POWERS.append(_fp2_mul(_GAMMA_P2_POWERS[-1], _GAMMA_P2))


def _fp12_frob_p2(a):
    out = []
    for i, half in enumerate(a):  # w^0 half, w^1 half
        coeffs = []
        for j, c in enumerate(half):  # v^j
            coeffs.append(_fp2_mul(c, _GAMMA_P2_POWERS[2 * j + i]))
        out.append(tuple(coeffs))
    return tuple(out)


# --------------------------------------------------------------------------
# Curve arithmetic, generic over the coordinate field. Jacobian
# coordinates (X, Y, Z) with x = X/Z^2, y = Y/Z^3 — no per-step field
# inversions, which is what keeps pure-python scalar multiplication in
# the milliseconds. `None` is the point at infinity throughout.


class _CurveOps:
    """Short-Weierstrass y^2 = x^3 + b over a field given by ops."""

    def __init__(self, add, sub, mul, sqr, inv, neg, scalar, zero, one, b):
        self.add, self.sub, self.mul, self.sqr = add, sub, mul, sqr
        self.inv, self.neg, self.scalar = inv, neg, scalar
        self.zero, self.one, self.b = zero, one, b

    def on_curve(self, pt) -> bool:
        if pt is None:
            return True
        x, y = pt
        return self.sqr(y) == self.add(self.mul(self.sqr(x), x), self.b)

    def dbl_j(self, pt):
        if pt is None:
            return None
        X, Y, Z = pt
        if Y == self.zero:
            return None
        A = self.sqr(X)
        B = self.sqr(Y)
        C = self.sqr(B)
        D = self.scalar(
            self.sub(self.sub(self.sqr(self.add(X, B)), A), C), 2
        )
        E = self.scalar(A, 3)
        X3 = self.sub(self.sqr(E), self.scalar(D, 2))
        Y3 = self.sub(self.mul(E, self.sub(D, X3)), self.scalar(C, 8))
        Z3 = self.scalar(self.mul(Y, Z), 2)
        return (X3, Y3, Z3)

    def add_j(self, p1, p2):
        if p1 is None:
            return p2
        if p2 is None:
            return p1
        X1, Y1, Z1 = p1
        X2, Y2, Z2 = p2
        Z1Z1 = self.sqr(Z1)
        Z2Z2 = self.sqr(Z2)
        U1 = self.mul(X1, Z2Z2)
        U2 = self.mul(X2, Z1Z1)
        S1 = self.mul(self.mul(Y1, Z2), Z2Z2)
        S2 = self.mul(self.mul(Y2, Z1), Z1Z1)
        if U1 == U2:
            if S1 != S2:
                return None
            return self.dbl_j(p1)
        H = self.sub(U2, U1)
        I = self.sqr(self.scalar(H, 2))
        J = self.mul(H, I)
        rr = self.scalar(self.sub(S2, S1), 2)
        V = self.mul(U1, I)
        X3 = self.sub(self.sub(self.sqr(rr), J), self.scalar(V, 2))
        Y3 = self.sub(
            self.mul(rr, self.sub(V, X3)),
            self.scalar(self.mul(S1, J), 2),
        )
        Z3 = self.scalar(self.mul(self.mul(Z1, Z2), H), 2)
        return (X3, Y3, Z3)

    def to_jacobian(self, pt):
        if pt is None:
            return None
        return (pt[0], pt[1], self.one)

    def to_affine(self, pt):
        if pt is None:
            return None
        X, Y, Z = pt
        zinv = self.inv(Z)
        zinv2 = self.sqr(zinv)
        return (self.mul(X, zinv2), self.mul(Y, self.mul(zinv, zinv2)))

    def add_affine(self, p1, p2):
        return self.to_affine(
            self.add_j(self.to_jacobian(p1), self.to_jacobian(p2))
        )

    def mul_affine(self, pt, k: int):
        if pt is None or k == 0:
            return None
        if k < 0:
            x, y = pt
            pt = (x, self.neg(y))
            k = -k
        acc = None
        base = self.to_jacobian(pt)
        while k:
            if k & 1:
                acc = self.add_j(acc, base)
            base = self.dbl_j(base)
            k >>= 1
        return self.to_affine(acc)


_FP_OPS = _CurveOps(
    add=lambda a, b: (a + b) % P,
    sub=lambda a, b: (a - b) % P,
    mul=lambda a, b: a * b % P,
    sqr=lambda a: a * a % P,
    inv=_inv,
    neg=lambda a: -a % P,
    scalar=lambda a, k: a * k % P,
    zero=0,
    one=1,
    b=B_G1,
)

_FP2_OPS = _CurveOps(
    add=_fp2_add,
    sub=_fp2_sub,
    mul=_fp2_mul,
    sqr=_fp2_sqr,
    inv=_fp2_inv,
    neg=_fp2_neg,
    scalar=_fp2_scalar,
    zero=FP2_ZERO,
    one=FP2_ONE,
    b=_fp2_scalar(XI, 4),  # 4(1 + i)
)

assert _FP_OPS.on_curve(G1_GEN), "G1 generator not on E(Fp)"
assert _FP2_OPS.on_curve(G2_GEN), "G2 generator not on the M-twist"


def _g2_cofactor() -> int:
    """#E'(Fp2) / r, derived (not memorized): the sextic twists of E over
    Fp2 have orders p^2 + 1 - (±3f ± t2)/2 where t2 = t^2 - 2p is the
    Fp2 Frobenius trace and t2^2 - 4p^2 = -3 f^2 (CM discriminant -3).
    The correct twist is the candidate divisible by r whose order
    annihilates the standard G2 generator."""
    t = X_PARAM + 1  # Frobenius trace of E/Fp for BLS12
    t2 = t * t - 2 * P
    f2 = (4 * P * P - t2 * t2) // 3
    f = _isqrt(f2)
    assert f * f == f2, "CM discriminant is not -3?"
    for c in ((3 * f + t2) // 2, (3 * f - t2) // 2, (-3 * f + t2) // 2,
              (-3 * f - t2) // 2):
        order = P * P + 1 - c
        if order % R_ORDER == 0 and _FP2_OPS.mul_affine(G2_GEN, order) is None:
            return order // R_ORDER
    raise AssertionError("no sextic twist order matched the G2 generator")


def _isqrt(n: int) -> int:
    return math.isqrt(n)


_G2_COFACTOR: int | None = None  # computed lazily (one ~760-bit scalar mul)


def _g2_clear_cofactor(pt):
    global _G2_COFACTOR
    if _G2_COFACTOR is None:
        _G2_COFACTOR = _g2_cofactor()
    return _FP2_OPS.mul_affine(pt, _G2_COFACTOR)


# --------------------------------------------------------------------------
# Serialization (ZCash flag convention: bit7 compressed, bit6 infinity,
# bit5 y-sign = lexicographically-largest y)


def _fp_is_larger(y: int) -> bool:
    return y > P - y


def _fp2_is_larger(y) -> bool:
    if y[1] != 0:
        return y[1] > P - y[1]
    return y[0] > P - y[0]


def compress_g1(pt) -> bytes:
    if pt is None:
        return bytes([0xC0]) + bytes(47)
    x, y = pt
    flags = 0x80 | (0x20 if _fp_is_larger(y) else 0)
    raw = bytearray(x.to_bytes(48, "big"))
    raw[0] |= flags
    return bytes(raw)


def decompress_g1(data: bytes):
    if len(data) != 48:
        raise ValueError("G1 point must be 48 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G1 encoding unsupported")
    if flags & 0x40:
        if any(data[1:]) or flags & 0x20 or data[0] & 0x1F:
            raise ValueError("malformed G1 infinity encoding")
        return None
    x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    y2 = (x * x % P * x + B_G1) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        raise ValueError("G1 x not on curve")
    if _fp_is_larger(y) != bool(flags & 0x20):
        y = P - y
    return (x, y)


def compress_g2(pt) -> bytes:
    if pt is None:
        return bytes([0xC0]) + bytes(95)
    (x0, x1), y = pt
    flags = 0x80 | (0x20 if _fp2_is_larger(y) else 0)
    raw = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    raw[0] |= flags
    return bytes(raw)


def decompress_g2(data: bytes):
    if len(data) != 96:
        raise ValueError("G2 point must be 96 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G2 encoding unsupported")
    if flags & 0x40:
        if any(data[1:]) or flags & 0x20 or data[0] & 0x1F:
            raise ValueError("malformed G2 infinity encoding")
        return None
    x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x out of range")
    x = (x0, x1)
    y2 = _fp2_add(_fp2_mul(_fp2_sqr(x), x), _FP2_OPS.b)
    y = _fp2_sqrt(y2)
    if y is None:
        raise ValueError("G2 x not on curve")
    if _fp2_is_larger(y) != bool(flags & 0x20):
        y = _fp2_neg(y)
    return (x, y)


# --------------------------------------------------------------------------
# Hash to G2: deterministic try-and-increment over counter-separated
# SHA-512 draws (NOT constant-time — fine for signing public protocol
# digests), then cofactor clearing into the r-torsion subgroup.


def hash_to_g2(msg: bytes):
    for ctr in range(256):
        h = hashlib.sha512(DST_DOMAIN + struct.pack("<B", ctr) + msg)
        d0 = h.digest()
        d1 = hashlib.sha512(b"\x01" + d0).digest()
        x = (int.from_bytes(d0, "big") % P, int.from_bytes(d1, "big") % P)
        y2 = _fp2_add(_fp2_mul(_fp2_sqr(x), x), _FP2_OPS.b)
        y = _fp2_sqrt(y2)
        if y is None:
            continue
        # Deterministic sign choice keyed off the draw, so the map is a
        # pure function of (DST_DOMAIN, msg).
        if _fp2_is_larger(y) != bool(d1[0] & 1):
            y = _fp2_neg(y)
        pt = _g2_clear_cofactor((x, y))
        if pt is not None:
            return pt
    raise AssertionError("hash_to_g2 exhausted 256 counters")


# --------------------------------------------------------------------------
# Pairing: untwist E'(Fp2) -> E(Fp12), Miller loop over |u|, final
# exponentiation split into the cheap (p^6-1)(p^2+1) part (conjugation +
# one Frobenius) and a plain pow for the hard (p^4-p^2+1)/r exponent.


def _fp12_from_fp(a: int):
    return (((a, 0), FP2_ZERO, FP2_ZERO), FP6_ZERO)


def _fp12_from_fp2(a):
    return ((a, FP2_ZERO, FP2_ZERO), FP6_ZERO)


_W = (FP6_ZERO, FP6_ONE)  # the tower generator w (w^2 = v, w^6 = XI)
_W2_INV = _fp12_inv(_fp12_mul(_W, _W))
_W3_INV = _fp12_inv(_fp12_mul(_fp12_mul(_W, _W), _W))

_FP12_OPS = _CurveOps(
    add=lambda a, b: (_fp6_add(a[0], b[0]), _fp6_add(a[1], b[1])),
    sub=lambda a, b: (_fp6_sub(a[0], b[0]), _fp6_sub(a[1], b[1])),
    mul=_fp12_mul,
    sqr=_fp12_sqr,
    inv=_fp12_inv,
    neg=lambda a: (_fp6_neg(a[0]), _fp6_neg(a[1])),
    scalar=lambda a, k: tuple(
        tuple(_fp2_scalar(c, k) for c in half) for half in a
    ),
    zero=(FP6_ZERO, FP6_ZERO),
    one=FP12_ONE,
    b=_fp12_from_fp(B_G1),
)


def _untwist(pt):
    """E'(Fp2) -> E(Fp12): (x', y') -> (x'/w^2, y'/w^3). With w^6 = XI
    this lands on y^2 = x^3 + 4 (the twist equation divides through)."""
    if pt is None:
        return None
    x, y = pt
    return (
        _fp12_mul(_fp12_from_fp2(x), _W2_INV),
        _fp12_mul(_fp12_from_fp2(y), _W3_INV),
    )


def _line(a, b, at):
    """Evaluate the line through a, b (or the tangent when a == b) at
    `at`; all points affine in Fp12. Vertical lines return the x-offset
    (the factor lives in a proper subfield and dies in the final
    exponentiation, the standard omission)."""
    ops = _FP12_OPS
    ax, ay = a
    bx, by = b
    tx, ty = at
    if ax == bx:
        if ay == by:
            if ay == ops.zero:
                return ops.sub(tx, ax), None
            lam = ops.mul(
                ops.scalar(ops.sqr(ax), 3),
                ops.inv(ops.scalar(ay, 2)),
            )
        else:
            return ops.sub(tx, ax), None
    else:
        lam = ops.mul(ops.sub(by, ay), ops.inv(ops.sub(bx, ax)))
    val = ops.sub(ops.sub(ty, ay), ops.mul(lam, ops.sub(tx, ax)))
    return val, lam


def _miller(q_tw, p_g1):
    """f_{|u|, Q}(P) for the ate pairing, conjugated for the negative u.
    Q arrives in twist coordinates; P in E(Fp) affine."""
    ops = _FP12_OPS
    Q = _untwist(q_tw)
    Pm = (_fp12_from_fp(p_g1[0]), _fp12_from_fp(p_g1[1]))
    f = FP12_ONE
    T = Q
    for bit in bin(_U)[3:]:  # skip the leading 1
        val, _ = _line(T, T, Pm)
        f = _fp12_mul(_fp12_sqr(f), val)
        T = ops.add_affine(T, T)
        if bit == "1":
            val, _ = _line(T, Q, Pm)
            f = _fp12_mul(f, val)
            T = ops.add_affine(T, Q)
    return _fp12_conj(f)  # u < 0: 1/f and conj(f) agree after final exp


_HARD_EXP = (P**4 - P**2 + 1) // R_ORDER
assert (P**4 - P**2 + 1) % R_ORDER == 0


def _final_exp(f):
    # easy part: f^((p^6 - 1)(p^2 + 1))
    f = _fp12_mul(_fp12_conj(f), _fp12_inv(f))
    f = _fp12_mul(_fp12_frob_p2(f), f)
    # hard part: plain square-and-multiply over (p^4 - p^2 + 1)/r
    return _fp12_pow(f, _HARD_EXP)


def _pairings_are_one(pairs) -> bool:
    """prod e(P_i, Q_i) == 1 with ONE shared final exponentiation —
    the aggregate-verify shape (P_i in E(Fp) affine, Q_i in twist
    coordinates)."""
    f = FP12_ONE
    for p_g1, q_tw in pairs:
        if p_g1 is None or q_tw is None:
            continue  # e(O, Q) = e(P, O) = 1
        f = _fp12_mul(f, _miller(q_tw, p_g1))
    return _final_exp(f) == FP12_ONE


def _g1_neg(pt):
    if pt is None:
        return None
    return (pt[0], -pt[1] % P)


def _g2_in_subgroup(pt) -> bool:
    return _FP2_OPS.mul_affine(pt, R_ORDER) is None


def _g1_in_subgroup(pt) -> bool:
    return _FP_OPS.mul_affine(pt, R_ORDER) is None


# --------------------------------------------------------------------------
# The scheme


class ExactBlsScheme:
    """Exact-integer BLS12-381 min-pk aggregate signatures."""

    name = "bls12381"
    pk_bytes = PK_BYTES
    sig_bytes = SIG_BYTES

    def keypair_from_seed(self, seed: bytes) -> tuple[bytes, int]:
        sk = (
            int.from_bytes(
                hashlib.sha512(KEY_DOMAIN + seed).digest(), "little"
            )
            % R_ORDER
        )
        if sk == 0:
            sk = 1
        return compress_g1(_FP_OPS.mul_affine(G1_GEN, sk)), sk

    def sign(self, sk: int, msg: bytes) -> bytes:
        return compress_g2(_FP2_OPS.mul_affine(hash_to_g2(msg), sk))

    def combine(self, a: bytes, b: bytes) -> bytes:
        return compress_g2(
            _FP2_OPS.add_affine(decompress_g2(a), decompress_g2(b))
        )

    def aggregate(self, sigs) -> bytes:
        acc = None
        for s in sigs:
            acc = _FP2_OPS.add_affine(acc, decompress_g2(s))
        return compress_g2(acc)

    def verify(self, pks, msg: bytes, sig: bytes) -> bool:
        return self.verify_groups([(list(pks), msg)], sig)

    def verify_groups(self, groups, sig: bytes) -> bool:
        """prod_g e(apk_g, H(msg_g)) == e(g1, S): the multi-message
        aggregate check (a TC spans one group per distinct high-qc
        round; a QC is the single-group case)."""
        try:
            s = decompress_g2(sig)
            if s is None or not _g2_in_subgroup(s):
                return False
            pairs = [(_g1_neg(G1_GEN), s)]
            for pks, msg in groups:
                if not pks:
                    return False
                apk = None
                for pk in pks:
                    apk = _FP_OPS.add_affine(apk, decompress_g1(pk))
                if apk is None:
                    return False
                pairs.append((apk, hash_to_g2(msg)))
            return _pairings_are_one(pairs)
        except ValueError:
            return False


# --------------------------------------------------------------------------
# Scheme seam (the pysigner.install_scheme pattern): virtual-time fleets
# install the trusted-stub aggregate analogue; everything else gets the
# exact curve. Restored by the installer (orchestrator teardown).

_AGG_SCHEME = None
_EXACT: ExactBlsScheme | None = None


def exact_scheme() -> ExactBlsScheme:
    global _EXACT
    if _EXACT is None:
        _EXACT = ExactBlsScheme()
    return _EXACT


def install_agg_scheme(scheme):
    """Swap the active aggregate-signature scheme; returns the previous
    value (None = exact) so callers can restore it."""
    global _AGG_SCHEME
    prev = _AGG_SCHEME
    _AGG_SCHEME = scheme
    return prev


def active_agg_scheme():
    return _AGG_SCHEME if _AGG_SCHEME is not None else exact_scheme()


# --------------------------------------------------------------------------
# Aggregate-key registry: consensus identity (Ed25519 pk bytes) ->
# aggregate pk bytes. Certificates carry NO keys on the wire (that is
# the point); verifiers resolve bitmap members here. Registration is
# the proof-of-possession boundary (module docstring).

_REGISTRY: dict[bytes, bytes] = {}


def install_agg_registry(mapping: dict[bytes, bytes] | None):
    """Replace the whole registry (None = empty); returns the previous
    mapping for restore-on-teardown."""
    global _REGISTRY
    prev = _REGISTRY
    _REGISTRY = dict(mapping or {})
    return prev


def register_agg_key(identity: bytes, agg_pk: bytes) -> None:
    _REGISTRY[bytes(identity)] = bytes(agg_pk)


def agg_key_of(identity: bytes) -> bytes | None:
    return _REGISTRY.get(bytes(identity))


class AggSigner:
    """One node's aggregate-signature identity, derived from the same
    seed as its Ed25519 keypair (the chaos/benchmark key ceremony)."""

    __slots__ = ("public_key", "_sk", "_scheme")

    def __init__(self, seed: bytes, scheme=None) -> None:
        self._scheme = scheme if scheme is not None else active_agg_scheme()
        self.public_key, self._sk = self._scheme.keypair_from_seed(seed)

    def sign(self, msg: bytes) -> bytes:
        return self._scheme.sign(self._sk, msg)


# --------------------------------------------------------------------------
# Committee bitmaps: bit i = sorted_keys()[i] of the round's committee.


def bitmap_of(members, sorted_keys) -> int:
    index = {pk: i for i, pk in enumerate(sorted_keys)}
    bm = 0
    for pk in members:
        bm |= 1 << index[pk]
    return bm


def members_of(bitmap: int, sorted_keys) -> list:
    """Resolve a bitmap against a sorted committee; raises ValueError on
    bits beyond the committee (a malformed or wrong-epoch bitmap)."""
    if bitmap < 0:
        raise ValueError("negative bitmap")
    if bitmap >> len(sorted_keys):
        raise ValueError(
            f"bitmap claims member {bitmap.bit_length() - 1} of a "
            f"{len(sorted_keys)}-member committee"
        )
    return [pk for i, pk in enumerate(sorted_keys) if bitmap >> i & 1]


def bitmap_to_bytes(bitmap: int) -> bytes:
    return bitmap.to_bytes(AGG_BITMAP_BYTES, "little")


def bitmap_from_bytes(data: bytes) -> int:
    return int.from_bytes(data, "little")
