"""Crypto sidecar: one process owns the TPU, many nodes share it.

A TPU chip is process-exclusive under JAX, but a local committee (and any
co-located deployment) runs several node processes per machine. The
reference's answer to async crypto is the SignatureService request/reply
seam (crypto/src/lib.rs:226-252); this module generalises that seam ACROSS
processes: a sidecar process holds the TpuBackend and serves batch
verification over a local TCP socket, and nodes install a `RemoteBackend`
that ships large batches to the sidecar while verifying small
(consensus-critical, sub-crossover) batches on the local CPU — the same
crossover policy TpuBackend applies in-process (SURVEY.md §7 hard-part 3).

Server-side, requests from ALL nodes funnel through one
BatchVerificationService, so batches coalesce across the whole committee
before hitting the device — strictly better device utilisation than any
per-node dispatch could get.

Wire protocol (little-endian, one request per round-trip per connection):
  request:  u32 body_len, u32 n, then n x { u32 mlen, msg, 32 B pk, 64 B sig }
  response: u32 n, then n x u8 validity
The body-length prefix lets the server read the whole request in ONE
stream read and parse it with memoryview slicing — per-item stream awaits
(4 per signature) measurably starved the shared CPU at sustained load.
"""

from __future__ import annotations

import asyncio
import logging
import socket
import struct
import threading
from typing import Sequence

from .backend import CpuBackend, CryptoBackend
from .primitives import PublicKey, Signature

log = logging.getLogger("hotstuff.crypto")


def _encode_request(
    messages: Sequence[bytes],
    keys: Sequence[PublicKey],
    signatures: Sequence[Signature],
) -> bytes:
    parts = [struct.pack("<I", len(messages))]
    for m, k, s in zip(messages, keys, signatures):
        parts.append(struct.pack("<I", len(m)))
        parts.append(m)
        parts.append(k.data if isinstance(k, PublicKey) else k)
        parts.append(s.data if isinstance(s, Signature) else s)
    body = b"".join(parts)
    return struct.pack("<I", len(body)) + body


def _parse_request(body: memoryview) -> tuple[list[bytes], list[tuple[PublicKey, Signature]]]:
    """Parse a request body (after the length prefix) without stream I/O.
    Raises ValueError on malformed framing or cap violations."""
    (n,) = struct.unpack("<I", body[:4])
    if n > MAX_REQUEST_ITEMS:
        raise ValueError(f"{n} items exceeds cap")
    off = 4
    msgs: list[bytes] = []
    pairs: list[tuple[PublicKey, Signature]] = []
    end = len(body)
    for _ in range(n):
        if off + 4 > end:
            raise ValueError("truncated item header")
        (mlen,) = struct.unpack("<I", body[off : off + 4])
        off += 4
        if mlen > MAX_MESSAGE_LEN or off + mlen + 96 > end:
            raise ValueError("item exceeds body")
        msgs.append(bytes(body[off : off + mlen]))
        off += mlen
        pairs.append(
            (
                PublicKey(bytes(body[off : off + 32])),
                Signature(bytes(body[off + 32 : off + 96])),
            )
        )
        off += 96
    if off != end:
        raise ValueError("trailing bytes in request body")
    return msgs, pairs


class RemoteBackend(CryptoBackend):
    """CryptoBackend that dispatches big batches to the sidecar process.

    Small batches (below `crossover`) verify on the local CPU: a localhost
    round-trip plus device dispatch would only add latency to the
    consensus-critical QC path. Falls back to CPU entirely if the sidecar
    is unreachable (a crypto sidecar outage must not halt the protocol)."""

    name = "remote"

    # Requests below this ride the dedicated urgent lane (socket + slot),
    # mirroring the sidecar's `urgent_below` service-side split: a
    # consensus-critical QC check must never queue behind workload-sized
    # transfers occupying every pooled socket.
    URGENT_BELOW = 256

    def __init__(
        self,
        addr: tuple[str, int],
        crossover: int = 64,
        timeout: float = 30.0,
        pool_size: int = 5,
    ):
        self.addr = addr
        self.crossover = crossover
        self.timeout = timeout
        self._cpu = CpuBackend()
        # Connection pool: concurrent service dispatches each borrow a
        # socket, so a second batch streams into the sidecar while the first
        # is on the device (one socket would serialize the round trips).
        # Sized above BatchVerificationService's max_concurrent_dispatches
        # (4) so in-flight workload round trips can never exhaust it.
        self._pool: list[socket.socket] = []
        self._pool_lock = threading.Lock()
        self._pool_sem = threading.BoundedSemaphore(pool_size)
        # Urgent lane: one reserved socket + slot for small requests.
        self._urgent_sem = threading.BoundedSemaphore(1)
        self._urgent_sock: socket.socket | None = None
        self.stats = {"remote_batches": 0, "remote_sigs": 0, "cpu_batches": 0, "cpu_sigs": 0}

    def _dial(self) -> socket.socket:
        s = socket.create_connection(self.addr, timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _borrow(self, urgent: bool) -> socket.socket:
        with self._pool_lock:
            if urgent:
                if self._urgent_sock is not None:
                    sock, self._urgent_sock = self._urgent_sock, None
                    return sock
            elif self._pool:
                return self._pool.pop()
        return self._dial()

    def _give_back(self, sock: socket.socket, urgent: bool) -> None:
        with self._pool_lock:
            if urgent and self._urgent_sock is None:
                self._urgent_sock = sock
            else:
                self._pool.append(sock)

    def _flush_pool(self) -> None:
        with self._pool_lock:
            stale, self._pool = self._pool, []
            if self._urgent_sock is not None:
                stale.append(self._urgent_sock)
                self._urgent_sock = None
        for s in stale:
            try:
                s.close()
            except OSError:
                pass

    def _recv_exact(self, sock: socket.socket, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("sidecar closed connection")
            buf += chunk
        return bytes(buf)

    def verify_batch_mask(
        self,
        messages: Sequence[bytes],
        keys: Sequence[PublicKey],
        signatures: Sequence[Signature],
    ) -> list[bool]:
        n = len(messages)
        if n == 0:
            return []
        if n < self.crossover:
            self.stats["cpu_batches"] += 1
            self.stats["cpu_sigs"] += n
            return self._cpu.verify_batch_mask(messages, keys, signatures)
        payload = _encode_request(messages, keys, signatures)
        urgent = n < self.URGENT_BELOW
        sem = self._urgent_sem if urgent else self._pool_sem
        with sem:  # bound concurrent round-trips per lane
            for attempt in (0, 1):
                sock = None
                try:
                    if attempt == 0:
                        sock = self._borrow(urgent)
                    else:
                        # Pooled sockets may ALL be stale (sidecar restart);
                        # the final attempt must dial fresh, and the rest of
                        # the suspect pool is dropped below.
                        self._flush_pool()
                        sock = self._dial()
                    sock.sendall(payload)
                    (count,) = struct.unpack("<I", self._recv_exact(sock, 4))
                    if count != n:
                        raise ConnectionError("sidecar count mismatch")
                    mask = self._recv_exact(sock, n)
                    self._give_back(sock, urgent)
                    self.stats["remote_batches"] += 1
                    self.stats["remote_sigs"] += n
                    return [b != 0 for b in mask]
                except (OSError, ConnectionError) as e:
                    if sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass
                    if attempt == 1:
                        log.warning(
                            "sidecar unreachable (%s); falling back to CPU", e
                        )
        self.stats["cpu_batches"] += 1
        self.stats["cpu_sigs"] += n
        return self._cpu.verify_batch_mask(messages, keys, signatures)


# ---------------------------------------------------------------------------
# Sidecar server


async def _read_exact(reader: asyncio.StreamReader, n: int) -> bytes:
    return await reader.readexactly(n)


# Ingress caps: the sidecar is a local trusted surface, but a buggy or
# compromised co-tenant must not be able to OOM the process that owns the
# accelerator (SURVEY §5.3: verify-everything-at-ingress discipline).
# Per-item caps alone don't bound a request's aggregate size, so the
# cumulative bytes buffered per request are capped too.
MAX_REQUEST_ITEMS = 1_000_000
MAX_MESSAGE_LEN = 16 * 1024 * 1024
# Largest legitimate request is one fully-coalesced batch (~8192 items of
# ~200 B ≈ 1.6 MB); 64 MiB caps the parse-time peak (body + item copies)
# at ~128 MiB on the accelerator-owning host.
MAX_REQUEST_BYTES = 64 * 1024 * 1024


async def _handle_connection(reader, writer, service, urgent_below: int):
    peer = writer.get_extra_info("peername")
    log.debug("sidecar connection from %s", peer)
    try:
        while True:
            try:
                (body_len,) = struct.unpack("<I", await _read_exact(reader, 4))
            except (asyncio.IncompleteReadError, ConnectionResetError):
                break
            if body_len > MAX_REQUEST_BYTES:
                log.warning(
                    "dropping connection %s: %s B request exceeds %s B cap",
                    peer,
                    body_len,
                    MAX_REQUEST_BYTES,
                )
                break
            if body_len < 4:
                log.warning("dropping connection %s: runt request", peer)
                break
            body = memoryview(await _read_exact(reader, body_len))
            try:
                msgs, pairs = _parse_request(body)
            except ValueError as e:
                log.warning("dropping connection %s: malformed request (%s)", peer, e)
                break
            n = len(msgs)
            del body  # free the wire buffer before the (long) dispatch wait
            # Small requests are consensus-critical (QC/TC checks above the
            # client's crossover but still latency-bound): flush immediately.
            mask = await service.verify_group(
                msgs, pairs, urgent=n < urgent_below
            )
            writer.write(struct.pack("<I", n) + bytes(int(b) for b in mask))
            await writer.drain()
    finally:
        writer.close()


def warmup_backend(backend: CryptoBackend) -> None:
    """Pre-compile every verifier bucket width BEFORE serving: a cold jit
    specialisation (~20-40 s on TPU) hitting mid-run would stall the whole
    committee's verification pipeline. With the persistent compilation cache
    enabled this is fast on every boot after the first. Delegates to the
    backend's own warmup (TpuBackend.warmup covers the device-hash AND
    host-hash variants); backends without one (CpuBackend) need none."""
    warm = getattr(backend, "warmup", None)
    if warm is not None:
        secs = warm()
        log.info("backend warmup finished in %.1f s", secs)


async def serve(
    addr: tuple[str, int],
    backend: CryptoBackend,
    max_batch: int = 8192,
    max_delay: float = 0.002,
    urgent_below: int = 256,
) -> None:
    """Run the sidecar server forever. One BatchVerificationService shared by
    every connection: batches coalesce across the whole committee."""
    from .batch_service import BatchVerificationService

    service = BatchVerificationService(
        backend, max_batch=max_batch, max_delay=max_delay
    )

    async def handler(reader, writer):
        await _handle_connection(reader, writer, service, urgent_below)

    server = await asyncio.start_server(handler, addr[0], addr[1])
    # NOTE: parsed by the benchmark harness to detect readiness.
    log.info("Crypto sidecar (%s) successfully booted on %s:%s", backend.name, addr[0], addr[1])
    async with server:
        await server.serve_forever()


def main(argv: list[str] | None = None) -> None:
    import argparse

    from ..utils.logging import setup_logging
    from .backend import make_backend

    p = argparse.ArgumentParser(description="crypto verification sidecar")
    p.add_argument("-v", "--verbose", action="count", default=2)
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--backend", default="tpu", choices=["cpu", "tpu"])
    p.add_argument("--max-batch", type=int, default=8192)
    p.add_argument(
        "--min-bucket",
        type=int,
        default=1024,
        help="smallest jit bucket width; fewer widths = faster warmup "
        "(small urgent batches pad up, ~12 ms device time at 1024 lanes)",
    )
    p.add_argument(
        "--multihost",
        action="store_true",
        help="join a multi-host JAX job (parallel.mesh.init_multihost; "
        "coordinator from the standard JAX_COORDINATOR_ADDRESS env) and "
        "shard verification batches over every chip in the job",
    )
    p.add_argument(
        "--committee",
        default=None,
        metavar="PATH",
        help="node committee file (node/config.py Committee JSON): register "
        "the consensus validator keys as device-resident verification "
        "precompute at boot — on a --multihost mesh this pushes one "
        "replicated table copy per chip, so committee-tagged batches ride "
        "the zero-decompression kernel on every device",
    )
    p.add_argument("--max-delay", type=float, default=0.002)
    p.add_argument(
        "--chunk",
        type=int,
        default=None,
        help="upload-pipeline chunk size (clamped to the bucket cap); the "
        "device chunk sweep (tools/tune_device.py --chunks) decides this",
    )
    p.add_argument(
        "--no-warmup", action="store_true", help="skip bucket pre-compilation"
    )
    args = p.parse_args(argv)
    if args.chunk is not None and args.chunk <= 0:
        # 0 would silently fall back to the default chunk downstream and a
        # negative value breaks the upload loop — neither may record a
        # sweep under a config the operator didn't specify.
        p.error("--chunk must be positive")
    setup_logging(args.verbose)
    if args.backend == "tpu":
        from ..ops import enable_persistent_cache

        enable_persistent_cache()
        if args.multihost:
            from ..parallel.mesh import init_multihost

            mesh = init_multihost()
            backend = make_backend(
                args.backend,
                mesh=mesh,
                min_bucket=args.min_bucket,
                chunk=args.chunk,
            )
        else:
            backend = make_backend(
                args.backend, min_bucket=args.min_bucket, chunk=args.chunk
            )
    else:
        # A sweep that silently ignored these flags would record numbers
        # under a different config than the operator specified.
        if args.multihost:
            p.error("--multihost requires --backend tpu")
        if args.min_bucket != p.get_default("min_bucket"):
            p.error("--min-bucket requires --backend tpu")
        if args.chunk is not None:
            p.error("--chunk requires --backend tpu")
        if args.committee is not None:
            p.error("--committee requires --backend tpu")
        backend = make_backend(args.backend)
    from ..utils.logging import quiet_jax_logs

    quiet_jax_logs(args.verbose)
    if not args.no_warmup:
        warmup_backend(backend)
        quiet_jax_logs(args.verbose)  # device init may reconfigure logging
    if args.committee is not None:
        # After the generic warmup (device initialized) and with the same
        # warmup policy: the committee kernel family compiles at every
        # dispatch width before the sidecar starts serving.
        from ..node.config import Committee as NodeCommittee

        backend.register_committee(
            NodeCommittee.read(args.committee).consensus.sorted_keys(),
            warmup=not args.no_warmup,
        )
    asyncio.run(
        serve(
            (args.host, args.port),
            backend,
            max_batch=args.max_batch,
            max_delay=args.max_delay,
        )
    )


if __name__ == "__main__":
    main()
