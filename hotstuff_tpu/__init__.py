"""tpu-hotstuff: a TPU-native 2-chain HotStuff BFT consensus framework.

A brand-new framework with the capabilities of the reference Rust implementation
(mvidigueira/hotstuff): a committee of nodes runs leader-based 2-chain HotStuff
(propose -> vote -> QC, with timeout/TC view change) over TCP, a mempool plane
batches and disseminates client transaction payloads so consensus orders only
digests, and a persistent store holds blocks/payloads.

The cryptographic hot path -- batched vote/signature verification and QC
aggregation (reference: crypto/src/lib.rs:194-220, consensus/src/messages.rs:197)
-- sits behind a pluggable CryptoBackend with a CPU ed25519 baseline and a
JAX TPU backend that verifies large signature batches as a single vmapped
kernel, sharded over a device mesh at scale (hotstuff_tpu.ops, hotstuff_tpu.parallel).
"""

__version__ = "0.1.0"
