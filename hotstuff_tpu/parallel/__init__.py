"""Device-mesh parallelism: sharded batch verification over jax.sharding
meshes with ICI collectives (SURVEY.md §2.8, §5.7)."""

from .mesh import (
    ShardedEd25519Verifier,
    default_mesh,
    init_multihost,
    mesh_2d,
    sharded_committee_fn,
    sharded_qc_verify_fn,
    sharded_verify_fn,
)

__all__ = [
    "ShardedEd25519Verifier",
    "default_mesh",
    "init_multihost",
    "mesh_2d",
    "sharded_committee_fn",
    "sharded_qc_verify_fn",
    "sharded_verify_fn",
]
