"""Device-mesh parallelism for the crypto plane.

The reference's only compute-dense kernel is batched signature verification
(SURVEY.md §2.8 item 3); at committee scale (64-100 nodes, 100k tx/s input,
BASELINE.json configs) one chip is not enough. This module shards the
verification batch across a `jax.sharding.Mesh`:

  * axis "dp" — data parallel over the vote/signature batch. Each device
    verifies its shard; masks stay sharded; quorum counting rides ICI via
    `psum` collectives inside `shard_map` (never DCN — consensus/mempool
    control traffic stays host-side, SURVEY.md §5.8).
  * axis "qc" — independent QCs / payload batches verified concurrently
    (one QC's votes never wait on another's), the committee-facing axis.

The reference's analogue is thread-level parallelism inside ed25519_dalek's
`verify_batch` (crypto/src/lib.rs:194-207); here the same SPMD shape is
expressed once with shard_map and compiled by XLA for any mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def shard_map(f, mesh, in_specs, out_specs):
    """Version-compat shard_map: jax>=0.8 (`jax.shard_map`, check_vma) with
    fallback to the experimental API (check_rep)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    from jax.experimental.shard_map import shard_map as _sm  # pragma: no cover

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )

from ..ops import ed25519 as ed


def default_mesh(n_devices: int | None = None, axis: str = "dp") -> Mesh:
    """1-D data-parallel mesh over the available devices."""
    devs = jax.devices()[: n_devices or len(jax.devices())]
    return Mesh(np.array(devs), (axis,))


def init_multihost(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> Mesh:
    """Initialize the multi-host crypto plane and return the global mesh.

    The reference scales its committee across hosts with one process per
    node and NO cross-host accelerator fabric; here the CRYPTO plane can
    additionally span hosts: each sidecar process calls this once, JAX's
    distributed runtime forms the global device set (ICI within a slice,
    DCN across slices), and the returned 1-D "dp" mesh shards verification
    batches over every chip in the job (`sharded_verify_fn`). Consensus/
    mempool control traffic stays on host-side TCP (SURVEY §5.8) — only
    the batch-verification collectives ride the accelerator fabric.

    Args default from the standard JAX env (JAX_COORDINATOR_ADDRESS etc.)
    when None; single-process callers can skip this entirely and use
    `default_mesh()`.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return default_mesh()


def mesh_2d(n_qc: int, n_dp: int, devices=None) -> Mesh:
    """(qc, dp) mesh: independent QC batches x vote data-parallel."""
    devs = np.array(devices if devices is not None else jax.devices())
    assert devs.size >= n_qc * n_dp, "not enough devices for mesh"
    return Mesh(devs[: n_qc * n_dp].reshape(n_qc, n_dp), ("qc", "dp"))


def _kernel_fn(kernel: str):
    if kernel == "pallas":
        from ..ops.pallas_ladder import _verify_kernel_pallas

        return _verify_kernel_pallas
    return ed._verify_kernel_w4 if kernel == "w4" else ed._verify_kernel


def sharded_verify_fn(mesh: Mesh, dp_axis: str = "dp", kernel: str = "w4"):
    """Jitted (a_y, a_sign, r_enc, s_scalars, h_scalars) -> (mask, n_valid).

    Inputs are sharded over the batch (lane) dimension on `dp_axis`; each
    device runs the full ladder on its shard; n_valid is an ICI psum.
    """
    batch_spec = P(None, dp_axis)
    flat_spec = P(dp_axis)
    base_kernel = _kernel_fn(kernel)

    def local(a_y, a_sign, r_enc, s_scalars, h_scalars):
        mask = base_kernel(a_y, a_sign, r_enc, s_scalars, h_scalars)
        n_valid = jax.lax.psum(
            jnp.sum(mask.astype(jnp.int32)), axis_name=dp_axis
        )
        return mask, n_valid

    mapped = shard_map(
        local,
        mesh=mesh,
        in_specs=(batch_spec, flat_spec, batch_spec, batch_spec, batch_spec),
        out_specs=(flat_spec, P()),
    )
    return jax.jit(mapped)


def sharded_qc_verify_fn(mesh: Mesh):
    """Two-axis QC verification over a (qc, dp) mesh.

    Inputs carry a leading QC dimension: shapes (Q, 32, B), (Q, B), ... .
    Q shards over "qc", the vote batch over "dp". Returns per-QC valid-vote
    counts (Q,) — the quorum-side reduction (`Aggregator::append`'s
    weight-sum, consensus/src/aggregator.rs:78-94) as a dp-axis psum.
    """

    def local(a_y, a_sign, r_enc, s_scalars, h_scalars, s_ok):
        # vmap the single-QC kernel over this shard's QC slice
        mask = jax.vmap(ed._verify_kernel_w4)(
            a_y, a_sign, r_enc, s_scalars, h_scalars
        )
        mask = mask & s_ok  # host-checked s < L canonicality (malleability)
        counts = jax.lax.psum(
            jnp.sum(mask.astype(jnp.int32), axis=1), axis_name="dp"
        )
        return mask, counts

    spec_limb = P("qc", None, "dp")
    spec_flat = P("qc", "dp")
    mapped = shard_map(
        local,
        mesh=mesh,
        in_specs=(
            spec_limb,
            spec_flat,
            spec_limb,
            spec_limb,
            spec_limb,
            spec_flat,
        ),
        out_specs=(spec_flat, P("qc")),
    )
    return jax.jit(mapped)


def sharded_packed_fn(
    mesh: Mesh,
    dp_axis: str = "dp",
    kernel: str = "w4",
    device_hash: bool = False,
):
    """Jitted (128, B) u8 packed wire array -> (B,) bool, batch sharded on
    `dp_axis`. Each device unpacks and verifies its shard — the SAME 6x-
    smaller wire format and unpack-on-device recipe as the single-chip
    packed path (`ed._verify_kernel_w4_packed128`), so the pipelined
    uploader and bucketing machinery work unchanged over a mesh. With
    `device_hash`, rows 96-127 carry 32-byte messages and each device also
    computes h = SHA-512(R||A||M) mod L for its shard (ops.sha512)."""
    if kernel == "pallas":
        from ..ops import pallas_ladder as pl_mod

        base = (
            pl_mod._verify_kernel_pallas_packed128_dh
            if device_hash
            else pl_mod._verify_kernel_pallas_packed128
        )
    else:
        base = (
            ed._verify_kernel_w4_packed128_dh
            if device_hash
            else ed._verify_kernel_w4_packed128
        )

    mapped = shard_map(
        base, mesh=mesh, in_specs=P(None, dp_axis), out_specs=P(dp_axis)
    )
    return jax.jit(mapped)


class ShardedEd25519Verifier(ed.Ed25519TpuVerifier):
    """Drop-in Ed25519TpuVerifier that shards batches over a mesh.

    Uses the packed (128 B/signature) wire format and the threaded upload
    pipeline of the base class; chunks are device_put with an explicit
    batch-axis NamedSharding so the transfer lands sharded (no device-0
    staging + reshard). `packed=False` restores the f32-argument
    `sharded_verify_fn` path (used by the legacy bit-ladder kernel).

    No committee-resident path yet: the committee kernel is not
    shard_map-wrapped, so TpuBackend.register_committee no-ops on a
    sharded backend (generic kernels keep serving committee traffic)."""

    supports_committee = False

    def __init__(self, mesh: Mesh | None = None, **kw):
        super().__init__(**kw)
        self.mesh = mesh or default_mesh()
        self._ndev = int(np.prod([self.mesh.shape[a] for a in self.mesh.axis_names]))
        me = jax.process_index()
        self._multiprocess = any(
            d.process_index != me for d in np.asarray(self.mesh.devices).flat
        )
        # per-device shard keeps full lanes (and pallas BLOCK alignment)
        lane = 128
        if self.kernel == "pallas":
            from ..ops.pallas_ladder import BLOCK

            lane = BLOCK
        self.min_bucket = max(self.min_bucket, lane * self._ndev)
        # max_bucket must stay a multiple of lane*ndev or shard_map cannot
        # split the capped bucket evenly (e.g. 3 devices: doubling 384
        # overshoots a 8192 cap that 384 does not divide).
        align = lane * self._ndev
        self.max_bucket = max(align, self.max_bucket // align * align)
        self.chunk = min(self.chunk, self.max_bucket)
        dp = self.mesh.axis_names[0]
        if self.packed:
            from jax.sharding import NamedSharding

            self._sharded_packed = sharded_packed_fn(self.mesh, dp, self.kernel)
            self._sharded_packed_dh = sharded_packed_fn(
                self.mesh, dp, self.kernel, device_hash=True
            )
            self._put = functools.partial(
                jax.device_put,
                device=NamedSharding(self.mesh, P(None, dp)),
            )
        else:
            self._fn = sharded_verify_fn(self.mesh, dp, self.kernel)

    def _packed_fn(self):
        return self._sharded_packed

    def _packed_dh_fn(self):
        return self._sharded_packed_dh

    def _materialize(self, masks) -> np.ndarray:
        """Multi-host mesh: the mask is sharded across PROCESSES, so a
        plain np.asarray raises ('spans non-addressable devices'); gather
        the global value first. Every process calls verify_batch_mask with
        the same inputs (SPMD), so the allgather is collective-safe."""
        full = masks[0] if len(masks) == 1 else jnp.concatenate(masks)
        if self._multiprocess:
            from jax.experimental import multihost_utils

            return np.asarray(
                multihost_utils.process_allgather(full, tiled=True)
            )
        return np.asarray(full)

    def _run_chunk(self, messages, keys, signatures) -> np.ndarray:
        n = len(messages)
        staged = ed.prepare_batch(
            messages, keys, signatures, want_bits=self.kernel == "bits"
        )
        width = self._bucket(n)
        mask, _ = self._fn(*ed.kernel_args(staged, width, self.kernel))
        return self._materialize([mask])[:n] & staged["s_ok"]
