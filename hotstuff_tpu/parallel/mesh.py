"""Device-mesh parallelism for the crypto plane.

The reference's only compute-dense kernel is batched signature verification
(SURVEY.md §2.8 item 3); at committee scale (64-100 nodes, 100k tx/s input,
BASELINE.json configs) one chip is not enough. This module shards the
verification batch across a `jax.sharding.Mesh`:

  * axis "dp" — data parallel over the vote/signature batch. Each device
    verifies its shard; masks stay sharded; quorum counting rides ICI via
    `psum` collectives inside `shard_map` (never DCN — consensus/mempool
    control traffic stays host-side, SURVEY.md §5.8).
  * axis "qc" — independent QCs / payload batches verified concurrently
    (one QC's votes never wait on another's), the committee-facing axis.

The reference's analogue is thread-level parallelism inside ed25519_dalek's
`verify_batch` (crypto/src/lib.rs:194-207); here the same SPMD shape is
expressed once with shard_map and compiled by XLA for any mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def shard_map(f, mesh, in_specs, out_specs):
    """Version-compat shard_map: jax>=0.8 (`jax.shard_map`, check_vma) with
    fallback to the experimental API (check_rep)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    from jax.experimental.shard_map import shard_map as _sm  # pragma: no cover

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )

from ..ops import ed25519 as ed
from ..utils import metrics


def default_mesh(n_devices: int | None = None, axis: str = "dp") -> Mesh:
    """1-D data-parallel mesh over the available devices."""
    devs = jax.devices()[: n_devices or len(jax.devices())]
    return Mesh(np.array(devs), (axis,))


def init_multihost(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> Mesh:
    """Initialize the multi-host crypto plane and return the global mesh.

    The reference scales its committee across hosts with one process per
    node and NO cross-host accelerator fabric; here the CRYPTO plane can
    additionally span hosts: each sidecar process calls this once, JAX's
    distributed runtime forms the global device set (ICI within a slice,
    DCN across slices), and the returned 1-D "dp" mesh shards verification
    batches over every chip in the job (`sharded_verify_fn`). Consensus/
    mempool control traffic stays on host-side TCP (SURVEY §5.8) — only
    the batch-verification collectives ride the accelerator fabric.

    Args default from the standard JAX env (JAX_COORDINATOR_ADDRESS etc.)
    when None; single-process callers can skip this entirely and use
    `default_mesh()`.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return default_mesh()


def mesh_2d(n_qc: int, n_dp: int, devices=None) -> Mesh:
    """(qc, dp) mesh: independent QC batches x vote data-parallel."""
    devs = np.array(devices if devices is not None else jax.devices())
    assert devs.size >= n_qc * n_dp, "not enough devices for mesh"
    return Mesh(devs[: n_qc * n_dp].reshape(n_qc, n_dp), ("qc", "dp"))


def _kernel_fn(kernel: str):
    if kernel == "pallas":
        from ..ops.pallas_ladder import _verify_kernel_pallas

        return _verify_kernel_pallas
    return ed._verify_kernel_w4 if kernel == "w4" else ed._verify_kernel


def sharded_verify_fn(mesh: Mesh, dp_axis: str = "dp", kernel: str = "w4"):
    """Jitted (a_y, a_sign, r_enc, s_scalars, h_scalars) -> (mask, n_valid).

    Inputs are sharded over the batch (lane) dimension on `dp_axis`; each
    device runs the full ladder on its shard; n_valid is an ICI psum.
    """
    batch_spec = P(None, dp_axis)
    flat_spec = P(dp_axis)
    base_kernel = _kernel_fn(kernel)

    def local(a_y, a_sign, r_enc, s_scalars, h_scalars):
        mask = base_kernel(a_y, a_sign, r_enc, s_scalars, h_scalars)
        n_valid = jax.lax.psum(
            jnp.sum(mask.astype(jnp.int32)), axis_name=dp_axis
        )
        return mask, n_valid

    mapped = shard_map(
        local,
        mesh=mesh,
        in_specs=(batch_spec, flat_spec, batch_spec, batch_spec, batch_spec),
        out_specs=(flat_spec, P()),
    )
    return jax.jit(mapped)


def sharded_qc_verify_fn(mesh: Mesh):
    """Two-axis QC verification over a (qc, dp) mesh.

    Inputs carry a leading QC dimension: shapes (Q, 32, B), (Q, B), ... .
    Q shards over "qc", the vote batch over "dp". Returns per-QC valid-vote
    counts (Q,) — the quorum-side reduction (`Aggregator::append`'s
    weight-sum, consensus/src/aggregator.rs:78-94) as a dp-axis psum.
    """

    def local(a_y, a_sign, r_enc, s_scalars, h_scalars, s_ok):
        # vmap the single-QC kernel over this shard's QC slice
        mask = jax.vmap(ed._verify_kernel_w4)(
            a_y, a_sign, r_enc, s_scalars, h_scalars
        )
        mask = mask & s_ok  # host-checked s < L canonicality (malleability)
        counts = jax.lax.psum(
            jnp.sum(mask.astype(jnp.int32), axis=1), axis_name="dp"
        )
        return mask, counts

    spec_limb = P("qc", None, "dp")
    spec_flat = P("qc", "dp")
    mapped = shard_map(
        local,
        mesh=mesh,
        in_specs=(
            spec_limb,
            spec_flat,
            spec_limb,
            spec_limb,
            spec_limb,
            spec_flat,
        ),
        out_specs=(spec_flat, P("qc")),
    )
    return jax.jit(mapped)


def sharded_packed_fn(
    mesh: Mesh,
    dp_axis: str = "dp",
    kernel: str = "w4",
    device_hash: bool = False,
):
    """Jitted (128, B) u8 packed wire array -> (B,) bool, batch sharded on
    `dp_axis`. Each device unpacks and verifies its shard — the SAME 6x-
    smaller wire format and unpack-on-device recipe as the single-chip
    packed path (`ed._verify_kernel_w4_packed128`), so the pipelined
    uploader and bucketing machinery work unchanged over a mesh. With
    `device_hash`, rows 96-127 carry 32-byte messages and each device also
    computes h = SHA-512(R||A||M) mod L for its shard (ops.sha512)."""
    if kernel == "pallas":
        from ..ops import pallas_ladder as pl_mod

        base = (
            pl_mod._verify_kernel_pallas_packed128_dh
            if device_hash
            else pl_mod._verify_kernel_pallas_packed128
        )
    else:
        base = (
            ed._verify_kernel_w4_packed128_dh
            if device_hash
            else ed._verify_kernel_w4_packed128
        )

    mapped = shard_map(
        base, mesh=mesh, in_specs=P(None, dp_axis), out_specs=P(dp_axis)
    )
    return jax.jit(mapped)


def sharded_committee_fn(mesh: Mesh, dp_axis: str = "dp", device_hash: bool = False):
    """Committee-resident verification over the mesh.

    The `CommitteeTable` arrays ride as REPLICATED operands (`P()` specs —
    one device-resident copy per chip, pushed once at registration by
    `ShardedEd25519Verifier.set_committee`); the (96, B) u8 wire rows and
    (B,) i32 validator indices shard on `dp_axis`. Each device gathers its
    lanes' precomputed -A window tables from its local replica — the
    multi-chip steady state performs zero per-batch decompressions or table
    builds, exactly like the single-chip committee path. With `device_hash`
    the replicated committee `keys_u8` gather feeds the on-device SHA-512
    (rows 64-95 carry 32-byte messages instead of host-computed h)."""
    base = (
        ed._verify_kernel_w4_committee_packed96_dh
        if device_hash
        else ed._verify_kernel_w4_committee_packed96
    )
    # (ta_ypx, ta_ymx, ta_xy2d, valid[, keys_u8]) replicated, then idx + wire
    table_specs = (P(),) * (5 if device_hash else 4)
    mapped = shard_map(
        base,
        mesh=mesh,
        in_specs=(*table_specs, P(dp_axis), P(None, dp_axis)),
        out_specs=P(dp_axis),
    )
    return jax.jit(mapped)


class ShardedEd25519Verifier(ed.Ed25519TpuVerifier):
    """Drop-in Ed25519TpuVerifier that shards batches over a mesh.

    Uses the packed (128 B/signature) wire format and the base class's
    owned DispatchPipeline (ops/pipeline.py: bounded in-flight window,
    pooled staging buffers, streamed per-chunk readback — single-process
    meshes only; a multi-process mesh forces the serial depth=1 window,
    see __init__); chunks are device_put with an explicit batch-axis
    NamedSharding so the transfer lands sharded (no device-0 staging +
    reshard). `packed=False` restores the f32-argument
    `sharded_verify_fn` path (used by the legacy bit-ladder kernel).

    The committee-resident path (`set_committee` /
    `verify_batch_mask_committee`) is first-class: registration pushes one
    replicated copy of the `CommitteeTable` arrays to every chip, and the
    committee kernels are shard_map-wrapped with the tables as replicated
    operands while the 96 B wire rows + 4 B indices shard on the dp axis —
    multi-chip deployments inherit the single-chip zero-decompression
    steady state, with the same snapshot-pinned reconfig-safety contract
    (an epoch re-registration never swaps tables under in-flight chunks)."""

    def __init__(self, mesh: Mesh | None = None, **kw):
        super().__init__(**kw)
        self.mesh = mesh or default_mesh()
        self._ndev = int(np.prod([self.mesh.shape[a] for a in self.mesh.axis_names]))
        me = jax.process_index()
        self._multiprocess = any(
            d.process_index != me for d in np.asarray(self.mesh.devices).flat
        )
        if self._multiprocess:
            # Streamed per-chunk readback is an ALLGATHER on a multi-
            # process mesh, and the pipeline's readback worker would race
            # its collective launches against the upload worker's kernel
            # launches — every process must issue collectives in one
            # global order, so the deeper window is single-process-only.
            # depth=1 keeps the launch order (dispatch k, dispatch k+1,
            # ...) identical on every process, and DEFERRED readback
            # restores the pre-pipeline multihost shape: every chunk's
            # dispatch is queued async (compute still overlaps later
            # chunks' staging), then ONE end-of-batch allgather
            # materializes all masks — per-transfer latency is paid
            # once, not per chunk, decisive over tunneled links.
            self.pipeline.set_depth(1)
            self._defer_readback = True
        # per-device shard keeps full lanes (and pallas BLOCK alignment)
        lane = 128
        if self.kernel == "pallas":
            from ..ops.pallas_ladder import BLOCK

            lane = BLOCK
        # Every bucket must stay a multiple of lane*ndev: shard_map splits
        # the batch axis evenly across devices, and each per-device shard
        # must keep full lanes (pallas additionally needs BLOCK-aligned
        # shards). min_bucket rounds UP to the alignment grid (a plain max
        # would let an off-grid user value through); max_bucket rounds down
        # (e.g. 3 devices: doubling 384 overshoots a 8192 cap that 384 does
        # not divide). `mesh_alignment` is the published floor — TpuBackend
        # scales the committee crossover with it so sub-alignment quorum
        # batches route to host CPU instead of padding up to a full mesh
        # bucket.
        align = lane * self._ndev
        self.mesh_alignment = align
        self.min_bucket = -(-max(self.min_bucket, align) // align) * align
        self.max_bucket = max(align, self.max_bucket // align * align)
        self.chunk = min(self.chunk, self.max_bucket)
        dp = self.mesh.axis_names[0]
        from jax.sharding import NamedSharding

        # Three placement lanes: batch-axis sharded 2-D wire arrays,
        # sharded 1-D lane vectors (committee indices), and fully
        # replicated arrays (committee tables — one copy per chip).
        self._put = functools.partial(
            jax.device_put, device=NamedSharding(self.mesh, P(None, dp))
        )
        self._put_lanes = functools.partial(
            jax.device_put, device=NamedSharding(self.mesh, P(dp))
        )
        self._replicate = functools.partial(
            jax.device_put, device=NamedSharding(self.mesh, P())
        )
        self._sharded_committee = sharded_committee_fn(self.mesh, dp)
        self._sharded_committee_dh = sharded_committee_fn(
            self.mesh, dp, device_hash=True
        )
        if self.packed:
            self._sharded_packed = sharded_packed_fn(self.mesh, dp, self.kernel)
            self._sharded_packed_dh = sharded_packed_fn(
                self.mesh, dp, self.kernel, device_hash=True
            )
        else:
            self._fn = sharded_verify_fn(self.mesh, dp, self.kernel)

    def _packed_fn(self):
        return self._sharded_packed

    def _packed_dh_fn(self):
        return self._sharded_packed_dh

    def _build_committee_table(self, keys):
        """Registration-time replication: every chip in the mesh gets its
        own device-resident copy of the window tables / validity mask /
        key bytes, so the sharded committee kernels consume them as
        replicated shard_map operands with zero per-batch movement."""
        return ed.CommitteeTable(keys, put=self._replicate)

    def _upload_dispatch_committee(self, ct, packed, idx, device_hash, tlkey=None):
        """Uploader-thread leg of the committee path over the mesh: the
        (96, W) wire rows and (W,) index vector land SHARDED on the dp axis
        (no device-0 staging + reshard) and dispatch against the PINNED
        replicated tables of `ct` — a concurrent epoch re-registration must
        not swap replicas under in-flight sharded chunks. `tlkey` threads
        the chunk's device-timeline key (ops/timeline.py) through, same as
        the single-chip leg."""
        tl = ed.timeline
        up_span = tl.span_for("upload", tlkey)
        di_span = tl.span_for("dispatch", tlkey)
        with metrics.span(ed._M_UPLOAD), up_span:
            dev_p = self._put(packed)
            dev_i = self._put_lanes(idx)
        with metrics.span(ed._M_DISPATCH), di_span:
            if device_hash:
                return self._sharded_committee_dh(
                    ct.ta_ypx,
                    ct.ta_ymx,
                    ct.ta_xy2d,
                    ct.valid,
                    ct.keys_u8,
                    dev_i,
                    dev_p,
                )
            return self._sharded_committee(
                ct.ta_ypx, ct.ta_ymx, ct.ta_xy2d, ct.valid, dev_i, dev_p
            )

    def _materialize(self, masks) -> np.ndarray:
        """Multi-host mesh: the mask is sharded across PROCESSES, so a
        plain np.asarray raises ('spans non-addressable devices'); gather
        the global value first. Every process calls verify_batch_mask
        with the same inputs (SPMD) and the multi-process window runs
        depth=1 with DEFERRED readback (__init__), so this allgather is
        reached once per batch in the same order on every process —
        collective-safe."""
        full = masks[0] if len(masks) == 1 else jnp.concatenate(masks)
        if self._multiprocess:
            from jax.experimental import multihost_utils

            # Called ONCE per batch (`_defer_readback` batches every
            # chunk handle into this single allgather); every process
            # reaches it in the same SPMD order — collective-safe.
            return np.asarray(
                multihost_utils.process_allgather(full, tiled=True)
            )
        return np.asarray(full)

    def _run_chunk(self, messages, keys, signatures) -> np.ndarray:
        n = len(messages)
        staged = ed.prepare_batch(
            messages, keys, signatures, want_bits=self.kernel == "bits"
        )
        width = self._bucket(n)
        ed._M_PAD_LANES.inc(width - n)
        mask, _ = self._fn(*ed.kernel_args(staged, width, self.kernel))
        return self._materialize([mask])[:n] & staged["s_ok"]
