"""Mempool core actor (reference mempool/src/core.rs).

Maintains the queue of undelivered payload digests, persists and gossips
payloads, answers PayloadRequests, and serves the consensus driver
(Get/Verify/Cleanup). Under benchmark mode it reproduces the fork's injected
workload: every own/others payload triggers a batched verification of
len(transactions) synthetic (message, key, signature) triples drawn from a
pre-generated pool (mempool/src/core.rs:68-101,135-148,211-224) -- this is
the compute-dense kernel the TPU CryptoBackend accelerates, measured as
votes-verified/sec.
"""

from __future__ import annotations

import asyncio
import logging
import os

from ..crypto import Digest, PublicKey, Signature, generate_keypair
from ..network.net import NetMessage
from ..store import Store
from ..utils import metrics, tracing
from ..utils.actors import Selector, spawn
from ..utils.serde import Reader, Writer
from ..consensus.mempool_driver import (
    MempoolCleanup,
    MempoolGet,
    MempoolVerify,
    PayloadStatus,
)
from .config import MempoolCommittee, MempoolParameters
from .errors import (
    InvalidPayloadSignatureError,
    MempoolError,
    PayloadTooBigError,
    QueueFullError,
    UnknownAuthorityError,
    ensure,
)
from .messages import OwnPayload, Payload, PayloadRequest
from .messages import encode_mempool_message
from .payload_maker import PayloadMaker
from .synchronizer import Synchronizer

log = logging.getLogger("hotstuff.mempool")

PAYLOAD_PREFIX = b"payload:"

_M_PAYLOADS_OWN = metrics.counter("mempool.payloads_own")
_M_PAYLOADS_OTHER = metrics.counter("mempool.payloads_other")
_M_PAYLOAD_BYTES = metrics.counter("mempool.payload_bytes")
_M_REQUESTS_SERVED = metrics.counter("mempool.payload_requests_served")
_M_GOSSIP_DROPPED = metrics.counter("mempool.gossip_dropped")
_M_SYNTHETIC_SKIPPED = metrics.counter("mempool.synthetic_skipped")
_M_REQUESTS_CLAMPED = metrics.counter("mempool.requests_clamped")
_M_VERIFY_BATCH = metrics.histogram(
    "mempool.verify_batch_size", metrics.SIZE_BUCKETS
)


class SyntheticPool:
    """Pre-generated (message, key, signature) triples for the benchmark
    workload (mempool/src/core.rs:68-101: 200k at startup in the fork; size is
    configurable here, drawn cyclically so per-payload work is identical)."""

    def __init__(self, size: int, seed: int = 7) -> None:
        import random

        rng = random.Random(seed)
        self.messages: list[bytes] = []
        self.pairs: list[tuple[PublicKey, Signature]] = []
        for _ in range(size):
            pk, sk = generate_keypair(rng)
            msg = rng.randbytes(32)
            self.messages.append(msg)
            self.pairs.append((pk, Signature.new(Digest(msg), sk)))
        self._cursor = 0

    def take(self, n: int) -> tuple[list[bytes], list[tuple[PublicKey, Signature]]]:
        msgs, pairs = [], []
        size = len(self.messages)
        for _ in range(n):
            i = self._cursor
            msgs.append(self.messages[i])
            pairs.append(self.pairs[i])
            self._cursor = (i + 1) % size
        return msgs, pairs


class Core:
    def __init__(
        self,
        name: PublicKey,
        committee: MempoolCommittee,
        parameters: MempoolParameters,
        store: Store,
        payload_maker: PayloadMaker,
        synchronizer: Synchronizer,
        core_channel: asyncio.Queue,
        consensus_mempool_channel: asyncio.Queue,
        network_tx: asyncio.Queue,
        verification_service=None,
        max_inflight_verifications: int = 8,
    ) -> None:
        from ..crypto.batch_service import BatchVerificationService

        self.name = name
        # MempoolCommittee (static, the pre-reconfig behaviour) or a
        # MempoolEpochView resolving through the node's shared
        # EpochManager: gossip fan-out (broadcast_addresses) follows the
        # CURRENT epoch's committee — a joiner starts receiving payload
        # gossip at the activation boundary, a leaver stops at it —
        # while acceptance (exists) and serving (mempool_address) span
        # the known epochs so boundary-adjacent payloads stay available.
        self.committee = committee
        self.parameters = parameters
        self.store = store
        self.payload_maker = payload_maker
        self.synchronizer = synchronizer
        self.core_channel = core_channel
        self.consensus_mempool_channel = consensus_mempool_channel
        self.network_tx = network_tx
        # Batched off-loop verification: synthetic workload batches and
        # foreign-payload signatures run as bounded background tasks so a
        # device dispatch never stalls the core's select loop (the reference
        # blocks its tokio task here, mempool/src/core.rs:135-148 — this is
        # strictly more pipelined).
        self.verification_service = (
            verification_service or BatchVerificationService()
        )
        self._verify_sem = asyncio.Semaphore(max_inflight_verifications)
        # Payload ACCEPTANCE (1 urgent signature + store) gets its own,
        # larger bound: cheap enough that 64 in flight is generous, but a
        # Byzantine peer streaming payloads must not grow _inflight (and
        # the heap) without limit. Overflowing gossip is dropped — it is
        # best-effort by contract; the payload synchronizer recovers any
        # payload consensus actually needs.
        self._accept_sem = asyncio.Semaphore(64)
        self._inflight: set[asyncio.Task] = set()
        self._gossip_dropped = 0  # payloads shed at full acceptance bound
        self._synthetic_skipped = 0  # workload sigs skipped at a full pipeline
        self._requests_clamped = 0  # oversized payload requests clamped
        # Undelivered payload digests, insertion-ordered (core.rs:50 queue).
        self.queue: dict[Digest, None] = {}
        # Digests already consumed by consensus cleanup. Background payload
        # verification may finish AFTER the block containing the payload
        # committed; inserting then would re-propose a committed payload.
        # Bounded insertion-ordered set (evicts oldest).
        self._cleaned: dict[Digest, None] = {}
        self._cleaned_cap = 4 * parameters.queue_capacity
        self.pool: SyntheticPool | None = None
        if parameters.benchmark_mode:
            log.info(
                "Generating %s synthetic signatures for the benchmark workload",
                parameters.synthetic_pool_size,
            )
            self.pool = SyntheticPool(parameters.synthetic_pool_size)

    # -- persistence ---------------------------------------------------------

    async def _store_payload(self, payload: Payload) -> None:
        w = Writer()
        payload.encode(w)
        await self.store.write(PAYLOAD_PREFIX + payload.digest().data, w.bytes())

    # -- benchmark workload --------------------------------------------------

    async def _submit_synthetic_batch(self, kind: str, n: int) -> None:
        """The fork's injected hot path (mempool/src/core.rs:135-148,211-224),
        run as a bounded background task — multiple batches stay in flight
        while the core keeps processing. The log line here is the single
        source of the votes/sec metric.
        NOTE: This log entry is used to compute performance."""
        if self.pool is None or n == 0:
            return
        if self._verify_sem.locked():
            # Pure measurement load must never block the core loop: with
            # the pipeline saturated, admitting another batch would park
            # this actor on the semaphore and stop it serving
            # PayloadRequests — the recovery path consensus stalls on.
            before = self._synthetic_skipped
            self._synthetic_skipped += n
            _M_SYNTHETIC_SKIPPED.inc(n)
            if before == 0 or before // 25_000 != self._synthetic_skipped // 25_000:
                log.warning(
                    "verification pipeline saturated: %s synthetic workload "
                    "signatures skipped so far (measured rate reflects "
                    "capacity, not demand)",
                    self._synthetic_skipped,
                )
            return
        log.info("Verifying %s transaction batch. Size: %s", kind, n)
        _M_VERIFY_BATCH.record(n)
        msgs, pairs = self.pool.take(n)
        await self._spawn_verification(self._run_synthetic, msgs, pairs)

    async def _run_synthetic(self, msgs, pairs) -> None:
        # dedup=False: the pool cycles a fixed set of pre-signed triples;
        # the verified-signature cache would otherwise absorb every repeat
        # and the measured rate would be the cache's, not the backend's.
        mask = await self.verification_service.verify_group(
            msgs, pairs, urgent=False, dedup=False, source="mempool"
        )
        if not all(mask):
            log.error("synthetic batch verification failed (backend bug?)")

    async def _spawn_verification(self, fn, *args, sem=None) -> None:
        """Run `fn(*args)` in a background task, holding a slot of `sem`
        (default: the workload pipeline cap `_verify_sem`; payload
        acceptance passes the wider `_accept_sem`). Callers check
        `sem.locked()` BEFORE calling (dropping or skipping instead), so
        the acquire here never actually parks the core loop. Deferred-call
        form (not a coroutine argument) so a task cancelled before it
        first runs leaves no never-awaited coroutine behind."""
        sem = self._verify_sem if sem is None else sem
        await sem.acquire()
        task = spawn(self._release_after(sem, fn, *args), name="mempool-verify")
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    async def _release_after(self, sem, fn, *args) -> None:
        try:
            await fn(*args)
        except Exception as e:  # must not kill the task group silently
            log.warning("background verification error: %r", e)
        finally:
            sem.release()

    # -- payload handling ----------------------------------------------------

    async def _handle_own_payload(self, payload: Payload) -> Digest:
        digest = payload.digest()
        _M_PAYLOADS_OWN.inc()
        _M_PAYLOAD_BYTES.inc(payload.size())
        await self._submit_synthetic_batch("OWN", len(payload.transactions))
        # NOTE: These log entries are used to compute performance.
        log.info("Payload %s contains %s B", digest, payload.size())
        for sample_id in payload.sample_tx_ids():
            log.info("Payload %s contains sample tx %s", digest, sample_id)
        await self._store_payload(payload)
        # Share early: disseminate bytes while consensus orders digests later
        # (core.rs:174-175).
        addrs = self.committee.broadcast_addresses(self.name)
        if addrs:
            # Payload gossip rides its own trace lane (round 0 + payload
            # digest prefix): the consensus-side "payload" stage then shows
            # WHETHER availability stalled, and these events show WHY.
            trace = None
            if tracing.enabled():
                trace = tracing.TraceContext(0, digest.data)
                tracing.event("payload.gossip", trace.trace_id, peers=len(addrs))
            await self.network_tx.put(
                NetMessage(encode_mempool_message(payload), addrs, trace=trace)
            )
        self._queue_insert(digest)
        return digest

    async def _handle_others_payload(self, payload: Payload) -> None:
        """Byzantine-input checks at ingress (core.rs:193-234). Structural
        checks raise typed MempoolErrors synchronously; the signature check
        and synthetic workload run in a bounded background task, after which
        the payload is stored (waking any notify_read synchronizer waiters)
        and queued."""
        ensure(
            self.committee.exists(payload.author),
            UnknownAuthorityError(payload.author.short()),
        )
        ensure(
            payload.size() <= self.parameters.max_payload_size,
            PayloadTooBigError(payload.size(), self.parameters.max_payload_size),
        )
        # Acceptance (verify the author's ONE signature, store, queue) is
        # cheap and consensus-critical: it rides its own wide bound
        # (_accept_sem), never the workload-saturated _verify_sem. Only
        # the synthetic workload batch rides the capped pipeline (see
        # _finish_others_payload): under saturation the measurement load
        # is skipped, never the payload. Blocking the core loop here (the
        # pre-round-5 design awaited a semaphore slot held by queued
        # workload batches) starved PayloadRequest serving and froze
        # commits after ~90 s in every 300 s saturation run; dropping at
        # the acceptance bound keeps the loop responsive against a
        # Byzantine payload flood, and the synchronizer re-fetches
        # anything consensus actually needs.
        if self._accept_sem.locked():
            self._gossip_dropped += 1
            _M_GOSSIP_DROPPED.inc()
            if self._gossip_dropped % 1_000 == 1:
                log.warning(
                    "payload acceptance bound full: %s gossiped payloads "
                    "dropped",
                    self._gossip_dropped,
                )
            return
        await self._spawn_verification(
            self._finish_others_payload, payload, sem=self._accept_sem
        )

    async def _finish_others_payload(self, payload: Payload) -> None:
        ok = await payload.verify_async(self.committee, self.verification_service)
        if not ok:
            raise InvalidPayloadSignatureError(payload.author.short())
        _M_PAYLOADS_OTHER.inc()
        _M_PAYLOAD_BYTES.inc(payload.size())
        # Store + queue as soon as the REAL signature verifies: consensus
        # blocks on payload availability, and the synthetic workload below is
        # pure load whose result never gates acceptance (the reference
        # verifies pre-generated triples, mempool/src/core.rs:211-224 — the
        # outcome is measured, not consumed).
        await self._store_payload(payload)
        if tracing.enabled():
            tracing.event(
                "payload.stored", tracing.trace_id(0, payload.digest().data)
            )
        self._queue_insert(payload.digest())
        # The synthetic OTHER batch rides the capped pipeline; at a full
        # pipeline the measurement load is skipped so acceptance never
        # queues behind it.
        await self._submit_synthetic_batch("OTHER", len(payload.transactions))

    def _queue_insert(self, digest: Digest) -> None:
        if digest in self._cleaned:
            return  # already ordered and cleaned up; do not re-propose
        ensure(
            len(self.queue) < self.parameters.queue_capacity,
            QueueFullError(self.parameters.queue_capacity),
        )
        self.queue[digest] = None

    async def _handle_request(self, request: PayloadRequest) -> None:
        """Serve stored payloads to a lagging peer (core.rs:236-249).

        Byzantine bound: replies ride the URGENT egress lane (they un-stall
        the requester's consensus), which a hostile requester could exploit
        as a priority-amplified reflector — at most
        `parameters.max_request_digests` payloads are served per request
        (the PREFIX, so an honest requester with an unusually large block
        still makes progress via its retry loop), and unknown requesters
        are ignored."""
        digests = request.digests
        cap = self.parameters.max_request_digests
        if len(digests) > cap:
            self._requests_clamped += 1
            _M_REQUESTS_CLAMPED.inc()
            if self._requests_clamped % 1_000 == 1:
                log.warning(
                    "clamping oversized payload request (%s digests) from "
                    "%s (%s clamped so far)",
                    len(digests),
                    request.requester.short(),
                    self._requests_clamped,
                )
            digests = digests[:cap]
        addr = self.committee.mempool_address(request.requester)
        if addr is None:
            return
        for digest in digests:
            raw = await self.store.read(PAYLOAD_PREFIX + digest.data)
            if raw is not None:
                _M_REQUESTS_SERVED.inc()
                payload = Payload.decode(Reader(raw))
                trace = None
                if tracing.enabled():
                    trace = tracing.context_for(0, digest.data)
                    tracing.event("payload.served", trace.trace_id)
                # Urgent: the requester's consensus is stalled on this
                # payload; behind the gossip backlog it would drop and the
                # requester would re-broadcast forever.
                await self.network_tx.put(
                    NetMessage(
                        encode_mempool_message(payload), [addr], urgent=True,
                        trace=trace,
                    )
                )

    # -- consensus driver ----------------------------------------------------

    async def _get_payload(self, max_size: int) -> list[Digest]:
        """Pop up to max_size/32 digests; if the queue is dry, force the
        PayloadMaker to flush (core.rs:251-268)."""
        limit = max(1, max_size // Digest.SIZE)
        if self.queue:
            out = []
            for digest in list(self.queue):
                if len(out) >= limit:
                    break
                out.append(digest)
                del self.queue[digest]
            return out
        payload = await self.payload_maker.request_make()
        if not payload.transactions:
            return []
        digest = await self._handle_own_payload(payload)
        # A freshly-made payload can collide with an already-committed digest
        # (identical tx content re-made after a cleanup): _queue_insert skips
        # cleaned digests, and re-proposing one would double-include it.
        if digest not in self.queue:
            return []
        del self.queue[digest]  # it is being delivered right now
        return [digest]

    async def _cleanup(self, msg: MempoolCleanup) -> None:
        for block in (msg.b0, msg.b1, msg.block):
            for digest in block.payload:
                self.queue.pop(digest, None)
                self._cleaned[digest] = None
        while len(self._cleaned) > self._cleaned_cap:
            self._cleaned.pop(next(iter(self._cleaned)))
        self.synchronizer.cleanup(msg.b0.round)

    # -- main loop -----------------------------------------------------------

    async def run(self) -> None:
        selector = Selector()
        selector.add("net", self.core_channel.get)
        selector.add("consensus", self.consensus_mempool_channel.get)
        while True:
            branch, msg = await selector.next()
            # Requests carrying a reply future MUST always be resolved, even
            # on internal errors: the consensus core blocks on the reply in
            # its single select loop, so a dropped future deadlocks the node.
            if isinstance(msg, MempoolGet):
                try:
                    result = await self._get_payload(msg.max_size)
                except Exception as e:
                    log.error("get_payload failed: %r", e)
                    result = []
                if not msg.reply.done():
                    msg.reply.set_result(result)
                continue
            if isinstance(msg, MempoolVerify):
                try:
                    status = await self.synchronizer.verify_payload(msg.block)
                except Exception as e:
                    log.error("verify_payload failed: %r", e)
                    status = PayloadStatus.WAIT
                if not msg.reply.done():
                    msg.reply.set_result(status)
                continue
            try:
                if isinstance(msg, OwnPayload):
                    await self._handle_own_payload(msg.payload)
                elif isinstance(msg, Payload):
                    await self._handle_others_payload(msg)
                elif isinstance(msg, PayloadRequest):
                    await self._handle_request(msg)
                elif isinstance(msg, MempoolCleanup):
                    await self._cleanup(msg)
                else:
                    log.warning("unexpected mempool message: %r", msg)
            except MempoolError as e:  # typed Byzantine-input rejection
                log.warning("%s", e)
            except Exception as e:  # a Byzantine message must not kill the actor
                log.warning("mempool core error: %r", e)

    async def drain_verifications(self) -> None:
        """Await all in-flight background verifications (test/shutdown aid)."""
        while self._inflight:
            await asyncio.gather(*list(self._inflight), return_exceptions=True)
