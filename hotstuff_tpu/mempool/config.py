"""Mempool committee and parameters (reference mempool/src/config.rs:8-84).

Each authority exposes two mempool-plane addresses: `front_address` (client
transactions) and `mempool_address` (mempool-to-mempool payload traffic).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto import PublicKey
from ..network.net import Address


@dataclass(slots=True)
class MempoolAuthority:
    name: PublicKey
    front_address: Address
    mempool_address: Address


@dataclass(slots=True)
class MempoolCommittee:
    authorities: dict[PublicKey, MempoolAuthority]
    epoch: int = 1

    @staticmethod
    def new(
        info: list[tuple[PublicKey, Address, Address]], epoch: int = 1
    ) -> "MempoolCommittee":
        return MempoolCommittee(
            {name: MempoolAuthority(name, front, mem) for name, front, mem in info},
            epoch,
        )

    def exists(self, name: PublicKey) -> bool:
        return name in self.authorities

    def front_address(self, name: PublicKey) -> Address | None:
        a = self.authorities.get(name)
        return a.front_address if a else None

    def mempool_address(self, name: PublicKey) -> Address | None:
        a = self.authorities.get(name)
        return a.mempool_address if a else None

    def broadcast_addresses(self, myself: PublicKey) -> list[Address]:
        return [
            a.mempool_address
            for n, a in self.authorities.items()
            if n != myself
        ]

    def to_json(self) -> dict:
        return {
            "epoch": self.epoch,
            "authorities": {
                n.encode_base64(): {
                    "front_address": f"{a.front_address[0]}:{a.front_address[1]}",
                    "mempool_address": f"{a.mempool_address[0]}:{a.mempool_address[1]}",
                }
                for n, a in self.authorities.items()
            },
        }

    @staticmethod
    def from_json(obj: dict) -> "MempoolCommittee":
        def parse(s: str) -> Address:
            host, port = s.rsplit(":", 1)
            return (host, int(port))

        auths = {}
        for name_b64, a in obj["authorities"].items():
            pk = PublicKey.decode_base64(name_b64)
            auths[pk] = MempoolAuthority(
                pk, parse(a["front_address"]), parse(a["mempool_address"])
            )
        return MempoolCommittee(auths, obj.get("epoch", 1))


class MempoolEpochView:
    """Epoch-aware mempool committee: the payload plane's half of the
    epoch-final handoff (consensus/reconfig.py, §5.5j).

    The genesis MempoolCommittee is static config; this view resolves
    membership through the node's shared EpochManager instead, so
    payload gossip fan-out, sync serving/requesting and address lookup
    cross an epoch boundary at the SAME position as consensus (the
    declared activation round — the manager's round hint is advanced by
    the consensus core, and both planes read one schedule):

      * `broadcast_addresses` — gossip fans out to the CURRENT epoch's
        committee only: a joiner starts receiving payload gossip at the
        switch, a leaver stops at it.
      * `exists` — payload acceptance spans every KNOWN epoch: blocks
        near the boundary still reference payloads authored by the
        adjacent epoch's members, and availability (not authorship
        admission) is the payload plane's contract — ordering authority
        stays with consensus.
      * `mempool_address` — resolves through the manager's payload-plane
        registry (genesis seeds it, applied EpochChanges extend it), so
        a JOINER's payloads become fetchable exactly at the switch and a
        departed member's stored payloads stay servable for old blocks.
      * `front_address` — genesis only: the client-facing port is the
        node's own config, never dialed by peers.

    Duck-type compatible with MempoolCommittee everywhere the mempool
    core/synchronizer consult a committee."""

    __slots__ = ("genesis", "epochs", "_known", "_known_epoch")

    def __init__(self, genesis: MempoolCommittee, epochs) -> None:
        self.genesis = genesis
        self.epochs = epochs
        epochs.seed_mempool_addresses(
            {
                pk: a.mempool_address
                for pk, a in genesis.authorities.items()
            }
        )
        # Cached union of every known epoch's member keys: `exists` runs
        # on the per-payload gossip-ingress hot path, and rescanning the
        # schedule per call would grow linearly with deployment age.
        # Rebuilt lazily when the applied epoch advances.
        self._known: frozenset = frozenset(genesis.authorities)
        self._known_epoch = epochs.applied_epoch

    @property
    def epoch(self) -> int:
        return self.epochs.applied_epoch

    def exists(self, name: PublicKey) -> bool:
        if name in self.genesis.authorities:
            return True
        if self.epochs.applied_epoch != self._known_epoch:
            known = set(self.genesis.authorities)
            for _activation, committee in self.epochs.schedule.entries():
                known.update(committee.authorities)
            self._known = frozenset(known)
            self._known_epoch = self.epochs.applied_epoch
        return name in self._known

    def front_address(self, name: PublicKey) -> Address | None:
        return self.genesis.front_address(name)

    def mempool_address(self, name: PublicKey) -> Address | None:
        addr = self.epochs.mempool_address(name)
        if addr is not None:
            return addr
        return self.genesis.mempool_address(name)

    def members_for_round(self, round_) -> tuple[PublicKey, ...]:
        """The payload-plane membership governing `round_` — by
        construction the consensus committee of the same round, which is
        the 'both planes switch at the same position' pin."""
        return tuple(self.epochs.committee_for_round(round_).sorted_keys())

    def broadcast_addresses(self, myself: PublicKey) -> list[Address]:
        out = []
        for pk in self.epochs.current().sorted_keys():
            if pk == myself:
                continue
            addr = self.mempool_address(pk)
            if addr is not None:
                out.append(addr)
        return out


@dataclass(slots=True)
class MempoolParameters:
    """Reference defaults (mempool/src/config.rs:15-24), plus the benchmark
    workload knobs the fork hard-codes (mempool/src/core.rs:68-101)."""

    queue_capacity: int = 10_000
    sync_retry_delay: int = 10_000
    max_payload_size: int = 100_000
    min_block_delay: int = 100
    # Fork's synthetic batched-signature-verification workload: every
    # own/others payload triggers a batch verification of len(transactions)
    # synthetic (message, key, signature) triples. The reference pre-generates
    # 200_000 triples at startup (mempool/src/core.rs:71-84); the pool size is
    # configurable here (the per-payload verification WORK is identical --
    # triples are drawn cyclically from the pool).
    benchmark_mode: bool = False
    synthetic_pool_size: int = 10_000
    # Bound on the Front's client-tx intake queue (drop-oldest past it,
    # counted in mempool.front_dropped) — the raw benchmark port's share
    # of the admission-control story (hotstuff_tpu/ingress has the
    # authenticated one).
    front_queue_capacity: int = 10_000
    # Authenticated client ingress (hotstuff_tpu/ingress): when enabled,
    # Mempool.run boots an IngressServer on front_port +
    # ingress_port_offset, feeding verified client transactions into the
    # PayloadMaker's DEDICATED ingress intake lane (the Front keeps its
    # own lane, so its drop-oldest overflow can never evict an accepted
    # ingress body — the two planes coexist; scheduler source classes,
    # ISSUE 7 / ROADMAP item 4).
    ingress_enabled: bool = False
    ingress_port_offset: int = 1_000
    # Bound on the ingress intake lane into the PayloadMaker. Unlike the
    # Front's drop-oldest queue, a full ingress lane BLOCKS its producer
    # (the IngressPipeline drain), which is the backpressure chain that
    # ends in admission shedding with retry-after.
    ingress_queue_capacity: int = 2_048
    # Commit-proof serving plane (hotstuff_tpu/proofs): with ingress
    # enabled and a ProofRegistry wired by the composition root,
    # Mempool.run boots a ProofServer on front_port + proofs_port_offset
    # — the finality-read counterpart of the ingress write port.
    proofs_port_offset: int = 2_000
    # Byzantine bound on PayloadRequest serving: at most this many payloads
    # are served per request frame (the prefix; the requester's retry loop
    # fetches the rest). Honest requests cover one block's digests —
    # consensus max_payload_size/32, 15 at the default config — so the
    # default leaves ample headroom while capping the reply amplification
    # a hostile requester can extract from one small frame.
    max_request_digests: int = 1_024

    def log(self, log) -> None:
        # NOTE: these log entries are parsed by the benchmark harness.
        log.info("Queue capacity set to %s", self.queue_capacity)
        log.info("Sync retry delay set to %s ms", self.sync_retry_delay)
        log.info("Max payload size set to %s B", self.max_payload_size)
        log.info("Min block delay set to %s ms", self.min_block_delay)

    def to_json(self) -> dict:
        return {
            "queue_capacity": self.queue_capacity,
            "sync_retry_delay": self.sync_retry_delay,
            "max_payload_size": self.max_payload_size,
            "min_block_delay": self.min_block_delay,
            "benchmark_mode": self.benchmark_mode,
            "synthetic_pool_size": self.synthetic_pool_size,
            "max_request_digests": self.max_request_digests,
            "front_queue_capacity": self.front_queue_capacity,
            "ingress_enabled": self.ingress_enabled,
            "ingress_port_offset": self.ingress_port_offset,
            "ingress_queue_capacity": self.ingress_queue_capacity,
            "proofs_port_offset": self.proofs_port_offset,
        }

    @staticmethod
    def from_json(obj: dict) -> "MempoolParameters":
        p = MempoolParameters()
        for k in (
            "queue_capacity",
            "sync_retry_delay",
            "max_payload_size",
            "min_block_delay",
            "benchmark_mode",
            "synthetic_pool_size",
            "max_request_digests",
            "front_queue_capacity",
            "ingress_enabled",
            "ingress_port_offset",
            "ingress_queue_capacity",
            "proofs_port_offset",
        ):
            if k in obj:
                setattr(p, k, obj[k])
        return p
