"""Mempool wire messages (reference mempool/src/messages.rs:10-55).

Payload{transactions, author, signature}: a signed batch of raw client
transactions. Consensus orders only the payload's 32-byte digest; these bytes
travel on the mempool plane -- the dissemination/ordering split that keeps
blocks small (SURVEY.md section 1).
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass, field

from ..crypto import Digest, PublicKey, SecretKey, Signature
from ..utils.serde import Reader, SerdeError, Writer

Transaction = bytes


@dataclass(frozen=True, slots=True)
class Payload:
    transactions: tuple[Transaction, ...]
    author: PublicKey
    signature: Signature
    # digest cache: a payload's digest is read on every store/queue/verify/
    # log touch (a ~30-hash recompute per touch dominated the mempool
    # profile); length-prefixed single-pass hash, computed once.
    _digest: Digest | None = field(
        default=None, init=False, repr=False, compare=False
    )

    @staticmethod
    def make_digest(author: PublicKey, transactions) -> Digest:
        h = hashlib.sha512()
        h.update(b"HSPAYLOAD")
        h.update(author.data)
        h.update(struct.pack("<I", len(transactions)))
        for tx in transactions:
            h.update(struct.pack("<I", len(tx)))  # keeps the encoding injective
            h.update(tx)
        return Digest(h.digest()[:32])

    @staticmethod
    def new_from_key(
        transactions: list[Transaction], author: PublicKey, secret: SecretKey
    ) -> "Payload":
        digest = Payload.make_digest(author, transactions)
        payload = Payload(tuple(transactions), author, Signature.new(digest, secret))
        object.__setattr__(payload, "_digest", digest)  # seed the cache
        return payload

    def digest(self) -> Digest:
        if self._digest is None:
            object.__setattr__(
                self, "_digest", Payload.make_digest(self.author, self.transactions)
            )
        return self._digest

    def size(self) -> int:
        return sum(len(tx) for tx in self.transactions)

    def verify(self, committee) -> bool:
        return self.signature.verify(self.digest(), self.author)

    async def verify_async(self, committee, service) -> bool:
        """Signature check through the BatchVerificationService, declared
        on the scheduler's SYNC lane: consensus blocks on payload
        AVAILABILITY (MempoolDriver verify -> Wait,
        consensus/src/mempool.rs:45-60) for both gossiped and sync-
        re-fetched payloads, so this check must never queue behind a bulk
        flush timer — the sync class drains first among the batched lanes
        with a 1 ms deadline, without riding the preemptive critical lane
        QC/TC checks own."""
        return await service.verify(
            self.digest().data, self.author, self.signature, source="sync"
        )

    def sample_tx_ids(self) -> list[int]:
        """Sample transactions start with a zero byte followed by a u64 id
        (node/src/client.rs:121-137); used for end-to-end latency tracking."""
        out = []
        for tx in self.transactions:
            if len(tx) >= 9 and tx[0] == 0:
                out.append(struct.unpack(">Q", tx[1:9])[0])
        return out

    def encode(self, w: Writer) -> None:
        w.seq(list(self.transactions), lambda wr, tx: wr.var_bytes(tx))
        w.fixed(self.author.data, 32)
        w.fixed(self.signature.data, 64)

    @staticmethod
    def decode(r: Reader) -> "Payload":
        txs = tuple(r.seq(lambda rd: rd.var_bytes()))
        return Payload(txs, PublicKey(r.fixed(32)), Signature(r.fixed(64)))

    def __str__(self) -> str:
        return f"Payload({self.digest().short()}, {len(self.transactions)} txs)"


# ---------------------------------------------------------------------------
# Wire envelope for the mempool port (reference MempoolMessage enum).

TAG_PAYLOAD = 0
TAG_PAYLOAD_REQUEST = 1


@dataclass(frozen=True, slots=True)
class PayloadRequest:
    digests: tuple[Digest, ...]
    requester: PublicKey


@dataclass(frozen=True, slots=True)
class OwnPayload:
    """Internal-only: a freshly made payload from the PayloadMaker."""

    payload: Payload


def encode_mempool_message(msg) -> bytes:
    w = Writer()
    if isinstance(msg, Payload):
        w.u8(TAG_PAYLOAD)
        msg.encode(w)
    elif isinstance(msg, PayloadRequest):
        w.u8(TAG_PAYLOAD_REQUEST)
        w.seq(list(msg.digests), lambda wr, d: wr.fixed(d.data, 32))
        w.fixed(msg.requester.data, 32)
    else:
        raise TypeError(f"not a mempool message: {msg!r}")
    return w.bytes()


def decode_mempool_message(data: bytes):
    r = Reader(data)
    tag = r.u8()
    if tag == TAG_PAYLOAD:
        out = Payload.decode(r)
    elif tag == TAG_PAYLOAD_REQUEST:
        digests = tuple(r.seq(lambda rd: Digest(rd.fixed(32))))
        out = PayloadRequest(digests, PublicKey(r.fixed(32)))
    else:
        raise SerdeError(f"unknown mempool tag {tag}")
    r.expect_done()
    return out
