from .config import MempoolCommittee, MempoolParameters
from .mempool import Mempool
from .messages import Payload, PayloadRequest, Transaction

__all__ = [
    "MempoolCommittee",
    "MempoolParameters",
    "Mempool",
    "Payload",
    "PayloadRequest",
    "Transaction",
]
