"""Transaction batching actor (reference mempool/src/payload.rs).

Accumulates client transactions and flushes a signed Payload when the batch
would exceed max_payload_size (then pauses min_block_delay, pacing block
production, payload.rs:43-53) or on-demand when consensus needs a payload and
the queue is empty (`make`, payload.rs:55-63,120).
"""

from __future__ import annotations

import asyncio
import logging

from ..crypto import PublicKey, SignatureService
from ..utils import tracing
from ..utils.actors import Selector, channel, spawn
from .messages import OwnPayload, Payload, Transaction

log = logging.getLogger("hotstuff.mempool")


class PayloadMaker:
    def __init__(
        self,
        name: PublicKey,
        signature_service: SignatureService,
        max_payload_size: int,
        min_block_delay: int,
        tx_in: asyncio.Queue,
        core_channel: asyncio.Queue,
    ) -> None:
        self.name = name
        self.signature_service = signature_service
        self.max_payload_size = max_payload_size
        self.min_block_delay = min_block_delay
        self.tx_in = tx_in
        self.core_channel = core_channel
        self._make_requests: asyncio.Queue = channel()
        self._buffer: list[Transaction] = []
        self._size = 0
        # Load shedding (set by Mempool.run): when this returns True the
        # mempool queue is at capacity, and flushing another payload would
        # only burn a signature + a committee broadcast before the insert
        # fails with QueueFullError (core.rs:131). Shed incoming txs
        # instead, so throughput stays flat past saturation.
        self.backlog_fn = lambda: False
        self.shed = 0
        self._backlogged = False  # last observed backpressure state
        spawn(self._run(), name="payload-maker")

    async def request_make(self) -> Payload:
        """Force an immediate flush; returns the payload (possibly empty).
        Used by the mempool core when consensus asks for digests and the
        queue is dry (mempool/src/core.rs:251-268)."""
        fut = asyncio.get_running_loop().create_future()
        await self._make_requests.put(fut)
        return await fut

    async def _make(self) -> Payload:
        txs, self._buffer, self._size = self._buffer, [], 0
        digest = Payload.make_digest(self.name, txs)
        signature = await self.signature_service.request_signature(digest)
        payload = Payload(tuple(txs), self.name, signature)
        object.__setattr__(payload, "_digest", digest)  # seed the cache
        return payload

    async def _ingest(self, tx: Transaction) -> None:
        backlogged = self.backlog_fn()
        if backlogged != self._backlogged or backlogged:
            # Transitions land in the flight recorder; sustained pressure
            # feeds the anomaly watchdog (the round-5 freeze signature:
            # cold-lane egress pinned at capacity while rounds stall).
            self._backlogged = backlogged
            tracing.WATCHDOG.note_backpressure(backlogged)
        if backlogged:
            self.shed += 1
            if self.shed % 10_000 == 1:
                log.warning(
                    "payload maker shedding: %s transactions dropped "
                    "(mempool queue at capacity)",
                    self.shed,
                )
            return
        if len(tx) > self.max_payload_size:
            # A single oversized tx would flush as a payload every honest
            # peer rejects at ingress (PayloadTooBigError), leaving a
            # forever-unavailable digest in our queue. Drop it here.
            log.warning(
                "dropping oversized transaction (%s B > %s B cap)",
                len(tx),
                self.max_payload_size,
            )
            return
        if self._size + len(tx) > self.max_payload_size and self._buffer:
            await self._flush()
        self._buffer.append(tx)
        self._size += len(tx)
        if self._size >= self.max_payload_size:
            await self._flush()

    async def _flush(self) -> None:
        payload = await self._make()
        await self.core_channel.put(OwnPayload(payload))
        if self.min_block_delay:
            # Pace block production (payload.rs:49-52).
            await asyncio.sleep(self.min_block_delay / 1000.0)

    async def _run(self) -> None:
        selector = Selector()
        selector.add("tx", self.tx_in.get)
        selector.add("make", self._make_requests.get)
        while True:
            branch, value = await selector.next()
            if branch == "tx":
                await self._ingest(value)
                # Drain whatever is already queued without an event-loop
                # round trip per transaction (~13% of node CPU at 4k tx/s
                # went to per-tx actor wakeups before this) — but yield to
                # any pending consensus-driven make request: starving it
                # would stall Core._get_payload and halt round progress.
                # NOTE: the request may sit in the selector's armed task
                # (which already consumed the queue item), so check both.
                while not selector.ready("make") and self._make_requests.empty():
                    try:
                        tx = self.tx_in.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    await self._ingest(tx)
            else:  # make request
                payload = await self._make()
                if not value.cancelled():
                    value.set_result(payload)
