"""Transaction batching actor (reference mempool/src/payload.rs).

Accumulates client transactions and flushes a signed Payload when the batch
would exceed max_payload_size (then pauses min_block_delay, pacing block
production, payload.rs:43-53) or on-demand when consensus needs a payload and
the queue is empty (`make`, payload.rs:55-63,120).

Intake is PER-PLANE (the scheduler source-class split applied to the
mempool seam, ISSUE 7): the anonymous Front feeds `tx_in` (bounded,
drop-oldest at the Front), the authenticated ingress pipeline feeds its
own `ingress_in` lane (bounded, BLOCKING producer). The PR 6 coexistence
caveat — the Front's drop-oldest overflow evicting accepted ingress
bodies out of a shared queue — is structurally gone: an eviction in one
lane cannot touch the other, and the ingress lane's backpressure chain
(full lane → pipeline drain blocks → admission sheds with retry-after)
actually engages instead of being defeated by Front evictions freeing
slots.
"""

from __future__ import annotations

import asyncio
import logging

from ..crypto import PublicKey, SignatureService
from ..utils import metrics, tracing
from ..utils.actors import Selector, channel, spawn
from .messages import OwnPayload, Payload, Transaction

log = logging.getLogger("hotstuff.mempool")

_M_INGRESS_TXS = metrics.counter("mempool.ingress_lane_txs")

# How often the guarded ingress intake re-checks a standing backlog; only
# ever polled while the core queue is at capacity (see _ingress_get).
_BACKLOG_POLL_S = 0.05


class PayloadMaker:
    def __init__(
        self,
        name: PublicKey,
        signature_service: SignatureService,
        max_payload_size: int,
        min_block_delay: int,
        tx_in: asyncio.Queue,
        core_channel: asyncio.Queue,
        ingress_in: asyncio.Queue | None = None,
        proof_registry=None,
    ) -> None:
        self.name = name
        self.signature_service = signature_service
        self.max_payload_size = max_payload_size
        self.min_block_delay = min_block_delay
        self.tx_in = tx_in
        self.ingress_in = ingress_in
        self.core_channel = core_channel
        # Commit-proof serving plane: flushed ingress bodies are paired
        # back to their admitted tx digests under the payload digest, so
        # a later commit of that payload resolves (client, nonce) →
        # proof (proofs/registry.py note_payload).
        self.proof_registry = proof_registry
        self._make_requests: asyncio.Queue = channel()
        self._buffer: list[Transaction] = []
        self._size = 0
        # Load shedding (set by Mempool.run): when this returns True the
        # mempool queue is at capacity, and flushing another payload would
        # only burn a signature + a committee broadcast before the insert
        # fails with QueueFullError (core.rs:131). Shed incoming FRONT txs
        # instead, so throughput stays flat past saturation; the ingress
        # lane never sheds here — its intake pauses and backpressure
        # propagates to admission (see _ingress_get).
        self.backlog_fn = lambda: False
        self.shed = 0
        self._backlogged = False  # last observed backpressure state
        spawn(self._run(), name="payload-maker")

    async def request_make(self) -> Payload:
        """Force an immediate flush; returns the payload (possibly empty).
        Used by the mempool core when consensus asks for digests and the
        queue is dry (mempool/src/core.rs:251-268)."""
        fut = asyncio.get_running_loop().create_future()
        await self._make_requests.put(fut)
        return await fut

    async def _make(self) -> Payload:
        # Never emit a payload past the wire cap: backlog-buffered ingress
        # txs append WITHOUT flushing (both flush conditions in _ingest are
        # gated off under backlog), so the buffer can sit over
        # max_payload_size when the backlog clears — and an oversized
        # payload fails every peer's `payload.size() <= max_payload_size`
        # ingress check (core.py), a forever-unavailable digest that would
        # stall any block referencing it. Split at the cap; the remainder
        # stays buffered for the next flush/make (every single tx fits:
        # oversized ones are dropped at _ingest).
        split, taken = 0, 0
        for tx in self._buffer:
            if taken + len(tx) > self.max_payload_size and split:
                break
            taken += len(tx)
            split += 1
        txs, self._buffer = self._buffer[:split], self._buffer[split:]
        self._size -= taken
        digest = Payload.make_digest(self.name, txs)
        if self.proof_registry is not None and txs:
            self.proof_registry.note_payload(txs, digest)
        signature = await self.signature_service.request_signature(digest)
        payload = Payload(tuple(txs), self.name, signature)
        object.__setattr__(payload, "_digest", digest)  # seed the cache
        return payload

    async def _ingest(self, tx: Transaction, shed_ok: bool = True) -> None:
        backlogged = self.backlog_fn()
        if backlogged != self._backlogged or backlogged:
            # Transitions land in the flight recorder; sustained pressure
            # feeds the anomaly watchdog (the round-5 freeze signature:
            # cold-lane egress pinned at capacity while rounds stall).
            self._backlogged = backlogged
            tracing.WATCHDOG.note_backpressure(backlogged)
        if backlogged and shed_ok:
            self.shed += 1
            if self.shed % 10_000 == 1:
                log.warning(
                    "payload maker shedding: %s transactions dropped "
                    "(mempool queue at capacity)",
                    self.shed,
                )
            return
        if len(tx) > self.max_payload_size:
            # A single oversized tx would flush as a payload every honest
            # peer rejects at ingress (PayloadTooBigError), leaving a
            # forever-unavailable digest in our queue. Drop it here.
            log.warning(
                "dropping oversized transaction (%s B > %s B cap)",
                len(tx),
                self.max_payload_size,
            )
            return
        if not shed_ok:
            _M_INGRESS_TXS.inc()
        # While backlogged, a shed_ok=False (ingress) tx BUFFERS without
        # flushing: _ingress_get stops consuming under backlog, so at most
        # the already-armed item lands here, and flushing now would sign +
        # gossip a payload the full core queue rejects (QueueFullError —
        # the whole payload, front txs included, would be lost).
        if (
            self._size + len(tx) > self.max_payload_size
            and self._buffer
            and not backlogged
        ):
            await self._flush()
        self._buffer.append(tx)
        self._size += len(tx)
        if self._size >= self.max_payload_size and not backlogged:
            await self._flush()

    async def _flush(self) -> None:
        payload = await self._make()
        await self.core_channel.put(OwnPayload(payload))
        if self.min_block_delay:
            # Pace block production (payload.rs:49-52).
            await asyncio.sleep(self.min_block_delay / 1000.0)

    async def _ingress_get(self) -> Transaction:
        """Guarded ingress intake: holds off CONSUMING while the core
        queue is backlogged — the lane is bounded and its producer (the
        IngressPipeline drain) blocks on put, which is the backpressure
        chain that ends in admission shedding with a retry-after hint.
        Consuming during backlog would instead strand an accepted body in
        the buffer (or force a shed the client was already promised
        ACCEPTED against)."""
        while self.backlog_fn():
            await asyncio.sleep(_BACKLOG_POLL_S)
        return await self.ingress_in.get()

    async def _run(self) -> None:
        selector = Selector()
        selector.add("tx", self.tx_in.get)
        if self.ingress_in is not None:
            # Lower priority number = wins same-instant races: an accepted
            # ingress body (client already told ACCEPTED) beats anonymous
            # Front traffic into the buffer.
            selector.add("ingress", self._ingress_get, priority=-1)
        selector.add("make", self._make_requests.get)
        while True:
            branch, value = await selector.next()
            if branch == "make":
                payload = await self._make()
                if not value.cancelled():
                    value.set_result(payload)
                continue
            await self._ingest(value, shed_ok=branch == "tx")
            # Drain whatever is already queued without an event-loop
            # round trip per transaction (~13% of node CPU at 4k tx/s
            # went to per-tx actor wakeups before this) — but yield to
            # any pending consensus-driven make request: starving it
            # would stall Core._get_payload and halt round progress.
            # NOTE: the request may sit in the selector's armed task
            # (which already consumed the queue item), so check both.
            # Ingress drains first (lane priority), and only while the
            # core queue has room — mirroring _ingress_get's guard.
            while not selector.ready("make") and self._make_requests.empty():
                if self.ingress_in is not None and not self.backlog_fn():
                    try:
                        tx = self.ingress_in.get_nowait()
                    except asyncio.QueueEmpty:
                        pass
                    else:
                        await self._ingest(tx, shed_ok=False)
                        continue
                try:
                    tx = self.tx_in.get_nowait()
                except asyncio.QueueEmpty:
                    break
                await self._ingest(tx)
