"""Transaction batching actor (reference mempool/src/payload.rs).

Accumulates client transactions and flushes a signed Payload when the batch
would exceed max_payload_size (then pauses min_block_delay, pacing block
production, payload.rs:43-53) or on-demand when consensus needs a payload and
the queue is empty (`make`, payload.rs:55-63,120).
"""

from __future__ import annotations

import asyncio
import logging

from ..crypto import PublicKey, SignatureService
from ..utils.actors import Selector, channel, spawn
from .messages import OwnPayload, Payload, Transaction

log = logging.getLogger("hotstuff.mempool")


class PayloadMaker:
    def __init__(
        self,
        name: PublicKey,
        signature_service: SignatureService,
        max_payload_size: int,
        min_block_delay: int,
        tx_in: asyncio.Queue,
        core_channel: asyncio.Queue,
    ) -> None:
        self.name = name
        self.signature_service = signature_service
        self.max_payload_size = max_payload_size
        self.min_block_delay = min_block_delay
        self.tx_in = tx_in
        self.core_channel = core_channel
        self._make_requests: asyncio.Queue = channel()
        self._buffer: list[Transaction] = []
        self._size = 0
        spawn(self._run(), name="payload-maker")

    async def request_make(self) -> Payload:
        """Force an immediate flush; returns the payload (possibly empty).
        Used by the mempool core when consensus asks for digests and the
        queue is dry (mempool/src/core.rs:251-268)."""
        fut = asyncio.get_running_loop().create_future()
        await self._make_requests.put(fut)
        return await fut

    async def _make(self) -> Payload:
        txs, self._buffer, self._size = self._buffer, [], 0
        digest = Payload.make_digest(self.name, txs)
        signature = await self.signature_service.request_signature(digest)
        return Payload(tuple(txs), self.name, signature)

    async def _run(self) -> None:
        selector = Selector()
        selector.add("tx", self.tx_in.get)
        selector.add("make", self._make_requests.get)
        while True:
            branch, value = await selector.next()
            if branch == "tx":
                if self._size + len(value) > self.max_payload_size and self._buffer:
                    payload = await self._make()
                    await self.core_channel.put(OwnPayload(payload))
                    # Pace block production (payload.rs:49-52).
                    await asyncio.sleep(self.min_block_delay / 1000.0)
                self._buffer.append(value)
                self._size += len(value)
                if self._size >= self.max_payload_size:
                    payload = await self._make()
                    await self.core_channel.put(OwnPayload(payload))
                    await asyncio.sleep(self.min_block_delay / 1000.0)
            else:  # make request
                payload = await self._make()
                if not value.cancelled():
                    value.set_result(payload)
