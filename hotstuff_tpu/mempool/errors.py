"""Typed mempool errors (reference mempool/src/error.rs).

The reference rejects Byzantine payloads with `MempoolError` variants via
`bail!`/`ensure!`; mirroring that here makes the ingress behaviour testable
by assertion (a dropped payload carries WHY it was dropped, not just a log
line). The consensus plane has the same pattern in consensus/errors.py.
"""

from __future__ import annotations


class MempoolError(Exception):
    """Base for every typed mempool rejection."""


class UnknownAuthorityError(MempoolError):
    def __init__(self, author) -> None:
        self.author = author
        super().__init__(f"payload from unknown authority {author}")


class PayloadTooBigError(MempoolError):
    def __init__(self, size: int, cap: int) -> None:
        self.size = size
        self.cap = cap
        super().__init__(f"payload size {size} exceeds cap {cap}")


class InvalidPayloadSignatureError(MempoolError):
    def __init__(self, author) -> None:
        self.author = author
        super().__init__(f"invalid payload signature from {author}")


class QueueFullError(MempoolError):
    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        super().__init__(f"mempool queue full (capacity {capacity})")


def ensure(condition: bool, error: MempoolError) -> None:
    """The reference's ensure! macro (mempool/src/error.rs)."""
    if not condition:
        raise error
