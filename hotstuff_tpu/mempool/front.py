"""Client-facing transaction listener (reference mempool/src/front.rs).

Accepts raw length-delimited transaction bytes from load generators / clients
and forwards them into the PayloadMaker's channel. No authentication: this is
the benchmark ingress port, exactly as in the reference.
"""

from __future__ import annotations

import asyncio
import logging

from ..network.net import Address, FrameReader
from ..utils import metrics
from ..utils.actors import spawn

log = logging.getLogger("hotstuff.mempool")

_M_FRONT_DROPPED = metrics.counter("mempool.front_dropped")


class Front:
    """Admission control at the ingress (SURVEY §5.3): the intake queue is
    bounded with drop-OLDEST overflow. Blocking on a full queue looks
    gentler but is worse under sustained overload — every queued tx ages
    while it waits, so the node spends its capacity committing stale
    transactions nobody is waiting for anymore, and end-to-end latency
    grows without bound. Dropping the oldest keeps the queue fresh and
    makes throughput flat (not collapsing) past saturation.

    The deliver queue's BOUND is the admission policy's other half:
    Mempool.run sizes it from `MempoolParameters.front_queue_capacity`
    (the previous implicit channel default left the bound undeclared),
    and every eviction counts into `mempool.front_dropped` — the same
    shed-visibility contract the authenticated ingress lanes
    (hotstuff_tpu/ingress) carry, minus the per-client backpressure
    response this anonymous port cannot deliver."""

    LOG_EVERY = 10_000  # dropped-tx log cadence

    def __init__(self, address: Address, deliver: asyncio.Queue) -> None:
        self._address = address
        self._deliver = deliver
        self.dropped = 0
        spawn(self._run(), name="front")

    async def _run(self) -> None:
        server = await asyncio.start_server(
            self._handle, host=self._address[0], port=self._address[1]
        )
        log.debug("front listening on %s", self._address)
        async with server:
            await server.serve_forever()

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        frames = FrameReader(reader)
        while True:
            try:
                tx = await frames.next_frame()
            except ConnectionError:
                break
            if tx is None:
                break
            try:
                self._deliver.put_nowait(tx)
            except asyncio.QueueFull:
                # Drop-oldest: evict the stalest queued tx for the new one.
                try:
                    self._deliver.get_nowait()
                except asyncio.QueueEmpty:
                    pass
                self._deliver.put_nowait(tx)
                self.dropped += 1
                _M_FRONT_DROPPED.inc()
                if self.dropped % self.LOG_EVERY == 1:
                    log.warning(
                        "front overloaded: %s transactions dropped "
                        "(drop-oldest admission control)",
                        self.dropped,
                    )
        try:
            writer.close()
        except Exception:
            pass
