"""Client-facing transaction listener (reference mempool/src/front.rs).

Accepts raw length-delimited transaction bytes from load generators / clients
and forwards them into the PayloadMaker's channel. No authentication: this is
the benchmark ingress port, exactly as in the reference.
"""

from __future__ import annotations

import asyncio
import logging

from ..network.net import Address, read_frame
from ..utils.actors import spawn

log = logging.getLogger("hotstuff.mempool")


class Front:
    def __init__(self, address: Address, deliver: asyncio.Queue) -> None:
        self._address = address
        self._deliver = deliver
        spawn(self._run(), name="front")

    async def _run(self) -> None:
        server = await asyncio.start_server(
            self._handle, host=self._address[0], port=self._address[1]
        )
        log.debug("front listening on %s", self._address)
        async with server:
            await server.serve_forever()

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        while True:
            try:
                tx = await read_frame(reader)
            except ConnectionError:
                break
            if tx is None:
                break
            await self._deliver.put(tx)
        try:
            writer.close()
        except Exception:
            pass
