"""Payload-availability synchronizer (reference mempool/src/synchronizer.rs).

When consensus asks whether a block's payloads are locally available
(`verify_payload`, synchronizer.rs:197-214):
  * all present  -> ACCEPT
  * any missing  -> send a PayloadRequest to the block's author, spawn a
    cancellable waiter on notify_read of ALL missing digests
    (try_join_all, :158-173), and return WAIT; when the last payload arrives
    the block is looped back to the consensus core (:114).
Waiters are cancelled when their block's round is cleaned up (:216), and a
retry ticker re-broadcasts stale requests (:123-147).
"""

from __future__ import annotations

import asyncio
import logging

from ..crypto import Digest, PublicKey
from ..network.net import NetMessage
from ..store import Store
from ..utils.actors import spawn
from ..consensus.messages import Block, LoopBack
from ..consensus.mempool_driver import PayloadStatus
from .messages import PayloadRequest, encode_mempool_message

log = logging.getLogger("hotstuff.mempool")

TIMER_ACCURACY_MS = 5_000


class Synchronizer:
    def __init__(
        self,
        name: PublicKey,
        committee,  # MempoolCommittee | MempoolEpochView (epoch-aware)
        store: Store,
        network_tx: asyncio.Queue,
        consensus_channel: asyncio.Queue,
        sync_retry_delay: int,
    ) -> None:
        self.name = name
        self.committee = committee
        self.store = store
        self.network_tx = network_tx
        self.consensus_channel = consensus_channel
        self.sync_retry_delay = sync_retry_delay
        # block digest -> (round, waiter task, requested payload digests, ts)
        self._pending: dict[Digest, tuple[int, asyncio.Task, tuple[Digest, ...], float]] = {}
        spawn(self._retry_loop(), name="mempool-sync-retry")

    async def verify_payload(self, block: Block) -> PayloadStatus:
        missing = []
        for digest in block.payload:
            if await self.store.read(b"payload:" + digest.data) is None:
                missing.append(digest)
        if not missing:
            return PayloadStatus.ACCEPT
        block_digest = block.digest()
        if block_digest not in self._pending:
            log.debug(
                "%s missing %d payloads; requesting from author", block, len(missing)
            )
            waiter = spawn(
                self._waiter(block, tuple(missing)),
                name=f"payload-wait-{block_digest.short()}",
            )
            self._pending[block_digest] = (
                block.round,
                waiter,
                tuple(missing),
                # Loop clock (== monotonic in production): the chaos
                # runner's virtual-time loop must drive the retry schedule.
                asyncio.get_running_loop().time(),
            )
            await self._request(tuple(missing), [block.author])
        return PayloadStatus.WAIT

    async def _waiter(self, block: Block, missing: tuple[Digest, ...]) -> None:
        await asyncio.gather(
            *(self.store.notify_read(b"payload:" + d.data) for d in missing)
        )
        self._pending.pop(block.digest(), None)
        await self.consensus_channel.put(LoopBack(block))

    async def _request(
        self, digests: tuple[Digest, ...], authors: list[PublicKey] | None
    ) -> None:
        data = encode_mempool_message(PayloadRequest(digests, self.name))
        if authors is None:  # retry path: broadcast
            # Epoch-aware: the CURRENT committee (a MempoolEpochView
            # resolves it through the shared EpochManager) — after a
            # boundary, retries reach the members who actually hold the
            # successor epoch's payloads.
            addrs = self.committee.broadcast_addresses(self.name)
        else:
            addrs = [
                a
                for a in (self.committee.mempool_address(x) for x in authors)
                if a is not None
            ]
        if addrs:
            # Urgent: a sync request stuck behind the very gossip backlog
            # that caused the miss would never un-stall consensus.
            await self.network_tx.put(NetMessage(data, addrs, urgent=True))

    def cleanup(self, round_: int) -> None:
        """Cancel waiters for blocks at or below the committed round
        (synchronizer.rs:216-221)."""
        for digest, (r, task, _, _) in list(self._pending.items()):
            if r <= round_:
                task.cancel()
                del self._pending[digest]

    async def _retry_loop(self) -> None:
        while True:
            await asyncio.sleep(TIMER_ACCURACY_MS / 1000.0)
            now = asyncio.get_running_loop().time()
            for digest, (r, task, missing, ts) in list(self._pending.items()):
                if (now - ts) * 1000.0 >= self.sync_retry_delay:
                    log.debug("retrying payload request for block %s", digest.short())
                    await self._request(missing, None)
