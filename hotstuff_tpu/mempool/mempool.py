"""Mempool subsystem launcher (reference mempool/src/mempool.rs:21-115):
wires the Front, net sender/receiver, payload maker, synchronizer, and core.
"""

from __future__ import annotations

import asyncio
import logging

from ..crypto import PublicKey, SignatureService
from ..network import NetReceiver, NetSender
from ..store import Store
from ..utils.actors import channel, spawn
from .config import MempoolCommittee, MempoolEpochView, MempoolParameters
from .core import Core
from .front import Front
from .messages import decode_mempool_message
from .payload_maker import PayloadMaker
from .synchronizer import Synchronizer

log = logging.getLogger("hotstuff.mempool")


class Mempool:
    @staticmethod
    def run(
        name: PublicKey,
        committee: MempoolCommittee,
        parameters: MempoolParameters,
        store: Store,
        signature_service: SignatureService,
        consensus_mempool_channel: asyncio.Queue,
        consensus_channel: asyncio.Queue,
        verification_service=None,
        epoch_manager=None,
        listen_addresses: tuple = None,
        proof_registry=None,
    ) -> Core:
        """Boot the mempool plane. `consensus_mempool_channel` carries
        Get/Verify/Cleanup requests FROM consensus; `consensus_channel` lets
        the payload synchronizer LoopBack blocks INTO the consensus core.

        `epoch_manager` (consensus/reconfig.py) is the node's SHARED
        epoch view: when given, the committee the core/synchronizer
        consult becomes a MempoolEpochView, so payload gossip fan-out,
        sync serving/requesting and address resolution cross a committed
        epoch boundary at the same activation round as consensus — the
        payload-plane half of the epoch-final handoff (§5.5j).
        `listen_addresses` = (front, mempool) covers a JOIN candidate
        not present in the genesis mempool committee: it still needs
        bound ports to serve and fetch payloads once admitted."""
        parameters.log(log)

        core_channel = channel()
        network_tx = channel()
        # Explicitly bounded client-tx intake: the Front's drop-oldest
        # admission and the ingress pipeline's backpressure both key off
        # this queue filling up.
        tx_client = channel(parameters.front_queue_capacity)

        front_addr = committee.front_address(name)
        mempool_addr = committee.mempool_address(name)
        if listen_addresses:
            # Fill only what the genesis committee does not provide — a
            # committee member with an explicit listen override is more
            # likely a misconfiguration than an intent to rebind.
            # (Programmatic seam for join candidates, mirroring
            # Consensus.run's listen_address; node/main.py CLI wiring
            # for live joins is named ROADMAP residue.)
            if front_addr is None:
                front_addr = listen_addresses[0]
            if mempool_addr is None:
                mempool_addr = listen_addresses[1]
        assert front_addr is not None and mempool_addr is not None, (
            "node must be in the mempool committee or supply listen_addresses"
        )
        if epoch_manager is not None:
            committee = MempoolEpochView(committee, epoch_manager)

        Front(("0.0.0.0", front_addr[1]), tx_client)
        NetReceiver(
            ("0.0.0.0", mempool_addr[1]),
            core_channel,
            decode=decode_mempool_message,
            name="mempool-receiver",
        )
        sender = NetSender(network_tx, name="mempool-sender")

        # Dedicated ingress intake lane (per-plane PayloadMaker intake,
        # ISSUE 7): bounded, BLOCKING producer — the opposite admission
        # contract from the Front's drop-oldest queue above, and what
        # makes ingress backpressure end-to-end when both planes carry
        # traffic at once.
        tx_ingress = (
            channel(parameters.ingress_queue_capacity)
            if parameters.ingress_enabled
            else None
        )

        payload_maker = PayloadMaker(
            name,
            signature_service,
            parameters.max_payload_size,
            parameters.min_block_delay,
            tx_client,
            core_channel,
            ingress_in=tx_ingress,
            proof_registry=proof_registry,
        )
        synchronizer = Synchronizer(
            name,
            committee,
            store,
            network_tx,
            consensus_channel,
            parameters.sync_retry_delay,
        )
        core = Core(
            name,
            committee,
            parameters,
            store,
            payload_maker,
            synchronizer,
            core_channel,
            consensus_mempool_channel,
            network_tx,
            verification_service=verification_service,
        )
        # Close the shedding loop: the payload maker stops flushing (and
        # starts dropping txs) while the core's payload queue is full —
        # every flush past that point would fail _queue_insert anyway — OR
        # while gossip egress is backlogged to a majority of peers: a
        # payload produced then would drop on the wire, leaving a digest
        # the committee can't fetch without sync round-trips (admission
        # shedding at the Front is where overload is supposed to land).
        payload_maker.backlog_fn = lambda: (
            len(core.queue) >= parameters.queue_capacity
            or sender.egress_backlogged()
        )
        if parameters.ingress_enabled:
            # Authenticated client plane: signed transactions verify
            # through the node's shared BatchVerificationService on the
            # scheduler's ingress lane, then join the PayloadMaker via
            # their OWN intake queue (tx_ingress). The Front's drop-oldest
            # overflow stays confined to its lane, so both planes carry
            # traffic at once without evicting each other's bodies — the
            # PR 6 shared-queue caveat is resolved by construction.
            from ..ingress.pipeline import IngressPipeline
            from ..ingress.server import IngressServer

            IngressServer(
                ("0.0.0.0", front_addr[1] + parameters.ingress_port_offset),
                IngressPipeline(
                    core.verification_service,
                    tx_ingress,
                    proof_registry=proof_registry,
                ),
            )
            if proof_registry is not None:
                # Commit-proof serving plane (§5.5q): the finality
                # counterpart of the ingress port — clients that
                # submitted on front+ingress_port_offset fetch their
                # commit proofs on front+proofs_port_offset.
                from ..proofs.server import ProofServer, ProofService

                ProofServer(
                    ("0.0.0.0", front_addr[1] + parameters.proofs_port_offset),
                    ProofService(proof_registry),
                )
        spawn(core.run(), name="mempool-core")
        log.info("Mempool of node %s successfully booted on %s", name.short(), mempool_addr)
        return core
