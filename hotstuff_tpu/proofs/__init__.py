"""Commit-proof serving plane (§5.5q): per-node registry indexing the
commit path, the O(1) CommitProof wire object, and the framed-TCP
serving front-end. Closes the submit→commit→proof loop: the same
clients the ingress plane admits get finality certificates back."""

from .messages import (
    MODE_QUERY,
    MODE_SUBSCRIBE,
    PROOF_OK,
    PROOF_PENDING,
    PROOF_SHED,
    PROOF_UNKNOWN,
    CommitProof,
    ProofQuery,
    ProofReply,
    ProofVerificationError,
    decode_proof_message,
    encode_proof_message,
)
from .registry import ProofRegistry
from .server import ProofClient, ProofServer, ProofService

__all__ = [
    "MODE_QUERY",
    "MODE_SUBSCRIBE",
    "PROOF_OK",
    "PROOF_PENDING",
    "PROOF_SHED",
    "PROOF_UNKNOWN",
    "CommitProof",
    "ProofQuery",
    "ProofReply",
    "ProofVerificationError",
    "decode_proof_message",
    "encode_proof_message",
    "ProofRegistry",
    "ProofClient",
    "ProofServer",
    "ProofService",
]
