"""Commit-proof wire messages: the O(1) finality certificate a client
gets back for a committed transaction, and the query/reply envelopes the
proof port speaks.

A `CommitProof` is the minimal statement a STATELESS client can check
with nothing but the committee's public keys: the committed block's
digest preimage fields (author, round, payload digests, parent link) and
the CERTIFYING certificate — the quorum certificate carried by the
block's successor, whose `hash` field IS the committed block's digest.
Verification recomputes the block digest from the header fields and then
verifies the certificate against it, so a proof cannot be grafted onto a
different payload set without breaking 2f+1 signatures. With aggregate
certificates (PR 17) the whole proof is ~300 B at ANY committee size —
the constant-size-quorums payoff served to clients.

What a proof claims (and honestly does not): the certificate proves
2f+1 of the committee CERTIFIED the block — by HotStuff safety at most
one certified block per round exists, and the serving node only ever
constructs proofs for blocks on its locally COMMITTED 2-chain. A client
that trusts at least one honest committee member to serve proofs gets
commit finality; a client trusting nobody still gets certification
(no conflicting block at that round can also reach quorum).

The codec is versioned like the certificate plane: one leading version
byte. Version 1 (current) carries an optional epoch-change digest and
either certificate form behind the `encode_any_qc` tag; version 0 is
the pre-reconfig legacy layout (no epoch field, bare entry-list QC) and
still decodes — the same forward-compat discipline AggQC introduced.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from ..consensus.messages import (
    QC,
    AggQC,
    decode_any_qc,
    encode_any_qc,
)
from ..crypto import Digest, PublicKey, sha512_32
from ..utils.serde import Reader, SerdeError, Writer

PROOF_VERSION = 1  # current layout; version 0 = legacy (bare QC, no epoch)

# Reply statuses (ProofReply.status).
PROOF_OK = 0  # proof attached
PROOF_PENDING = 1  # (client, nonce) admitted, not yet committed: poll later
PROOF_SHED = 2  # subscription table full / unknown-nonce subscribe: back off
PROOF_UNKNOWN = 3  # (client, nonce) never admitted here
PROOF_MALFORMED = 4  # undecodable frame / unknown shape

PROOF_STATUS_NAMES = {
    PROOF_OK: "ok",
    PROOF_PENDING: "pending",
    PROOF_SHED: "shed",
    PROOF_UNKNOWN: "unknown",
    PROOF_MALFORMED: "malformed",
}

# Query modes (ProofQuery.mode).
MODE_QUERY = 0  # answer immediately (OK / PENDING / UNKNOWN)
MODE_SUBSCRIBE = 1  # hold until commit; shed with a retry hint when bounded out

TAG_PROOF_QUERY = 0
TAG_PROOF_REPLY = 1


class ProofVerificationError(Exception):
    """The proof's internal binding failed BEFORE certificate crypto:
    certificate hash does not match the recomputed block digest, wrong
    round, or the queried payload digest is not in the block."""


@dataclass(frozen=True, slots=True)
class CommitProof:
    """One committed block's finality certificate, self-contained.

    `payload` is the block's ordered payload digests; `parent_hash` and
    `parent_round` are the block's OWN embedded QC link (part of the
    digest preimage, so they must travel); `cert` is the SUCCESSOR
    block's certificate over this block's digest — the 2-chain edge that
    certified it. `reconfig_digest` is the carried epoch change's digest
    when the block had one (committed-to only when present, mirroring
    Block.make_digest)."""

    author: PublicKey
    round: int
    payload: tuple[Digest, ...]
    parent_hash: Digest
    parent_round: int
    cert: QC | AggQC
    reconfig_digest: Digest | None = None

    def block_digest(self) -> Digest:
        """Recompute the committed block's digest from the header fields
        — byte-for-byte the Block.make_digest preimage, rebuilt here so
        a stateless client needs no Block object (and no payload
        bodies), only this proof."""
        # graftlint: allow[wire-schema] deliberate SAME-artifact recomputation: a proof binds to the consensus Block digest, byte-for-byte the Block.make_digest preimage
        h = b"HSBLOCK" + self.author.data + struct.pack("<Q", self.round)
        for d in self.payload:
            h += d.data
        h += self.parent_hash.data + struct.pack("<Q", self.parent_round)
        if self.reconfig_digest is not None:
            h += b"HSEPOCH" + self.reconfig_digest.data
        return Digest(sha512_32(h))

    def verify(self, committee, payload_digest: Digest | None = None) -> None:
        """Stateless verification: recompute the block digest, check the
        certificate binds to it (same hash, certificate round = block
        round — the vote digest domain-separates both), then verify the
        certificate's quorum + signatures against `committee`. With
        `payload_digest`, additionally require the queried transaction's
        digest to be IN the committed payload set. Raises on failure."""
        digest = self.block_digest()
        if self.cert.hash != digest:
            raise ProofVerificationError(
                "certificate does not bind the recomputed block digest"
            )
        if self.cert.round != self.round:
            raise ProofVerificationError(
                f"certificate round {self.cert.round} != block round {self.round}"
            )
        if payload_digest is not None and payload_digest not in self.payload:
            raise ProofVerificationError(
                "queried payload digest not in the committed block"
            )
        self.cert.verify(committee)

    def encode(self, w: Writer, version: int = PROOF_VERSION) -> None:
        w.u8(version)
        w.fixed(self.author.data, 32)
        w.u64(self.round)
        w.seq(list(self.payload), lambda wr, d: wr.fixed(d.data, 32))
        w.fixed(self.parent_hash.data, 32)
        w.u64(self.parent_round)
        if version == 0:
            # Legacy layout: reconfig-free, entry-list certificate only.
            if self.reconfig_digest is not None:
                raise ValueError("version-0 proofs cannot carry an epoch change")
            if not isinstance(self.cert, QC):
                raise ValueError("version-0 proofs carry entry-list QCs only")
            self.cert.encode(w)
            return
        if version != PROOF_VERSION:
            raise ValueError(f"unknown proof version {version}")
        if self.reconfig_digest is None:
            w.u8(0)
        else:
            w.u8(1)
            w.fixed(self.reconfig_digest.data, 32)
        encode_any_qc(w, self.cert)

    @staticmethod
    def decode(r: Reader) -> "CommitProof":
        version = r.u8()
        if version > PROOF_VERSION:
            raise SerdeError(f"unknown proof version {version}")
        author = PublicKey(r.fixed(32))
        round_ = r.u64()
        payload = tuple(r.seq(lambda rd: Digest(rd.fixed(32))))
        parent_hash = Digest(r.fixed(32))
        parent_round = r.u64()
        if version == 0:
            return CommitProof(
                author, round_, payload, parent_hash, parent_round, QC.decode(r)
            )
        reconfig_digest = Digest(r.fixed(32)) if r.u8() else None
        cert = decode_any_qc(r)
        return CommitProof(
            author, round_, payload, parent_hash, parent_round, cert,
            reconfig_digest,
        )

    def encoded_size(self) -> int:
        w = Writer()
        self.encode(w)
        return len(w.bytes())

    def __str__(self) -> str:
        return (
            f"CommitProof(B{self.round}, {len(self.payload)} payloads, "
            f"cert={self.cert})"
        )


@dataclass(frozen=True, slots=True)
class ProofQuery:
    """One finality question: has (client, nonce)'s transaction
    committed? `MODE_QUERY` answers immediately; `MODE_SUBSCRIBE` parks
    the reply until the commit lands (bounded — see server.py)."""

    client: PublicKey
    nonce: int
    mode: int = MODE_QUERY

    def encode(self, w: Writer) -> None:
        w.fixed(self.client.data, 32)
        w.u64(self.nonce)
        w.u8(self.mode)

    @staticmethod
    def decode(r: Reader) -> "ProofQuery":
        return ProofQuery(PublicKey(r.fixed(32)), r.u64(), r.u8())


@dataclass(frozen=True, slots=True)
class ProofReply:
    """Per-query outcome, correlated by the echoed nonce (same
    discipline as IngressResponse). SHED and PENDING carry
    `retry_after_ms` — the node's estimate of when asking again has a
    real chance; OK carries the proof itself."""

    nonce: int
    status: int
    retry_after_ms: int = 0
    proof: CommitProof | None = None

    @property
    def status_name(self) -> str:
        return PROOF_STATUS_NAMES.get(self.status, f"status-{self.status}")

    def encode(self, w: Writer) -> None:
        w.u64(self.nonce)
        w.u8(self.status)
        w.u32(self.retry_after_ms)
        if self.proof is None:
            w.u8(0)
        else:
            w.u8(1)
            self.proof.encode(w)

    @staticmethod
    def decode(r: Reader) -> "ProofReply":
        nonce = r.u64()
        status = r.u8()
        retry = r.u32()
        proof = CommitProof.decode(r) if r.u8() else None
        return ProofReply(nonce, status, retry, proof)


def encode_proof_message(msg) -> bytes:
    w = Writer()
    if isinstance(msg, ProofQuery):
        w.u8(TAG_PROOF_QUERY)
    elif isinstance(msg, ProofReply):
        w.u8(TAG_PROOF_REPLY)
    else:
        raise TypeError(f"not a proof message: {msg!r}")
    msg.encode(w)
    return w.bytes()


def decode_proof_message(data: bytes):
    r = Reader(data)
    tag = r.u8()
    if tag == TAG_PROOF_QUERY:
        out = ProofQuery.decode(r)
    elif tag == TAG_PROOF_REPLY:
        out = ProofReply.decode(r)
    else:
        raise SerdeError(f"unknown proof tag {tag}")
    r.expect_done()
    return out
