"""Proof serving: in-process service + framed-TCP front-end.

`ProofService` answers ProofQuery against the node's ProofRegistry with
the same overload discipline as the ingress admission plane
(ingress/admission.py): explicit shedding with a retry-after hint
derived from an observed-rate EWMA, never unbounded queueing. The two
modes differ only in WHO waits:

  * MODE_QUERY resolves immediately — OK with the proof, PENDING with a
    retry hint (admitted here, commit not yet seen), or UNKNOWN.
  * MODE_SUBSCRIBE parks the reply until the commit lands, but ONLY for
    a (client, nonce) this node actually admitted: a subscription for a
    never-admitted nonce is SHED with a retry hint and allocates
    NOTHING — the nonce-squatting flood costs the attacker a round trip
    and this node a dict lookup (the Byzantine proof-squatter scenario
    pins `proofs.subs_shed` and the bounded registry size). Admitted
    subscriptions are bounded globally too (registry.max_waiters);
    overflow sheds the same way, and an obedient client's retry lands
    after the backlog drained.

`ProofServer`/`ProofClient` are the framed-TCP wrappers, riding the
exact connection discipline of the ingress RPC (ingress/server.py): one
reader + one serialized writer task per connection, responses correlated
by echoed nonce, MALFORMED replies for undecodable frames.

The retry hint mirrors admission's drain-rate estimate: an EWMA over
resolutions observed per note-commit tick, quoting the time for the
current waiter backlog to half-drain (clamped to the same
[RETRY_MIN_MS, RETRY_MAX_MS] band). Deterministic under the chaos
virtual clock — only event-loop time, passed by the caller, is read.
"""

from __future__ import annotations

import asyncio
import logging

from ..network.net import Address, FrameReader, frame
from ..utils import metrics
from ..utils.actors import channel, spawn
from .messages import (
    MODE_SUBSCRIBE,
    PROOF_MALFORMED,
    PROOF_OK,
    PROOF_PENDING,
    PROOF_SHED,
    PROOF_UNKNOWN,
    ProofQuery,
    ProofReply,
    decode_proof_message,
    encode_proof_message,
)
from .registry import ProofRegistry

log = logging.getLogger("hotstuff.proofs")

_M_QUERIES = metrics.counter("proofs.queries")
_M_SERVED = metrics.counter("proofs.served")
_M_UNKNOWN = metrics.counter("proofs.unknown")
_M_SUBS_SHED = metrics.counter("proofs.subs_shed")
_M_WIRE_MALFORMED = metrics.counter("proofs.malformed")
_M_SERVE_S = metrics.histogram("proofs.serve_s")
_M_PROOF_BYTES = metrics.histogram("proofs.proof_bytes", metrics.SIZE_BUCKETS)

RETRY_MIN_MS = 50
RETRY_MAX_MS = 5_000


class ProofService:
    """One per node; answers queries against the node's registry."""

    def __init__(self, registry: ProofRegistry) -> None:
        self.registry = registry
        # Resolution-rate EWMA (proofs/sec), fed by the registry's commit
        # notes through note_resolved(); seeds pessimistic like admission.
        self._resolve_rate = 0.0
        self._last_resolve_t: float | None = None
        self.stats = {
            "queries": 0, "served": 0, "pending": 0, "unknown": 0,
            "subs": 0, "subs_shed": 0, "worst_proof_bytes": 0,
        }

    async def handle(self, query: ProofQuery, now: float) -> ProofReply:
        """Answer one query; `now` is event-loop time (virtual under
        chaos). A SUBSCRIBE for an admitted-but-uncommitted key awaits
        the commit; everything else resolves immediately."""
        self.stats["queries"] += 1
        _M_QUERIES.inc()
        proof, known = self.registry.proof_for_client(query.client, query.nonce)
        if proof is not None:
            return self._serve(query, proof, now, now)
        if query.mode != MODE_SUBSCRIBE:
            if known:
                self.stats["pending"] += 1
                return ProofReply(
                    query.nonce, PROOF_PENDING, self._retry_after_ms()
                )
            self.stats["unknown"] += 1
            _M_UNKNOWN.inc()
            return ProofReply(query.nonce, PROOF_UNKNOWN)
        if not known:
            # Never-admitted subscribe: shed WITHOUT allocating — the
            # squatter's slot budget is zero, the honest client whose
            # submit raced just retries after the hint.
            self.stats["subs_shed"] += 1
            _M_SUBS_SHED.inc()
            return ProofReply(query.nonce, PROOF_SHED, self._retry_after_ms())
        fut = self.registry.add_waiter(query.client, query.nonce)
        if fut is None:  # waiter table full (registry counted the shed)
            self.stats["subs_shed"] += 1
            return ProofReply(query.nonce, PROOF_SHED, self._retry_after_ms())
        self.stats["subs"] += 1
        try:
            proof = await fut
        except asyncio.CancelledError:
            self.registry.drop_waiter(query.client, query.nonce, fut)
            raise
        loop = asyncio.get_running_loop()
        return self._serve(query, proof, now, loop.time())

    def _serve(
        self, query: ProofQuery, proof, t0: float, now: float
    ) -> ProofReply:
        self.stats["served"] += 1
        _M_SERVED.inc()
        _M_SERVE_S.record(now - t0)
        size = proof.encoded_size()
        _M_PROOF_BYTES.record(size)
        if size > self.stats["worst_proof_bytes"]:
            self.stats["worst_proof_bytes"] = size
        self.note_resolved(1, now)
        # NOTE: cumulative, last-line-wins; parsed by the benchmark
        # LogParser (+ PROOFS section).
        log.info(
            "Proof served: %d proofs served, %d subscriptions, "
            "%d shed, worst proof %d B",
            self.stats["served"],
            self.stats["subs"],
            self.stats["subs_shed"],
            self.stats["worst_proof_bytes"],
        )
        return ProofReply(query.nonce, PROOF_OK, 0, proof)

    def note_resolved(self, n: int, now: float) -> None:
        """EWMA resolution-rate update (admission.note_drained's shape)."""
        if self._last_resolve_t is not None:
            dt = now - self._last_resolve_t
            if dt > 0:
                inst = n / dt
                self._resolve_rate = (
                    inst
                    if self._resolve_rate == 0.0
                    else 0.8 * self._resolve_rate + 0.2 * inst
                )
        self._last_resolve_t = now

    def _retry_after_ms(self) -> int:
        """Time for the waiter backlog to half-drain at the observed
        resolution rate — admission's estimator applied to the proof
        plane (a zero-observation start quotes the conservative max)."""
        if self._resolve_rate <= 0.0:
            return RETRY_MAX_MS
        backlog = max(1, self.registry.waiters())
        ms = int(1000.0 * (backlog / 2.0) / self._resolve_rate)
        return max(RETRY_MIN_MS, min(RETRY_MAX_MS, ms))


class ProofServer:
    """Accept loop on the proof port; one reader + one writer task per
    connection, queries fan out into the shared service."""

    def __init__(self, address: Address, service: ProofService) -> None:
        self._address = address
        self.service = service
        self._task = spawn(self._run(), name="proof-server")

    async def _run(self) -> None:
        server = await asyncio.start_server(
            self._handle, host=self._address[0], port=self._address[1]
        )
        log.info("Proof server listening on %s", self._address)
        async with server:
            await server.serve_forever()

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        # Same per-connection shape as the ingress RPC: responses
        # serialize through one queue + writer task (subscriptions
        # complete out of order), per-query tasks die with the
        # connection.
        responses = channel()
        writer_task = spawn(
            self._write_replies(responses, writer), name="proof-writer"
        )
        inflight: set[asyncio.Task] = set()
        frames = FrameReader(reader)
        try:
            while True:
                try:
                    data = await frames.next_frame()
                except ConnectionError as e:
                    log.warning(
                        "proofs: dropping connection from %s: %s", peer, e
                    )
                    break
                if data is None:
                    break
                try:
                    msg = decode_proof_message(data)
                except Exception as e:
                    _M_WIRE_MALFORMED.inc()
                    log.warning(
                        "proofs: undecodable frame from %s: %r", peer, e
                    )
                    await responses.put(ProofReply(0, PROOF_MALFORMED))
                    continue
                if not isinstance(msg, ProofQuery):
                    _M_WIRE_MALFORMED.inc()
                    await responses.put(ProofReply(0, PROOF_MALFORMED))
                    continue
                task = spawn(self._answer(msg, responses), name="proof-handle")
                inflight.add(task)
                task.add_done_callback(inflight.discard)
        finally:
            writer_task.cancel()
            for task in list(inflight):
                task.cancel()
            try:
                writer.close()
            except Exception:
                pass

    async def _answer(self, query: ProofQuery, responses) -> None:
        loop = asyncio.get_running_loop()
        reply = await self.service.handle(query, loop.time())
        await responses.put(reply)

    async def _write_replies(self, responses, writer) -> None:
        while True:
            reply = await responses.get()
            try:
                writer.write(frame(encode_proof_message(reply)))
                await writer.drain()
            except (ConnectionError, OSError):
                return  # client went away; reader loop will notice EOF


class ProofClient:
    """Client side: pipelined queries over one connection, reply futures
    keyed by nonce (FIFO per nonce, like the ingress client). Used by
    tools/loadgen.py --proofs; in-process drivers call
    ProofService.handle directly."""

    def __init__(self) -> None:
        self._writer: asyncio.StreamWriter | None = None
        self._waiters: dict[int, list[asyncio.Future]] = {}
        self._reader_task: asyncio.Task | None = None

    async def connect(self, address: Address) -> None:
        reader, self._writer = await asyncio.open_connection(
            address[0], address[1]
        )
        self._reader_task = spawn(
            self._read_replies(reader), name="proof-client-reader"
        )

    async def _read_replies(self, reader: asyncio.StreamReader) -> None:
        frames = FrameReader(reader)
        while True:
            try:
                data = await frames.next_frame()
            except ConnectionError:
                data = None
            if data is None:
                break
            try:
                msg = decode_proof_message(data)
            except Exception as e:
                log.warning("proof client: undecodable reply: %r", e)
                continue
            queue = self._waiters.get(getattr(msg, "nonce", -1))
            if queue:
                fut = queue.pop(0)
                if not queue:
                    del self._waiters[msg.nonce]
                if not fut.done():
                    fut.set_result(msg)
        waiters, self._waiters = self._waiters, {}
        for queue in waiters.values():
            for fut in queue:
                if not fut.done():
                    fut.set_exception(
                        ConnectionError("proof connection closed")
                    )

    async def query(self, query: ProofQuery) -> ProofReply:
        if self._writer is None:
            raise ConnectionError("proof client not connected")
        fut = asyncio.get_running_loop().create_future()
        self._waiters.setdefault(query.nonce, []).append(fut)
        self._writer.write(frame(encode_proof_message(query)))
        await self._writer.drain()
        return await fut

    def close(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
            self._writer = None
