"""ProofRegistry: the per-node commit-proof index.

Watches the consensus commit path (`Core._commit` calls `note_commit`
with each committed block and its CERTIFYING certificate — the
successor's QC) and maintains three bounded maps that together close the
submit→commit→proof loop:

  * payload digest → CommitProof, over a bounded ring of the newest
    committed blocks (eviction is by commit order; `proofs.evicted`
    counts dropped payload entries);
  * (client, nonce) → transaction digest, fed by the ingress pipeline
    at admission (`note_tx`), bounded like the admission replay window;
  * transaction digest → payload digest, fed by the PayloadMaker at
    flush (`note_payload`) — in the chaos plane, where transaction
    digests ride blocks DIRECTLY as payload digests, the identity
    mapping applies and this map stays empty.

Every bound is explicit and every overflow is counted: a proof plane
that leaked memory per never-committed nonce would hand Byzantine
clients a free resource-exhaustion lever (the nonce-squatting scenario
pins this). Persistence covers the newest window of the ring only — a
restarted node re-serves recent proofs immediately and regrows the rest
from new commits; old proofs are reconstructible from the chain, not
precious state.

Determinism: chaos-reachable — no wall clock, no ambient randomness;
waiter wake-ups ride the commit path itself.
"""

from __future__ import annotations

import asyncio
import logging
from collections import OrderedDict, deque

from ..crypto import Digest, PublicKey
from ..utils import metrics
from ..utils.serde import Reader, SerdeError, Writer
from .messages import CommitProof

log = logging.getLogger("hotstuff.proofs")

_M_INDEXED = metrics.counter("proofs.indexed")
_M_RESOLVED = metrics.counter("proofs.resolved")
_M_EVICTED = metrics.counter("proofs.evicted")
_M_MISMATCH = metrics.counter("proofs.cert_mismatch")
_M_SUBS_SHED = metrics.counter("proofs.subs_shed")
_M_SIZE = metrics.gauge("proofs.registry_size")

# Store blob holding the persisted newest-window of the proof ring.
_RING_KEY = b"proof-ring"


class ProofRegistry:
    """One per node. `store` (store/store.py) is optional — without it
    the ring is memory-only (the chaos default)."""

    def __init__(
        self,
        store=None,
        capacity: int = 1_024,
        persist_window: int = 64,
        tx_window: int = 65_536,
        max_waiters: int = 1_024,
    ) -> None:
        self.store = store
        self.capacity = capacity
        self.persist_window = persist_window
        self.tx_window = tx_window
        self.max_waiters = max_waiters
        # Commit-ordered ring of (payload digests, proof); oldest evicts.
        self._ring: deque[tuple[tuple[Digest, ...], CommitProof]] = deque()
        self._by_payload: dict[Digest, CommitProof] = {}
        # (client bytes, nonce) -> tx digest, admission-fed, bounded FIFO.
        self._tx_of: OrderedDict[tuple[bytes, int], Digest] = OrderedDict()
        self._key_of_tx: dict[Digest, tuple[bytes, int]] = {}
        # Body bytes -> FIFO of admitted tx digests awaiting their flush
        # (real-node path: the PayloadMaker sees BODIES, not digests, so
        # the pairing happens here). Bounded by total queued digests.
        self._pending_bodies: OrderedDict[bytes, deque[Digest]] = OrderedDict()
        self._n_pending_bodies = 0
        # payload digest -> ingress tx digests flushed into it (resolved
        # and dropped at commit). Bounded by tx_window alongside.
        self._txs_of_payload: OrderedDict[Digest, list[Digest]] = OrderedDict()
        # Resolved (client, nonce) -> proof, bounded FIFO.
        self._resolved: OrderedDict[tuple[bytes, int], CommitProof] = OrderedDict()
        # Commit waiters (subscribe-until-commit), bounded GLOBALLY.
        self._waiters: dict[tuple[bytes, int], list[asyncio.Future]] = {}
        self._n_waiters = 0
        self.stats = {
            "indexed": 0, "resolved": 0, "evicted": 0, "mismatch": 0,
        }

    # -- ingress feed --------------------------------------------------------

    def note_tx(
        self,
        client: PublicKey,
        nonce: int,
        tx_digest: Digest,
        body: bytes | None = None,
    ) -> None:
        """Record an ADMITTED (signature-verified) transaction's digest
        under its (client, nonce). Called by the ingress pipeline just
        before the body is handed to the mempool lane. `body` threads
        the real-node path: the PayloadMaker reports flushes by BODY
        (note_payload), and this FIFO pairs each flushed body back to
        its tx digest. Chaos drivers, where the tx digest rides blocks
        directly, omit it."""
        key = (client.data, nonce)
        self._tx_of[key] = tx_digest
        self._key_of_tx[tx_digest] = key
        while len(self._tx_of) > self.tx_window:
            old_key, old_digest = self._tx_of.popitem(last=False)
            if self._key_of_tx.get(old_digest) == old_key:
                del self._key_of_tx[old_digest]
        if body is not None:
            self._pending_bodies.setdefault(body, deque()).append(tx_digest)
            self._n_pending_bodies += 1
            while self._n_pending_bodies > self.tx_window:
                _, old = self._pending_bodies.popitem(last=False)
                self._n_pending_bodies -= len(old)

    def note_payload(self, bodies: list[bytes], payload_digest: Digest) -> None:
        """Record which payload a flushed batch of transaction bodies
        rode (PayloadMaker._make). Ingress bodies pair FIFO against
        their admitted digests; Front bodies have no pending entry and
        are simply not provable by (client, nonce), by design."""
        tx_digests: list[Digest] = []
        for body in bodies:
            queue = self._pending_bodies.get(body)
            if not queue:
                continue
            tx_digests.append(queue.popleft())
            self._n_pending_bodies -= 1
            if not queue:
                del self._pending_bodies[body]
        if not tx_digests:
            return
        self._txs_of_payload.setdefault(payload_digest, []).extend(tx_digests)
        while len(self._txs_of_payload) > self.tx_window:
            self._txs_of_payload.popitem(last=False)

    # -- commit feed ---------------------------------------------------------

    async def note_commit(self, block, cert) -> None:
        """Index one committed block under its certifying certificate
        (the successor's QC: cert.hash == block.digest()). Builds the
        CommitProof, indexes every payload digest, resolves any
        (client, nonce) keys and wakes their waiters, then persists the
        newest window."""
        proof = CommitProof(
            author=block.author,
            round=block.round,
            payload=tuple(block.payload),
            parent_hash=block.qc.hash,
            parent_round=block.qc.round,
            cert=cert,
            reconfig_digest=(
                block.reconfig.digest() if block.reconfig is not None else None
            ),
        )
        if cert.hash != block.digest() or cert.round != block.round:
            # Defensive: a certificate that does not certify this block
            # would serve clients an unverifiable proof. Never index it.
            self.stats["mismatch"] += 1
            _M_MISMATCH.inc()
            log.error(
                "proof registry: certificate %s does not certify committed "
                "block B%s — proof not indexed", cert, block.round,
            )
            return
        payloads = tuple(block.payload)
        self._ring.append((payloads, proof))
        for pd in payloads:
            self._by_payload[pd] = proof
            self.stats["indexed"] += 1
            _M_INDEXED.inc()
            self._resolve(pd, proof)
        while len(self._ring) > self.capacity:
            old_payloads, old_proof = self._ring.popleft()
            for pd in old_payloads:
                if self._by_payload.get(pd) is old_proof:
                    del self._by_payload[pd]
                    self.stats["evicted"] += 1
                    _M_EVICTED.inc()
        _M_SIZE.set(self.size())
        if self.store is not None:
            await self._persist()

    def _resolve(self, payload_digest: Digest, proof: CommitProof) -> None:
        """Map one committed payload digest back to the (client, nonce)
        keys it carries: the tx digests flushed into it (real-node path)
        plus the digest ITSELF as a tx digest (chaos identity path)."""
        tx_digests = self._txs_of_payload.pop(payload_digest, [])
        tx_digests.append(payload_digest)
        for txd in tx_digests:
            key = self._key_of_tx.pop(txd, None)
            if key is None:
                continue
            self._tx_of.pop(key, None)
            self._resolved[key] = proof
            self.stats["resolved"] += 1
            _M_RESOLVED.inc()
            while len(self._resolved) > self.tx_window:
                self._resolved.popitem(last=False)
            for fut in self._waiters.pop(key, ()):
                self._n_waiters -= 1
                if not fut.done():
                    fut.set_result(proof)

    # -- lookups -------------------------------------------------------------

    def proof_for_payload(self, payload_digest: Digest) -> CommitProof | None:
        return self._by_payload.get(payload_digest)

    def proof_for_client(
        self, client: PublicKey, nonce: int
    ) -> tuple[CommitProof | None, bool]:
        """(proof | None, known): `known` is True when the (client,
        nonce) was admitted here (proof pending) or already resolved."""
        key = (client.data, nonce)
        proof = self._resolved.get(key)
        if proof is not None:
            return proof, True
        return None, key in self._tx_of

    def add_waiter(self, client: PublicKey, nonce: int) -> asyncio.Future | None:
        """Park a subscribe-until-commit future for a KNOWN-pending key.
        Returns None when the global waiter table is full — the caller
        sheds with a retry hint instead of queueing unboundedly."""
        if self._n_waiters >= self.max_waiters:
            _M_SUBS_SHED.inc()
            return None
        key = (client.data, nonce)
        fut = asyncio.get_running_loop().create_future()
        self._waiters.setdefault(key, []).append(fut)
        self._n_waiters += 1
        return fut

    def drop_waiter(self, client: PublicKey, nonce: int, fut) -> None:
        """Release a cancelled/abandoned subscription's slot."""
        key = (client.data, nonce)
        queue = self._waiters.get(key)
        if queue and fut in queue:
            queue.remove(fut)
            self._n_waiters -= 1
            if not queue:
                del self._waiters[key]

    def size(self) -> int:
        """Bounded-memory pin read by the Byzantine scenarios: total
        entries across every map (all individually bounded)."""
        return (
            len(self._by_payload)
            + len(self._tx_of)
            + self._n_pending_bodies
            + len(self._txs_of_payload)
            + len(self._resolved)
            + self._n_waiters
        )

    def waiters(self) -> int:
        return self._n_waiters

    # -- persistence ---------------------------------------------------------

    async def _persist(self) -> None:
        """Write the newest `persist_window` ring entries under
        `proof-ring`: enough for a restarted node to re-serve the recent
        past immediately; everything older regrows from new commits."""
        w = Writer()
        window = list(self._ring)[-self.persist_window:]
        w.seq(window, _encode_ring_entry)
        await self.store.write(_RING_KEY, w.bytes())

    async def load(self) -> int:
        """Reload the persisted window (node restart). Returns the
        number of ring entries restored; 0 when nothing was persisted."""
        if self.store is None:
            return 0
        raw = await self.store.read(_RING_KEY)
        if raw is None:
            return 0
        try:
            r = Reader(raw)
            window = r.seq(_decode_ring_entry)
            r.expect_done()
        except SerdeError as e:
            log.warning("proof ring blob undecodable (%s); starting empty", e)
            return 0
        for payloads, proof in window:
            self._ring.append((payloads, proof))
            for pd in payloads:
                self._by_payload[pd] = proof
        _M_SIZE.set(self.size())
        return len(window)


def _encode_ring_entry(
    w: Writer, entry: tuple[tuple[Digest, ...], CommitProof]
) -> None:
    payloads, proof = entry
    w.seq(list(payloads), lambda wr, d: wr.fixed(d.data, 32))
    inner = Writer()
    proof.encode(inner)
    w.var_bytes(inner.bytes())


def _decode_ring_entry(r: Reader) -> tuple[tuple[Digest, ...], CommitProof]:
    payloads = tuple(r.seq(lambda rd: Digest(rd.fixed(32))))
    inner = Reader(r.var_bytes())
    proof = CommitProof.decode(inner)
    inner.expect_done()
    return payloads, proof
