"""Persistent keyed byte store with a single-writer actor and notify_read.

Capability parity with the reference `store` crate (store/src/lib.rs:15-92):
  * one writer task owns all state; commands arrive over a channel
  * Write / Read / NotifyRead commands with oneshot replies
  * NotifyRead registers an obligation resolved by a FUTURE Write of that key
    -- the synchronizers' wait primitive for out-of-order block/payload arrival

The reference persists via rocksdb; here the data plane is pluggable behind
the same command protocol:
  * native C++ log-structured engine (native/store.cpp via ctypes) — hash
    index + append-only length-prefixed log + crash-safe torn-tail truncate,
    the default for file-backed stores when the toolchain is available;
  * pure-Python engine with the same log format (fallback);
  * plain dict for path-less (in-memory, test) stores.

Both persistent engines COMPACT: when the log grows past an adaptive
threshold the live keys are rewritten and the file atomically replaced —
the role rocksdb's background compaction plays in the reference. Without
it, the safety-state key rewritten every round (consensus/core.py) would
grow the log and the restart replay time without bound.
"""

from __future__ import annotations

import asyncio
import ctypes
import logging
import os
import struct
from collections import defaultdict, deque

from ..utils.actors import channel, spawn

log = logging.getLogger("hotstuff.store")

# Compact when the log exceeds this many bytes AND twice the live size.
MIN_COMPACT_BYTES = 8 * 1024 * 1024


class _MemEngine:
    """Path-less store: a dict, no durability (tests, MockMempool)."""

    log_bytes = 0

    def __init__(self) -> None:
        self._data: dict[bytes, bytes] = {}

    def get(self, key: bytes) -> bytes | None:
        return self._data.get(key)

    def put(self, key: bytes, value: bytes) -> None:
        self._data[key] = value

    def compact(self) -> int:
        return 0

    def close(self) -> None:
        pass


class _PyLogEngine:
    """Append-only length-prefixed log + in-memory index (pure Python)."""

    def __init__(self, path: str) -> None:
        self._data: dict[bytes, bytes] = {}
        self._path = path
        self._replay(path)
        # Truncate any torn tail so appended records stay replayable.
        with open(path, "ab") as f:
            pass
        with open(path, "r+b") as f:
            f.truncate(self._good_offset)
        self._log = open(path, "ab")
        self.log_bytes = self._good_offset

    def _replay(self, path: str) -> None:
        self._good_offset = 0
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            buf = f.read()
        pos = 0
        while pos + 8 <= len(buf):
            klen, vlen = struct.unpack_from("<II", buf, pos)
            end = pos + 8 + klen + vlen
            if end > len(buf):
                break  # torn tail write; dropped by the truncate above
            key = buf[pos + 8 : pos + 8 + klen]
            self._data[key] = buf[pos + 8 + klen : end]
            pos = end
        self._good_offset = pos

    def get(self, key: bytes) -> bytes | None:
        return self._data.get(key)

    def put(self, key: bytes, value: bytes) -> None:
        self._data[key] = value
        self._log.write(struct.pack("<II", len(key), len(value)))
        self._log.write(key)
        self._log.write(value)
        self._log.flush()
        self.log_bytes += 8 + len(key) + len(value)

    def compact(self) -> int:
        """Rewrite live keys only; atomic replace via rename. Returns the new
        log size, or -1 on failure (the log is reopened either way — a failed
        compaction must leave the engine writable)."""
        tmp = self._path + ".compact"
        try:
            with open(tmp, "wb") as out:
                for k, v in self._data.items():
                    out.write(struct.pack("<II", len(k), len(v)))
                    out.write(k)
                    out.write(v)
                out.flush()
                os.fsync(out.fileno())
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return -1
        self._log.close()
        try:
            os.replace(tmp, self._path)
        finally:
            self._log = open(self._path, "ab")
        self.log_bytes = os.path.getsize(self._path)
        return self.log_bytes

    def close(self) -> None:
        self._log.close()


class _NativeEngine:
    """The C++ engine (native/store.cpp): index and values live outside the
    Python heap; replay, torn-tail truncate, and compaction are native."""

    def __init__(self, lib, path: str) -> None:
        self._lib = lib
        handle = lib.hs_store_open(path.encode(), 0)
        if not handle:
            raise OSError(f"hs_store_open failed for {path}")
        self._handle = ctypes.c_void_p(handle)
        self.log_bytes = os.path.getsize(path) if os.path.exists(path) else 0

    def get(self, key: bytes) -> bytes | None:
        out = ctypes.POINTER(ctypes.c_uint8)()
        kbuf = (ctypes.c_uint8 * len(key)).from_buffer_copy(key)
        n = self._lib.hs_store_read(
            self._handle, kbuf, len(key), ctypes.byref(out)
        )
        if n < 0:
            return None
        try:
            return ctypes.string_at(out, n)
        finally:
            self._lib.hs_free(out)

    def put(self, key: bytes, value: bytes) -> None:
        kbuf = (ctypes.c_uint8 * len(key)).from_buffer_copy(key)
        vbuf = (ctypes.c_uint8 * max(1, len(value))).from_buffer_copy(
            value or b"\x00"
        )
        rc = self._lib.hs_store_write(
            self._handle, kbuf, len(key), vbuf, len(value)
        )
        if rc != 0:
            raise OSError("hs_store_write failed")
        self.log_bytes += 8 + len(key) + len(value)

    def compact(self) -> int:
        new_size = self._lib.hs_store_compact(self._handle)
        if new_size >= 0:
            self.log_bytes = new_size
        return new_size

    def close(self) -> None:
        if self._handle:
            self._lib.hs_store_close(self._handle)
            self._handle = None


def _make_engine(path: str | None):
    if path is None:
        return _MemEngine()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    from ..crypto import native_staging

    lib = native_staging.get_lib()
    if lib is not None and hasattr(lib, "hs_store_open"):
        try:
            return _NativeEngine(lib, path)
        except OSError:
            pass
    return _PyLogEngine(path)


class Store:
    """Async KV store handle; cheap to share (all ops go through one queue)."""

    def __init__(self, path: str | None = None) -> None:
        self._engine = _make_engine(path)
        # Back-compat: direct index access for in-memory/Python engines
        # (tests introspect it); None for the native engine.
        self._data = getattr(self._engine, "_data", None)
        self._obligations: dict[bytes, deque[asyncio.Future]] = defaultdict(deque)
        self._queue = channel()
        self._path = path
        self._compact_threshold = MIN_COMPACT_BYTES
        self.compactions = 0
        self._cmd_count = 0
        self._task = spawn(self._run(), name="store-writer")

    def _sweep_obligations(self) -> None:
        """Drop cancelled waiters and empty keys. Obligations for keys that
        are NEVER written (e.g. a Byzantine block referencing bogus payload
        digests, whose waiter the synchronizer later cancels) would otherwise
        accumulate without bound; amortized over the command stream."""
        dead = []
        for key, waiters in self._obligations.items():
            if not any(w.cancelled() for w in waiters):
                continue  # nothing to prune; avoid rebuilding live deques
            live = deque(w for w in waiters if not w.cancelled())
            if live:
                self._obligations[key] = live
            else:
                dead.append(key)
        for key in dead:
            del self._obligations[key]

    @property
    def engine_name(self) -> str:
        return type(self._engine).__name__.strip("_")

    async def _maybe_compact(self) -> None:
        if self._engine.log_bytes <= self._compact_threshold:
            return
        # Off the event loop: a full live-set rewrite + fsync would stall
        # consensus timers and network I/O. Store commands queue behind it
        # (the actor serializes), the rest of the node keeps running.
        new_size = await asyncio.to_thread(self._engine.compact)
        self.compactions += 1
        if new_size < 0:
            # Failed (e.g. disk full): back off relative to the CURRENT log
            # so every subsequent write doesn't re-attempt a full rewrite.
            self._compact_threshold = max(
                MIN_COMPACT_BYTES, 2 * self._engine.log_bytes
            )
            log.error("store compaction failed; next attempt at %s bytes",
                      self._compact_threshold)
            return
        # Adaptive: if most of the log was live, double the threshold so
        # steady-state growth doesn't trigger quadratic rewrites.
        self._compact_threshold = max(MIN_COMPACT_BYTES, 2 * new_size)

    async def _run(self) -> None:
        while True:
            cmd, args, fut = await self._queue.get()
            self._cmd_count += 1
            if self._cmd_count % 4096 == 0:
                self._sweep_obligations()
            if cmd == "write":
                key, value = args
                try:
                    self._engine.put(key, value)
                    await self._maybe_compact()
                except (OSError, ValueError) as e:
                    # A failed write (disk full, failed compact) must neither
                    # kill the writer actor (every later command would hang
                    # forever) nor resolve the caller as if durable.
                    log.error("store write failed: %r", e)
                    if fut is not None and not fut.cancelled():
                        fut.set_exception(e)
                    continue
                # Resolve pending notify_read obligations for this key
                # (store/src/lib.rs:36-47).
                for waiter in self._obligations.pop(key, ()):
                    if not waiter.cancelled():
                        waiter.set_result(value)
                if fut is not None and not fut.cancelled():
                    fut.set_result(None)
            elif cmd == "read":
                (key,) = args
                if not fut.cancelled():
                    fut.set_result(self._engine.get(key))
            elif cmd == "notify_read":
                (key,) = args
                value = self._engine.get(key)
                if value is not None:
                    if not fut.cancelled():
                        fut.set_result(value)
                else:
                    self._obligations[key].append(fut)

    async def write(self, key: bytes, value: bytes) -> None:
        fut = asyncio.get_running_loop().create_future()
        await self._queue.put(("write", (key, value), fut))
        await fut

    async def read(self, key: bytes) -> bytes | None:
        fut = asyncio.get_running_loop().create_future()
        await self._queue.put(("read", (key,), fut))
        return await fut

    async def notify_read(self, key: bytes) -> bytes:
        """Blocking read: resolves immediately if present, else when a later
        write stores the key (store/src/lib.rs:49-57)."""
        fut = asyncio.get_running_loop().create_future()
        await self._queue.put(("notify_read", (key,), fut))
        return await fut

    def close(self) -> None:
        self._task.cancel()
        self._engine.close()
