"""Persistent keyed byte store with a single-writer actor and notify_read.

Capability parity with the reference `store` crate (store/src/lib.rs:15-92):
  * one writer task owns all state; commands arrive over a channel
  * Write / Read / NotifyRead commands with oneshot replies
  * NotifyRead registers an obligation resolved by a FUTURE Write of that key
    -- the synchronizers' wait primitive for out-of-order block/payload arrival

The reference persists via rocksdb; here durability comes from an append-only
length-prefixed log replayed on open (a native C++ log-structured store under
native/ can be slotted in behind the same command protocol).
"""

from __future__ import annotations

import asyncio
import os
import struct
from collections import defaultdict, deque

from ..utils.actors import channel, spawn


class Store:
    """Async KV store handle; cheap to share (all ops go through one queue)."""

    def __init__(self, path: str | None = None) -> None:
        self._data: dict[bytes, bytes] = {}
        self._obligations: dict[bytes, deque[asyncio.Future]] = defaultdict(deque)
        self._queue = channel()
        self._path = path
        self._log = None
        if path is not None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._replay(path)
            self._log = open(path, "ab")
        self._task = spawn(self._run(), name="store-writer")

    def _replay(self, path: str) -> None:
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            buf = f.read()
        pos = 0
        while pos + 8 <= len(buf):
            klen, vlen = struct.unpack_from("<II", buf, pos)
            end = pos + 8 + klen + vlen
            if end > len(buf):
                break  # torn tail write; ignore
            key = buf[pos + 8 : pos + 8 + klen]
            val = buf[pos + 8 + klen : end]
            self._data[key] = val
            pos = end

    async def _run(self) -> None:
        while True:
            cmd, args, fut = await self._queue.get()
            if cmd == "write":
                key, value = args
                self._data[key] = value
                if self._log is not None:
                    self._log.write(struct.pack("<II", len(key), len(value)))
                    self._log.write(key)
                    self._log.write(value)
                    self._log.flush()
                # Resolve pending notify_read obligations for this key
                # (store/src/lib.rs:36-47).
                for waiter in self._obligations.pop(key, ()):
                    if not waiter.cancelled():
                        waiter.set_result(value)
                if fut is not None and not fut.cancelled():
                    fut.set_result(None)
            elif cmd == "read":
                (key,) = args
                if not fut.cancelled():
                    fut.set_result(self._data.get(key))
            elif cmd == "notify_read":
                (key,) = args
                if key in self._data:
                    if not fut.cancelled():
                        fut.set_result(self._data[key])
                else:
                    self._obligations[key].append(fut)

    async def write(self, key: bytes, value: bytes) -> None:
        fut = asyncio.get_running_loop().create_future()
        await self._queue.put(("write", (key, value), fut))
        await fut

    async def read(self, key: bytes) -> bytes | None:
        fut = asyncio.get_running_loop().create_future()
        await self._queue.put(("read", (key,), fut))
        return await fut

    async def notify_read(self, key: bytes) -> bytes:
        """Blocking read: resolves immediately if present, else when a later
        write stores the key (store/src/lib.rs:49-57)."""
        fut = asyncio.get_running_loop().create_future()
        await self._queue.put(("notify_read", (key,), fut))
        return await fut

    def close(self) -> None:
        self._task.cancel()
        if self._log is not None:
            self._log.close()
