from .store import Store

__all__ = ["Store"]
