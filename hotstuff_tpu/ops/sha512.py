"""Batched SHA-512(R || A || M) mod L on TPU — device-side scalar staging.

The verification equation needs h = SHA-512(R || A || M) mod L per item
(crypto/src/lib.rs:209-220 computes this on the CPU inside ed25519_dalek).
Host-side hashing is serial per-item byte work — on a small host it is the
one stage of the packed pipeline that cannot overlap with device compute
(`ops/ed25519._stage_scalars`, `native/staging.cpp`). The protocol's hot
path only ever signs 32-byte digests (votes/QCs sign `Block::digest`,
payloads sign `Payload::make_digest`), so the hash input is a FIXED
96-byte message = exactly one padded SHA-512 block; this module computes
the whole thing batched on device:

  * SHA-512: 64-bit words as (hi, lo) uint32 pairs on the VPU (TPUs have
    no native u64); 80 rounds fully unrolled at trace time; (B,)-shaped
    lanes so the batch rides the vector unit.
  * mod L: radix-256 f32 limb folds reusing the exact-f32 discipline of
    `ops.field` — 2^256 ≡ -16c and 2^252 ≡ -c (mod L) with c = L - 2^252,
    nonnegative limbs via precomputed multiple-of-L biases, then two
    exact conditional subtractions of L.

Output is bit-exact with the host path (hashlib + Python bigint mod) for
every input — consensus safety requires all replicas, CPU or TPU, to
accept exactly the same signature set.
"""

from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import field as f

L = 2**252 + 27742317777372353535851937790883648493
C = L - 2**252  # 2^252 ≡ -C (mod L)

# --- round constants (FIPS 180-4: frac of cube/square roots of primes) -----


def _primes(n: int) -> list[int]:
    out, k = [], 2
    while len(out) < n:
        if all(k % p for p in out):
            out.append(k)
        k += 1
    return out


def _icbrt(x: int) -> int:
    r = 1 << ((x.bit_length() + 2) // 3)
    while True:
        nr = (2 * r + x // (r * r)) // 3
        if nr >= r:
            break
        r = nr
    while (r + 1) ** 3 <= x:
        r += 1
    return r


K64 = [_icbrt(p << 192) & (2**64 - 1) for p in _primes(80)]
H0 = [math.isqrt(p << 128) & (2**64 - 1) for p in _primes(8)]

# --- 64-bit ops on (hi, lo) uint32 pairs -----------------------------------

U32 = jnp.uint32


def _add64(a, b):
    lo = a[1] + b[1]
    carry = (lo < a[1]).astype(U32)
    return a[0] + b[0] + carry, lo


def _add64_many(*xs):
    acc = xs[0]
    for x in xs[1:]:
        acc = _add64(acc, x)
    return acc


def _xor64(a, b):
    return a[0] ^ b[0], a[1] ^ b[1]


def _rotr64(x, n: int):
    hi, lo = x
    if n == 32:
        return lo, hi
    if n < 32:
        return (
            (hi >> n) | (lo << (32 - n)),
            (lo >> n) | (hi << (32 - n)),
        )
    m = n - 32
    return (
        (lo >> m) | (hi << (32 - m)),
        (hi >> m) | (lo << (32 - m)),
    )


def _shr64(x, n: int):
    hi, lo = x
    if n < 32:
        return hi >> n, (lo >> n) | (hi << (32 - n))
    return jnp.zeros_like(hi), hi >> (n - 32)


def _big_sigma0(x):
    return _xor64(_xor64(_rotr64(x, 28), _rotr64(x, 34)), _rotr64(x, 39))


def _big_sigma1(x):
    return _xor64(_xor64(_rotr64(x, 14), _rotr64(x, 18)), _rotr64(x, 41))


def _small_sigma0(x):
    return _xor64(_xor64(_rotr64(x, 1), _rotr64(x, 8)), _shr64(x, 7))


def _small_sigma1(x):
    return _xor64(_xor64(_rotr64(x, 19), _rotr64(x, 61)), _shr64(x, 6))


def _ch(e, fv, g):
    return (
        (e[0] & fv[0]) ^ (~e[0] & g[0]),
        (e[1] & fv[1]) ^ (~e[1] & g[1]),
    )


def _maj(a, b, c):
    return (
        (a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
        (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]),
    )


def _const64(v: int, batch: int):
    hi = jnp.full((batch,), (v >> 32) & 0xFFFFFFFF, U32)
    lo = jnp.full((batch,), v & 0xFFFFFFFF, U32)
    return hi, lo


def sha512_96(r_bytes, a_bytes, m_bytes):
    """SHA-512 of the 96-byte message R||A||M, batched.

    Inputs: three (32, B) u8 arrays. Output: (64, B) f32 little-endian
    radix-256 limbs of the digest interpreted as an integer (RFC 8032
    digest-to-scalar convention), ready for `reduce_mod_l`.
    """
    batch = r_bytes.shape[1]
    msg = jnp.concatenate([r_bytes, a_bytes, m_bytes], axis=0)  # (96, B)
    u = msg.astype(U32)

    # One padded block: 96 message bytes, 0x80, zeros, 128-bit length (768).
    def word(j):  # big-endian 64-bit word j of the padded block
        base = 8 * j
        if base + 8 <= 96:
            hi = (
                (u[base] << 24)
                | (u[base + 1] << 16)
                | (u[base + 2] << 8)
                | u[base + 3]
            )
            lo = (
                (u[base + 4] << 24)
                | (u[base + 5] << 16)
                | (u[base + 6] << 8)
                | u[base + 7]
            )
            return hi, lo
        if j == 12:  # bytes 96-103: 0x80 then zeros
            return _const64(0x8000000000000000, batch)
        if j == 15:  # length in bits, big-endian: 96*8 = 768
            return _const64(768, batch)
        return _const64(0, batch)

    # Rolling-window fori_loop: W holds w[t..t+15]; round t consumes W[0]
    # and appends w[t+16]. An unrolled 80-round trace compiles minutes-slow
    # on XLA; the loop body traces once (~60 ops).
    w16 = jnp.stack(
        [jnp.stack(word(j), axis=0) for j in range(16)], axis=0
    )  # (16, 2, B) u32
    k_tab = jnp.array(
        [[(k >> 32) & 0xFFFFFFFF, k & 0xFFFFFFFF] for k in K64], U32
    )  # (80, 2)
    state0 = jnp.broadcast_to(
        jnp.array(
            [[(h >> 32) & 0xFFFFFFFF, h & 0xFFFFFFFF] for h in H0], U32
        )[:, :, None],
        (8, 2, batch),
    )

    def pair(arr2b):  # (2, B) -> (hi, lo)
        return arr2b[0], arr2b[1]

    def round_body(t, carry):
        state, w = carry
        a, b, c, d = (pair(state[i]) for i in range(4))
        e, fv, g, h = (pair(state[i]) for i in range(4, 8))
        w_t = pair(w[0])
        kt = lax.dynamic_index_in_dim(k_tab, t, 0, keepdims=False)
        k_pair = (
            jnp.broadcast_to(kt[0], (batch,)),
            jnp.broadcast_to(kt[1], (batch,)),
        )
        t1 = _add64_many(h, _big_sigma1(e), _ch(e, fv, g), k_pair, w_t)
        t2 = _add64(_big_sigma0(a), _maj(a, b, c))
        new_a = _add64(t1, t2)
        new_e = _add64(d, t1)
        state = jnp.stack(
            [
                jnp.stack(new_a),
                state[0],
                state[1],
                state[2],
                jnp.stack(new_e),
                state[4],
                state[5],
                state[6],
            ],
            axis=0,
        )
        w_new = _add64_many(
            _small_sigma1(pair(w[14])),
            pair(w[9]),
            _small_sigma0(pair(w[1])),
            w_t,
        )
        w = jnp.concatenate([w[1:], jnp.stack(w_new)[None]], axis=0)
        return state, w

    state, _ = lax.fori_loop(0, 80, round_body, (state0, w16))
    digest = [
        _add64(pair(state0[i]), pair(state[i])) for i in range(8)
    ]

    # Digest bytes (big-endian per word) -> little-endian integer limbs:
    # limb[8j + k] = byte k of word j = (word_j >> (56 - 8k)) & 0xFF.
    rows = []
    for hi, lo in digest:
        for part in (hi, lo):
            rows.extend(
                ((part >> sh) & 0xFF).astype(jnp.float32)
                for sh in (24, 16, 8, 0)
            )
    return jnp.stack(rows, axis=0)  # (64, B) f32


# --- mod L reduction (exact-f32 limb folds) --------------------------------
#
# Fold identities: 2^256 ≡ -16C, 2^252 ≡ -C (mod L). Subtractions stay
# nonnegative by adding a precomputed multiple-of-L bias whose limbs all
# exceed the subtrahend's normalized limb bound (field.py's BIAS16P trick,
# generalized to L and arbitrary widths).

C16_LIMBS = f.limbs_of_int(16 * C, 17)  # 16C < 2^129


def _bias_of_l(width: int, lo: int = 768) -> np.ndarray:
    """(width + 1, 1) f32 limbs of a multiple of L whose limbs 0..width-1
    are all in [lo, 2^13): the per-limb lower bound lets folds subtract
    normalized (<= 294) product limbs without borrows. The top row holds
    the remaining mass (unconstrained below 2^13)."""
    mult = (lo * (256**width - 1) // 255) // L + 2
    # Any multiple of L is >= 2^252, so the representation needs at least
    # 33 rows even when only a few leading rows carry floors.
    rows = max(width + 1, 33)
    assert mult * L < 256**rows
    digits = [(mult * L >> (8 * i)) & 0xFF for i in range(rows)]
    digits[rows - 1] += 256 * (mult * L >> (8 * rows))
    for i in range(width):
        while digits[i] < lo:
            digits[i] += 256
            digits[i + 1] -= 1
    # Cascade borrows through the unfloored tail (its digits may be 0).
    for i in range(width, rows - 1):
        if digits[i] < 0:
            k = (-digits[i] + 255) // 256
            digits[i] += 256 * k
            digits[i + 1] -= k
    assert digits[rows - 1] >= 0 and all(0 <= d < 2**13 for d in digits)
    assert sum(d << (8 * i) for i, d in enumerate(digits)) == mult * L
    return np.array(digits, np.float32).reshape(rows, 1)


# Fold width derivations (value bounds -> nonzero normalized limb rows):
#   fold 1: input < 2^512 (64 limbs), hi = 32 limbs < 2^256;
#           prod = 16C*hi < 2^385 -> rows 0..48 (49); bias width 49;
#           out < bias_total + 2^256 < 2^395 -> 51 rows (49 + 2 headroom).
#   fold 2: hi = rows 32..50 (19 limbs) < 2^139; prod < 2^268 -> 34 rows;
#           bias width 34; out < 2^275 -> 36 rows.
#   fold 3 (2^252 boundary): hi < 2^24-ish; prod = C*hi < 2^149 -> 19
#           rows; bias width 19; out < 2^252 + 2^155 < 2L.
BIAS_F1 = _bias_of_l(49)
BIAS_F2 = _bias_of_l(34)
BIAS_F3 = _bias_of_l(19)
C_LIMBS = f.limbs_of_int(C, 16)
L_COMPLEMENT = f.limbs_of_int(2**264 - L, 33)


def _carry_n(c: jnp.ndarray, passes: int = 3) -> jnp.ndarray:
    """Vectorized no-wrap carry passes; callers provide headroom rows.
    Input limbs < 2^24 exact -> output limbs <= 294."""
    for _ in range(passes):
        c = f._carry_pass(c, wrap=False)
    return c


def _seq_carry_n(c: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Exact sequential carry over ALL rows (f._seq_carry is fixed at 32);
    returns (limbs in [0, 256), carry_out)."""

    def body(i, state):
        limbs, carry = state
        t = lax.dynamic_index_in_dim(limbs, i, axis=0, keepdims=False) + carry
        hi = jnp.floor(t * (1.0 / 256.0))
        lo = t - hi * 256.0
        return lax.dynamic_update_index_in_dim(limbs, lo, i, axis=0), hi

    carry0 = jnp.zeros(c.shape[1:], c.dtype)
    return lax.fori_loop(0, c.shape[0], body, (c, carry0))


def _mul_const(hi_limbs: jnp.ndarray, const: np.ndarray, out_rows: int):
    """(n, B) limbs x (k, 1) constant -> (out_rows, B) raw product limbs.
    Exactness: limb values <= ~5000, constant limbs < 2^13 would break the
    2^24 bound, so constants here are canonical (< 256): products <= 5000
    * 255 < 2^21, <= k terms per row -> sums < 2^24, f32-exact."""
    n = hi_limbs.shape[0]
    k = const.shape[0]
    batch = hi_limbs.shape[1:]
    rows = []
    for r in range(n + k - 1):
        lo_i = max(0, r - k + 1)
        hi_i = min(r, n - 1)
        term = hi_limbs[lo_i] * float(const[r - lo_i, 0])
        for i in range(lo_i + 1, hi_i + 1):
            term = term + hi_limbs[i] * float(const[r - i, 0])
        rows.append(jnp.broadcast_to(term, batch)[None])
    pad = out_rows - len(rows)
    assert pad >= 0, (out_rows, n, k)
    if pad:
        rows.append(jnp.zeros((pad,) + batch, jnp.float32))
    return jnp.concatenate(rows, axis=0)


def _fold_256(limbs: jnp.ndarray, bias: np.ndarray) -> jnp.ndarray:
    """v = lo_32 + 2^256 * hi  ->  lo_32 + bias - 16C * hi, normalized.
    `bias` rows must cover every nonzero row of the normalized product
    (asserted by the width derivations above)."""
    width = bias.shape[0]
    batch = limbs.shape[1:]
    lo = limbs[:32]
    hi = limbs[32:]
    raw = _mul_const(hi, C16_LIMBS, max(width, hi.shape[0] + 17 - 1) + 3)
    prod = _carry_n(raw)[:width]  # rows >= width are provably zero
    lo_w = jnp.concatenate(
        [lo, jnp.zeros((width - 32,) + batch, jnp.float32)], axis=0
    )
    t = lo_w + jnp.asarray(bias) - prod
    t = jnp.concatenate([t, jnp.zeros((2,) + batch, jnp.float32)], axis=0)
    return _carry_n(t)


def _fold_252(limbs: jnp.ndarray) -> jnp.ndarray:
    """Final fold at the 2^252 boundary: result < 2L (34 rows)."""
    batch = limbs.shape[1:]
    width = BIAS_F3.shape[0]
    l31 = limbs[31]
    q = jnp.floor(l31 * (1.0 / 16.0))
    r = l31 - 16.0 * q
    # v = lo + 2^252 * hi with hi = q + 16*l32 + 16*l33*256 + ... — exact
    # for ANY nonnegative limb values (no canonicality assumption).
    tail = limbs[32:]
    hi_rows = [q + (16.0 * tail[0] if tail.shape[0] > 0 else 0.0)]
    for i in range(1, tail.shape[0]):
        hi_rows.append(16.0 * tail[i])
    hi_limbs = jnp.stack(
        [jnp.broadcast_to(x, batch) for x in hi_rows], axis=0
    )
    raw = _mul_const(
        hi_limbs, C_LIMBS, max(width, hi_limbs.shape[0] + 16 - 1) + 3
    )
    prod = _carry_n(raw)[:width]
    rows = max(32, width)
    lo_w = _pad_rows(
        jnp.concatenate(
            [limbs[:31], jnp.broadcast_to(r, batch)[None]], axis=0
        ),
        rows,
    )
    t = (
        lo_w
        + _pad_rows(jnp.asarray(BIAS_F3), rows)
        - _pad_rows(prod, rows)
    )
    t = jnp.concatenate([t, jnp.zeros((2,) + batch, jnp.float32)], axis=0)
    return _carry_n(t)


def _pad_rows(x: jnp.ndarray, rows: int) -> jnp.ndarray:
    if x.shape[0] >= rows:
        return x[:rows]
    cfg = [(0, rows - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, cfg)


def _cond_sub_l(x33: jnp.ndarray) -> jnp.ndarray:
    """One exact conditional subtraction of L on (33, B) limbs < 2^264."""
    t = x33 + jnp.asarray(L_COMPLEMENT)
    t, carry = _seq_carry_n(t)
    return f.select(carry >= 1.0, t, x33)


def reduce_mod_l(limbs64: jnp.ndarray) -> jnp.ndarray:
    """(64, B) f32 limbs (value < 2^512) -> (32, B) canonical limbs of
    value mod L (limbs in [0, 255], value in [0, L))."""
    v = _fold_256(limbs64, BIAS_F1)  # < 2^395
    v = _fold_256(v, BIAS_F2)  # < 2^275
    # Fold-3 output < lo_max + 2L where lo_max can exceed 2^252 slightly
    # (the low 31 limbs are normalized-but-not-canonical, <= 294 each, so
    # their sum reaches ~2^252.01): bound is < 2^253 + 2L < 4L.
    v = _fold_252(v)
    v = _pad_rows(v, 33)
    v, _ = _seq_carry_n(v)  # exact limbs before comparisons
    v = _cond_sub_l(v)  # < 4L -> three conditional subtractions to [0, L)
    v = _cond_sub_l(v)
    v = _cond_sub_l(v)
    return v[:32]


def _nibble_rows(limbs32: jnp.ndarray) -> jnp.ndarray:
    """(32, B) canonical byte limbs -> (64, B) 4-bit ladder digits
    (row 2k = low nibble of limb k), matching ed25519._nibbles."""
    hi = jnp.floor(limbs32 * (1.0 / 16.0))
    lo = limbs32 - 16.0 * hi
    return jnp.stack((lo, hi), axis=1).reshape(
        2 * limbs32.shape[0], limbs32.shape[1]
    )


def h_digits_on_device(r_bytes, a_bytes, m_bytes) -> jnp.ndarray:
    """(32, B) u8 x3 -> (64, B) f32 ladder digits of SHA-512(R||A||M) mod L."""
    return _nibble_rows(reduce_mod_l(sha512_96(r_bytes, a_bytes, m_bytes)))
