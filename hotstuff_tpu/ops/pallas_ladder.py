"""Pallas TPU kernel for the windowed double-scalar-mult ladder.

The jnp ladder (ops.ed25519._verify_kernel_w4) leaves XLA to schedule ~3.5k
field mults as separate HBM-roundtripping fusions per fori iteration. This
kernel runs the whole 64-group ladder VMEM-resident: one grid program per
256-lane batch block holds the accumulator point, both digit arrays and the
16-entry tables (shared k*B and per-item k*(-A)) on-chip for all 256
doubling steps — the only HBM traffic is the initial block load and the
final point store.

All arithmetic is ops.field on (32, BLOCK) f32 limb vectors (exact-integer
f32, see field.py). Table lookups are unrolled masked sums over the 16
entries (VPU fma chains — no gathers, which TPUs do poorly). Digit rows are
selected by an iota-mask reduction instead of dynamic slicing (supported +
cheap: 64xBLOCK fma per group).

Decompression, table construction and final compression stay in plain jnp
around the pallas_call (~15% of total work) — they run once per batch, not
per ladder step, so VMEM residency buys little there.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import field as f
from . import ed25519 as ed

BLOCK = 256  # lanes per grid program (multiple of 128)


def _digit_row(digits: jnp.ndarray, row) -> jnp.ndarray:
    """digits (64, B), dynamic row index -> (B,) via iota-mask reduction."""
    rows = lax.broadcasted_iota(jnp.int32, digits.shape, 0)
    return jnp.sum(jnp.where(rows == row, digits, 0.0), axis=0)


def _lookup_shared(table: jnp.ndarray, digit: jnp.ndarray) -> jnp.ndarray:
    """table (16, 32) canonical, digit (B,) -> (32, B) masked-sum select."""
    acc = jnp.zeros((f.NLIMB, digit.shape[0]), jnp.float32)
    for e in range(16):
        m = (digit == e).astype(jnp.float32)
        acc = acc + table[e][:, None] * m[None, :]
    return acc


def _lookup_item(table: jnp.ndarray, digit: jnp.ndarray) -> jnp.ndarray:
    """table (16, 32, B) per-item, digit (B,) -> (32, B)."""
    acc = jnp.zeros(table.shape[1:], jnp.float32)
    for e in range(16):
        m = (digit == e).astype(jnp.float32)
        acc = acc + table[e] * m[None, :]
    return acc


def _ladder_kernel(
    sd_ref,
    hd_ref,
    bypx_ref,
    bymx_ref,
    bxy2d_ref,
    ta_ypx_ref,
    ta_ymx_ref,
    ta_z_ref,
    ta_t2d_ref,
    x_out,
    y_out,
    z_out,
    t_out,
):
    sd = sd_ref[:]
    hd = hd_ref[:]
    b_ypx, b_ymx, b_xy2d = bypx_ref[:], bymx_ref[:], bxy2d_ref[:]
    ta_ypx, ta_ymx, ta_z, ta_t2d = (
        ta_ypx_ref[:],
        ta_ymx_ref[:],
        ta_z_ref[:],
        ta_t2d_ref[:],
    )

    def group(g, acc):
        # T-skip schedule: see ed._verify_kernel_w4.body — only the last
        # doubling (feeding the madd) produces T; the cached add skips it.
        for i in range(ed.WINDOW):
            acc = ed.point_dbl(acc, with_t=i == ed.WINDOW - 1)
        row = ed.NGROUPS - 1 - g
        sdg = _digit_row(sd, row)
        hdg = _digit_row(hd, row)
        acc = ed.point_madd(
            acc,
            _lookup_shared(b_ypx, sdg),
            _lookup_shared(b_ymx, sdg),
            _lookup_shared(b_xy2d, sdg),
        )
        acc = ed.point_add_cached(
            acc,
            _lookup_item(ta_ypx, hdg),
            _lookup_item(ta_ymx, hdg),
            _lookup_item(ta_z, hdg),
            _lookup_item(ta_t2d, hdg),
            with_t=False,
        )
        return acc

    with f.mosaic_safe():
        X, Y, Z, T = lax.fori_loop(
            0, ed.NGROUPS, group, ed.point_identity(sd.shape[1])
        )
    x_out[:] = X
    y_out[:] = Y
    z_out[:] = Z
    t_out[:] = T


@functools.partial(jax.jit, static_argnames=())
def ladder_pallas(s_digits, h_digits, ta_ypx, ta_ymx, ta_z, ta_t2d):
    """(64,B) digits + per-item tables (16,32,B) -> ladder result Point."""
    batch = s_digits.shape[1]
    assert batch % BLOCK == 0, f"batch {batch} must be a multiple of {BLOCK}"
    grid = (batch // BLOCK,)

    digit_spec = pl.BlockSpec(
        (ed.NGROUPS, BLOCK), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    shared_spec = pl.BlockSpec(
        (16, f.NLIMB), lambda i: (0, 0), memory_space=pltpu.VMEM
    )
    item_spec = pl.BlockSpec(
        (16, f.NLIMB, BLOCK), lambda i: (0, 0, i), memory_space=pltpu.VMEM
    )
    out_spec = pl.BlockSpec(
        (f.NLIMB, BLOCK), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    out_shape = jax.ShapeDtypeStruct((f.NLIMB, batch), jnp.float32)

    base = [np.ascontiguousarray(t.T) for t in ed.BASE_TABLE]  # (16, 32)
    x, y, z, t = pl.pallas_call(
        _ladder_kernel,
        grid=grid,
        in_specs=[digit_spec, digit_spec] + [shared_spec] * 3 + [item_spec] * 4,
        out_specs=[out_spec] * 4,
        out_shape=[out_shape] * 4,
    )(s_digits, h_digits, *base, ta_ypx, ta_ymx, ta_z, ta_t2d)
    return x, y, z, t


def _verify_kernel_pallas(a_y, a_sign, r_enc, s_digits, h_digits):
    """Full verification with the ladder in pallas; same contract as
    ed._verify_kernel_w4."""
    x_a, xneg_a, valid = ed.decompress(a_y, a_sign)
    ta = ed._build_neg_a_table(xneg_a, a_y)
    result = ladder_pallas(s_digits, h_digits, *ta)
    enc = ed.compress(result)
    return valid & jnp.all(enc == r_enc, axis=0)


_verify_pallas_jit = jax.jit(_verify_kernel_pallas)


def _verify_kernel_pallas_packed128(packed):
    """(128, B) u8 wire array (see ed.prepare_batch_packed) -> (B,) bool."""
    return _verify_kernel_pallas(
        *ed.unpack_packed_inputs(*ed.split_packed128(packed))
    )


def _verify_kernel_pallas_packed128_dh(packed):
    """Device-hash wire format: rows 96-127 are the 32-byte message; h is
    computed on device (ops.sha512) in plain jnp around the pallas ladder."""
    return _verify_kernel_pallas(*ed.unpack_packed_inputs_dh(packed))


_verify_pallas_p128_jit = jax.jit(_verify_kernel_pallas_packed128)
_verify_pallas_p128dh_jit = jax.jit(_verify_kernel_pallas_packed128_dh)
