"""TPU compute kernels: GF(2^255-19) limb arithmetic and batched ed25519
verification (the reference crypto hot path, crypto/src/lib.rs:194-220,
rebuilt as JAX SPMD kernels)."""

import os

from . import field
from .ed25519 import Ed25519TpuVerifier, prepare_batch, prepare_batch_packed

__all__ = [
    "field",
    "ed25519",
    "Ed25519TpuVerifier",
    "prepare_batch",
    "prepare_batch_packed",
    "enable_persistent_cache",
]


def enable_persistent_cache(path: str | None = None) -> None:
    """Persistent XLA compilation cache: each verifier bucket width is a
    separate jit specialisation (~20-40 s compile on TPU), so a cold process
    would otherwise stall mid-benchmark on every new width. Safe to call
    more than once; disable with HOTSTUFF_JAX_CACHE=0."""
    if os.environ.get("HOTSTUFF_JAX_CACHE", "1") == "0":
        return
    import jax

    cache_dir = path or os.environ.get(
        "HOTSTUFF_JAX_CACHE_DIR",
        os.path.expanduser("~/.cache/hotstuff_tpu_jax"),
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # older jax without these flags
        pass
