"""TPU compute kernels: GF(2^255-19) limb arithmetic and batched ed25519
verification (the reference crypto hot path, crypto/src/lib.rs:194-220,
rebuilt as JAX SPMD kernels).

The jax-backed submodules (`field`, `ed25519`, ...) load LAZILY (PEP 562):
`hotstuff_tpu.ops.timeline` (device-occupancy timeline) and
`hotstuff_tpu.ops.pipeline` (async dispatch pipeline) plus the two
relay/cache helpers below are dependency-free, and the telemetry plane,
chaos runner, and the graftlint tool import them on hosts with no jax
at all. `from hotstuff_tpu.ops import ed25519 as ed` still works unchanged
(submodule imports bypass this shim); only attribute access on the package
goes through __getattr__.
"""

import os

from . import pipeline, timeline  # dependency-free; eager on purpose

__all__ = [
    "field",
    "ed25519",
    "bls",
    "pipeline",
    "timeline",
    "Ed25519TpuVerifier",
    "prepare_batch",
    "prepare_batch_packed",
    "enable_persistent_cache",
    "check_axon_relay",
]

# Package attributes resolved lazily so `import hotstuff_tpu.ops` (and the
# timeline/telemetry modules) never pull jax.
_LAZY_MODULES = ("field", "field12", "ed25519", "sha512", "pallas_ladder", "bls")
_LAZY_ED25519 = ("Ed25519TpuVerifier", "prepare_batch", "prepare_batch_packed")


def __getattr__(name: str):
    if name in _LAZY_MODULES:
        import importlib

        return importlib.import_module(f".{name}", __name__)
    if name in _LAZY_ED25519:
        from . import ed25519

        return getattr(ed25519, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def check_axon_relay(port: int = 8082, timeout: float = 5.0) -> None:
    """Fail fast (RuntimeError) when the axon TPU relay is unreachable —
    jax device init otherwise blocks indefinitely with no diagnostics
    (observed: the loopback relay process died mid-round and every device
    probe hung for hours).

    Fires when PALLAS_AXON_POOL_IPS is set, unless JAX_PLATFORMS already
    selects a different backend explicitly (the axon import hook force-
    sets JAX_PLATFORMS=axon during `import jax`, so an unset variable
    still means the axon path will be taken). Every pool IP is probed;
    any live relay passes."""
    pool = os.environ.get("PALLAS_AXON_POOL_IPS")
    plat = os.environ.get("JAX_PLATFORMS", "")
    platforms = [p.strip() for p in plat.split(",") if p.strip()]
    if not pool or (platforms and "axon" not in platforms):
        return
    # A caller that already imported jax and overrode the platform config
    # (the tests/conftest.py CPU-mesh dance) is not going to touch axon.
    import sys

    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            cfg = jax_mod.config.jax_platforms
            if cfg and "axon" not in str(cfg):
                return
        except Exception:
            pass
    import socket

    errors = []
    for ip in pool.split(","):
        try:
            socket.create_connection((ip.strip(), port), timeout).close()
            return
        except OSError as e:
            errors.append(f"{ip.strip()}:{port}: {e}")
    raise RuntimeError(
        "axon TPU relay unreachable (" + "; ".join(errors) + "); "
        "refusing to hang on device init"
    )


def enable_persistent_cache(path: str | None = None) -> None:
    """Persistent XLA compilation cache: each verifier bucket width is a
    separate jit specialisation (~20-40 s compile on TPU), so a cold process
    would otherwise stall mid-benchmark on every new width. Safe to call
    more than once; disable with HOTSTUFF_JAX_CACHE=0."""
    if os.environ.get("HOTSTUFF_JAX_CACHE", "1") == "0":
        return
    import jax

    cache_dir = path or os.environ.get(
        "HOTSTUFF_JAX_CACHE_DIR",
        os.path.expanduser("~/.cache/hotstuff_tpu_jax"),
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # older jax without these flags
        pass
