"""TPU compute kernels: GF(2^255-19) limb arithmetic and batched ed25519
verification (the reference crypto hot path, crypto/src/lib.rs:194-220,
rebuilt as JAX SPMD kernels)."""

import os

from . import field
from .ed25519 import Ed25519TpuVerifier, prepare_batch, prepare_batch_packed

__all__ = [
    "field",
    "ed25519",
    "Ed25519TpuVerifier",
    "prepare_batch",
    "prepare_batch_packed",
    "enable_persistent_cache",
    "check_axon_relay",
]


def check_axon_relay(port: int = 8082, timeout: float = 5.0) -> None:
    """Fail fast (RuntimeError) when the axon TPU relay is unreachable —
    jax device init otherwise blocks indefinitely with no diagnostics
    (observed: the loopback relay process died mid-round and every device
    probe hung for hours).

    Fires when PALLAS_AXON_POOL_IPS is set, unless JAX_PLATFORMS already
    selects a different backend explicitly (the axon import hook force-
    sets JAX_PLATFORMS=axon during `import jax`, so an unset variable
    still means the axon path will be taken). Every pool IP is probed;
    any live relay passes."""
    pool = os.environ.get("PALLAS_AXON_POOL_IPS")
    plat = os.environ.get("JAX_PLATFORMS", "")
    platforms = [p.strip() for p in plat.split(",") if p.strip()]
    if not pool or (platforms and "axon" not in platforms):
        return
    # A caller that already imported jax and overrode the platform config
    # (the tests/conftest.py CPU-mesh dance) is not going to touch axon.
    import sys

    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            cfg = jax_mod.config.jax_platforms
            if cfg and "axon" not in str(cfg):
                return
        except Exception:
            pass
    import socket

    errors = []
    for ip in pool.split(","):
        try:
            socket.create_connection((ip.strip(), port), timeout).close()
            return
        except OSError as e:
            errors.append(f"{ip.strip()}:{port}: {e}")
    raise RuntimeError(
        "axon TPU relay unreachable (" + "; ".join(errors) + "); "
        "refusing to hang on device init"
    )


def enable_persistent_cache(path: str | None = None) -> None:
    """Persistent XLA compilation cache: each verifier bucket width is a
    separate jit specialisation (~20-40 s compile on TPU), so a cold process
    would otherwise stall mid-benchmark on every new width. Safe to call
    more than once; disable with HOTSTUFF_JAX_CACHE=0."""
    if os.environ.get("HOTSTUFF_JAX_CACHE", "1") == "0":
        return
    import jax

    cache_dir = path or os.environ.get(
        "HOTSTUFF_JAX_CACHE_DIR",
        os.path.expanduser("~/.cache/hotstuff_tpu_jax"),
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # older jax without these flags
        pass
