"""TPU compute kernels: GF(2^255-19) limb arithmetic and batched ed25519
verification (the reference crypto hot path, crypto/src/lib.rs:194-220,
rebuilt as JAX SPMD kernels)."""

from . import field
from .ed25519 import Ed25519TpuVerifier, prepare_batch

__all__ = ["field", "ed25519", "Ed25519TpuVerifier", "prepare_batch"]
