"""BLS12-381 G1 committee-aggregation kernels (§5.5o).

The aggregate-certificate plane (consensus/messages.py AggQC/AggTC)
verifies ONE aggregate signature per certificate against the SUM of the
bitmap members' G1 public keys. The pairing itself is a per-certificate
constant, but the key sum is O(committee): at 256 validators the exact
host backend (crypto/aggsig._FP_OPS.add_affine) burns a field inversion
per added key. This module moves that sum onto the accelerator:

  * Fp in radix-2^12 uint32 limbs (32 limbs x 12 bits = 384 >= 381).
    BLS12-381's p is NOT pseudo-Mersenne, so the GF(2^255-19) fold trick
    (ops/field12.py) does not apply; multiplication is word-serial
    Montgomery (CIOS over 12-bit digits): the 64-digit schoolbook
    product, then 32 rounds of m = c_i * (-p^-1 mod 2^12) & MASK,
    c += m * p << 12i. Every accumulator stays uint32-exact:
    products <= 32 * 8191^2 < 2^31, reduction adds < 2^29, carries
    < 2^19 — sum < 2^31.6 < 2^32.
  * Residues live in [0, 2p) (Montgomery form, R = 2^384): with
    8p < R, a mul of a [0,2p) by a [0,4p) operand lands back in
    [0, 2p), so add/sub need only a conditional 2p-subtraction.
  * Jacobian points with Z = 0 as the identity; point_add is fully
    branchless — generic add-2007-bl, doubling, and the four identity/
    inverse cases resolved by masked selects — so a masked committee
    table tree-reduces in log2(N) vectorized adds with no host
    round-trips.

A CommitteeTable (mirroring ops/ed25519.CommitteeTable) pays the exact
host decompression of each registered 48-byte pk once per committee and
keeps Montgomery-affine limbs device-resident; `aggregate_bitmaps` then
turns certificate bitmaps into aggregate public keys in one batched
kernel launch. On hosts without jax the same API degrades to the exact
integer backend (`bls.host_fallbacks` counts it) — the chaos plane and
graftlint never import this module (it is lazy in ops/__init__), so the
dependency gate only matters for direct callers like bench.py.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..crypto import aggsig
from ..utils import metrics

try:  # CPU fallback: the module stays importable with no jax at all.
    import jax
    import jax.numpy as jnp
    from jax import lax

    HAVE_JAX = True
except Exception:  # pragma: no cover - exercised on jax-less hosts
    jax = jnp = lax = None
    HAVE_JAX = False

P = aggsig.P
NLIMB = 32
BITS = 12
RADIX = 1 << BITS
MASK = RADIX - 1
R_MONT = (1 << (BITS * NLIMB)) % P  # 2^384 mod p
PINV12 = (-pow(P, -1, RADIX)) % RADIX  # -p^-1 mod 2^12 (CIOS digit factor)

_M_TABLE_BUILDS = metrics.counter("bls.table_builds")
_M_AGGREGATIONS = metrics.counter("bls.aggregations")
_M_POINTS = metrics.counter("bls.points_aggregated")
_M_FALLBACKS = metrics.counter("bls.host_fallbacks")


def limbs_of_int(x: int, n: int = NLIMB) -> np.ndarray:
    assert 0 <= x < (1 << (BITS * n))
    out = np.zeros((n, 1), np.uint32)
    for i in range(n):
        out[i, 0] = (x >> (BITS * i)) & MASK
    return out


def int_of_limbs(limbs) -> list[int]:
    arr = np.asarray(limbs, np.uint64)
    flat = arr.reshape(arr.shape[0], -1)
    return [
        sum(int(flat[i, b]) << (BITS * i) for i in range(flat.shape[0]))
        for b in range(flat.shape[1])
    ]


def to_mont(x: int) -> int:
    return x * R_MONT % P


def from_mont(x: int) -> int:
    # x / R mod p, exact-integer (host-side only, per fetched result).
    return x * pow(R_MONT, P - 2, P) % P


P_LIMBS = limbs_of_int(P)
TWOP_LIMBS = limbs_of_int(2 * P)
TWOP_COMPLEMENT = limbs_of_int((1 << (BITS * NLIMB)) - 2 * P)


if HAVE_JAX:
    U32 = jnp.uint32

    def _seq_carry(c):
        """Sequential full carry: limbs < 2^32 -> limbs < 2^12 exactly
        (unique digit representation; required by the value-equality
        masks in point_add). Carry out of limb 31 must be zero — every
        caller's value fits 384 bits."""

        def body(i, state):
            limbs, cin = state
            t = lax.dynamic_index_in_dim(limbs, i, 0, keepdims=False) + cin
            lo = t & U32(MASK)
            return (
                lax.dynamic_update_index_in_dim(limbs, lo, i, 0),
                t >> BITS,
            )

        out, _ = lax.fori_loop(
            0, NLIMB, body, (c, jnp.zeros(c.shape[1:], U32))
        )
        return out

    def _cond_sub_2p(x):
        """x in [0, 4p), limbs normalized -> [0, 2p). Adds 2^384 - 2p;
        a carry out of the top limb means x >= 2p and the wrapped sum IS
        x - 2p."""
        t = x + jnp.asarray(TWOP_COMPLEMENT, U32).reshape(
            (NLIMB,) + (1,) * (x.ndim - 1)
        )

        def body(i, state):
            limbs, cin = state
            v = lax.dynamic_index_in_dim(limbs, i, 0, keepdims=False) + cin
            return (
                lax.dynamic_update_index_in_dim(limbs, v & U32(MASK), i, 0),
                v >> BITS,
            )

        t, cout = lax.fori_loop(
            0, NLIMB, body, (t, jnp.zeros(x.shape[1:], U32))
        )
        return jnp.where((cout >= 1)[None], t, x)

    def add_mod(a, b):
        """(a + b) brought back to [0, 2p), limbs normalized."""
        return _cond_sub_2p(_seq_carry(a + b))

    def sub_mod(a, b):
        """a - b in [0, 2p): sequential-borrow subtraction mod 2^384,
        then a conditional 2p add-back on the lanes that went negative.
        No bias headroom needed — p spans 381 of the 384 limb bits, so
        the field12 bias-with-floors trick has no room here. Inputs
        normalized in [0, 2p)."""
        batch = jnp.broadcast_shapes(a.shape[1:], b.shape[1:])
        a = jnp.broadcast_to(a, (NLIMB,) + batch)
        b = jnp.broadcast_to(b, (NLIMB,) + batch)

        def borrow_body(i, state):
            limbs, borrow = state
            ai = lax.dynamic_index_in_dim(a, i, 0, keepdims=False)
            bi = lax.dynamic_index_in_dim(b, i, 0, keepdims=False)
            t = ai + U32(RADIX) - bi - borrow  # in [1, 2^13)
            return (
                lax.dynamic_update_index_in_dim(limbs, t & U32(MASK), i, 0),
                U32(1) - (t >> BITS),
            )

        diff, borrow = lax.fori_loop(
            0,
            NLIMB,
            borrow_body,
            (jnp.zeros((NLIMB,) + batch, U32), jnp.zeros(batch, U32)),
        )
        twop = jnp.asarray(TWOP_LIMBS, U32).reshape(
            (NLIMB,) + (1,) * len(batch)
        )
        return _seq_carry(diff + borrow[None] * twop)

    def mont_mul(a, b):
        """Montgomery product a*b/R mod p, output in [0, 2p) normalized.
        Inputs: values < 2p x < 4p with limbs <= 2^13 (one lazy add on
        one operand is admissible; both normalized is the common case)."""
        batch = jnp.broadcast_shapes(a.shape[1:], b.shape[1:])
        a = jnp.broadcast_to(a, (NLIMB,) + batch)
        b = jnp.broadcast_to(b, (NLIMB,) + batch)
        c = jnp.zeros((2 * NLIMB,) + batch, U32)

        def prod(i, c):
            ai = lax.dynamic_index_in_dim(a, i, 0, keepdims=False)
            cur = lax.dynamic_slice_in_dim(c, i, NLIMB, 0)
            return lax.dynamic_update_slice_in_dim(c, cur + ai[None] * b, i, 0)

        c = lax.fori_loop(0, NLIMB, prod, c)
        p_limbs = jnp.asarray(P_LIMBS, U32).reshape(
            (NLIMB,) + (1,) * len(batch)
        )

        def reduce(i, c):
            ci = lax.dynamic_index_in_dim(c, i, 0, keepdims=False)
            m = (ci * U32(PINV12)) & U32(MASK)
            cur = lax.dynamic_slice_in_dim(c, i, NLIMB, 0)
            cur = cur + m[None] * p_limbs
            # Digit i is now ≡ 0 mod 2^12; retire it into digit i+1.
            cur = cur.at[1].add(cur[0] >> BITS)
            cur = cur.at[0].set(U32(0))
            return lax.dynamic_update_slice_in_dim(c, cur, i, 0)

        c = lax.fori_loop(0, NLIMB, reduce, c)
        return _seq_carry(lax.dynamic_slice_in_dim(c, NLIMB, NLIMB, 0))

    def mont_sqr(a):
        return mont_mul(a, a)

    def is_zero_mod_p(a):
        """Value ≡ 0 (mod p) for a normalized [0, 2p) residue: the digit
        string is exactly 0 or exactly p's."""
        p_limbs = jnp.asarray(P_LIMBS, U32).reshape(
            (NLIMB,) + (1,) * (a.ndim - 1)
        )
        return jnp.all(a == 0, axis=0) | jnp.all(a == p_limbs, axis=0)

    def _select(mask, a, b):
        return jnp.where(mask[None], a, b)

    def point_identity(batch: tuple):
        one = jnp.broadcast_to(
            jnp.asarray(limbs_of_int(to_mont(1)), U32).reshape(
                (NLIMB,) + (1,) * len(batch)
            ),
            (NLIMB,) + batch,
        )
        return one, one, jnp.zeros((NLIMB,) + batch, U32)

    def dbl_mod(a):
        return add_mod(a, a)

    def point_dbl(pt):
        """Jacobian doubling (dbl-2007-bl shape, a = 0). Y = 0 (outside
        the prime-order subgroup) degenerates to Z3 = 0 = identity with
        no special case."""
        X, Y, Z = pt
        A = mont_sqr(X)
        B = mont_sqr(Y)
        C = mont_sqr(B)
        D = dbl_mod(sub_mod(sub_mod(mont_sqr(add_mod(X, B)), A), C))
        E = add_mod(dbl_mod(A), A)
        X3 = sub_mod(sub_mod(mont_sqr(E), D), D)
        Y3 = sub_mod(mont_mul(E, sub_mod(D, X3)), dbl_mod(dbl_mod(dbl_mod(C))))
        Z3 = dbl_mod(mont_mul(Y, Z))
        return X3, Y3, Z3

    def point_add(p1, p2):
        """Branchless Jacobian addition (add-2007-bl) with the identity,
        doubling, and inverse cases resolved by lane masks — the shape a
        masked tree reduction needs."""
        X1, Y1, Z1 = p1
        X2, Y2, Z2 = p2
        Z1Z1 = mont_sqr(Z1)
        Z2Z2 = mont_sqr(Z2)
        U1 = mont_mul(X1, Z2Z2)
        U2 = mont_mul(X2, Z1Z1)
        S1 = mont_mul(mont_mul(Y1, Z2), Z2Z2)
        S2 = mont_mul(mont_mul(Y2, Z1), Z1Z1)
        H = sub_mod(U2, U1)
        Rr = dbl_mod(sub_mod(S2, S1))
        I = mont_sqr(dbl_mod(H))
        J = mont_mul(H, I)
        V = mont_mul(U1, I)
        X3 = sub_mod(sub_mod(mont_sqr(Rr), J), dbl_mod(V))
        Y3 = sub_mod(
            mont_mul(Rr, sub_mod(V, X3)), dbl_mod(mont_mul(S1, J))
        )
        Z3 = dbl_mod(mont_mul(mont_mul(Z1, Z2), H))

        inf1 = is_zero_mod_p(Z1)
        inf2 = is_zero_mod_p(Z2)
        eq_x = is_zero_mod_p(H)
        eq_y = is_zero_mod_p(sub_mod(S2, S1))
        dX, dY, dZ = point_dbl(p1)
        iX, iY, iZ = point_identity(X1.shape[1:])

        # Lane resolution, later selects win: doubling and inverse-pair
        # first (H = 0 is also true on identity lanes — U1 = U2 = 0 —
        # so the identity selects must come after), then p1-identity
        # -> p2, then p2-identity -> p1. Both-identity lands on p1,
        # whose Z ≡ 0 already encodes the identity.
        def pick(m, a, b):
            return tuple(_select(m, x, y) for x, y in zip(a, b))

        out = pick(eq_x & eq_y, (dX, dY, dZ), (X3, Y3, Z3))
        out = pick(eq_x & ~eq_y, (iX, iY, iZ), out)
        out = pick(inf1, (X2, Y2, Z2), out)
        out = pick(inf2, (X1, Y1, Z1), out)
        return out

    def masked_tree_aggregate(tx, ty, mask):
        """Sum the masked committee points: tx/ty (NLIMB, N) Montgomery
        affine limbs, mask (B, N) bool -> one Jacobian point per batch
        row, in ceil(log2 N) vectorized point adds."""
        B, N = mask.shape
        one = jnp.asarray(limbs_of_int(to_mont(1)), U32).reshape(NLIMB, 1, 1)
        X = jnp.broadcast_to(tx[:, None, :], (NLIMB, B, N))
        Y = jnp.broadcast_to(ty[:, None, :], (NLIMB, B, N))
        Z = jnp.where(mask[None], jnp.broadcast_to(one, (NLIMB, B, N)), 0)
        pt = (X, Y, Z)
        n = N
        while n > 1:
            half = (n + 1) // 2
            if n % 2:
                pad = point_identity((B, 1))
                pt = tuple(
                    jnp.concatenate([c, p], axis=2) for c, p in zip(pt, pad)
                )
            lo = tuple(c[:, :, :half] for c in pt)
            hi = tuple(c[:, :, half:] for c in pt)
            pt = point_add(lo, hi)
            n = half
        return tuple(c[:, :, 0] for c in pt)


# --------------------------------------------------------------------------
# Committee-resident aggregate-key table + host conversions.


class CommitteeTable:
    """Device-resident Montgomery-affine G1 limbs for one committee's
    registered aggregate keys, built once per epoch (the per-certificate
    amortization lever — same shape as ops/ed25519.CommitteeTable).

    `keys` are 48-byte compressed G1 public keys in bitmap order
    (aggsig registry values resolved over Committee.sorted_keys()).
    Un-decompressable or infinity keys occupy identity lanes and are
    reported in `invalid` — their bits contribute nothing to a sum,
    matching the exact backend's verify failure for such members (the
    caller rejects certificates whose bitmap selects an invalid lane).
    """

    def __init__(self, keys: Sequence[bytes], put=None) -> None:
        keys = [bytes(k) for k in keys]
        if not keys:
            raise ValueError("committee must have at least one key")
        n = len(keys)
        self.keys = keys
        self.index: dict[bytes, int] = {}
        for i, k in enumerate(keys):
            self.index.setdefault(k, i)
        self.points: list[tuple[int, int] | None] = []
        tx = np.zeros((NLIMB, n), np.uint32)
        ty = np.zeros((NLIMB, n), np.uint32)
        present = np.zeros(n, bool)
        invalid = np.zeros(n, bool)
        for i, kb in enumerate(keys):
            try:
                pt = aggsig.decompress_g1(kb)
            except ValueError:
                pt = None
                invalid[i] = True
            self.points.append(pt)
            if pt is None:
                continue
            present[i] = True
            tx[:, i] = limbs_of_int(to_mont(pt[0]))[:, 0]
            ty[:, i] = limbs_of_int(to_mont(pt[1]))[:, 0]
        self.size = n
        self.invalid = invalid
        if HAVE_JAX:
            if put is None:
                put = jax.device_put
            self.tx = put(tx)
            self.ty = put(ty)
            self.present = put(present)
        else:
            self.tx, self.ty, self.present = tx, ty, present
        _M_TABLE_BUILDS.inc()

    # -- host fallback ----------------------------------------------------

    def _aggregate_host(self, masks: np.ndarray):
        ops = aggsig._FP_OPS
        out = []
        for row in masks:
            acc = None
            for i in np.flatnonzero(row):
                acc = ops.add_affine(acc, self.points[i])
            out.append(acc)
        return out

    def aggregate_masks(self, masks) -> list[tuple[int, int] | None]:
        """(B, N) bool mask rows -> affine integer G1 sums (None = the
        identity). Masked lanes whose key was invalid contribute the
        identity — callers gate on `invalid` first."""
        masks = np.asarray(masks, bool)
        if masks.ndim == 1:
            masks = masks[None]
        if masks.shape[1] != self.size:
            raise ValueError(
                f"mask width {masks.shape[1]} != committee size {self.size}"
            )
        _M_AGGREGATIONS.inc(masks.shape[0])
        _M_POINTS.inc(int(masks.sum()))
        if not HAVE_JAX:
            _M_FALLBACKS.inc(masks.shape[0])
            return self._aggregate_host(masks)
        eff = jnp.asarray(masks) & self.present[None]
        X, Y, Z = _aggregate_jit(self.tx, self.ty, eff)
        xs = int_of_limbs(np.asarray(X))
        ys = int_of_limbs(np.asarray(Y))
        zs = int_of_limbs(np.asarray(Z))
        out = []
        for x, y, z in zip(xs, ys, zs):
            x, y, z = from_mont(x % P), from_mont(y % P), from_mont(z % P)
            if z == 0:
                out.append(None)
                continue
            zinv = pow(z, P - 2, P)
            zi2 = zinv * zinv % P
            out.append((x * zi2 % P, y * zinv % P * zi2 % P))
        return out

    def aggregate_bitmaps(
        self, bitmaps: Sequence[int]
    ) -> list[tuple[int, int] | None]:
        masks = np.zeros((len(bitmaps), self.size), bool)
        for b, bm in enumerate(bitmaps):
            if bm < 0 or bm >> self.size:
                raise ValueError(f"bitmap {bm:#x} exceeds committee")
            for i in range(self.size):
                masks[b, i] = bool(bm >> i & 1)
        return self.aggregate_masks(masks)

    def verify_aggregate(self, bitmap: int, msg: bytes, sig: bytes) -> bool:
        """One AggQC-shaped check: the device-summed aggregate key of
        `bitmap`, one pairing equation on the exact host backend. The
        bitmap must not select an invalid (un-decompressable) lane."""
        for i in range(self.size):
            if bitmap >> i & 1 and self.invalid[i]:
                return False
        apk = self.aggregate_bitmaps([bitmap])[0]
        if apk is None:
            return False
        try:
            s = aggsig.decompress_g2(sig)
        except ValueError:
            return False
        if s is None or not aggsig._g2_in_subgroup(s):
            return False
        return aggsig._pairings_are_one(
            [
                (aggsig._g1_neg(aggsig.G1_GEN), s),
                (apk, aggsig.hash_to_g2(msg)),
            ]
        )


if HAVE_JAX:
    _aggregate_jit = jax.jit(masked_tree_aggregate)
else:  # pragma: no cover - jax-less hosts take the host path above
    _aggregate_jit = None
