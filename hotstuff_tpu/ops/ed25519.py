"""Batched ed25519 verification on TPU — the north-star kernel.

Replaces the reference's CPU `ed25519_dalek` batch paths
(`Signature::verify_batch` crypto/src/lib.rs:194-207, used by `QC::verify`
consensus/src/messages.rs:197; `verify_batch_alt` crypto/src/lib.rs:209-220,
the mempool workload mempool/src/core.rs:135-148) with a single jitted
SPMD kernel over the batch:

    for each item i:  valid_i  <=>  enc([s_i]B - [h_i]A_i) == R_i
    with h_i = SHA-512(R_i || A_i || M_i) mod L

which is the strict (cofactorless) verification equation — per-item masks
come for free, strictly stronger than the reference's all-or-nothing batch.

TPU mapping:
  * All field math is `ops.field` (32, B)-limb f32 vectors: batch on lanes.
  * The double-scalar multiply is a shared-doubling (Straus) ladder:
    253 iterations of [double; conditional mixed-add of the constant base
    point B; conditional mixed-add of the per-item -A_i] under
    `lax.fori_loop` — fixed trip count, no data-dependent control flow,
    selects instead of branches (SIMD over the batch).
  * Point decompression (sqrt via x^((p-5)/8)) and final compression
    (inverse via x^(p-2)) run on-device with ref10 addition chains.
  * SHA-512 and the mod-L scalar reductions are host-side (cheap, byte-
    oriented; the EC math is >99% of the work and all on TPU).

Curve ops use the extended-coordinate formulas for a = -1 twisted Edwards
(dbl-2008-hwcd / madd-2008-hwcd-3): unified mixed addition handles identity
and doubling inputs, so the ladder needs no special cases.
"""

from __future__ import annotations

import hashlib
import logging
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import field as f
from . import timeline
from .pipeline import ChunkTask, DispatchPipeline
from ..utils import metrics

log = logging.getLogger("hotstuff.ops")

# Stage-tracing handles (names match tools/profile_e2e.py's phase rows; see
# the COMPONENTS.md metric table). `readback_s` times the single
# device->host mask fetch, which in the pipelined path also drains the
# device compute queue — profile_e2e.py separates compute from readback by
# probing phases in isolation, which an in-process span cannot.
_M_STAGE = metrics.histogram("verifier.stage_s")
_M_UPLOAD = metrics.histogram("verifier.upload_s")
_M_DISPATCH = metrics.histogram("verifier.dispatch_s")
_M_READBACK = metrics.histogram("verifier.readback_s")
_M_E2E = metrics.histogram("verifier.e2e_s")
_M_BATCH_SIZE = metrics.histogram("verifier.batch_size", metrics.SIZE_BUCKETS)
_M_SIGS = metrics.counter("verifier.sigs")
_M_BATCHES = metrics.counter("verifier.batches")
_M_CHUNKS = metrics.counter("verifier.chunks")
_M_DH_FALLBACKS = metrics.counter("verifier.device_hash_fallbacks")
# Committee-residency accounting: the generic kernels re-decompress every
# lane's public key and rebuild its 16-entry -A window table per chunk
# (decompressions / table_builds); the committee path gathers precomputed
# tables by validator index and increments NEITHER — the acceptance check
# for steady-state zero-rebuild batches.
_M_DECOMPRESSIONS = metrics.counter("verifier.decompressions")
_M_TABLE_BUILDS = metrics.counter("verifier.table_builds")
# Lanes shipped only to fill a bucket (width - occupancy), summed per chunk.
# A mesh verifier's buckets are never narrower than lane * ndev, so small
# quorum batches inflate this counter — the visibility hook behind the
# mesh-aware committee_crossover (sub-alignment batches belong on host CPU).
_M_PAD_LANES = metrics.counter("verifier.pad_lanes")
_M_COMMITTEE_BATCHES = metrics.counter("verifier.committee_batches")
_M_COMMITTEE_SIGS = metrics.counter("verifier.committee_sigs")
_M_COMMITTEE_REGS = metrics.counter("verifier.committee_registrations")
_M_COMMITTEE_SIZE = metrics.gauge("verifier.committee_size")

P = f.P
L_ORDER = 2**252 + 27742317777372353535851937790883648493

# --- curve constants (host Python ints -> limb arrays) ---------------------
D_INT = (-121665 * pow(121666, P - 2, P)) % P
D2_INT = (2 * D_INT) % P
SQRTM1_INT = pow(2, (P - 1) // 4, P)

BY_INT = (4 * pow(5, P - 2, P)) % P
_u = (BY_INT * BY_INT - 1) % P
_v = (D_INT * BY_INT * BY_INT + 1) % P
_x2 = (_u * pow(_v, P - 2, P)) % P
BX_INT = pow(_x2, (P + 3) // 8, P)
if (BX_INT * BX_INT - _x2) % P != 0:
    BX_INT = (BX_INT * SQRTM1_INT) % P
if BX_INT % 2 != 0:
    BX_INT = P - BX_INT
assert (BX_INT * BX_INT - _x2) % P == 0

D = f.limbs_of_int(D_INT)
D2 = f.limbs_of_int(D2_INT)
SQRTM1 = f.limbs_of_int(SQRTM1_INT)
# Precomputed affine base point for mixed addition: (y+x, y-x, 2*d*x*y).
BASE_YPX = f.limbs_of_int((BY_INT + BX_INT) % P)
BASE_YMX = f.limbs_of_int((BY_INT - BX_INT) % P)
BASE_XY2D = f.limbs_of_int((D2_INT * BX_INT * BY_INT) % P)

SCALAR_BITS = 253  # both s < L < 2^253 and h < L

Point = tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]  # X,Y,Z,T


def point_identity(batch: int, dtype=jnp.float32) -> Point:
    zero = jnp.zeros((f.NLIMB, batch), dtype)
    one = jnp.concatenate([jnp.ones((1, batch), dtype), zero[1:]], axis=0)
    return zero, one, one, zero


def point_dbl(p: Point, with_t: bool = True) -> Point:
    """dbl-2008-hwcd for a=-1 (complete for doubling, identity included).

    Doubling never READS the input T, so a doubling whose consumer is
    another doubling can skip producing it (`with_t=False`, one field mul
    saved — 3 of every 4 ladder doublings qualify, ~5% of kernel ops);
    the returned T is zeros then, and must not feed an addition."""
    X, Y, Z, _ = p
    xx = f.sqr(X)
    yy = f.sqr(Y)
    zz = f.sqr(Z)
    zz2 = f.add(zz, zz)
    aa = f.sqr(f.add(X, Y))
    yp = f.add(yy, xx)  # Y' = Y^2 - a*X^2 = Y^2 + X^2
    zp = f.sub(yy, xx)
    xp = f.sub(aa, yp)  # = 2XY
    tp = f.sub(zz2, zp)
    t_out = f.mul(xp, yp) if with_t else jnp.zeros_like(xp)
    return f.mul(xp, tp), f.mul(yp, zp), f.mul(zp, tp), t_out


def point_madd(p: Point, q_ypx, q_ymx, q_xy2d, with_t: bool = True) -> Point:
    """Unified mixed addition (madd-2008-hwcd-3): P + affine precomp Q.
    `with_t=False` skips producing T (valid when the consumer is a doubling
    or the final compress, neither of which reads it)."""
    X1, Y1, Z1, T1 = p
    a = f.mul(f.add(Y1, X1), q_ypx)
    b = f.mul(f.sub(Y1, X1), q_ymx)
    c = f.mul(T1, q_xy2d)
    d2z = f.add(Z1, Z1)
    x3 = f.sub(a, b)
    y3 = f.add(a, b)
    z3 = f.add(d2z, c)
    t3 = f.sub(d2z, c)
    t_out = f.mul(x3, y3) if with_t else jnp.zeros_like(x3)
    return f.mul(x3, t3), f.mul(y3, z3), f.mul(z3, t3), t_out


def _select_point(mask: jnp.ndarray, a: Point, b: Point) -> Point:
    return tuple(f.select(mask, x, y) for x, y in zip(a, b))


def point_add_cached(p: Point, q_ypx, q_ymx, q_z, q_t2d, with_t: bool = True) -> Point:
    """Unified addition with a cached point (Y2+X2, Y2-X2, Z2, 2d*T2)
    (add-2008-hwcd-3). Cached identity is (1, 1, 1, 0). `with_t=False`
    skips producing T (valid when the consumer is a doubling or the final
    compress, neither of which reads it)."""
    X1, Y1, Z1, T1 = p
    a = f.mul(f.add(Y1, X1), q_ypx)
    b = f.mul(f.sub(Y1, X1), q_ymx)
    c = f.mul(T1, q_t2d)
    zz = f.mul(Z1, q_z)
    d2z = f.add(zz, zz)
    x3 = f.sub(a, b)
    y3 = f.add(a, b)
    z3 = f.add(d2z, c)
    t3 = f.sub(d2z, c)
    t_out = f.mul(x3, y3) if with_t else jnp.zeros_like(x3)
    return f.mul(x3, t3), f.mul(y3, z3), f.mul(z3, t3), t_out


# --- 4-bit windowed ladder -------------------------------------------------
#
# Straus with 4-bit windows: 64 groups of [4 doublings; add T_B[digit_s];
# add T_A[digit_h]] where T_B is a shared 16-entry table of k*B (host
# precomputed, canonical) and T_A is a per-item 16-entry table of k*(-A)
# built on device. Entry 0 is the identity, absorbed by the unified
# addition formulas — zero digits cost nothing extra and need no selects.

WINDOW = 4
NGROUPS = 64  # ceil(256/4); scalars < 2^253 so top digits are small


def _edwards_add_int(p1, p2):
    """Exact affine Edwards addition over Python ints (host precompute)."""
    (x1, y1), (x2, y2) = p1, p2
    dxy = D_INT * x1 * x2 % P * y1 * y2 % P
    x3 = (x1 * y2 + x2 * y1) * pow(1 + dxy, P - 2, P) % P
    y3 = (y1 * y2 + x1 * x2) * pow(1 - dxy, P - 2, P) % P
    return x3, y3


def _base_table_np() -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(32, 16) f32 tables of k*B in precomp-affine form, k = 0..15."""
    pts = [(0, 1)]
    for _ in range(15):
        pts.append(_edwards_add_int(pts[-1], (BX_INT, BY_INT)))
    cols = lambda vals: np.concatenate(
        [f.limbs_of_int(v) for v in vals], axis=1
    )
    ypx = cols([(y + x) % P for x, y in pts])
    ymx = cols([(y - x) % P for x, y in pts])
    xy2d = cols([D2_INT * x * y % P for x, y in pts])
    return ypx, ymx, xy2d


BASE_TABLE = _base_table_np()


def _lookup_shared(table: np.ndarray, onehot: jnp.ndarray) -> jnp.ndarray:
    """(32,16) canonical table x (16,B) one-hot -> (32,B). bf16 MXU matmul:
    one-hot entries and canonical limbs (<=255) are bf16-exact, and exactly
    one product per output is nonzero, so the f32 accumulation is exact."""
    return jax.lax.dot(
        jnp.asarray(table, jnp.bfloat16),
        onehot.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )


def _lookup_per_item(table: jnp.ndarray, onehot: jnp.ndarray) -> jnp.ndarray:
    """(16,32,B) per-item table x (16,B) one-hot -> (32,B) (VPU masked sum).

    HIGHEST precision is load-bearing: per-item table limbs reach ~590
    (beyond bf16-exact integers), so a default-precision einsum lowered to
    bf16 MXU passes on real TPU would corrupt limbs and verification masks.
    """
    return jnp.einsum(
        "elb,eb->lb", table, onehot, precision=jax.lax.Precision.HIGHEST
    )


def _build_neg_a_table(x_neg, a_y):
    """16-entry cached table of k*(-A), stacked (16, 32, B) per component."""
    # k=0: identity (1,1,1,0); k=1: (-A) itself with Z=1, T=x*y
    na_ypx = f.add(a_y, x_neg)
    na_ymx = f.sub(a_y, x_neg)
    na_xy2d = f.mul(D2, f.mul(x_neg, a_y))
    batch = a_y.shape[1]
    pts = [point_identity(batch)]
    cur = (
        x_neg,
        a_y,
        jnp.broadcast_to(jnp.asarray(f.ONE), a_y.shape),
        f.mul(x_neg, a_y),
    )
    pts.append(cur)
    for _ in range(14):
        cur = point_madd(cur, na_ypx, na_ymx, na_xy2d)
        pts.append(cur)
    ypx = jnp.stack([f.add(p[1], p[0]) for p in pts])
    ymx = jnp.stack([f.sub(p[1], p[0]) for p in pts])
    z = jnp.stack([p[2] for p in pts])
    t2d = jnp.stack([f.mul(D2, p[3]) for p in pts])
    return ypx, ymx, z, t2d


def _verify_kernel_w4(a_y, a_sign, r_enc, s_digits, h_digits):
    """Windowed variant of `_verify_kernel`; digits are (64, B) f32 of 4-bit
    windows, most-significant window last (row 63)."""
    x_a, xneg_a, valid = decompress(a_y, a_sign)
    ta_ypx, ta_ymx, ta_z, ta_t2d = _build_neg_a_table(xneg_a, a_y)
    b_ypx, b_ymx, b_xy2d = BASE_TABLE

    batch = a_y.shape[1]

    def body(g, acc: Point) -> Point:
        row = NGROUPS - 1 - g
        # Only the LAST doubling needs T (the madd reads it); the group-
        # final cached add skips T too (its consumer is the next group's
        # doubling, or compress — neither reads T).
        for i in range(WINDOW):
            acc = point_dbl(acc, with_t=i == WINDOW - 1)
        sd = lax.dynamic_index_in_dim(s_digits, row, 0, keepdims=False)
        hd = lax.dynamic_index_in_dim(h_digits, row, 0, keepdims=False)
        s_oh = jax.nn.one_hot(sd.astype(jnp.int32), 16, axis=0, dtype=a_y.dtype)
        h_oh = jax.nn.one_hot(hd.astype(jnp.int32), 16, axis=0, dtype=a_y.dtype)
        acc = point_madd(
            acc,
            _lookup_shared(b_ypx, s_oh),
            _lookup_shared(b_ymx, s_oh),
            _lookup_shared(b_xy2d, s_oh),
        )
        acc = point_add_cached(
            acc,
            _lookup_per_item(ta_ypx, h_oh),
            _lookup_per_item(ta_ymx, h_oh),
            _lookup_per_item(ta_z, h_oh),
            _lookup_per_item(ta_t2d, h_oh),
            with_t=False,
        )
        return acc

    result = lax.fori_loop(0, NGROUPS, body, point_identity(batch))
    enc = compress(result)
    return valid & jnp.all(enc == r_enc, axis=0)


# --- committee-resident key precomputation --------------------------------
#
# The protocol's hot path verifies signatures from a FIXED set of <= ~100
# validator keys, yet the generic kernel re-decompresses each lane's key
# (sqrt addition chain, ~250 field ops) and rebuilds its 16-entry -A window
# table (14 cached adds) on device EVERY batch. A CommitteeTable pays that
# once per committee on the host with exact integer math and keeps the
# result device-resident; committee lanes then GATHER their table by
# validator index — zero per-batch decompressions or table builds, the
# per-verification amortization lever of "Performance of EdDSA and BLS
# Signatures in Committee-Based Consensus" (PAPERS.md).
#
# Host precompute yields AFFINE table entries (canonical limbs <= 255), so
# the per-item adds become mixed additions (madd-2008-hwcd-3) — one field
# mul per add cheaper than the generic path's cached adds, on top of the
# skipped decompress/build.


def _decompress_int(key: bytes) -> tuple[int, int] | None:
    """Exact host decompression of a 32-byte compressed point.

    Matches the device `decompress` semantics bit for bit: y is reduced
    mod p (non-canonical encodings are NOT rejected, mirroring the field-
    element decode of the device limbs and of ed25519_dalek), x = 0 absorbs
    either sign, and None is returned only when no square root exists."""
    enc = int.from_bytes(key, "little")
    sign = enc >> 255
    y = (enc & ((1 << 255) - 1)) % P
    u = (y * y - 1) % P
    v = (D_INT * y * y + 1) % P
    x2 = u * pow(v, P - 2, P) % P
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRTM1_INT % P
    if (x * x - x2) % P != 0:
        return None
    if x % 2 != sign:
        x = (P - x) % P
    return x, y


class CommitteeTable:
    """Device-resident per-validator -A window tables, built once per
    committee.

    Layout (N = committee size):
      ta_ypx / ta_ymx / ta_xy2d : (16, 32, N) f32 — affine precomp of
          k*(-A_i) for k = 0..15 (row 0 is the madd identity (1, 1, 0))
      valid   : (N,) bool — False for keys with no valid decompression
          (their lanes always fail, matching the generic kernel)
      keys_u8 : (32, N) u8 — raw key bytes, gathered on device by the
          device-hash kernel for h = SHA-512(R||A||M)

    `index` maps raw 32-byte key -> validator index for host-side routing.

    `put` overrides device placement of the finished arrays: the mesh
    verifier passes a replicated `NamedSharding` transfer so every chip in
    the mesh holds its own copy of the tables (built once, at registration
    — the sharded kernels take them as replicated shard_map operands).
    """

    def __init__(self, keys: Sequence[bytes], put=None) -> None:
        import jax as _jax

        if put is None:
            put = _jax.device_put
        keys = [bytes(k) for k in keys]
        if not keys:
            raise ValueError("committee must have at least one key")
        self.keys = keys
        self.index: dict[bytes, int] = {}
        for i, k in enumerate(keys):
            self.index.setdefault(k, i)
        n = len(keys)
        ypx = np.zeros((16, f.NLIMB, n), np.float32)
        ymx = np.zeros_like(ypx)
        xy2d = np.zeros_like(ypx)
        valid = np.zeros(n, bool)
        keys_u8 = np.zeros((32, n), np.uint8)
        for i, kb in enumerate(keys):
            keys_u8[:, i] = np.frombuffer(kb, np.uint8)
            ypx[0, 0, i] = 1.0  # madd identity: (ypx, ymx, xy2d) = (1, 1, 0)
            ymx[0, 0, i] = 1.0
            pt = _decompress_int(kb)
            if pt is None:
                continue
            valid[i] = True
            x, y = pt
            neg = ((P - x) % P, y)
            cur = (0, 1)
            for k in range(1, 16):
                cur = _edwards_add_int(cur, neg)
                cx, cy = cur
                ypx[k, :, i] = f.limbs_of_int((cy + cx) % P)[:, 0]
                ymx[k, :, i] = f.limbs_of_int((cy - cx) % P)[:, 0]
                xy2d[k, :, i] = f.limbs_of_int(D2_INT * cx * cy % P)[:, 0]
        self.ta_ypx = put(ypx)
        self.ta_ymx = put(ymx)
        self.ta_xy2d = put(xy2d)
        self.valid = put(valid)
        self.keys_u8 = put(keys_u8)
        self.size = n


def _verify_kernel_w4_committee(
    ta_ypx, ta_ymx, ta_xy2d, valid, idx, r_enc, s_digits, h_digits
):
    """Committee variant of `_verify_kernel_w4`: lanes gather their -A
    window table from the device-resident committee precompute by validator
    index — no decompression, no `_build_neg_a_table`. Affine tables make
    the per-item adds mixed additions."""
    g_ypx = jnp.take(ta_ypx, idx, axis=2)
    g_ymx = jnp.take(ta_ymx, idx, axis=2)
    g_xy2d = jnp.take(ta_xy2d, idx, axis=2)
    b_ypx, b_ymx, b_xy2d = BASE_TABLE
    batch = idx.shape[0]
    dtype = r_enc.dtype

    def body(g, acc: Point) -> Point:
        row = NGROUPS - 1 - g
        for i in range(WINDOW):
            acc = point_dbl(acc, with_t=i == WINDOW - 1)
        sd = lax.dynamic_index_in_dim(s_digits, row, 0, keepdims=False)
        hd = lax.dynamic_index_in_dim(h_digits, row, 0, keepdims=False)
        s_oh = jax.nn.one_hot(sd.astype(jnp.int32), 16, axis=0, dtype=dtype)
        h_oh = jax.nn.one_hot(hd.astype(jnp.int32), 16, axis=0, dtype=dtype)
        acc = point_madd(
            acc,
            _lookup_shared(b_ypx, s_oh),
            _lookup_shared(b_ymx, s_oh),
            _lookup_shared(b_xy2d, s_oh),
        )
        acc = point_madd(
            acc,
            _lookup_per_item(g_ypx, h_oh),
            _lookup_per_item(g_ymx, h_oh),
            _lookup_per_item(g_xy2d, h_oh),
            with_t=False,
        )
        return acc

    result = lax.fori_loop(0, NGROUPS, body, point_identity(batch))
    enc = compress(result)
    return jnp.take(valid, idx) & jnp.all(enc == r_enc, axis=0)


def _verify_kernel_w4_committee_packed96(
    ta_ypx, ta_ymx, ta_xy2d, valid, idx, packed
):
    """(96, B) u8 wire rows (R, S, host-computed h) + (B,) i32 indices."""
    r_b, s_b, h_b = packed[0:32], packed[32:64], packed[64:96]
    return _verify_kernel_w4_committee(
        ta_ypx,
        ta_ymx,
        ta_xy2d,
        valid,
        idx,
        r_b.astype(jnp.float32),
        _device_nibbles(s_b),
        _device_nibbles(h_b),
    )


def _verify_kernel_w4_committee_packed96_dh(
    ta_ypx, ta_ymx, ta_xy2d, valid, keys_u8, idx, packed
):
    """Device-hash committee variant: rows 64-95 carry the 32-byte MESSAGE;
    the key bytes for h = SHA-512(R||A||M) are gathered on device from the
    committee-resident `keys_u8`, so the host ships neither keys nor h."""
    from . import sha512

    r_b, s_b, m_b = packed[0:32], packed[32:64], packed[64:96]
    a_b = jnp.take(keys_u8, idx, axis=1)
    return _verify_kernel_w4_committee(
        ta_ypx,
        ta_ymx,
        ta_xy2d,
        valid,
        idx,
        r_b.astype(jnp.float32),
        _device_nibbles(s_b),
        sha512.h_digits_on_device(r_b, a_b, m_b),
    )


# --- packed (u8) wire format ----------------------------------------------
#
# The f32 kernel arguments are 772 B/signature (a_y, r_enc 128 B each;
# s/h_digits 256 B each) — 6.3 MB at batch 8192, which dominates end-to-end
# time when host<->device bandwidth is scarce (e.g. a tunneled chip). The
# packed path ships the raw 32-byte u8 rows (a, R, s, h = 128 B/signature,
# a 6x reduction) and unpacks to limbs/digits on device (a handful of VPU
# byte ops, free next to the 253-step ladder).


def _device_nibbles(b: jnp.ndarray) -> jnp.ndarray:
    """(32, B) u8 -> (64, B) f32 of 4-bit little-endian digits (row 2k = low
    nibble of byte k), matching the host-side `_nibbles` layout."""
    lo = (b & 0x0F).astype(jnp.float32)
    hi = (b >> 4).astype(jnp.float32)
    return jnp.stack((lo, hi), axis=1).reshape(2 * b.shape[0], b.shape[1])


def _unpack_ars(a_bytes, r_bytes, s_bytes):
    """u8 (32, B) A/R/S wire rows -> (a_y, a_sign, r_enc, s_digits)."""
    top = a_bytes[31]
    a_y = a_bytes.astype(jnp.float32).at[31].set(
        (top & 0x7F).astype(jnp.float32)
    )
    a_sign = (top >> 7).astype(jnp.float32)
    r_enc = r_bytes.astype(jnp.float32)
    return a_y, a_sign, r_enc, _device_nibbles(s_bytes)


def unpack_packed_inputs(a_bytes, r_bytes, s_bytes, h_bytes):
    """u8 (32, B) wire arrays -> the standard f32 kernel arguments."""
    return *_unpack_ars(a_bytes, r_bytes, s_bytes), _device_nibbles(h_bytes)


def unpack_packed_inputs_dh(packed):
    """(128, B) device-hash wire array (rows 96-127 = 32-byte message) ->
    the standard f32 kernel arguments, with h = SHA-512(R||A||M) mod L
    computed on device (ops.sha512)."""
    from . import sha512

    a_b, r_b, s_b, m_b = split_packed128(packed)
    return *_unpack_ars(a_b, r_b, s_b), sha512.h_digits_on_device(
        r_b, a_b, m_b
    )


def _verify_kernel_w4_packed(a_bytes, r_bytes, s_bytes, h_bytes):
    return _verify_kernel_w4(*unpack_packed_inputs(a_bytes, r_bytes, s_bytes, h_bytes))


def split_packed128(packed: jnp.ndarray) -> tuple:
    """(128, B) u8 wire array -> (a, r, s, h) (32, B) row groups."""
    return packed[0:32], packed[32:64], packed[64:96], packed[96:128]


def _verify_kernel_w4_packed128(packed):
    return _verify_kernel_w4(*unpack_packed_inputs(*split_packed128(packed)))


def _verify_kernel_w4_packed128_dh(packed):
    """Device-hash variant: rows 96-127 carry the 32-byte MESSAGE instead
    of a host-computed h; the device computes h = SHA-512(R||A||M) mod L
    itself (ops.sha512), so host staging is reduced to byte concatenation.
    Only valid for 32-byte messages — the protocol's hot path (votes, QCs
    and payloads all sign digests; messages.py `Vote.digest`)."""
    return _verify_kernel_w4(*unpack_packed_inputs_dh(packed))


def decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray):
    """Compressed y (+ sign of x) -> affine (x, -x, y) + validity mask.

    Follows the ref10 recipe: x = u*v^3 * (u*v^7)^((p-5)/8) with
    u = y^2-1, v = d*y^2+1; multiply by sqrt(-1) when v*x^2 == -u; invalid
    when v*x^2 != +-u (no square root exists). Returns canonical x and p-x
    so the caller can pick either A or -A cheaply.
    """
    yy = f.sqr(y_limbs)
    u = f.sub(yy, f.ONE)
    v = f.add(f.mul(D, yy), f.ONE)
    v3 = f.mul(f.sqr(v), v)
    v7 = f.mul(f.sqr(v3), v)
    w = f.pow2523(f.mul(u, v7))
    r = f.mul(f.mul(u, v3), w)
    chk = f.canonical(f.mul(v, f.sqr(r)))
    u_c = f.canonical(u)
    negu_c = f.canonical(f.sub(f.ZERO, u))
    is_pos = f.eq_canonical(chk, u_c)
    is_neg = f.eq_canonical(chk, negu_c) & ~is_pos
    valid = is_pos | is_neg
    x = f.select(is_neg, f.mul(r, SQRTM1), r)
    x_c = f.canonical(x)
    xneg_c = f.canonical(f.sub(f.ZERO, x_c))
    flip = f.parity(x_c) != sign
    x_final = f.select(flip, xneg_c, x_c)
    xneg_final = f.select(flip, x_c, xneg_c)
    return x_final, xneg_final, valid


def compress(p: Point) -> jnp.ndarray:
    """Point -> canonical 32-limb encoding (y with sign bit of x in bit 255)."""
    zinv = f.invert(p[2])
    x_c = f.canonical(f.mul(p[0], zinv))
    y_c = f.canonical(f.mul(p[1], zinv))
    return y_c.at[f.NLIMB - 1].add(128.0 * f.parity(x_c))


def _verify_kernel(a_y, a_sign, r_enc, s_bits, h_bits):
    """(32,B) a_y, (B,) a_sign, (32,B) r_enc, (253,B) s/h bits -> (B,) bool.

    Computes enc([s]B + [h](-A)) and compares to the signature's R bytes;
    byte equality against a canonical re-encoding also enforces canonical R
    (the reference's verify_strict semantics, crypto/src/lib.rs:186-192).
    """
    x_a, xneg_a, valid = decompress(a_y, a_sign)
    # Affine precomp of -A = (p - x, y) for the ladder's mixed adds.
    na_ypx = f.add(a_y, xneg_a)
    na_ymx = f.add(a_y, x_a)
    na_xy2d = f.mul(D2, f.mul(xneg_a, a_y))

    batch = a_y.shape[1]

    def body(i, acc: Point) -> Point:
        acc = point_dbl(acc)
        bit = SCALAR_BITS - 1 - i
        sb = lax.dynamic_index_in_dim(s_bits, bit, 0, keepdims=False) > 0.5
        hb = lax.dynamic_index_in_dim(h_bits, bit, 0, keepdims=False) > 0.5
        with_b = point_madd(acc, BASE_YPX, BASE_YMX, BASE_XY2D)
        acc = _select_point(sb, with_b, acc)
        with_a = point_madd(acc, na_ypx, na_ymx, na_xy2d)
        return _select_point(hb, with_a, acc)

    result = lax.fori_loop(0, SCALAR_BITS, body, point_identity(batch))
    enc = compress(result)
    return valid & jnp.all(enc == r_enc, axis=0)


_verify_jit = jax.jit(_verify_kernel)
_verify_w4_jit = jax.jit(_verify_kernel_w4)
_verify_w4p_jit = jax.jit(_verify_kernel_w4_packed)
_verify_w4p128_jit = jax.jit(_verify_kernel_w4_packed128)
_verify_w4p128dh_jit = jax.jit(_verify_kernel_w4_packed128_dh)
_verify_w4c_jit = jax.jit(_verify_kernel_w4_committee)
_verify_w4c96_jit = jax.jit(_verify_kernel_w4_committee_packed96)
_verify_w4c96dh_jit = jax.jit(_verify_kernel_w4_committee_packed96_dh)


# ---------------------------------------------------------------------------
# Host glue: bytes -> limb/bit arrays, hashing, mod-L reduction, bucketing
# ---------------------------------------------------------------------------


def prepare_batch(
    messages: Sequence[bytes],
    keys: Sequence[bytes],
    signatures: Sequence[bytes],
    want_bits: bool = False,
    allow_native: bool = True,
) -> dict:
    """numpy staging of a batch. keys: 32-byte pks; signatures: 64 bytes.

    Dispatches to the C++ staging plane (crypto/native_staging) when built —
    the Python path below is the reference implementation and fallback.
    `want_bits` additionally materialises the (253, B) bit arrays used only
    by the legacy bit-ladder kernel.
    """
    if allow_native and not want_bits:
        from ..crypto import native_staging

        staged = native_staging.stage_batch(messages, keys, signatures)
        if staged is not None:
            return staged
    n = len(messages)
    a = np.frombuffer(b"".join(keys), np.uint8).reshape(n, 32)
    sig = np.frombuffer(b"".join(signatures), np.uint8).reshape(n, 64)
    r, s = sig[:, :32], sig[:, 32:]

    a_y = a.astype(np.float32).T.copy()
    a_y[31] = (a[:, 31] & 0x7F).astype(np.float32)
    a_sign = (a[:, 31] >> 7).astype(np.float32)
    r_enc = r.astype(np.float32).T.copy()

    s_ok, h_bytes = _stage_scalars(messages, a, r, s)

    staged = dict(
        a_y=a_y,
        a_sign=a_sign,
        r_enc=r_enc,
        s_digits=_nibbles(s),
        h_digits=_nibbles(h_bytes),
        s_ok=s_ok,
    )
    if want_bits:  # legacy bit-ladder kernel only
        sb = np.unpackbits(s, axis=1, bitorder="little").T[:SCALAR_BITS]
        hb = np.unpackbits(h_bytes, axis=1, bitorder="little").T[:SCALAR_BITS]
        staged["s_bits"] = sb.astype(np.float32)
        staged["h_bits"] = hb.astype(np.float32)
    return staged


def prepare_batch_packed(
    messages: Sequence[bytes],
    keys: Sequence[bytes],
    signatures: Sequence[bytes],
    allow_native: bool = True,
) -> dict:
    """Packed (wire-format) staging: dict(packed=(128, B) u8, s_ok=(B,) bool).

    Rows 0-31 = A, 32-63 = R, 64-95 = S, 96-127 = h (SHA-512(R||A||M) mod L).
    128 B/signature on the host->device link — 6x less than the f32 form of
    `prepare_batch`; the kernel unpacks on device (`split_packed128` +
    `unpack_packed_inputs`, a handful of VPU byte ops next to the ladder).
    """
    if allow_native:
        from ..crypto import native_staging

        staged = native_staging.stage_batch_packed(messages, keys, signatures)
        if staged is not None:
            return staged
    n = len(messages)
    a = np.frombuffer(b"".join(keys), np.uint8).reshape(n, 32)
    sig = np.frombuffer(b"".join(signatures), np.uint8).reshape(n, 64)
    r, s = sig[:, :32], sig[:, 32:]
    s_ok, h_bytes = _stage_scalars(messages, a, r, s)
    packed = np.ascontiguousarray(np.vstack([a.T, r.T, s.T, h_bytes.T]))
    return dict(packed=packed, s_ok=s_ok)


_L_BE = np.frombuffer(L_ORDER.to_bytes(32, "big"), np.uint8)


def _s_canonical_mask(s: np.ndarray) -> np.ndarray:
    """(B, 32) little-endian s rows -> (B,) bool s < L, vectorized (no
    per-item Python bigint loop)."""
    diff = s[:, ::-1].astype(np.int16) - _L_BE.astype(np.int16)
    nz = diff != 0
    first = nz.argmax(axis=1)
    return nz.any(axis=1) & (diff[np.arange(len(s)), first] < 0)


def prepare_batch_packed_dh(
    messages: Sequence[bytes],
    keys: Sequence[bytes],
    signatures: Sequence[bytes],
) -> dict:
    """Device-hash staging: dict(packed=(128, B) u8, s_ok=(B,) bool).

    Rows 0-31 = A, 32-63 = R, 64-95 = S, 96-127 = the 32-byte MESSAGE —
    h = SHA-512(R||A||M) mod L is computed ON DEVICE (ops.sha512), so the
    host does no per-item hashing at all: staging is numpy concatenation
    plus a vectorized s < L check. Requires every message to be exactly
    32 bytes (the protocol signs digests; `Ed25519TpuVerifier` falls back
    to `prepare_batch_packed` otherwise)."""
    n = len(messages)
    a = np.frombuffer(b"".join(keys), np.uint8).reshape(n, 32)
    sig = np.frombuffer(b"".join(signatures), np.uint8).reshape(n, 64)
    m = np.frombuffer(b"".join(messages), np.uint8).reshape(n, 32)
    r, s = sig[:, :32], sig[:, 32:]
    packed = np.ascontiguousarray(np.vstack([a.T, r.T, s.T, m.T]))
    return dict(packed=packed, s_ok=_s_canonical_mask(s))


def prepare_batch_committee(
    messages: Sequence[bytes],
    key_bytes: Sequence[bytes],
    indices: Sequence[int],
    signatures: Sequence[bytes],
) -> dict:
    """Committee host-hash staging: dict(packed=(96, B) u8, idx=(B,) i32,
    s_ok=(B,) bool). Rows 0-31 = R, 32-63 = S, 64-95 = h; `key_bytes` are
    the resolved committee key rows, needed only to compute h on host —
    they are NOT shipped to the device."""
    n = len(messages)
    sig = np.frombuffer(b"".join(signatures), np.uint8).reshape(n, 64)
    r, s = sig[:, :32], sig[:, 32:]
    a = np.frombuffer(b"".join(key_bytes), np.uint8).reshape(n, 32)
    s_ok, h_bytes = _stage_scalars(messages, a, r, s)
    packed = np.ascontiguousarray(np.vstack([r.T, s.T, h_bytes.T]))
    return dict(packed=packed, idx=np.asarray(indices, np.int32), s_ok=s_ok)


def prepare_batch_committee_dh(
    messages: Sequence[bytes],
    indices: Sequence[int],
    signatures: Sequence[bytes],
) -> dict:
    """Committee device-hash staging: dict(packed=(96, B) u8, idx, s_ok).

    Rows 64-95 carry the 32-byte MESSAGE; the device gathers the key bytes
    from the committee-resident table and hashes on device — host staging
    is byte concatenation plus the vectorized s < L check, and the wire
    cost drops to 96 B + 4 B index per signature (no key row at all)."""
    n = len(messages)
    sig = np.frombuffer(b"".join(signatures), np.uint8).reshape(n, 64)
    m = np.frombuffer(b"".join(messages), np.uint8).reshape(n, 32)
    r, s = sig[:, :32], sig[:, 32:]
    packed = np.ascontiguousarray(np.vstack([r.T, s.T, m.T]))
    return dict(
        packed=packed,
        idx=np.asarray(indices, np.int32),
        s_ok=_s_canonical_mask(s),
    )


def _stage_scalars(messages, a, r, s) -> tuple[np.ndarray, np.ndarray]:
    """Python staging of the per-item scalar work shared by both wire
    formats: the s<L canonicality mask and h = SHA-512(R||A||M) mod L."""
    n = len(messages)
    s_ok = np.empty(n, bool)
    h_bytes = np.empty((n, 32), np.uint8)
    for i in range(n):
        s_ok[i] = int.from_bytes(s[i].tobytes(), "little") < L_ORDER
        hd = hashlib.sha512(r[i].tobytes() + a[i].tobytes() + messages[i]).digest()
        h = int.from_bytes(hd, "little") % L_ORDER
        h_bytes[i] = np.frombuffer(h.to_bytes(32, "little"), np.uint8)
    return s_ok, h_bytes


def _nibbles(b: np.ndarray) -> np.ndarray:
    """(B, 32) u8 -> (64, B) f32 of 4-bit little-endian digits (row d has
    significance 16^d)."""
    n = b.shape[0]
    out = np.empty((n, 64), np.float32)
    out[:, 0::2] = b & 0x0F
    out[:, 1::2] = b >> 4
    return out.T.copy()


def _pad(arr: np.ndarray, width: int) -> np.ndarray:
    pad = width - arr.shape[-1]
    if pad == 0:
        return arr
    cfg = [(0, 0)] * (arr.ndim - 1) + [(0, pad)]
    return np.pad(arr, cfg)


def _upload_dispatch(fn, padded: np.ndarray, put=None, tlkey=None):
    """Runs on the pipeline's upload worker: ship one packed chunk,
    dispatch the kernel (async), return the device mask handle. `put`
    overrides the host->device transfer (the mesh verifier shards the
    batch axis here, so the jitted shard_map never reshards a device-0
    array). `tlkey` is the chunk's (batch, chunk, n) device-timeline key
    (ops/timeline.py), None when timeline recording is disabled.

    Measured on a tunneled chip: issuing device_put from the main thread
    serializes transfers with kernel execution (one RPC stream), while a
    second thread overlaps them (~1.5x e2e). Each verifier's
    DispatchPipeline has ONE upload worker, keeping chunk order (FIFO
    executor queue) and avoiding parallel-transfer RPC contention WITHIN
    a verifier — but the serialization is per-pipeline now, not
    process-global: cross-chip work stealing (§5.5i) deliberately runs
    sibling backends' uploads in parallel, on the assumption that
    distinct chips ride distinct links/RPC streams. Steal targets
    sharing ONE tunneled stream will contend; measure before enabling
    stealing on a shared tunnel."""
    import jax as _jax

    up_span = timeline.span_for("upload", tlkey)
    di_span = timeline.span_for("dispatch", tlkey)
    with metrics.span(_M_UPLOAD), up_span:
        dev = (put or _jax.device_put)(padded)
    with metrics.span(_M_DISPATCH), di_span:
        return fn(dev)


class Ed25519TpuVerifier:
    """Bucketed, pipelined dispatcher for the jitted kernel.

    Batches are padded up to power-of-two lane widths (>= 128 so the lane
    dimension is full) to bound the number of XLA compilations; oversize
    batches are split at `chunk` and ride an owned `DispatchPipeline`
    (ops/pipeline.py): each chunk ships as a packed (128, W) u8 wire array
    (`prepare_batch_packed`) packed into a REUSED staging buffer, uploaded
    + dispatched from the pipeline's FIFO upload worker while the NEXT
    chunk stages, and its mask is fetched on the streaming readback worker
    while the next chunk dispatches — a bounded window of `pipeline_depth`
    chunks (default 2 = double buffering) is in flight between staging and
    readback. `pipeline_depth=1` is the serial/inline mode: no worker
    threads, deterministic order (the chaos rule, COMPONENTS.md §5.5i).

    `packed=False` restores the f32 argument path (used by the sharded
    mesh verifier and the legacy bit-ladder kernel).
    """

    # Committee-resident fast path (set_committee /
    # verify_batch_mask_committee). The mesh subclass inherits it with
    # shard_map-wrapped kernels and per-chip replicated tables; verifier
    # types with genuinely no committee path set this False.
    supports_committee = True

    def __init__(
        self,
        min_bucket: int = 128,
        max_bucket: int = 8192,
        kernel: str = "w4",
        packed: bool | None = None,
        chunk: int | None = None,
        pipeline_depth: int | None = None,
    ):
        self.kernel = kernel
        if kernel == "pallas":
            # the pallas grid tiles the batch in BLOCK-lane programs
            from .pallas_ladder import BLOCK

            min_bucket = -(-max(min_bucket, BLOCK) // BLOCK) * BLOCK
            max_bucket = max(BLOCK, max_bucket // BLOCK * BLOCK)
        self.min_bucket = min_bucket
        self.max_bucket = max_bucket
        self.packed = packed if packed is not None else kernel != "bits"
        self.chunk = min(chunk or 4096, max_bucket)
        # The owned dispatch pipeline (ops/pipeline.py): bounded in-flight
        # window, pooled staging buffers, streamed per-chunk readback.
        # Lazy threads — constructing a verifier spawns nothing; close()
        # (or GC, or atexit) reaps whatever a run created.
        self.pipeline = DispatchPipeline(
            depth=pipeline_depth, name=f"ed25519-{kernel}"
        )
        self._put = None  # optional device_put override (mesh sharding)
        # Deferred readback (multi-process mesh, parallel/mesh.py): the
        # per-chunk readback returns the raw device handle and the chunk
        # loop materializes ALL handles in one end-of-batch
        # `_materialize` call — a single allgather instead of one
        # collective per chunk, the pre-pipeline multihost shape. Stage
        # then pads into FRESH buffers (jax keeps the host array alive
        # through the async transfer) because nothing blocks per chunk to
        # mark a pooled buffer reusable.
        self._defer_readback = False
        # Device-hash health latch: if the SHA-512/mod-L kernel ever fails
        # at runtime (an unexpected backend lowering gap would otherwise
        # take down every verification), fall back to host hashing for the
        # life of this verifier.
        self._device_hash_ok = True
        # Device-resident committee precompute (set_committee). The
        # committee path always rides the w4 jnp kernel: the pallas ladder
        # has no committee variant yet, and skipping decompress + table
        # build dominates the flavour difference at committee batch sizes.
        self._committee: CommitteeTable | None = None

    # -- committee-resident fast path ---------------------------------------

    @property
    def committee(self) -> "CommitteeTable | None":
        return self._committee

    def set_committee(self, keys: Sequence[bytes]) -> CommitteeTable:
        """Install (or rebuild) the device-resident committee table.

        An identical key sequence is a no-op (same table object); a changed
        key set INVALIDATES the previous table and rebuilds — the
        reconfiguration contract. Returns the active table."""
        if not self.supports_committee:
            raise NotImplementedError(
                f"{type(self).__name__} has no committee-resident path"
            )
        keys = [bytes(k) for k in keys]
        if self._committee is not None and self._committee.keys == keys:
            return self._committee
        self._committee = self._build_committee_table(keys)
        _M_COMMITTEE_REGS.inc()
        _M_COMMITTEE_SIZE.set(self._committee.size)
        return self._committee

    def _build_committee_table(self, keys: Sequence[bytes]) -> CommitteeTable:
        """Placement hook: the mesh verifier overrides this to push one
        replicated copy of the tables to every device in its mesh."""
        return CommitteeTable(keys)

    def verify_batch_mask_committee(
        self,
        messages: Sequence[bytes],
        indices: Sequence[int],
        signatures: Sequence[bytes],
        table: "CommitteeTable | None" = None,
    ) -> np.ndarray:
        """Committee fast path: items carry validator INDICES into the
        registered table — steady-state batches perform zero on-device
        decompressions or window-table builds.

        `table` pins the CommitteeTable the indices were resolved against:
        a concurrent re-registration (epoch reconfiguration) must not swap
        the table under an in-flight batch, or lanes would gather another
        validator's precompute. Defaults to the currently registered one.
        """
        ct = table or self._committee
        if ct is None:
            raise RuntimeError(
                "no committee registered (call set_committee first)"
            )
        n = len(messages)
        if n == 0:
            return np.empty(0, bool)
        _M_BATCHES.inc()
        _M_SIGS.inc(n)
        _M_BATCH_SIZE.record(n)
        _M_COMMITTEE_BATCHES.inc()
        _M_COMMITTEE_SIGS.inc(n)
        with metrics.span(_M_E2E):
            device_hash = self._device_hash_ok and all(
                len(m) == 32 for m in messages
            )
            try:
                return self._run_committee(
                    ct, messages, list(indices), signatures, device_hash
                )
            except Exception:
                if not device_hash:
                    raise
                log.exception(
                    "committee device-hash kernel failed; retrying with "
                    "host hashing"
                )
                _M_DH_FALLBACKS.inc()
                out = self._run_committee(
                    ct, messages, list(indices), signatures, False
                )
                self._device_hash_ok = False
                return out

    def _run_committee(self, ct, messages, indices, signatures, device_hash: bool):
        n = len(messages)
        tl_on = timeline.enabled()
        tl_batch = timeline.TIMELINE.next_batch() if tl_on else 0
        pool = self.pipeline.pool
        defer = self._defer_readback
        tasks, oks = [], []

        def make_task(ci: int, lo: int, hi: int) -> ChunkTask:
            tlkey = (tl_batch, ci, hi - lo) if tl_on else None
            release: list = []

            def stage():
                _M_CHUNKS.inc()
                idx_chunk = indices[lo:hi]
                with metrics.span(_M_STAGE):
                    if device_hash:
                        staged = prepare_batch_committee_dh(
                            messages[lo:hi], idx_chunk, signatures[lo:hi]
                        )
                    else:
                        staged = prepare_batch_committee(
                            messages[lo:hi],
                            [ct.keys[i] for i in idx_chunk],
                            idx_chunk,
                            signatures[lo:hi],
                        )
                width = self._bucket(hi - lo)
                _M_PAD_LANES.inc(width - (hi - lo))
                oks.append((lo, hi, staged["s_ok"]))
                if defer:
                    # Deferred readback never blocks per chunk, so no
                    # point marks a pooled buffer reusable — fresh
                    # buffers, jax holds them through the async upload.
                    return _pad(staged["packed"], width), _pad(staged["idx"], width)
                packed = pool.pad(staged["packed"], width)
                idx = pool.pad(staged["idx"], width)
                release.extend((packed, idx))
                return packed, idx

            def submit(payload):
                packed, idx = payload
                # `ct` stays PINNED through the closure — a concurrent
                # epoch re-registration cannot swap tables under this
                # in-flight chunk (the §5.5c contract).
                return self._upload_dispatch_committee(
                    ct, packed, idx, device_hash, tlkey
                )

            def readback(handle):
                if defer:
                    return handle
                with metrics.span(_M_READBACK):
                    return self._materialize([handle])

            return ChunkTask(
                stage=stage, submit=submit, readback=readback, tlkey=tlkey,
                release=release,
            )

        for ci, lo in enumerate(range(0, n, self.chunk)):
            tasks.append(make_task(ci, lo, min(lo + self.chunk, n)))
        hosts = self.pipeline.run(tasks)
        if defer:
            hosts = self._materialize_deferred(hosts, n)
        out = np.empty(n, bool)
        for (lo, hi, ok), host in zip(oks, hosts):
            out[lo:hi] = host[: hi - lo] & ok
        return out

    def _upload_dispatch_committee(
        self, ct, packed: np.ndarray, idx: np.ndarray, device_hash: bool,
        tlkey=None,
    ):
        """Uploader-thread leg of the committee path: ship the (96, W) wire
        array + (W,) index vector, dispatch against the RESIDENT tables of
        `ct` (pinned by the caller — never re-read from self, a concurrent
        re-registration must not swap tables under in-flight chunks)."""
        import jax as _jax

        put = self._put or _jax.device_put
        up_span = timeline.span_for("upload", tlkey)
        di_span = timeline.span_for("dispatch", tlkey)
        with metrics.span(_M_UPLOAD), up_span:
            dev_p = put(packed)
            dev_i = put(idx)
        with metrics.span(_M_DISPATCH), di_span:
            if device_hash:
                return _verify_w4c96dh_jit(
                    ct.ta_ypx,
                    ct.ta_ymx,
                    ct.ta_xy2d,
                    ct.valid,
                    ct.keys_u8,
                    dev_i,
                    dev_p,
                )
            return _verify_w4c96_jit(
                ct.ta_ypx, ct.ta_ymx, ct.ta_xy2d, ct.valid, dev_i, dev_p
            )

    def close(self) -> None:
        """Drain the owned dispatch pipeline's worker threads. Safe to
        call more than once; a closed verifier keeps working (every
        subsequent batch runs the serial inline path). Un-closed
        verifiers are reaped by GC/atexit — tests may construct and drop
        verifiers freely without leaking threads."""
        self.pipeline.close()

    def _bucket(self, n: int) -> int:
        b = self.min_bucket
        while b < n:
            b *= 2
        return min(b, self.max_bucket)

    def _packed_fn(self):
        if self.kernel == "pallas":
            from . import pallas_ladder

            return pallas_ladder._verify_pallas_p128_jit
        return _verify_w4p128_jit

    def _packed_dh_fn(self):
        if self.kernel == "pallas":
            from . import pallas_ladder

            return pallas_ladder._verify_pallas_p128dh_jit
        return _verify_w4p128dh_jit

    def verify_batch_mask(
        self,
        messages: Sequence[bytes],
        keys: Sequence[bytes],
        signatures: Sequence[bytes],
    ) -> np.ndarray:
        n = len(messages)
        if n == 0:
            return np.empty(0, bool)
        _M_BATCHES.inc()
        _M_SIGS.inc(n)
        _M_BATCH_SIZE.record(n)
        with metrics.span(_M_E2E):
            return self._verify_batch_mask(messages, keys, signatures)

    def _verify_batch_mask(self, messages, keys, signatures) -> np.ndarray:
        n = len(messages)
        if not self.packed:
            out = np.empty(n, bool)
            for lo in range(0, n, self.max_bucket):
                hi = min(lo + self.max_bucket, n)
                out[lo:hi] = self._run_chunk(
                    messages[lo:hi], keys[lo:hi], signatures[lo:hi]
                )
            return out
        # Device-hash fast path: when every message is a 32-byte digest
        # (the protocol hot path), h is computed on device and host
        # staging is pure byte concatenation.
        device_hash = self._device_hash_ok and all(
            len(m) == 32 for m in messages
        )
        try:
            return self._run_packed(messages, keys, signatures, device_hash)
        except Exception:
            if not device_hash:
                raise
            # An unexpected backend failure in the SHA-512/mod-L kernel
            # must not take down verification: redo the batch with
            # host-side hashing. Latch the fast path off ONLY if the host
            # path succeeds where device-hash failed (a deterministic
            # kernel problem) — a transient device outage makes the retry
            # raise too, and the latch stays untouched for recovery.
            log.exception(
                "device-hash kernel failed; retrying with host hashing"
            )
            _M_DH_FALLBACKS.inc()
            out = self._run_packed(messages, keys, signatures, False)
            self._device_hash_ok = False
            return out

    def _run_packed(self, messages, keys, signatures, device_hash: bool):
        n = len(messages)
        fn = self._packed_dh_fn() if device_hash else self._packed_fn()
        stage_fn = prepare_batch_packed_dh if device_hash else prepare_batch_packed
        tl_on = timeline.enabled()
        tl_batch = timeline.TIMELINE.next_batch() if tl_on else 0
        pool = self.pipeline.pool
        defer = self._defer_readback
        tasks, oks = [], []

        def make_task(ci: int, lo: int, hi: int) -> ChunkTask:
            tlkey = (tl_batch, ci, hi - lo) if tl_on else None
            release: list = []

            def stage():
                _M_CHUNKS.inc()
                # The generic kernel decompresses every lane's key and
                # rebuilds its -A window table on device — the per-batch
                # cost the committee path amortizes away.
                _M_TABLE_BUILDS.inc()
                _M_DECOMPRESSIONS.inc(hi - lo)
                with metrics.span(_M_STAGE):
                    staged = stage_fn(
                        messages[lo:hi], keys[lo:hi], signatures[lo:hi]
                    )
                width = self._bucket(hi - lo)
                _M_PAD_LANES.inc(width - (hi - lo))
                oks.append((lo, hi, staged["s_ok"]))
                if defer:
                    # Deferred readback never blocks per chunk, so no
                    # point marks a pooled buffer reusable — fresh
                    # buffers, jax holds them through the async upload.
                    return _pad(staged["packed"], width)
                packed = pool.pad(staged["packed"], width)
                release.append(packed)
                return packed

            def submit(packed):
                return _upload_dispatch(fn, packed, self._put, tlkey)

            def readback(handle):
                if defer:
                    return handle
                with metrics.span(_M_READBACK):
                    return self._materialize([handle])

            return ChunkTask(
                stage=stage, submit=submit, readback=readback, tlkey=tlkey,
                release=release,
            )

        for ci, lo in enumerate(range(0, n, self.chunk)):
            tasks.append(make_task(ci, lo, min(lo + self.chunk, n)))
        hosts = self.pipeline.run(tasks)
        if defer:
            hosts = self._materialize_deferred(hosts, n)
        out = np.empty(n, bool)
        for (lo, hi, ok), host in zip(oks, hosts):
            out[lo:hi] = host[: hi - lo] & ok
        return out

    def _materialize(self, masks) -> np.ndarray:
        """Device mask handles -> one host bool array (overridden by the
        mesh verifier: a multi-process mesh needs an allgather first)."""
        if len(masks) == 1:
            return np.asarray(masks[0])
        return np.asarray(jnp.concatenate(masks))

    def _materialize_deferred(self, handles: list, n: int) -> list:
        """Deferred-readback tail (`_defer_readback`, multi-process
        mesh): ONE `_materialize` over every chunk's device handle — a
        single end-of-batch allgather, the pre-pipeline multihost shape
        ('per-transfer latency is paid once, not per chunk') — split
        back into per-chunk host arrays on the deterministic bucket
        widths."""
        with metrics.span(_M_READBACK):
            full = self._materialize(handles)
        out, off = [], 0
        for lo in range(0, n, self.chunk):
            width = self._bucket(min(lo + self.chunk, n) - lo)
            out.append(full[off:off + width])
            off += width
        return out

    def _run_chunk(self, messages, keys, signatures) -> np.ndarray:
        n = len(messages)
        _M_CHUNKS.inc()
        _M_TABLE_BUILDS.inc()
        _M_DECOMPRESSIONS.inc(n)
        # Legacy f32 path: no separate upload leg (args device_put inside
        # the jit call), so the timeline records stage/dispatch/readback
        # and the overlap-headroom pairing has nothing to pair — headroom
        # honestly reads 0 for a path with no pipelined transfer.
        tl_on = timeline.enabled()
        tlkey = (timeline.TIMELINE.next_batch(), 0, n) if tl_on else None
        st_span = timeline.span_for("stage", tlkey)
        with metrics.span(_M_STAGE), st_span:
            staged = prepare_batch(
                messages, keys, signatures, want_bits=self.kernel == "bits"
            )
        width = self._bucket(n)
        _M_PAD_LANES.inc(width - n)
        di_span = timeline.span_for("dispatch", tlkey)
        with di_span:
            mask = _verify_jit_args(staged, width, self.kernel)
        rb_span = timeline.span_for("readback", tlkey)
        with metrics.span(_M_READBACK), rb_span:
            host = np.asarray(mask)
        return host[:n] & staged["s_ok"]


def kernel_args(staged: dict, width: int, kernel: str = "w4") -> tuple:
    """Padded device-call args for the chosen kernel flavour."""
    scalar_keys = (
        ("s_bits", "h_bits")
        if kernel == "bits"
        else ("s_digits", "h_digits")  # w4 and pallas take 4-bit digits
    )
    return tuple(
        _pad(staged[k], width)
        for k in ("a_y", "a_sign", "r_enc", *scalar_keys)
    )


def _verify_jit_args(staged: dict, width: int, kernel: str):
    if kernel == "pallas":
        from . import pallas_ladder

        return pallas_ladder._verify_pallas_jit(
            *kernel_args(staged, width, "w4")
        )
    fn = _verify_w4_jit if kernel == "w4" else _verify_jit
    return fn(*kernel_args(staged, width, kernel))
