"""Device-occupancy timeline: per-chunk (stage, upload, dispatch, readback)
intervals for host<->device gap attribution.

ROADMAP item 1 claims the committed 9.7x-device vs 4.5x-e2e gap is
host<->device staging, not kernel math — but the stage histograms in
utils/metrics.py are AGGREGATES: they can say "upload cost X ms total",
not "how much of chunk N+1's upload could have hidden under chunk N's
dispatch". This module records every pipeline phase of the
Ed25519TpuVerifier chunk loop as an INTERVAL on one monotonic timeline,
so the three numbers the next perf session needs are measured, not
asserted:

  * **occupancy** — the fraction of the recorded span in which the
    device-facing pipeline (upload / dispatch / readback) was busy; the
    complement is host-only time the device sat idle.
  * **idle-gap distribution** — the gaps between consecutive busy
    segments (count / total / p50 / max): how the idle time is shaped
    (many small bubbles pipeline away; one big bubble is a serialization
    point).
  * **overlap headroom** — for consecutive chunks of one batch, the
    fraction of chunk-N+1 upload time that fits under chunk-N dispatch:
    sum(min(upload_dur(N+1), dispatch_dur(N))) / sum(upload_dur). This
    is the number ROADMAP item 1's async double-buffering claim must be
    judged against — a headroom near 1.0 means a double-buffered
    dispatch path can hide nearly the whole transfer cost; near 0.0
    means the transfer is not hideable and the win must come from
    shrinking it. (Conservative by construction: dispatch intervals time
    the async issue, so queued device compute behind the issue only adds
    hideable room this metric does not count.)

Recording is a ring-bounded deque append (oldest evicted), gated on
`HOTSTUFF_TIMELINE=0` exactly like the metrics/tracing flags; timestamps
are `time.monotonic()` and dumps carry the flight recorder's (mono, wall)
anchor pair so `tools/trace_report.py` can align device-timeline rows
beside the six-stage block rows.

Dependency-free by design: stdlib + utils.metrics/tracing only — no jax
(the graftlint tool and the chaos/telemetry planes import this
module on hosts with no accelerator stack at all).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from ..utils import metrics, tracing

__all__ = [
    "PHASES",
    "DEVICE_PHASES",
    "DeviceTimeline",
    "TIMELINE",
    "enabled",
    "enable",
    "span",
    "span_for",
    "NULL",
    "summary",
    "dump",
    "write_json",
    "reset",
]

# The four pipeline phases of one verifier chunk, in pipeline order.
# `stage` is host CPU (numpy/C++ wire-format staging); the other three
# face the device and define occupancy.
PHASES: tuple[str, ...] = ("stage", "upload", "dispatch", "readback")
DEVICE_PHASES: frozenset[str] = frozenset({"upload", "dispatch", "readback"})

_M_INTERVALS = metrics.counter("timeline.intervals")
_M_DROPPED = metrics.counter("timeline.dropped")

_enabled = os.environ.get("HOTSTUFF_TIMELINE", "1") != "0"


def enabled() -> bool:
    return _enabled


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


class DeviceTimeline:
    """Ring of (batch, chunk, phase, t0, t1, n) intervals.

    `batch` numbers one verify_batch_mask[_committee] call; `chunk` is the
    chunk's index within its batch (the uploader is a 1-worker FIFO, so
    chunk order IS dispatch order). Appends are deque-atomic under the
    GIL — the staging thread and the uploader thread both record."""

    def __init__(self, capacity: int | None = None) -> None:
        if capacity is None:
            try:
                capacity = int(os.environ.get("HOTSTUFF_TIMELINE_RING", "4096"))
            except ValueError:
                capacity = 4096
        self.capacity = max(16, capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._count = 0
        self._batch_seq = 0
        self._lock = threading.Lock()

    def next_batch(self) -> int:
        with self._lock:
            self._batch_seq += 1
            return self._batch_seq

    def note(
        self, batch: int, chunk: int, phase: str, t0: float, t1: float, n: int = 0
    ) -> None:
        if not _enabled:
            return
        with self._lock:
            self._count += 1
        _M_INTERVALS.inc()
        if self._count > self.capacity:
            _M_DROPPED.inc()
        self._ring.append((batch, chunk, phase, t0, t1, n))

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def dropped(self) -> int:
        return max(0, self._count - self.capacity)

    def intervals(self) -> list[dict]:
        return [
            {
                "batch": b,
                "chunk": c,
                "phase": p,
                "t0": round(t0, 6),
                "t1": round(t1, 6),
                "n": n,
            }
            for b, c, p, t0, t1, n in list(self._ring)
        ]

    # -- derived numbers -----------------------------------------------------

    def summary(self) -> dict:
        """Occupancy / idle-gap / overlap-headroom over the whole ring.

        All fields derive from ONE ring snapshot. Empty ring -> zeros (the
        shape is stable so BENCH json and dashboards never KeyError)."""
        iv = list(self._ring)
        out = {
            "batches": 0,
            "chunks": 0,
            "span_s": 0.0,
            "occupancy": 0.0,
            "overlap_headroom": 0.0,
            "phase_s": {p: 0.0 for p in PHASES},
            "idle": {"count": 0, "total_s": 0.0, "p50_s": 0.0, "max_s": 0.0},
        }
        if not iv:
            return out
        t_lo = min(t0 for _b, _c, _p, t0, _t1, _n in iv)
        t_hi = max(t1 for _b, _c, _p, _t0, t1, _n in iv)
        phase_s = {p: 0.0 for p in PHASES}
        busy: list[tuple[float, float]] = []
        chunks = set()
        batches = set()
        upload_dur: dict[tuple[int, int], float] = {}
        dispatch_dur: dict[tuple[int, int], float] = {}
        for b, c, p, t0, t1, n in iv:
            dur = max(0.0, t1 - t0)
            phase_s[p] = phase_s.get(p, 0.0) + dur
            chunks.add((b, c))
            batches.add(b)
            if p in DEVICE_PHASES:
                busy.append((t0, t1))
            if p == "upload":
                upload_dur[(b, c)] = upload_dur.get((b, c), 0.0) + dur
            elif p == "dispatch":
                dispatch_dur[(b, c)] = dispatch_dur.get((b, c), 0.0) + dur
        # merge the device-busy segments into a union, then read occupancy
        # and the idle gaps off the merged cover
        busy.sort()
        merged: list[list[float]] = []
        for t0, t1 in busy:
            if merged and t0 <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], t1)
            else:
                merged.append([t0, t1])
        busy_s = sum(t1 - t0 for t0, t1 in merged)
        span_s = max(t_hi - t_lo, 1e-12)
        gaps = [
            merged[i + 1][0] - merged[i][1]
            for i in range(len(merged) - 1)
            if merged[i + 1][0] > merged[i][1]
        ]
        # overlap headroom: chunk N+1's upload vs chunk N's dispatch,
        # paired within one batch (see module docstring)
        total_upload = sum(upload_dur.values())
        hideable = sum(
            min(dur, dispatch_dur.get((b, c - 1), 0.0))
            for (b, c), dur in upload_dur.items()
            if c > 0
        )
        out.update(
            {
                "batches": len(batches),
                "chunks": len(chunks),
                # 6 decimals: bench.py --pipeline-ab compares serial vs
                # pipelined occupancy STRICTLY, and on fast hosts the gap
                # can live below 1e-4 (4-digit rounding would tie).
                "span_s": round(span_s, 6),
                "occupancy": round(busy_s / span_s, 6),
                "overlap_headroom": round(
                    hideable / total_upload if total_upload > 0 else 0.0, 6
                ),
                "phase_s": {p: round(s, 6) for p, s in phase_s.items()},
                "idle": {
                    "count": len(gaps),
                    "total_s": round(sum(gaps), 6),
                    "p50_s": round(metrics.percentile(gaps, 0.50), 6),
                    "max_s": round(max(gaps), 6) if gaps else 0.0,
                },
            }
        )
        return out

    def dump(self) -> dict:
        """Structured artifact; (mono, wall) anchor pair matches the flight
        recorder's convention so trace_report.py aligns both on one wall
        timeline."""
        return {
            "v": 1,
            "kind": "device_timeline",
            "node": tracing.NODE_LABEL.get(),
            "capacity": self.capacity,
            "recorded": self._count,
            "dropped": self.dropped,
            # graftlint: allow[determinism] dump-alignment stamp, mirrors the flight recorder's (mono, wall) anchor
            "anchor": {"mono": time.monotonic(), "wall": time.time()},
            "intervals": self.intervals(),
            "summary": self.summary(),
        }

    def write_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.dump(), f, indent=2, sort_keys=True)
            f.write("\n")

    def reset(self) -> None:
        self._ring.clear()
        self._count = 0


TIMELINE = DeviceTimeline()


class _Span:
    """Context manager recording one interval (monotonic enter/exit).

    `start` backdates the interval's opening edge to a moment the caller
    already observed (clamped to never sit in the future): the dispatch
    pipeline opens each `readback` span at dispatch completion, because
    the device has been computing since then even if the readback worker
    dequeued the chunk late.
    """

    __slots__ = ("_tl", "_batch", "_chunk", "_phase", "_n", "_t0", "_start")

    def __init__(
        self,
        tl: DeviceTimeline,
        phase: str,
        batch: int,
        chunk: int,
        n: int,
        start: float | None = None,
    ):
        self._tl = tl
        self._phase = phase
        self._batch = batch
        self._chunk = chunk
        self._n = n
        self._t0 = 0.0
        self._start = start

    def __enter__(self) -> "_Span":
        now = time.monotonic()
        self._t0 = now if self._start is None else min(self._start, now)
        return self

    def __exit__(self, *exc) -> None:
        self._tl.note(
            self._batch, self._chunk, self._phase, self._t0, time.monotonic(), self._n
        )


class _NullSpan:
    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NULL = _NullSpan()


def span(
    phase: str,
    batch: int,
    chunk: int,
    n: int = 0,
    timeline: DeviceTimeline | None = None,
    start: float | None = None,
):
    """`with timeline.span("upload", b, c, n): ...` — no-op when disabled."""
    if not _enabled:
        return NULL
    # `is None`, not truthiness: an EMPTY DeviceTimeline is falsy (__len__).
    return _Span(
        TIMELINE if timeline is None else timeline, phase, batch, chunk, n, start
    )


def span_for(phase: str, tlkey: tuple | None, start: float | None = None):
    """`span` over the chunk loops' optional (batch, chunk, n) key:
    NULL when the key is None (their "timeline off" sentinel). One
    guard here instead of one per call site — and `is None`, so a
    future falsy key shape cannot silently disable recording."""
    if tlkey is None:
        return NULL
    return span(phase, *tlkey, start=start)


def summary() -> dict:
    return TIMELINE.summary()


def dump() -> dict:
    return TIMELINE.dump()


def write_json(path: str) -> None:
    TIMELINE.write_json(path)


def reset() -> None:
    TIMELINE.reset()
