"""Double-buffered async dispatch pipeline for the verifier chunk loops.

ROADMAP item 1's committed gap (9.7x device vs 4.5x e2e) is host<->device
staging, and PR 8's DeviceTimeline measures exactly how much of it is
hideable: `overlap_headroom` = the fraction of chunk-N+1 upload time that
fits under chunk-N dispatch. This module is the machinery that actually
hides it. The previous shape — one module-global single-worker uploader
thread shared by every verifier, plus a one-shot end-of-batch readback —
overlapped staging with upload but (a) serialized ALL mask fetches after
the LAST dispatch, (b) allocated a fresh padded staging buffer per chunk,
and (c) leaked its executor for the life of the process.

`DispatchPipeline` replaces it with a bounded-depth in-flight window:

  * **depth** (default 2 = double buffering) bounds how many chunks may
    be between staging-start and readback-complete. Staging chunk k+depth
    blocks until chunk k's mask is on the host — backpressure, counted as
    `pipeline.stalls` / `pipeline.stall_s`.
  * **Staging-buffer pool.** Padded wire buffers are taken from a
    per-shape free list and returned once the chunk's READBACK settles
    (device_put's transfer is async — PJRT may read, or on CPU alias,
    the host bytes until the kernel's results are back), so packing
    chunk k+2 never allocates in steady state (`pipeline.buffer_reuse`
    vs `pipeline.buffer_allocs`).
  * **Streamed readback.** Each chunk's mask is fetched on a dedicated
    readback worker as soon as its dispatch handle exists, so the
    device->host fetch of chunk k overlaps the dispatch of chunk k+1
    instead of serializing after the last dispatch.
  * **FIFO order.** Both workers are single-threaded FIFO executors, so
    chunk upload order IS dispatch order IS readback order — the
    DeviceTimeline's `chunk` index stays meaningful and result order is
    task order.
  * **Owned, closeable workers.** Each pipeline owns its executors
    (created lazily on the first depth>1 run), `close()` shuts them
    down, a `weakref.finalize` reaps them when the owner is collected,
    and one atexit hook drains every live pipeline — repeated verifier
    construction in tests leaks nothing.
  * **depth=1 is the serial/inline mode**: stage, upload, dispatch and
    readback run synchronously on the caller thread with NO worker
    threads at all — the deterministic degeneration the chaos
    virtual-time plane requires (COMPONENTS.md §5.5i), and the "serial"
    leg of `bench.py --pipeline-ab`.

The pipeline stamps the `stage` and `readback` phases of each task's
DeviceTimeline key; the task's `submit` callable owns the `upload` and
`dispatch` phases (the existing `_upload_dispatch` /
`_upload_dispatch_committee` seams, which the mesh verifier overrides).
`TIMELINE_STAGES` is the full vocabulary — the graftlint `pipeline`
pass asserts
it stays inside `timeline.PHASES` so trace_report.py's device rows keep
rendering.

Dependency-free by design: stdlib + numpy + utils.metrics + ops.timeline
only — no jax (tests/test_pipeline.py drives it with a paced fake
backend on jax-less hosts, like DeviceScheduler).
"""

from __future__ import annotations

import atexit
import os
import threading
import time
import weakref
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from ..utils import metrics
from . import timeline

__all__ = [
    "TIMELINE_STAGES",
    "ChunkTask",
    "StagingBufferPool",
    "DispatchPipeline",
    "default_depth",
    "close_all",
]

# Every DeviceTimeline phase a DispatchPipeline run can stamp (directly —
# stage/readback — or through its tasks' submit callables — upload/
# dispatch). The graftlint `pipeline` pass fails the build if this ever
# leaves timeline.PHASES: a renamed stage would silently fall out of the
# occupancy/headroom math and the trace_report device rows.
TIMELINE_STAGES: tuple[str, ...] = ("stage", "upload", "dispatch", "readback")

_M_CHUNKS = metrics.counter("pipeline.chunks")
_M_DEPTH = metrics.gauge("pipeline.depth")
_M_INFLIGHT = metrics.gauge("pipeline.inflight")
_M_STALLS = metrics.counter("pipeline.stalls")
_M_STALL_S = metrics.histogram("pipeline.stall_s")
_M_BUF_REUSE = metrics.counter("pipeline.buffer_reuse")
_M_BUF_ALLOC = metrics.counter("pipeline.buffer_allocs")


def default_depth() -> int:
    """Pipeline depth when the caller passes none: HOTSTUFF_PIPELINE_DEPTH
    (>=1), default 2 — stage the next chunk while one is on the device;
    deeper windows only add host-memory pressure for transfers the device
    cannot consume faster."""
    try:
        return max(1, int(os.environ.get("HOTSTUFF_PIPELINE_DEPTH", "2")))
    except ValueError:
        return 2


@dataclass(slots=True)
class ChunkTask:
    """One chunk's three pipeline legs.

    `stage`    — pack the chunk's wire bytes (caller thread; CPU-only).
    `submit`   — ship the staged payload and dispatch the kernel, returning
                 the async device handle (upload worker; must stamp the
                 `upload`/`dispatch` timeline phases itself — the
                 `_upload_dispatch*` seams already do).
    `readback` — resolve the handle to a host result (readback worker).
    `tlkey`    — the chunk's (batch, chunk, n) DeviceTimeline key, None
                 when recording is off; the pipeline stamps `stage` and
                 `readback` spans with it.
    `release`  — pooled staging buffers to return once the chunk has
                 fully settled (filled by `stage`, drained by the
                 pipeline after `readback` completes — not at
                 submit-return: the upload is asynchronous and may
                 still be reading the host bytes).
    """

    stage: Callable[[], Any]
    submit: Callable[[Any], Any]
    readback: Callable[[Any], Any]
    tlkey: tuple | None = None
    release: list = field(default_factory=list)


class StagingBufferPool:
    """Reusable host staging buffers, one free list per (shape, dtype).

    Every chunk of a batch pads to the same bucket width, so the padded
    wire arrays are identically shaped and a tiny per-shape free list
    gives steady-state zero-allocation staging (the "pinned buffer pool":
    numpy cannot page-pin, but reuse keeps the pages hot and the
    allocator out of the loop — the measurable cost on a tunneled link).
    Thread-safe: the caller thread takes, the readback worker gives back.
    """

    def __init__(self, max_per_shape: int = 4) -> None:
        self.max_per_shape = max(1, max_per_shape)
        self._free: dict[tuple, list[np.ndarray]] = {}
        self._lock = threading.Lock()

    def take(self, shape: tuple, dtype) -> np.ndarray:
        key = (tuple(shape), np.dtype(dtype).str)
        with self._lock:
            free = self._free.get(key)
            if free:
                _M_BUF_REUSE.inc()
                return free.pop()
        _M_BUF_ALLOC.inc()
        return np.empty(shape, dtype)

    def give(self, arr: np.ndarray) -> None:
        key = (arr.shape, arr.dtype.str)
        with self._lock:
            free = self._free.setdefault(key, [])
            if len(free) < self.max_per_shape:
                free.append(arr)

    def pad(self, arr: np.ndarray, width: int) -> np.ndarray:
        """`ed25519._pad` into a pooled buffer: the last axis grows to
        `width` with zeroed padding, no allocation on reuse. Always copies
        (even at zero pad) — the staged array is about to be handed to an
        async upload, and only pooled buffers have a defined give-back
        point."""
        shape = (*arr.shape[:-1], width)
        out = self.take(shape, arr.dtype)
        n = arr.shape[-1]
        out[..., :n] = arr
        if n < width:
            out[..., n:] = 0
        return out

    def sizes(self) -> dict[tuple, int]:
        """Free-list occupancy per shape (test/diagnostic hook)."""
        with self._lock:
            return {k: len(v) for k, v in self._free.items()}


# Live pipelines, reaped at interpreter exit: worker threads must never
# outlive the process teardown (a verifier constructed in a test and
# dropped without close() is also reaped per-instance by weakref.finalize
# as soon as it is collected).
_LIVE: "weakref.WeakSet[DispatchPipeline]" = weakref.WeakSet()


def close_all() -> None:
    """Drain every live pipeline's workers (atexit hook; also callable
    from SIGTERM paths — `node run` and bench exit through atexit)."""
    for p in list(_LIVE):
        p.close(wait=False)


atexit.register(close_all)


def _drain(execs: dict) -> None:
    """Finalizer body: owns only the executor dict, never the pipeline
    (a bound method would keep the pipeline alive forever)."""
    for ex in list(execs.values()):
        ex.shutdown(wait=False, cancel_futures=True)
    execs.clear()


class DispatchPipeline:
    """Bounded-depth upload/dispatch/readback window over FIFO workers.

    `run(tasks)` executes each `ChunkTask`'s stage on the calling thread,
    its submit on the single upload worker, and its readback on the
    single readback worker, holding at most `depth` chunks between
    staging-start and readback-complete. Results return in task order.
    Exceptions propagate to the caller after every submitted leg has
    settled (no orphaned jobs keep pooled buffers or device handles).
    """

    def __init__(
        self,
        depth: int | None = None,
        name: str = "verify",
        pool: StagingBufferPool | None = None,
        tl: "timeline.DeviceTimeline | None" = None,
    ) -> None:
        self.depth = max(1, depth if depth is not None else default_depth())
        self.name = name
        # depth+1 buffers per shape: `depth` chunks in flight (each holds
        # its buffers until readback settles) plus the one being packed.
        self.pool = pool or StagingBufferPool(max_per_shape=self.depth + 1)
        self._tl = tl  # None -> the process-global timeline (span_for)
        self._execs: dict[str, ThreadPoolExecutor] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._inflight = 0
        self.stats = {"chunks": 0, "stalls": 0}
        self._finalizer = weakref.finalize(self, _drain, self._execs)
        _LIVE.add(self)

    # -- lifecycle -----------------------------------------------------------

    @property
    def inflight(self) -> int:
        """Chunks currently between staging-start and readback-complete."""
        return self._inflight

    def set_depth(self, depth: int) -> None:
        """Clamp the in-flight window after construction (the
        multi-process mesh forces 1 — parallel/mesh.py)."""
        self.depth = max(1, int(depth))

    def close(self, wait: bool = True) -> None:
        """Shut the owned workers down. Idempotent; a closed pipeline
        still runs — every subsequent run degrades to the serial inline
        path, so late callers never touch dead executors."""
        with self._lock:
            self._closed = True
            execs, to_stop = self._execs, list(self._execs.values())
            execs.clear()
        for ex in to_stop:
            ex.shutdown(wait=wait, cancel_futures=not wait)

    def _executor(self, kind: str) -> ThreadPoolExecutor:
        ex = self._execs.get(kind)
        if ex is None:
            with self._lock:
                ex = self._execs.get(kind)
                if ex is None:
                    ex = ThreadPoolExecutor(
                        1, thread_name_prefix=f"pipe-{kind}-{self.name}"
                    )
                    self._execs[kind] = ex
        return ex

    # -- timeline spans ------------------------------------------------------

    def _span(self, phase: str, tlkey: tuple | None, start: float | None = None):
        if tlkey is None:
            return timeline.NULL
        if self._tl is not None:
            return timeline.span(phase, *tlkey, timeline=self._tl, start=start)
        return timeline.span_for(phase, tlkey, start=start)

    # -- execution -----------------------------------------------------------

    def _staged(self, task: ChunkTask):
        self.stats["chunks"] += 1
        _M_CHUNKS.inc()
        with self._span("stage", task.tlkey):
            return task.stage()

    def _submitted(self, task: ChunkTask, payload):
        return task.submit(payload), time.monotonic()

    def _release_buffers(self, task: ChunkTask) -> None:
        """Hand the chunk's pooled staging buffers back — only once the
        chunk's READBACK has settled. jax.device_put does NOT promise a
        synchronous copy (PJRT may keep reading the host bytes until the
        transfer lands, and the CPU backend can zero-copy alias an
        aligned array outright), so releasing at submit-return would let
        the next chunk's packing overwrite wire bytes still in flight.
        A mask on the host proves the inputs were consumed."""
        while task.release:
            self.pool.give(task.release.pop())

    def _read(self, task: ChunkTask, handle_fut: "Future") -> Any:
        try:
            handle, dispatched_t = handle_fut.result()
            # The readback span opens at dispatch completion: the device
            # has been computing since the dispatch returned its async
            # handle, so the readback worker's dequeue latency
            # (GIL/scheduler) is not device idle — without the backdate,
            # every worker handoff shows up as an idle gap that cancels
            # exactly the occupancy the overlap bought.
            with self._span("readback", task.tlkey, start=dispatched_t):
                return task.readback(handle)
        finally:
            self._release_buffers(task)

    def run(self, tasks) -> list:
        """Run every task through the window; returns readbacks in task
        order. depth=1 (or a closed pipeline) runs fully inline."""
        tasks = list(tasks)
        if not tasks:
            return []
        # Gauge semantics: the depth of the pipeline that ran MOST
        # RECENTLY (the gauge is process-global; several live pipelines
        # would otherwise report whichever was constructed last, active
        # or not).
        _M_DEPTH.set(self.depth)
        if self.depth <= 1 or self._closed:
            return [self._run_serial(t) for t in tasks]
        return self._run_windowed(tasks)

    def _run_serial(self, task: ChunkTask) -> Any:
        """The inline/serial leg: caller-thread stage -> submit ->
        readback, nothing overlapped — deterministic under the chaos
        virtual-time loop, and the baseline of bench.py --pipeline-ab."""
        try:
            payload = self._staged(task)
            handle, dispatched_t = self._submitted(task, payload)
            # Same backdate rule as the windowed path (fair A/B): the span
            # opens at dispatch completion — on this thread that is only
            # microseconds ago, so serial semantics are unchanged.
            with self._span("readback", task.tlkey, start=dispatched_t):
                return task.readback(handle)
        finally:
            self._release_buffers(task)

    def _run_windowed(self, tasks: list[ChunkTask]) -> list:
        up = self._executor("upload")
        rb = self._executor("readback")
        window = threading.Semaphore(self.depth)
        results: list[Future] = []

        def _release(_fut: Future) -> None:
            with self._lock:
                self._inflight -= 1
                _M_INFLIGHT.set(self._inflight)
            window.release()

        try:
            for task in tasks:
                if not window.acquire(blocking=False):
                    # Window full: the device is `depth` chunks behind the
                    # host. The stall is the backpressure working — count
                    # it so occupancy regressions have a host-side signal.
                    self.stats["stalls"] += 1
                    _M_STALLS.inc()
                    t0 = time.monotonic()
                    window.acquire()
                    _M_STALL_S.record(time.monotonic() - t0)
                with self._lock:
                    self._inflight += 1
                    _M_INFLIGHT.set(self._inflight)
                # The slot just taken has no future yet: until _release is
                # attached, a failing stage must free it (and the staged
                # buffers) itself.
                attached = False
                handle_fut = None
                try:
                    payload = self._staged(task)
                    handle_fut = up.submit(self._submitted, task, payload)
                    res_fut = rb.submit(self._read, task, handle_fut)
                    res_fut.add_done_callback(_release)
                    attached = True
                finally:
                    if not attached:
                        if handle_fut is not None:
                            # An upload may already be consuming the
                            # buffers — settle it before pooling them.
                            try:
                                handle_fut.result()
                            except BaseException:
                                pass
                        self._release_buffers(task)
                        _release(None)
                results.append(res_fut)
        except BaseException:
            # A failed stage must not strand earlier chunks: settle every
            # submitted future (their own errors surface via the first
            # .result() below or are superseded by this raise).
            for f in results:
                try:
                    f.result()
                except BaseException:
                    pass
            raise
        # Settle EVERY chunk before surfacing the first failure: a raise
        # mid-gather would leave later readbacks running against pooled
        # buffers the caller thinks are free.
        out, first_exc = [], None
        for f in results:
            try:
                out.append(f.result())
            except BaseException as e:  # noqa: BLE001 — re-raised below
                if first_exc is None:
                    first_exc = e
                out.append(None)
        if first_exc is not None:
            raise first_exc
        return out
