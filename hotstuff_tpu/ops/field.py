"""GF(2^255 - 19) arithmetic on TPU, batched over the lane dimension.

This is the bignum substrate for the TPU ed25519 batch-verification kernel
(the north-star offload of the reference's `Signature::verify_batch` /
`verify_batch_alt` hot path, crypto/src/lib.rs:194-220).

Design (TPU-first, not a port):
  * A field element batch is a `(32, B)` float32 array: 32 radix-256 limbs on
    the sublane axis, the batch on the lane axis (full 128-lane utilisation
    for B >= 128, tiled for larger B).
  * float32, not int32: every intermediate value is kept strictly below 2^24,
    where f32 arithmetic on integers is EXACT, and f32 multiply-add is the
    TPU VPU's fast path (TPU int32 multiplies lower to multi-op sequences).
    The radix/bound discipline below guarantees exactness:
      - "normalized" elements have limbs <= 294            (_carry32 output)
      - `add` is lazy (no carry): inputs <= 294 -> output <= 588
      - `mul` accepts limbs <= 700:  conv sum <= 32*700^2 = 15.7M < 2^24
      - `sub(a, b)` = a + BIAS16P - b with BIAS16P = 16p arranged so every
        limb >= 768 >= any subtrahend limb (<= 588); result is re-normalized
  * Multiplication is a 32-tap shifted multiply-accumulate (schoolbook
    convolution) over `(64, B)` vectors — static-slice updates that XLA fuses
    into VPU FMA chains; reduction folds limbs >= 32 via 2^256 = 38 (mod p).
  * No data-dependent control flow: carry chains are fixed-depth vectorized
    passes; the only sequential carries (exact canonicalisation) are
    `lax.fori_loop`s with O(32) trip counts, used once per verify.
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

P = 2**255 - 19
NLIMB = 32
RADIX = 256

# ---------------------------------------------------------------------------
# Host-side constant construction (Python ints -> limb arrays)
# ---------------------------------------------------------------------------


def limbs_of_int(x: int, n: int = NLIMB) -> np.ndarray:
    """Little-endian radix-256 limbs of a nonnegative int as (n, 1) f32."""
    assert 0 <= x < RADIX**n
    out = np.zeros((n, 1), np.float32)
    for i in range(n):
        out[i, 0] = (x >> (8 * i)) & 0xFF
    return out


def int_of_limbs(limbs: np.ndarray) -> list[int]:
    """Exact big-int value per batch column (for tests / host checks)."""
    arr = np.asarray(limbs, np.float64)
    return [
        sum(int(arr[i, b]) << (8 * i) for i in range(arr.shape[0]))
        for b in range(arr.shape[1])
    ]


def _make_bias(mult: int, lo: int) -> np.ndarray:
    """Limbs of mult*p with every limb in [lo, 2^13): per-limb lower bound
    lets `sub` stay nonnegative without borrows."""
    digits = [(mult * P >> (8 * i)) & 0xFF for i in range(NLIMB)]
    digits[NLIMB - 1] += 256 * (mult * P >> (8 * NLIMB))  # fold the overflow
    for i in range(NLIMB - 1):
        while digits[i] < lo:
            digits[i] += 256
            digits[i + 1] -= 1
    assert digits[NLIMB - 1] >= lo and all(0 <= d < 2**13 for d in digits)
    assert sum(d << (8 * i) for i, d in enumerate(digits)) == mult * P
    return np.array(digits, np.float32).reshape(NLIMB, 1)


BIAS16P = _make_bias(16, 768)  # per-limb >= 768 > 588 = max lazy-add limb
# In-trace construction of the bias (mostly-uniform limbs + a few specials
# via iota selects): Pallas kernels cannot capture array constants, and XLA
# constant-folds this outside Pallas, so both paths share one definition.
_BIAS_MID = float(np.bincount(BIAS16P[:, 0].astype(np.int64)).argmax())
_BIAS_SPECIAL = tuple(
    (i, float(BIAS16P[i, 0]))
    for i in range(NLIMB)
    if BIAS16P[i, 0] != _BIAS_MID
)


def bias_limbs() -> jnp.ndarray:
    """(NLIMB, 1) f32 limbs of 16p, built from scalars (Pallas-safe)."""
    i = lax.broadcasted_iota(jnp.int32, (NLIMB, 1), 0)
    out = jnp.full((NLIMB, 1), _BIAS_MID, jnp.float32)
    for idx, v in _BIAS_SPECIAL:
        out = jnp.where(i == idx, jnp.float32(v), out)
    return out
# 2^256 - p = 2^255 + 19: adding this and checking carry-out of limb 31
# implements the `x >= p` comparison used by canonical reduction.
P_COMPLEMENT = limbs_of_int(2**256 - P)

ZERO = limbs_of_int(0)
ONE = limbs_of_int(1)

# ---------------------------------------------------------------------------
# Carry propagation
# ---------------------------------------------------------------------------


def _carry_pass(c: jnp.ndarray, wrap: bool) -> jnp.ndarray:
    """One vectorized carry pass. If `wrap`, the top-limb carry folds into
    limb 0 via 2^(8*32) = 2^256 = 38 (mod p); else it adds into the next
    (existing) limb row — callers provide headroom rows."""
    hi = jnp.floor(c * (1.0 / RADIX))
    lo = c - hi * RADIX
    if wrap:
        head = lo[:1] + hi[-1:] * 38.0
    else:
        head = lo[:1]
    return jnp.concatenate([head, lo[1:] + hi[:-1]], axis=0)


def _carry32(c: jnp.ndarray) -> jnp.ndarray:
    """Three wrap passes: any input < 2^24 per limb -> limbs <= 294."""
    for _ in range(3):
        c = _carry_pass(c, wrap=True)
    return c


# ---------------------------------------------------------------------------
# Core ops (all shapes (32, B) f32 unless noted)
# ---------------------------------------------------------------------------


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lazy addition. At most one before a mul/sub (bound: 294+294=588)."""
    return a + b


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b (mod p), inputs' limbs <= 588; normalized output (<= 294)."""
    return _carry32(a + bias_limbs() - b)


# Mosaic (Pallas TPU) cannot lower scatter-add, so kernels switch the
# convolution to explicit per-row sums at trace time via this flag. The
# scatter form traces smaller/faster for the plain-XLA path.
_MOSAIC_SAFE = False


@contextlib.contextmanager
def mosaic_safe():
    """Trace field ops without scatter/dynamic-update (for Pallas bodies)."""
    global _MOSAIC_SAFE
    prev, _MOSAIC_SAFE = _MOSAIC_SAFE, True
    try:
        yield
    finally:
        _MOSAIC_SAFE = prev


def _conv_scatter(a, b, batch):
    c = jnp.zeros((2 * NLIMB + 2,) + batch, jnp.float32)
    for i in range(NLIMB):
        c = c.at[i : i + NLIMB].add(a[i] * b)
    return c


def _conv_rows(a, b, batch):
    rows = []
    for k in range(2 * NLIMB - 1):
        lo, hi = max(0, k - NLIMB + 1), min(k, NLIMB - 1)
        term = a[lo] * b[k - lo]
        for i in range(lo + 1, hi + 1):
            term = term + a[i] * b[k - i]
        rows.append(jnp.broadcast_to(term, batch)[None])
    rows.append(jnp.zeros((3,) + batch, jnp.float32))  # carry headroom
    return jnp.concatenate(rows, axis=0)


def _conv_shift(a, b, batch):
    """Scatter-free conv on full (66, B) tiles: tree-sum of zero-padded
    shifted products. Same FLOPs as _conv_rows but each op covers whole
    (sublane, lane) tiles instead of single (B,) rows — better VPU issue
    efficiency inside Mosaic kernels."""
    parts = []
    for i in range(NLIMB):
        prod = jnp.broadcast_to(a[i] * b, (NLIMB,) + batch)
        parts.append(
            jnp.pad(prod, ((i, NLIMB + 2 - i), (0, 0)))
        )
    while len(parts) > 1:  # balanced tree keeps live values narrow
        parts = [
            parts[j] + parts[j + 1] if j + 1 < len(parts) else parts[j]
            for j in range(0, len(parts), 2)
        ]
    return parts[0]


def _reduce_512(c: jnp.ndarray) -> jnp.ndarray:
    """(66, B) raw product -> normalized 32-limb element."""
    # carry the product down to <=256/limb (no wrap: rows 63..65 give the
    # carries headroom and nothing overflows out of row 65), then fold
    # rows 32..63 via 2^256 = 38 and rows 64..65 via 2^512 = 1444 (mod p).
    for _ in range(3):
        c = _carry_pass(c, wrap=False)
    folded = c[:NLIMB] + 38.0 * c[NLIMB : 2 * NLIMB]
    extra = jnp.concatenate(
        [
            1444.0 * c[2 * NLIMB : 2 * NLIMB + 2],
            jnp.zeros_like(folded[2:]),
        ],
        axis=0,
    )
    return _carry32(folded + extra)


MOSAIC_CONV = "shift"  # "rows" | "shift" — conv flavour inside Pallas


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field multiplication; normalized output (limbs <= ~295).

    Input bound: max_limb(a) * max_limb(b) <= 2^19 (so each of the <=32
    convolution terms is < 2^19 and their sum < 2^24 stays f32-exact);
    normalized (<=295) and single-lazy-add (<=590) operands, and the
    madd pattern (<=590 x <=885), all satisfy this.

    The product of two lazily-reduced 256-bit-plus values can slightly
    exceed 2^512, so the convolution gets 66 rows (see _reduce_512).
    """
    batch = jnp.broadcast_shapes(a.shape[1:], b.shape[1:])
    if _MOSAIC_SAFE:
        conv = _conv_shift if MOSAIC_CONV == "shift" else _conv_rows
    else:
        conv = _conv_scatter
    return _reduce_512(conv(a, b, batch))


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    """Squaring: symmetric convolution, ~55% of mul's multiplies
    (c_k = a_i^2 [i+i=k] + 2*a_i*a_j [i<j, i+j=k]); same bounds as mul."""
    batch = a.shape[1:]
    a2 = a + a
    if _MOSAIC_SAFE:
        # shift form: block i contributes [a_i^2, 2*a_i*a_{i+1..}] at
        # offset 2i; zero-padded full-tile adds (see _conv_shift)
        parts = []
        for i in range(NLIMB):
            sq = jnp.broadcast_to(a[i] * a[i], batch)[None]
            if i + 1 < NLIMB:
                cross = jnp.broadcast_to(
                    a2[i] * a[i + 1 :], (NLIMB - 1 - i,) + batch
                )
                block = jnp.concatenate([sq, cross], axis=0)
            else:
                block = sq
            top, rows = 2 * i, NLIMB - i
            parts.append(jnp.pad(block, ((top, 2 * NLIMB + 2 - top - rows), (0, 0))))
        while len(parts) > 1:
            parts = [
                parts[j] + parts[j + 1] if j + 1 < len(parts) else parts[j]
                for j in range(0, len(parts), 2)
            ]
        return _reduce_512(parts[0])
    c = jnp.zeros((2 * NLIMB + 2,) + batch, a.dtype)
    for i in range(NLIMB):
        c = c.at[2 * i].add(a[i] * a[i])
        if i + 1 < NLIMB:
            c = c.at[2 * i + 1 : i + NLIMB].add(a2[i] * a[i + 1 :])
    return _reduce_512(c)


def sqr_n(a: jnp.ndarray, n: int) -> jnp.ndarray:
    """n successive squarings via fori_loop (body traced once)."""
    return lax.fori_loop(0, n, lambda _, x: mul(x, x), a)


def select(mask: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Per-item select: mask (B,) bool -> a where True else b."""
    return jnp.where(mask[None, :], a, b)


# ---------------------------------------------------------------------------
# Fixed-exponent chains (ref10 addition chains; fori_loop keeps HLO small)
# ---------------------------------------------------------------------------


def _chain_250(z: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (z^(2^250 - 1), z^11) — the shared prefix of invert/pow2523."""
    z2 = sqr(z)
    z8 = sqr_n(z2, 2)
    z9 = mul(z, z8)
    z11 = mul(z2, z9)
    z22 = sqr(z11)
    z_5_0 = mul(z9, z22)  # 2^5 - 1
    z_10_0 = mul(sqr_n(z_5_0, 5), z_5_0)  # 2^10 - 1
    z_20_0 = mul(sqr_n(z_10_0, 10), z_10_0)  # 2^20 - 1
    z_40_0 = mul(sqr_n(z_20_0, 20), z_20_0)  # 2^40 - 1
    z_50_0 = mul(sqr_n(z_40_0, 10), z_10_0)  # 2^50 - 1
    z_100_0 = mul(sqr_n(z_50_0, 50), z_50_0)  # 2^100 - 1
    z_200_0 = mul(sqr_n(z_100_0, 100), z_100_0)  # 2^200 - 1
    z_250_0 = mul(sqr_n(z_200_0, 50), z_50_0)  # 2^250 - 1
    return z_250_0, z11


def invert(z: jnp.ndarray) -> jnp.ndarray:
    """z^(p-2) = z^(2^255 - 21): multiplicative inverse (0 -> 0)."""
    z_250_0, z11 = _chain_250(z)
    return mul(sqr_n(z_250_0, 5), z11)


def pow2523(z: jnp.ndarray) -> jnp.ndarray:
    """z^((p-5)/8) = z^(2^252 - 3): the square-root exponent."""
    z_250_0, _ = _chain_250(z)
    return mul(sqr_n(z_250_0, 2), z)


# ---------------------------------------------------------------------------
# Exact canonicalisation (value mod p, limbs in [0, 255])
# ---------------------------------------------------------------------------


def _seq_carry(c: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Exact sequential carry over 32 limbs; returns (limbs in [0,256),
    carry_out (B,)). fori_loop, 32 iterations."""

    def body(i, state):
        limbs, carry = state
        t = lax.dynamic_index_in_dim(limbs, i, axis=0, keepdims=False) + carry
        hi = jnp.floor(t * (1.0 / RADIX))
        lo = t - hi * RADIX
        limbs = lax.dynamic_update_index_in_dim(limbs, lo, i, axis=0)
        return limbs, hi

    carry0 = jnp.zeros(c.shape[1:], c.dtype)
    return lax.fori_loop(0, NLIMB, body, (c, carry0))


def _cond_sub_p(x: jnp.ndarray) -> jnp.ndarray:
    """One conditional subtraction of p (x < 2^256, limbs canonical)."""
    t = x + P_COMPLEMENT  # x + (2^256 - p)
    t, carry = _seq_carry(t)
    ge_p = carry >= 1.0  # carry out of 2^256 <=> x >= p
    return select(ge_p, t, x)


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Reduce a normalized (limbs <= ~600) element to THE canonical
    representative: limbs in [0, 255], value in [0, p)."""
    x, carry = _seq_carry(x)
    x = x.at[0].add(carry * 38.0)  # fold 2^256 overflow
    x, carry = _seq_carry(x)
    x = x.at[0].add(carry * 38.0)  # second fold can leave limb 0 in [256,293]
    x, _ = _seq_carry(x)  # value < 2^256 here, so the carry-out is 0
    x = _cond_sub_p(x)
    x = _cond_sub_p(x)
    return x


def eq_canonical(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(B,) bool equality of two canonical elements."""
    return jnp.all(a == b, axis=0)


def parity(x_canonical: jnp.ndarray) -> jnp.ndarray:
    """(B,) f32 in {0,1}: low bit of the canonical value (sign of x)."""
    return x_canonical[0] - 2.0 * jnp.floor(x_canonical[0] * 0.5)
