"""EXPERIMENTAL: GF(2^255 - 19) in radix-2^12 uint32 limbs (22 limbs).

The production field (`ops.field`) uses 32 radix-256 f32 limbs because f32
accumulation is exact only below 2^24: with 32 limbs the schoolbook sum
bound forces b <= 9 bits per limb (32 * 2^(2b) < 2^24). A uint32
accumulator lifts the bound to 2^32, admitting 12-bit limbs:

    22 limbs x 12 bits = 264 >= 255
    products <= 8200 * 12400 < 2^26.6;  22 terms < 2^31.1 < 2^32  (exact)

so a multiply is a 22x22 convolution — 484 limb products vs the f32
field's 1024 (2.1x fewer), with shorter carry chains (22 rows vs 32).

Whether this BEATS the f32 field on a real TPU depends on the VPU's
int32 multiply issue rate vs f32 fma (not public; measured by
`tools/tune_device.py --vpu` / `--field`). This module exists to make
that decision a benchmark away: it implements the exact same contract as
`ops.field` for the core ops (mul/sqr/add/sub/carry/canonical) with
value-level tests against Python bigints (`tests/test_field12.py`). The
verify kernel stays on `ops.field` until the device measurement says
otherwise.

Reference hot path this would accelerate: crypto/src/lib.rs:194-220.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

P = 2**255 - 19
NLIMB = 22
BITS = 12
RADIX = 1 << BITS  # 4096
MASK = RADIX - 1
# 2^264 = 2^9 * 2^255 ≡ 2^9 * 19 (mod p)
FOLD = 19 << 9  # 9728

U32 = jnp.uint32


def limbs_of_int(x: int, n: int = NLIMB) -> np.ndarray:
    assert 0 <= x < (1 << (BITS * n))
    out = np.zeros((n, 1), np.uint32)
    for i in range(n):
        out[i, 0] = (x >> (BITS * i)) & MASK
    return out


def int_of_limbs(limbs) -> list[int]:
    arr = np.asarray(limbs, np.uint64)
    return [
        sum(int(arr[i, b]) << (BITS * i) for i in range(arr.shape[0]))
        for b in range(arr.shape[1])
    ]


def _make_bias(mult: int, lo: int) -> np.ndarray:
    """Limbs of mult*p with every limb in [lo, 2^17): per-limb lower bound
    lets `sub` stay nonnegative without borrows."""
    digits = [(mult * P >> (BITS * i)) & MASK for i in range(NLIMB)]
    digits[NLIMB - 1] += RADIX * (mult * P >> (BITS * NLIMB))
    for i in range(NLIMB - 1):
        while digits[i] < lo:
            digits[i] += RADIX
            digits[i + 1] -= 1
    assert digits[NLIMB - 1] >= lo and all(0 <= d < 2**17 for d in digits)
    assert sum(d << (BITS * i) for i, d in enumerate(digits)) == mult * P
    return np.array(digits, np.uint32).reshape(NLIMB, 1)


# sub inputs can carry one lazy add of two normalized elements; limb 0's
# normalized bound is FOLD-amplified (~14k, see carry()), so the per-limb
# floor is 8*RADIX = 32768 > 2*14k.
# mult 8192 keeps the TOP digit (~ mult * p / 2^252 ≈ 8 * mult) above the
# floor after the borrow cascade.
BIAS = _make_bias(8192, 8 * RADIX)
P_COMPLEMENT = limbs_of_int((1 << (BITS * NLIMB)) - P)  # 2^264 - p

ZERO = limbs_of_int(0)
ONE = limbs_of_int(1)


def _carry_pass(c: jnp.ndarray, wrap: bool) -> jnp.ndarray:
    hi = c >> BITS
    lo = c & MASK
    if wrap:
        head = lo[:1] + hi[-1:] * jnp.uint32(FOLD)
    else:
        head = lo[:1]
    return jnp.concatenate([head, lo[1:] + hi[:-1]], axis=0)


def carry(c: jnp.ndarray) -> jnp.ndarray:
    """Input limbs < 2^30.6 -> normalized limbs: <= ~4100 for rows 1..21
    and <= RADIX + FOLD + eps (~14k) for row 0 (the 2^264 ≡ 9728 wrap can
    keep re-feeding limb 0, which converges to 4095 + 9728; this limb-0
    amplification is accounted for in the mul/sub input bounds)."""
    for _ in range(3):
        c = _carry_pass(c, wrap=True)
    return c


def add(a, b):
    """Lazy addition (at most one before a mul/sub)."""
    return a + b


def sub(a, b):
    """a - b (mod p); normalized output. Input bound: at most ONE lazy
    add of normalized elements per operand (limb 0 <= ~28k, others <=
    ~8.2k — the BIAS per-limb floor of 8*RADIX = 32768 must exceed every
    subtrahend limb or the uint32 difference wraps silently)."""
    return carry(a + jnp.asarray(BIAS) - b)


def _reduce(c46: jnp.ndarray) -> jnp.ndarray:
    """(46, B) raw product rows -> normalized 22-limb element.

    Carry the raw rows down (no wrap; rows 43-45 are headroom), fold rows
    44-45 (sig 2^528+) into rows 22-23 via 2^264 ≡ FOLD first (their
    values are tiny, so FOLD * row stays small), then fold rows 22-43
    into 0-21 with one more FOLD multiply (<= 4100 + FOLD * ~160k < 2^31,
    uint32-exact) and normalize."""
    for _ in range(3):
        c46 = _carry_pass(c46, wrap=False)
    tail = c46[2 * NLIMB :]  # rows 44-45, <= ~16 after carries
    mid = c46[NLIMB : 2 * NLIMB]
    mid = mid.at[0 : tail.shape[0]].add(jnp.uint32(FOLD) * tail)
    folded = c46[:NLIMB] + jnp.uint32(FOLD) * mid
    return carry(folded)


def mul(a, b):
    """Field multiplication; inputs' limbs <= ~12400 x ~8200 (normalized
    or one lazy add); exact in uint32 (sum < 2^31.1)."""
    batch = jnp.broadcast_shapes(a.shape[1:], b.shape[1:])
    c = jnp.zeros((2 * NLIMB + 2,) + batch, U32)
    for i in range(NLIMB):
        c = c.at[i : i + NLIMB].add(a[i] * b)
    return _reduce(c)


def sqr(a):
    batch = a.shape[1:]
    a2 = a + a
    c = jnp.zeros((2 * NLIMB + 2,) + batch, U32)
    for i in range(NLIMB):
        c = c.at[2 * i].add(a[i] * a[i])
        if i + 1 < NLIMB:
            c = c.at[2 * i + 1 : i + NLIMB].add(a2[i] * a[i + 1 :])
    return _reduce(c)


def sqr_n(a, n: int):
    return lax.fori_loop(0, n, lambda _, x: sqr(x), a)


def select(mask, a, b):
    return jnp.where(mask[None, :], a, b)


def _seq_carry(c: jnp.ndarray):
    def body(i, state):
        limbs, cin = state
        t = lax.dynamic_index_in_dim(limbs, i, axis=0, keepdims=False) + cin
        hi = t >> BITS
        lo = t & MASK
        return lax.dynamic_update_index_in_dim(limbs, lo, i, axis=0), hi

    carry0 = jnp.zeros(c.shape[1:], c.dtype)
    return lax.fori_loop(0, NLIMB, body, (c, carry0))


def _cond_sub_p(x):
    t = x + jnp.asarray(P_COMPLEMENT)
    t, cout = _seq_carry(t)
    return select(cout >= 1, t, x)


def canonical(x):
    """Normalized element -> THE canonical representative in [0, p).

    Unlike the radix-256 field (value < 2^256 < 3p, two conditional
    subtractions suffice), a 22x12-bit element spans 264 bits — up to
    ~512p — so the bits above 2^255 must fold down first: 2^255 ≡ 19,
    and bit 255 sits at bit 3 of limb 21. Two fold+carry passes bring
    the value below p + 38, then two conditional subtractions finish."""
    x, cout = _seq_carry(x)
    x = x.at[0].add(cout * jnp.uint32(FOLD))
    x, cout = _seq_carry(x)
    x = x.at[0].add(cout * jnp.uint32(FOLD))
    x, _ = _seq_carry(x)  # limbs < 4096, value < 2^264
    for _ in range(2):
        q = x[NLIMB - 1] >> 3  # value >> 255, <= 2^9 after the seq carry
        x = x.at[NLIMB - 1].set(x[NLIMB - 1] & jnp.uint32(7))
        x = x.at[0].add(q * jnp.uint32(19))
        x, _ = _seq_carry(x)
    x = _cond_sub_p(x)
    x = _cond_sub_p(x)
    return x


def eq_canonical(a, b):
    return jnp.all(a == b, axis=0)
