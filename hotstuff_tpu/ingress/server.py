"""Ingress RPC front-end: framed TCP server + client.

Rides the exact wire discipline of the rest of the stack — 4-byte
big-endian length prefixes read through `network/net.FrameReader` — so a
client speaks to the ingress port the same way nodes speak to each
other. Unlike the node-to-node planes this is a REQUEST/RESPONSE
surface: every decoded ClientTransaction gets exactly one
IngressResponse back on the same connection, correlated by nonce (a
client may pipeline submissions; responses can complete out of order
because admission rejections resolve immediately while accepted
transactions wait out their verification batch).

An undecodable frame is answered with MALFORMED(nonce=0) and the
connection survives — frame boundaries are intact (the length prefix
parsed), so subsequent frames are still well-delimited. A frame
violating the length cap drops the connection, same as NetReceiver.
"""

from __future__ import annotations

import asyncio
import logging

from ..network.net import Address, FrameReader, frame
from ..utils import metrics
from ..utils.actors import channel, spawn
from . import messages
from .messages import (
    ClientTransaction,
    IngressResponse,
    decode_ingress_message,
    encode_ingress_message,
)
from .pipeline import IngressPipeline

log = logging.getLogger("hotstuff.ingress")

# Wire-level rejects (undecodable frames) never reach admission, but a
# garbage-frame flood must still be visible to monitoring.
_M_WIRE_MALFORMED = metrics.counter("ingress.malformed")


class IngressServer:
    """Accept loop on the ingress port; one reader + one writer task per
    connection, submissions fan out into the shared pipeline."""

    def __init__(self, address: Address, pipeline: IngressPipeline) -> None:
        self._address = address
        self.pipeline = pipeline
        self._task = spawn(self._run(), name="ingress-server")

    async def _run(self) -> None:
        server = await asyncio.start_server(
            self._handle, host=self._address[0], port=self._address[1]
        )
        log.info("Ingress listening on %s", self._address)
        async with server:
            await server.serve_forever()

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        # Responses serialize through one queue + writer task: per-tx
        # submit tasks complete concurrently and interleaved writes would
        # corrupt the frame stream. Bounded: a client that stops reading
        # eventually blocks its own submissions, nobody else's.
        responses = channel()
        writer_task = spawn(
            self._write_responses(responses, writer), name="ingress-writer"
        )
        # Per-connection submit tasks, cancelled on disconnect: once the
        # writer stops draining `responses`, a completed submit would
        # otherwise park forever on its put and leak with the connection.
        inflight: set[asyncio.Task] = set()
        frames = FrameReader(reader)
        try:
            while True:
                try:
                    data = await frames.next_frame()
                except ConnectionError as e:
                    log.warning(
                        "ingress: dropping connection from %s: %s", peer, e
                    )
                    break
                if data is None:
                    break
                try:
                    msg = decode_ingress_message(data)
                except Exception as e:
                    _M_WIRE_MALFORMED.inc()
                    log.warning(
                        "ingress: undecodable frame from %s: %r", peer, e
                    )
                    await responses.put(
                        IngressResponse(0, messages.MALFORMED)
                    )
                    continue
                if not isinstance(msg, ClientTransaction):
                    _M_WIRE_MALFORMED.inc()
                    await responses.put(
                        IngressResponse(0, messages.MALFORMED)
                    )
                    continue
                task = spawn(
                    self._submit(msg, responses), name="ingress-handle"
                )
                inflight.add(task)
                task.add_done_callback(inflight.discard)
        finally:
            writer_task.cancel()
            for task in list(inflight):
                task.cancel()
            try:
                writer.close()
            except Exception:
                pass

    async def _submit(self, tx: ClientTransaction, responses) -> None:
        resp = await self.pipeline.submit(tx)
        await responses.put(resp)

    async def _write_responses(self, responses, writer) -> None:
        while True:
            resp = await responses.get()
            try:
                writer.write(frame(encode_ingress_message(resp)))
                await writer.drain()
            except (ConnectionError, OSError):
                return  # client went away; reader loop will notice EOF


class IngressClient:
    """Client side of the RPC: pipelined submissions over one connection,
    response futures keyed by nonce. Used by tools/loadgen.py (TCP mode);
    in-process drivers call IngressPipeline.submit directly."""

    def __init__(self) -> None:
        self._writer: asyncio.StreamWriter | None = None
        # nonce -> FIFO of waiters: submitters SHOULD use unique nonces
        # (the replay filter rejects repeats), but a repeat in flight must
        # cross-match FIFO rather than silently orphan the first future.
        self._waiters: dict[int, list[asyncio.Future]] = {}
        self._reader_task: asyncio.Task | None = None

    async def connect(self, address: Address) -> None:
        reader, self._writer = await asyncio.open_connection(
            address[0], address[1]
        )
        self._reader_task = spawn(
            self._read_responses(reader), name="ingress-client-reader"
        )

    async def _read_responses(self, reader: asyncio.StreamReader) -> None:
        frames = FrameReader(reader)
        while True:
            try:
                data = await frames.next_frame()
            except ConnectionError:
                data = None
            if data is None:
                break
            try:
                msg = decode_ingress_message(data)
            except Exception as e:
                log.warning("ingress client: undecodable response: %r", e)
                continue
            queue = self._waiters.get(getattr(msg, "nonce", -1))
            if queue:
                fut = queue.pop(0)
                if not queue:
                    del self._waiters[msg.nonce]
                if not fut.done():
                    fut.set_result(msg)
        # Connection gone: fail every outstanding waiter.
        waiters, self._waiters = self._waiters, {}
        for queue in waiters.values():
            for fut in queue:
                if not fut.done():
                    fut.set_exception(
                        ConnectionError("ingress connection closed")
                    )

    async def submit(self, tx: ClientTransaction) -> IngressResponse:
        if self._writer is None:
            raise ConnectionError("ingress client not connected")
        fut = asyncio.get_running_loop().create_future()
        self._waiters.setdefault(tx.nonce, []).append(fut)
        self._writer.write(frame(encode_ingress_message(tx)))
        await self._writer.drain()
        return await fut

    def close(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
            self._writer = None
