"""Client ingress wire messages: per-client signed transactions and the
backpressure responses the ingress returns for them.

Unlike the benchmark `Front` (mempool/front.py), which accepts raw
unauthenticated bytes, the ingress plane is the authenticated client
boundary: every transaction is ed25519-signed by its submitting client
over a domain-separated digest of (client, nonce, fee, body), and every
submission gets an explicit response — ACCEPTED after the signature
verified and the body was handed to the mempool, or a typed rejection
(SHED carries a retry-after hint so clients can back off instead of
hammering a saturated node).

The fee is part of the signed content: it selects the admission lane
(ingress/admission.py), and an unsigned fee would let a relay promote or
demote someone else's transaction.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from ..crypto import Digest, PublicKey, Signature
from ..crypto import pysigner
from ..utils.serde import Reader, SerdeError, Writer

TX_DOMAIN = b"HSINGRESSTX"

# Response statuses (IngressResponse.status).
ACCEPTED = 0  # signature verified, body forwarded to the mempool
SHED = 1  # admission lane full: back off for retry_after_ms
BAD_SIGNATURE = 2  # signature failed verification
REPLAY = 3  # (client, nonce) already seen inside the replay window
MALFORMED = 4  # undecodable frame / oversized body / unknown shape

STATUS_NAMES = {
    ACCEPTED: "accepted",
    SHED: "shed",
    BAD_SIGNATURE: "bad_signature",
    REPLAY: "replay",
    MALFORMED: "malformed",
}

TAG_TX = 0
TAG_RESPONSE = 1


@dataclass(frozen=True, slots=True)
class ClientTransaction:
    """One signed client submission. `nonce` is client-chosen and must be
    unique per client (the admission replay filter rejects repeats);
    `fee` selects the admission lane; `body` is the opaque transaction
    payload that — once the signature verifies — flows into the
    PayloadMaker exactly like a Front-submitted transaction (so the
    sample-tx latency convention of node/client.py keeps working)."""

    client: PublicKey
    nonce: int
    fee: int
    body: bytes
    signature: Signature
    _digest: Digest | None = field(
        default=None, init=False, repr=False, compare=False
    )

    @staticmethod
    def make_digest(client: PublicKey, nonce: int, fee: int, body: bytes) -> Digest:
        h = hashlib.sha512()
        h.update(TX_DOMAIN)
        h.update(client.data)
        h.update(nonce.to_bytes(8, "little"))
        h.update(fee.to_bytes(8, "little"))
        h.update(len(body).to_bytes(4, "little"))  # keeps the encoding injective
        h.update(body)
        return Digest(h.digest()[:32])

    @staticmethod
    def new_signed(
        seed: bytes, nonce: int, fee: int, body: bytes
    ) -> "ClientTransaction":
        """Sign with the dependency-free RFC 8032 signer (crypto/pysigner):
        load generators and chaos drivers run without the OpenSSL wheel."""
        pk, _ = pysigner.keypair_from_seed(seed)
        client = PublicKey(pk)
        digest = ClientTransaction.make_digest(client, nonce, fee, body)
        sig = Signature(pysigner.sign(seed, digest.data))
        tx = ClientTransaction(client, nonce, fee, body, sig)
        object.__setattr__(tx, "_digest", digest)  # seed the cache
        return tx

    def digest(self) -> Digest:
        if self._digest is None:
            object.__setattr__(
                self,
                "_digest",
                ClientTransaction.make_digest(
                    self.client, self.nonce, self.fee, self.body
                ),
            )
        return self._digest

    def encode(self, w: Writer) -> None:
        w.fixed(self.client.data, 32)
        w.u64(self.nonce)
        w.u64(self.fee)
        w.var_bytes(self.body)
        w.fixed(self.signature.data, 64)

    @staticmethod
    def decode(r: Reader) -> "ClientTransaction":
        client = PublicKey(r.fixed(32))
        nonce = r.u64()
        fee = r.u64()
        body = r.var_bytes()
        sig = Signature(r.fixed(64))
        return ClientTransaction(client, nonce, fee, body, sig)

    def __str__(self) -> str:
        return (
            f"ClientTx({self.client.short()}, nonce={self.nonce}, "
            f"fee={self.fee}, {len(self.body)} B)"
        )


@dataclass(frozen=True, slots=True)
class IngressResponse:
    """Per-transaction outcome, correlated by the echoed nonce (nonces
    are client-unique, so responses may arrive out of order). A SHED
    response carries `retry_after_ms` — the node's estimate of when the
    rejected lane will have drained enough to admit again; clients that
    ignore it just burn their own round trips on further sheds."""

    nonce: int
    status: int
    retry_after_ms: int = 0

    @property
    def status_name(self) -> str:
        return STATUS_NAMES.get(self.status, f"status-{self.status}")

    def encode(self, w: Writer) -> None:
        w.u64(self.nonce)
        w.u8(self.status)
        w.u32(self.retry_after_ms)

    @staticmethod
    def decode(r: Reader) -> "IngressResponse":
        return IngressResponse(r.u64(), r.u8(), r.u32())


def encode_ingress_message(msg) -> bytes:
    w = Writer()
    if isinstance(msg, ClientTransaction):
        w.u8(TAG_TX)
    elif isinstance(msg, IngressResponse):
        w.u8(TAG_RESPONSE)
    else:
        raise TypeError(f"not an ingress message: {msg!r}")
    msg.encode(w)
    return w.bytes()


def decode_ingress_message(data: bytes):
    r = Reader(data)
    tag = r.u8()
    if tag == TAG_TX:
        out = ClientTransaction.decode(r)
    elif tag == TAG_RESPONSE:
        out = IngressResponse.decode(r)
    else:
        raise SerdeError(f"unknown ingress tag {tag}")
    r.expect_done()
    return out
