"""Admission control for the client ingress: fee/priority lanes, bounded
queues with explicit shedding, and retry-after backpressure hints.

Design choices (vs the Front's drop-oldest, mempool/front.py):

  * **Reject-newest with a signal.** The Front serves anonymous benchmark
    load, where keeping the queue fresh matters more than telling anyone.
    Ingress clients are authenticated and get a response per submission,
    so the correct overload behaviour is to REJECT the new arrival with a
    retry-after hint: the client's latency accounting stays truthful
    (an accepted tx is actually in the pipeline) and the aggregate
    arrival rate becomes controllable — shedding is the node's only
    lever against an open-loop crowd that does not slow down on its own.

  * **Fee-selected lanes, strict-priority drain.** A transaction's signed
    `fee` maps it to the highest lane whose `min_fee` it clears; the
    pipeline drains lanes in priority order, so under overload the bulk
    lane starves first and the priority lane's latency stays flat. Each
    lane's queue is bounded separately — a bulk flood cannot consume the
    priority lane's headroom.

  * **Replay filter before signature work.** A duplicate (client, nonce)
    is rejected from a bounded recently-seen set BEFORE verification, so
    replaying a captured valid transaction costs the node a dict lookup,
    not an ed25519 check (and the verified-signature dedup cache stays
    out of the client path entirely — see pipeline.py).

Retry-after derives from observed drain: the pipeline reports every
batch it verifies, an EWMA tracks the drain rate, and the hint is the
time the rejected lane's current depth needs to half-drain at that rate
(clamped to [RETRY_MIN_MS, RETRY_MAX_MS]). Deterministic under the chaos
virtual clock — the estimate only reads the event-loop time its caller
passes in.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from dataclasses import dataclass, field

from ..utils import metrics
from . import messages
from .messages import ClientTransaction

_M_SHED = metrics.counter("ingress.shed")
_M_REPLAYS = metrics.counter("ingress.replays")
_M_MALFORMED = metrics.counter("ingress.malformed")
_M_ADMITTED = metrics.counter("ingress.admitted")
_M_LANE_DEPTH = metrics.gauge("ingress.lane_depth")
_M_RETRY_AFTER = metrics.histogram("ingress.retry_after_ms", metrics.SIZE_BUCKETS)

RETRY_MIN_MS = 50
RETRY_MAX_MS = 5_000


@dataclass(frozen=True, slots=True)
class LaneSpec:
    """One admission lane: transactions with fee >= min_fee ride it
    (highest-min_fee lane wins), up to `capacity` queued."""

    name: str
    min_fee: int
    capacity: int


@dataclass(slots=True)
class IngressConfig:
    # Highest-priority first; the last lane should have min_fee=0 so every
    # fee maps somewhere (fees below every floor reject as MALFORMED).
    lanes: tuple[LaneSpec, ...] = (
        LaneSpec("priority", min_fee=1_000, capacity=512),
        LaneSpec("standard", min_fee=1, capacity=2_048),
        LaneSpec("bulk", min_fee=0, capacity=8_192),
    )
    max_tx_bytes: int = 64 * 1024  # per-tx body cap (one frame, never a payload)
    replay_window: int = 65_536  # recently-seen (client, nonce) pairs kept
    verify_batch: int = 64  # txs per verification group
    # Seconds to pause between verification batches: a deliberate drain
    # pacer modelling finite verify capacity (batch/interval tx/s). 0 =
    # backend-bound (production); the chaos scenarios and the loadgen
    # selftest set it so overload — and therefore shedding — is reachable
    # under a virtual clock where Python work costs zero virtual time.
    verify_interval: float = 0.0


@dataclass(slots=True)
class _Lane:
    spec: LaneSpec
    queue: deque = field(default_factory=deque)


class AdmissionController:
    """Stateful admission decisions; owned by one IngressPipeline.

    `admit()` either returns the lane index the transaction was queued
    into, or an (status, retry_after_ms) rejection. The pipeline pops
    admitted transactions via `take()` in strict priority order and
    reports drain progress via `note_drained()`.
    """

    def __init__(self, config: IngressConfig | None = None) -> None:
        self.config = config or IngressConfig()
        if not self.config.lanes or self.config.lanes[-1].min_fee != 0:
            raise ValueError("the last ingress lane must have min_fee=0")
        self.lanes = [_Lane(spec) for spec in self.config.lanes]
        self._seen: OrderedDict[tuple[bytes, int], None] = OrderedDict()
        # Drain-rate EWMA (txs/sec): seeded pessimistically low so the
        # first overload quotes a conservative (long) retry-after rather
        # than an optimistic one computed from zero observations.
        self._drain_rate = 0.0
        self._last_drain_t: float | None = None
        self.shed = 0

    # -- admission -----------------------------------------------------------

    def lane_for(self, fee: int) -> int | None:
        for i, lane in enumerate(self.lanes):
            if fee >= lane.spec.min_fee:
                return i
        return None

    def depth(self) -> int:
        return sum(len(lane.queue) for lane in self.lanes)

    def admit(self, tx: ClientTransaction, entry) -> tuple[int | None, int, int]:
        """Admit `tx` (queueing `entry`, the pipeline's (tx, t0, future)
        record) or reject it. Returns (lane index | None, status,
        retry_after_ms); lane is None exactly when rejected."""
        if len(tx.body) > self.config.max_tx_bytes or not tx.body:
            _M_MALFORMED.inc()
            return None, messages.MALFORMED, 0
        lane_idx = self.lane_for(tx.fee)
        if lane_idx is None:
            _M_MALFORMED.inc()
            return None, messages.MALFORMED, 0
        # Recorded at ADMISSION (not after verification) so an in-flight
        # duplicate is caught cheaply — but a nonce whose signature later
        # fails is released again via forget(): otherwise anyone knowing a
        # victim's public key could burn the victim's nonces forever with
        # garbage-signature submissions (zero crypto cost to the attacker,
        # since this filter runs before verification).
        key = (tx.client.data, tx.nonce)
        if key in self._seen:
            _M_REPLAYS.inc()
            return None, messages.REPLAY, 0
        lane = self.lanes[lane_idx]
        if len(lane.queue) >= lane.spec.capacity:
            self.shed += 1
            _M_SHED.inc()
            retry = self._retry_after_ms(lane)
            _M_RETRY_AFTER.record(retry)
            return None, messages.SHED, retry
        self._seen[key] = None
        while len(self._seen) > self.config.replay_window:
            self._seen.popitem(last=False)
        lane.queue.append(entry)
        _M_ADMITTED.inc()
        _M_LANE_DEPTH.set(self.depth())
        return lane_idx, messages.ACCEPTED, 0

    def forget(self, tx: ClientTransaction) -> None:
        """Release a (client, nonce) whose signature FAILED verification:
        only a verified transaction consumes its nonce, so a forged
        submission under someone else's key cannot squat the real
        client's nonce beyond its own in-flight window."""
        self._seen.pop((tx.client.data, tx.nonce), None)

    # -- drain side (pipeline) ----------------------------------------------

    def take(self, limit: int) -> list:
        """Pop up to `limit` queued entries in strict priority order
        (priority lane first; bulk starves under sustained overload —
        that is the lane contract, not a bug)."""
        out: list = []
        for lane in self.lanes:
            while lane.queue and len(out) < limit:
                out.append(lane.queue.popleft())
            if len(out) >= limit:
                break
        if out:
            _M_LANE_DEPTH.set(self.depth())
        return out

    def note_drained(self, n: int, now: float) -> None:
        """EWMA drain-rate update, fed by the pipeline after each verified
        batch; `now` is event-loop time (virtual under chaos)."""
        if self._last_drain_t is not None:
            dt = now - self._last_drain_t
            if dt > 0:
                inst = n / dt
                self._drain_rate = (
                    inst
                    if self._drain_rate == 0.0
                    else 0.8 * self._drain_rate + 0.2 * inst
                )
        self._last_drain_t = now

    def _retry_after_ms(self, lane: _Lane) -> int:
        """Time for the rejected lane's backlog to half-drain at the
        observed rate — long enough that an obedient client's retry has a
        real chance, short enough to keep goodput once pressure lifts."""
        if self._drain_rate <= 0.0:
            return RETRY_MAX_MS
        ms = int(1000.0 * (len(lane.queue) / 2.0) / self._drain_rate)
        return max(RETRY_MIN_MS, min(RETRY_MAX_MS, ms))
