"""Open-loop client load generation: arrival curves, signed traffic, and
per-client latency accounting.

OPEN loop means arrivals follow the curve regardless of how the node
responds — the model for "millions of users", who do not politely stop
clicking when the service slows down (the closed-loop Front client in
node/client.py throttles itself and therefore can never demonstrate
admission control). Each generated transaction is ed25519-signed by one
of a pool of client identities through the dependency-free pysigner, so
the generator runs anywhere: over TCP against a live node's ingress port
(tools/loadgen.py), or in-process against an IngressPipeline under the
chaos virtual-time loop, where the same seed replays the same traffic.

Curves:
  * sustained  — flat `rate` tx/s for the whole run;
  * diurnal    — smooth cosine ramp between `rate` and `peak` over
                 `period` seconds (the daily tide, compressed);
  * flash      — flat `rate` with a rectangular spike to `peak` inside
                 [t_start, t_end) (the thundering herd).

The summary reports offered/accepted/shed/rejected counts, the shed
rate, and client-observed latency percentiles; `log_summary()` emits the
log lines `benchmark/logs.py` scrapes into the harness report.
"""

from __future__ import annotations

import asyncio
import logging
import math
import random
from dataclasses import dataclass, field
from typing import Awaitable, Callable

from ..utils import metrics
from ..utils.actors import spawn
from . import messages
from .admission import IngressConfig
from .messages import ClientTransaction, IngressResponse

log = logging.getLogger("hotstuff.loadgen")

TICK_S = 0.05  # arrival scheduling granularity (matches node/client.py)


@dataclass(frozen=True, slots=True)
class ArrivalCurve:
    kind: str = "sustained"  # sustained | diurnal | flash
    rate: float = 100.0  # base tx/s
    peak: float = 0.0  # diurnal/flash peak tx/s
    t_start: float = 0.0  # flash spike window
    t_end: float = 0.0
    period: float = 60.0  # diurnal period (s)

    def __post_init__(self) -> None:
        if self.kind not in ("sustained", "diurnal", "flash"):
            raise ValueError(f"unknown arrival curve {self.kind!r}")

    def rate_at(self, t: float) -> float:
        if self.kind == "sustained":
            return self.rate
        if self.kind == "diurnal":
            # rate at the trough, peak at period/2; one full day per period.
            phase = 0.5 * (1.0 - math.cos(2.0 * math.pi * t / self.period))
            return self.rate + (self.peak - self.rate) * phase
        return self.peak if self.t_start <= t < self.t_end else self.rate

    def to_json(self) -> dict:
        return {
            "kind": self.kind,
            "rate": self.rate,
            "peak": self.peak,
            "t_start": self.t_start,
            "t_end": self.t_end,
            "period": self.period,
        }


# Canonical list-percentile (utils/metrics.py): one definition across
# loadgen, scheduler LaneStats, and the trace-report tables.
percentile = metrics.percentile


# Fee mix: mostly standard traffic, a slice paying for the priority lane,
# a slice riding bulk for free (see admission.IngressConfig defaults).
_FEE_CHOICES = ((1_000, 0.15), (1, 0.75), (0, 0.10))


class OpenLoopLoadGen:
    """Drives `submit` (an async callable: ClientTransaction →
    IngressResponse) with curve-shaped traffic from `clients` signing
    identities. All randomness comes from the injected rng, so a seeded
    run is deterministic (the chaos replay contract)."""

    def __init__(
        self,
        submit: Callable[[ClientTransaction], Awaitable[IngressResponse]],
        curve: ArrivalCurve,
        duration: float,
        clients: int = 8,
        tx_bytes: int = 64,
        rng: random.Random | None = None,
        label: str = "loadgen",
    ) -> None:
        if tx_bytes < 9:
            raise ValueError("tx_bytes must be >= 9 (sample-tx header)")
        from ..crypto import pysigner

        self.submit = submit
        self.curve = curve
        self.duration = duration
        self.tx_bytes = tx_bytes
        self.label = label
        self.rng = rng or random.Random(0)
        self._seeds = [self.rng.randbytes(32) for _ in range(clients)]
        # pysigner keypair derivation is ~ms each; done once per client here.
        self._keys = [pysigner.keypair_from_seed(s) for s in self._seeds]
        # Disjoint per-client nonce ranges: nonces are client-chosen and
        # only need per-client uniqueness for the replay filter, but the
        # TCP IngressClient correlates responses by nonce across the ONE
        # shared connection — overlapping ranges would cross-match them.
        self._nonces = [c << 40 for c in range(clients)]
        self.offered = 0
        self.by_status: dict[str, int] = {}
        self.latencies_s: list[float] = []
        self.retry_hints = 0  # SHED responses carrying retry_after_ms > 0
        self.unresolved = 0  # submissions still in flight at teardown
        self._inflight: set[asyncio.Task] = set()

    # -- traffic -------------------------------------------------------------

    def _make_tx(self) -> ClientTransaction:
        c = self.rng.randrange(len(self._seeds))
        self._nonces[c] += 1
        r = self.rng.random()
        acc = 0.0
        fee = _FEE_CHOICES[-1][0]
        for value, weight in _FEE_CHOICES:
            acc += weight
            if r < acc:
                fee = value
                break
        # Front-compatible body: 0x01 + u64 tag + padding (never a sample
        # tx — sample accounting belongs to the closed-loop client).
        body = (
            b"\x01"
            + self.rng.randbytes(8)
            + bytes(self.tx_bytes - 9)
        )
        return ClientTransaction.new_signed(
            self._seeds[c], self._nonces[c], fee, body
        )

    async def _one(self, tx: ClientTransaction, t0: float) -> None:
        loop = asyncio.get_running_loop()
        try:
            resp = await self.submit(tx)
        except (ConnectionError, OSError) as e:
            self.by_status["error"] = self.by_status.get("error", 0) + 1
            log.debug("%s: submission failed: %r", self.label, e)
            return
        self.latencies_s.append(loop.time() - t0)
        name = resp.status_name
        self.by_status[name] = self.by_status.get(name, 0) + 1
        if resp.status == messages.SHED and resp.retry_after_ms > 0:
            self.retry_hints += 1

    async def run(self) -> dict:
        loop = asyncio.get_running_loop()
        start = loop.time()
        carry = 0.0
        next_tick = start
        while True:
            now = loop.time()
            t = now - start
            if t >= self.duration:
                break
            carry += self.curve.rate_at(t) * TICK_S
            n = int(carry)
            carry -= n
            for _ in range(n):
                tx = self._make_tx()
                self.offered += 1
                # actors.spawn, not bare ensure_future: in-process chaos
                # runs the generator inside a node-side SpawnScope, and a
                # crash-cancel must take the in-flight submissions with it.
                task = spawn(
                    self._one(tx, loop.time()),
                    name=f"{self.label}-tx{self.offered}",
                )
                self._inflight.add(task)
                task.add_done_callback(self._inflight.discard)
            next_tick += TICK_S
            delay = next_tick - loop.time()
            # Open loop: never slow the schedule down; a late tick fires
            # immediately and the curve's integral is preserved via carry.
            await asyncio.sleep(max(0.0, delay))
        # Grace for stragglers (one retry-max window), then count leftovers.
        if self._inflight:
            await asyncio.wait(list(self._inflight), timeout=5.0)
        self.unresolved = len(self._inflight)
        return self.summary()

    # -- reporting -----------------------------------------------------------

    def summary(self) -> dict:
        accepted = self.by_status.get("accepted", 0)
        shed = self.by_status.get("shed", 0)
        responded = sum(self.by_status.values())
        lat_ms = [s * 1000.0 for s in self.latencies_s]
        return {
            "curve": self.curve.to_json(),
            "duration_s": self.duration,
            "clients": len(self._seeds),
            "offered": self.offered,
            "responded": responded,
            "accepted": accepted,
            "shed": shed,
            "retry_hints": self.retry_hints,
            "bad_signature": self.by_status.get("bad_signature", 0),
            "replay": self.by_status.get("replay", 0),
            "malformed": self.by_status.get("malformed", 0),
            "errors": self.by_status.get("error", 0),
            "unresolved": self.unresolved,
            "shed_rate": (shed / responded) if responded else 0.0,
            "latency_ms": {
                "p50": round(percentile(lat_ms, 0.50), 3),
                "p99": round(percentile(lat_ms, 0.99), 3),
                "max": round(max(lat_ms), 3) if lat_ms else 0.0,
            },
        }

    def log_summary(self) -> dict:
        """Emit the scrapeable result lines (benchmark/logs.py contract).
        NOTE: these log entries are used to compute performance."""
        s = self.summary()
        log.info("Ingress offered: %s transactions", s["offered"])
        log.info("Ingress accepted: %s transactions", s["accepted"])
        log.info("Ingress shed: %s transactions", s["shed"])
        log.info(
            "Ingress client latency p50: %s ms", s["latency_ms"]["p50"]
        )
        log.info(
            "Ingress client latency p99: %s ms", s["latency_ms"]["p99"]
        )
        log.info("Ingress shed rate: %.2f %%", 100.0 * s["shed_rate"])
        return s


@dataclass(slots=True)
class IngressLoad:
    """Declarative ingress-load spec for chaos scenarios: the orchestrator
    boots one IngressPipeline + OpenLoopLoadGen per target node (seeded
    from the scenario's master seed, so replay stays bit-identical) and
    embeds each generator's summary in the report under `ingress`."""

    curve: ArrivalCurve
    duration: float
    clients: int = 4
    tx_bytes: int = 32
    targets: tuple[int, ...] | None = None  # node indices; None = all honest
    config: Callable[[], IngressConfig] = field(default=IngressConfig)
