"""Client ingress plane: authenticated transaction submission with
admission control, riding the shared batch-verification path.

Layering (each file one responsibility):
  * `messages.py`  — signed ClientTransaction + IngressResponse wire format
  * `admission.py` — fee/priority lanes, bounded queues, shed + retry-after
  * `pipeline.py`  — admission → BatchVerificationService → mempool seam
  * `server.py`    — framed TCP RPC front-end (+ client)
  * `loadgen.py`   — open-loop arrival curves, signed traffic, latency stats

Entry points: `Mempool.run` boots an `IngressServer` when
`MempoolParameters.ingress_enabled` is set (`node run --ingress`);
`tools/loadgen.py` drives it; chaos scenarios attach in-process
pipelines via `IngressLoad` (see chaos/orchestrator.py).
"""

from .admission import AdmissionController, IngressConfig, LaneSpec
from .loadgen import ArrivalCurve, IngressLoad, OpenLoopLoadGen
from .messages import (
    ACCEPTED,
    BAD_SIGNATURE,
    MALFORMED,
    REPLAY,
    SHED,
    ClientTransaction,
    IngressResponse,
    decode_ingress_message,
    encode_ingress_message,
)
from .pipeline import IngressPipeline
from .server import IngressClient, IngressServer

__all__ = [
    "ACCEPTED",
    "BAD_SIGNATURE",
    "MALFORMED",
    "REPLAY",
    "SHED",
    "AdmissionController",
    "ArrivalCurve",
    "ClientTransaction",
    "IngressClient",
    "IngressConfig",
    "IngressLoad",
    "IngressPipeline",
    "IngressResponse",
    "IngressServer",
    "LaneSpec",
    "OpenLoopLoadGen",
    "decode_ingress_message",
    "encode_ingress_message",
]
