"""IngressPipeline: admission → batched signature verification → mempool.

The committee-independent verification lane the ROADMAP's traffic-plane
item calls for: client transactions are admitted (ingress/admission.py),
their ed25519 signatures verified in GROUPS through the node's shared
`BatchVerificationService` — the same actor (and therefore the same
TPU/CPU backend and crossover routing) that consensus certificates ride,
but tagged `committee=False` (client keys are never in the validator
table) and `dedup=False` — and only then forwarded into the
PayloadMaker's transaction queue, the exact seam the raw Front feeds.

Why `dedup=False`: the verified-signature LRU exists for consensus
certificates, where one vote signature legitimately recurs across its
QC's many appearances. Client transactions never legitimately repeat —
a repeat is a replay, and the admission nonce filter rejects it before
any crypto. Keeping client traffic out of the cache both preserves the
cache for the certificate working set and closes a poisoning lever (a
million distinct client txs would otherwise evict every consensus
entry). It is also what makes the acceptance criterion measurable: under
ingress load, `ingress.verified_sigs` advances while the dedup cache
stays untouched by the client lane.

Backpressure is end-to-end: if the mempool's transaction queue is full,
`deliver.put` blocks the drain loop → lanes fill → admission sheds with
retry-after. Nothing in the client path can grow without bound.

Every stage stamps the PR 5 trace plane (`ingress.*` events, trace id
derived from the transaction digest like the payload lane) and counts
into the `ingress.*` metric namespace.
"""

from __future__ import annotations

import asyncio
import logging

from ..crypto.batch_service import BatchVerificationService
from ..utils import metrics, tracing
from ..utils.actors import spawn
from . import messages
from .admission import AdmissionController, IngressConfig
from .messages import ClientTransaction, IngressResponse

log = logging.getLogger("hotstuff.ingress")

_M_RECEIVED = metrics.counter("ingress.received")
_M_VERIFIED = metrics.counter("ingress.verified_sigs")
_M_REJECTED = metrics.counter("ingress.rejected_sigs")
_M_FORWARDED = metrics.counter("ingress.forwarded")
_M_VERIFY_BATCH = metrics.histogram(
    "ingress.verify_batch_size", metrics.SIZE_BUCKETS
)
_M_LATENCY = metrics.histogram("ingress.latency_s")

LOG_EVERY = 10_000  # shed/reject log cadence


class IngressPipeline:
    """One per node. `deliver` is the PayloadMaker's tx queue (or any
    bounded sink); `service` is the node's BatchVerificationService."""

    def __init__(
        self,
        service: BatchVerificationService,
        deliver: asyncio.Queue,
        config: IngressConfig | None = None,
        proof_registry=None,
    ) -> None:
        self.service = service
        self.deliver = deliver
        # Commit-proof serving plane (proofs/registry.py): when wired,
        # every VERIFIED-accepted transaction's (client, nonce) → digest
        # mapping is recorded just before its body enters the mempool
        # lane — the first link of the submit→commit→proof chain.
        self.proof_registry = proof_registry
        self.admission = AdmissionController(config)
        self._pending = asyncio.Event()  # set whenever a lane has work
        self._task: asyncio.Task | None = None
        self.stats = {"received": 0, "accepted": 0, "responded": 0}

    def _ensure_task(self) -> None:
        if self._task is None or self._task.done():
            # actors.spawn: the drain loop joins the creating scope, so a
            # chaos crash of the owning node tears it down too.
            self._task = spawn(self._run(), name="ingress-drain")

    # -- submission ----------------------------------------------------------

    async def submit(self, tx: ClientTransaction) -> IngressResponse:
        """Submit one client transaction; resolves to its response once
        admission rejects it (immediately) or its verification batch
        completes and the body is in the mempool queue."""
        self._ensure_task()
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        _M_RECEIVED.inc()
        self.stats["received"] += 1
        if tracing.enabled():
            tracing.event("ingress.recv", tracing.trace_id(0, tx.digest().data))
        future = loop.create_future()
        lane, status, retry_ms = self.admission.admit(tx, (tx, t0, future))
        if lane is None:
            if tracing.enabled():
                kind = (
                    "ingress.shed" if status == messages.SHED else "ingress.reject"
                )
                tracing.event(
                    kind,
                    tracing.trace_id(0, tx.digest().data),
                    status=messages.STATUS_NAMES.get(status, status),
                    retry_after_ms=retry_ms,
                )
            shed = self.admission.shed
            if status == messages.SHED and shed % LOG_EVERY == 1:
                log.warning(
                    "ingress overloaded: %s transactions shed with "
                    "retry-after backpressure", shed,
                )
            _M_LATENCY.record(loop.time() - t0)
            return IngressResponse(tx.nonce, status, retry_ms)
        if tracing.enabled():
            tracing.event(
                "ingress.admit", tracing.trace_id(0, tx.digest().data), lane=lane
            )
        self._pending.set()
        resp = await future
        _M_LATENCY.record(loop.time() - t0)
        return resp

    # -- drain loop ----------------------------------------------------------

    async def _run(self) -> None:
        cfg = self.admission.config
        loop = asyncio.get_running_loop()
        while True:
            batch = self.admission.take(cfg.verify_batch)
            if not batch:
                self._pending.clear()
                await self._pending.wait()
                continue
            msgs = [tx.digest().data for tx, _t0, _f in batch]
            pairs = [(tx.client, tx.signature) for tx, _t0, _f in batch]
            _M_VERIFY_BATCH.record(len(batch))
            trace = None
            if tracing.enabled():
                # Batch-head trace id: tags the group's verify.batch event
                # so trace_report's verify-lane table attributes ingress
                # queueing delay alongside the consensus lane's.
                trace = tracing.trace_id(0, batch[0][0].digest().data)
                tracing.event("ingress.verify", trace, n=len(batch))
            try:
                mask = await self.service.verify_group(
                    msgs, pairs, urgent=False, committee=False, dedup=False,
                    source="ingress", trace=trace,
                )
            except Exception as e:
                # A backend failure must not wedge clients: fail the whole
                # batch as BAD_SIGNATURE (conservative — nothing unverified
                # ever reaches the mempool) and keep draining.
                log.warning("ingress verification dispatch failed: %r", e)
                mask = [False] * len(batch)
            accepted = 0
            for (tx, _t0, future), ok in zip(batch, mask):
                if ok:
                    _M_VERIFIED.inc()
                    accepted += 1
                    if self.proof_registry is not None:
                        self.proof_registry.note_tx(
                            tx.client, tx.nonce, tx.digest(), body=tx.body
                        )
                    # Bounded sink: blocking here is the backpressure path
                    # (lanes fill behind us, admission sheds with
                    # retry-after) — the one place ingress may wait.
                    await self.deliver.put(tx.body)
                    _M_FORWARDED.inc()
                    if tracing.enabled():
                        tracing.event(
                            "ingress.forward", tracing.trace_id(0, tx.digest().data)
                        )
                    resp = IngressResponse(tx.nonce, messages.ACCEPTED)
                else:
                    _M_REJECTED.inc()
                    self.admission.forget(tx)  # failed sigs release the nonce
                    if tracing.enabled():
                        tracing.event(
                            "ingress.reject",
                            tracing.trace_id(0, tx.digest().data),
                            status="bad_signature",
                        )
                    resp = IngressResponse(tx.nonce, messages.BAD_SIGNATURE)
                if not future.done():
                    future.set_result(resp)
                self.stats["responded"] += 1
            self.stats["accepted"] += accepted
            self.admission.note_drained(len(batch), loop.time())
            if cfg.verify_interval:
                # Deliberate drain pacing (see IngressConfig): capacity =
                # verify_batch / verify_interval tx/s.
                await asyncio.sleep(cfg.verify_interval)
