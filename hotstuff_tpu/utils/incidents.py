"""Run-level incident plane: fault→alert→recovery attribution (§5.5r).

The chaos plane can inject faults (plan crash windows, partitions, lossy
links, floods, boundary crashes, epoch switches) and the fleet can fire
alerts (the telemetry plane's two-window SLO burn evaluator, the
AnomalyWatchdog's stall/backpressure/handoff reasons) — this module is
the ledger that connects the two, on the run's virtual clock, after the
fact and from report data alone:

  * **Fault windows** — `(kind, start, end, nodes)` intervals extracted
    from the orchestrator's report: crash/restart event pairs, plan
    partitions, lossy links (drop/duplicate/reorder > 0 — pure
    delay/jitter is geometry, not a fault), late boots, epoch switches,
    plus the injected-load windows (flood, ingress spike) the
    orchestrator passes explicitly because their parameters never land
    in the report. `end=None` means the fault was never healed.
  * **Alert spans** — `(class, name, node, fired, cleared)` from every
    node's telemetry `alerts` stream (SLO fire/clear pairs; a fire with
    no clear is a RESIDUAL span) and the process-global watchdog
    triggers (instantaneous spans; `slo_burn` triggers are skipped —
    they mirror the plane's own fired alert through `note_slo_burn`).
  * **Attribution** — interval overlap: an alert attributes to a fault
    window iff it FIRED inside `[start, end + grace]` (grace =
    `ATTRIBUTION_GRACE_S`: burn windows and backlog drain legitimately
    trail the fault) on a node the window covers. When several windows
    match, the latest-starting one wins — the innermost fault of a
    nested pair is the proximate cause. Alerts no window explains land
    in an explicit **unattributed** class: those are findings, not
    noise, and scenarios pin `unattributed == 0`.

Every fault window becomes one **incident** row — including alert-less
ones (the undetected class). Per incident: `mttd_s` (first attributed
fire − window start), `mttr_s` (last attributed clear − window start;
None while any attributed span is residual), and a `residual` flag.
Fleet MTTD/MTTR percentiles per fault class merge the per-node samples
through `telemetry.merge_lane_summaries` (fault classes as lanes), so
the rollup carries the same worst-node attribution as every other
fleet percentile. The **burn budget** sums seconds-in-violation per
SLO row (span seconds, unclosed spans run to end-of-run) against a
scenario-declared per-row budget; the `health` verdict block —
embedded in every chaos report and `fleet_rollup` — is green iff
`unattributed == 0` and every declared budget row is within budget.

Determinism contract: the ledger is a pure function of report data
(virtual-clock timestamps, already rounded to 6 dp at the source),
every collection is sorted before use, and nothing here reads the wall
clock — a same-seed rerun yields a bit-identical ledger, which
tests/test_incidents.py pins.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from . import metrics
from .telemetry import merge_lane_summaries

log = logging.getLogger("hotstuff.incidents")

__all__ = [
    "ATTRIBUTION_GRACE_S",
    "WATCHDOG_ALERT_CLASSES",
    "FaultWindow",
    "AlertSpan",
    "fault_windows_from_report",
    "alert_spans_from_report",
    "build_ledger",
    "report_ledger",
    "record_metrics",
    "log_ledger",
]

# An alert may legitimately trail the fault that explains it (burn
# evaluation windows, backlog drain): a fire within this many virtual
# seconds after a window closes still attributes to it. One constant for
# every scenario — per-scenario grace would make MTTD/MTTR figures
# non-comparable across matrix revisions.
ATTRIBUTION_GRACE_S = 5.0

# Every AnomalyWatchdog reason string resolves to a ledger alert class
# (the graftlint `incidents` pass enforces completeness against the
# `_trigger(...)` call sites in utils/tracing.py — an unmapped reason
# would silently fall out of attribution).
WATCHDOG_ALERT_CLASSES: dict[str, str] = {
    "round_stall": "stall",
    "backpressure": "backpressure",
    "slo_burn": "slo_burn",
    "handoff_violation": "handoff",
    "verify_regression": "verify",
}

_M_OPENED = metrics.counter("incident.opened")
_M_ATTRIBUTED = metrics.counter("incident.attributed")
_M_UNATTRIBUTED = metrics.counter("incident.unattributed")
_M_MTTD = metrics.histogram("incident.mttd_s")
_M_MTTR = metrics.histogram("incident.mttr_s")
_M_BURN = metrics.histogram("incident.budget_burn_s")


@dataclass(frozen=True)
class FaultWindow:
    """One injected disruption on the virtual clock. `end=None` = never
    healed (open at run end); `nodes=None` = fleet-wide."""

    kind: str
    start: float
    end: float | None = None
    nodes: tuple[int, ...] | None = None


@dataclass(frozen=True)
class AlertSpan:
    """One alert lifetime. `cleared=None` = residual (never cleared);
    `node=None` = process-global (the shared watchdog)."""

    alert_class: str
    name: str
    node: int | None
    fired: float
    cleared: float | None = None


def _link_is_faulty(link: dict) -> bool:
    # drop/duplicate/reorder mutate traffic; delay/jitter shape it —
    # healthy scenarios run 10-150 ms links, which must not become a
    # run-long window that attributes every alert by construction.
    return any(
        float(link.get(k) or 0.0) > 0.0
        for k in ("drop", "duplicate", "reorder")
    )


def fault_windows_from_report(
    report: dict, extra: tuple[FaultWindow, ...] = ()
) -> list[FaultWindow]:
    """Extract every injected fault window from a chaos report: the plan
    (partitions, lossy links), the event stream (crash/restart pairs at
    their EXECUTED times — covers boundary crashes too — plus late
    boots and epoch switches), and any `extra` windows the orchestrator
    knows about that the report does not parameterize (flood/ingress
    spans)."""
    windows: list[FaultWindow] = list(extra)
    run_end = float(report.get("virtual_seconds") or 0.0)
    plan = report.get("plan") or {}
    if _link_is_faulty(plan.get("default_link") or {}):
        windows.append(FaultWindow("link_fault", 0.0, run_end, None))
    lossy_pair_nodes: set[int] = set()
    for key, link in sorted((plan.get("links") or {}).items()):
        if _link_is_faulty(link or {}):
            src, _, dst = key.partition("->")
            lossy_pair_nodes.update((int(src), int(dst)))
    if lossy_pair_nodes:
        windows.append(
            FaultWindow(
                "link_fault", 0.0, run_end, tuple(sorted(lossy_pair_nodes))
            )
        )
    for p in plan.get("partitions") or ():
        nodes = tuple(sorted({n for g in p["groups"] for n in g}))
        windows.append(
            FaultWindow(
                "partition", float(p["start"]), float(p["end"]), nodes or None
            )
        )
    open_crash: dict[int, float] = {}
    epoch_ts: dict[int, list[float]] = {}
    for ev in report.get("events") or ():
        kind, t = ev.get("event"), float(ev.get("t") or 0.0)
        node = ev.get("node")
        if kind == "crash" and node not in open_crash:
            open_crash[node] = t
        elif kind == "restart" and node in open_crash:
            windows.append(
                FaultWindow("crash", open_crash.pop(node), t, (node,))
            )
        elif kind == "boot":
            # A late boot's disruption is the ABSENCE before it: the
            # window runs from genesis to the boot instant.
            windows.append(FaultWindow("late_boot", 0.0, t, (node,)))
        elif kind == "epoch_switch":
            epoch_ts.setdefault(int(ev["epoch"]), []).append(t)
    for node, t in sorted(open_crash.items()):
        windows.append(FaultWindow("crash", t, None, (node,)))
    for _epoch, ts in sorted(epoch_ts.items()):
        # The switch lands per node; the fleet-wide window spans first
        # to last observation (handoff alerts attribute here).
        windows.append(FaultWindow("epoch_switch", min(ts), max(ts), None))
    return sorted(windows, key=_window_sort_key)


def _window_sort_key(w: FaultWindow):
    return (
        w.start,
        w.end is None,
        w.end if w.end is not None else 0.0,
        w.kind,
        w.nodes if w.nodes is not None else (),
    )


def alert_spans_from_report(report: dict) -> list[AlertSpan]:
    """Fold every node's telemetry alert stream (fire/clear pairs, FIFO
    per SLO) plus the watchdog trigger list into sorted AlertSpans."""
    spans: list[AlertSpan] = []
    for label, dump in sorted(
        (report.get("telemetry") or {}).items(), key=lambda kv: str(kv[0])
    ):
        node = int(label)
        open_fires: dict[str, list[float]] = {}
        for a in dump.get("alerts") or ():
            slo = str(a.get("slo"))
            if a.get("event") == "fired":
                open_fires.setdefault(slo, []).append(float(a["t"]))
            elif a.get("event") == "cleared" and open_fires.get(slo):
                fired = open_fires[slo].pop(0)
                spans.append(
                    AlertSpan("slo_burn", slo, node, fired, float(a["t"]))
                )
        for slo, fires in sorted(open_fires.items()):
            spans.extend(
                AlertSpan("slo_burn", slo, node, fired, None)
                for fired in fires
            )
    for trig in report.get("watchdog_triggers") or ():
        reason = str(trig.get("reason"))
        if reason == "slo_burn":
            # The watchdog's slo_burn trigger is the telemetry plane's
            # own fired alert relayed through note_slo_burn — counting
            # both would double every burn in the ledger.
            continue
        cls = WATCHDOG_ALERT_CLASSES.get(reason, reason)
        t = float(trig.get("t") or 0.0)
        spans.append(AlertSpan(cls, reason, None, t, t))
    return sorted(
        spans,
        key=lambda s: (
            s.fired,
            s.alert_class,
            s.name,
            -1 if s.node is None else s.node,
        ),
    )


def _pct_summary(vals: list[float]) -> dict:
    return {
        "count": len(vals),
        "p50_ms": round(metrics.percentile(vals, 0.50), 3),
        "p99_ms": round(metrics.percentile(vals, 0.99), 3),
        "max_ms": round(max(vals), 3),
    }


def _fleet_percentiles(samples: dict[str, dict[str, list[float]]]) -> dict:
    """{node_label: {fault_class: [ms samples]}} -> fleet percentiles per
    fault class via merge_lane_summaries (fault classes as lanes), so
    MTTD/MTTR roll up exactly like every other fleet latency figure —
    worst-node attribution included."""
    per_node = {
        node: {kind: _pct_summary(vals) for kind, vals in by_kind.items()}
        for node, by_kind in sorted(samples.items())
    }
    return merge_lane_summaries(per_node)


def build_ledger(
    windows: list[FaultWindow],
    alerts: list[AlertSpan],
    *,
    run_end: float,
    budget: dict[str, float] | None = None,
    grace: float = ATTRIBUTION_GRACE_S,
) -> dict:
    """Attribute every alert span to a fault window (or the unattributed
    class) and materialize the ledger: incident rows, fleet MTTD/MTTR
    percentiles per fault class, the per-SLO burn budget, and the
    `health` verdict block."""
    windows = sorted(windows, key=_window_sort_key)
    attributed: list[list[AlertSpan]] = [[] for _ in windows]
    unattributed: list[AlertSpan] = []
    for a in sorted(
        alerts,
        key=lambda s: (
            s.fired,
            s.alert_class,
            s.name,
            -1 if s.node is None else s.node,
        ),
    ):
        best: int | None = None
        for idx, w in enumerate(windows):
            end = w.end if w.end is not None else run_end
            if not (w.start <= a.fired <= end + grace):
                continue  # alert-before-fault is NEVER explained by it
            if (
                w.nodes is not None
                and a.node is not None
                and a.node not in w.nodes
            ):
                continue
            # Windows are start-sorted: keeping the last match selects
            # the latest-starting cover — the innermost of nested faults.
            best = idx
        if best is None:
            unattributed.append(a)
        else:
            attributed[best].append(a)

    rows: list[dict] = []
    mttd_samples: dict[str, dict[str, list[float]]] = {}
    mttr_samples: dict[str, dict[str, list[float]]] = {}
    for w, spans in zip(windows, attributed):
        first_fired = min((a.fired for a in spans), default=None)
        residual = any(a.cleared is None for a in spans)
        clears = [a.cleared for a in spans if a.cleared is not None]
        mttd = (
            round(first_fired - w.start, 6)
            if first_fired is not None
            else None
        )
        mttr = (
            round(max(clears) - w.start, 6)
            if spans and not residual
            else None
        )
        classes: dict[str, int] = {}
        for a in spans:
            classes[a.alert_class] = classes.get(a.alert_class, 0) + 1
        rows.append(
            {
                "kind": w.kind,
                "start": round(w.start, 6),
                "end": round(w.end, 6) if w.end is not None else None,
                "nodes": list(w.nodes) if w.nodes is not None else None,
                "alerts": len(spans),
                "alert_classes": dict(sorted(classes.items())),
                "mttd_s": mttd,
                "mttr_s": mttr,
                "residual": residual,
            }
        )
        # Per-node samples: detection = the node's FIRST attributed fire,
        # recovery = its LAST clear (skipped while it holds a residual
        # span) — merged fleet-wide below with fault classes as lanes.
        by_node: dict[str, list[AlertSpan]] = {}
        for a in spans:
            label = "watchdog" if a.node is None else str(a.node)
            by_node.setdefault(label, []).append(a)
        for label, node_spans in sorted(by_node.items()):
            d_ms = (min(s.fired for s in node_spans) - w.start) * 1000.0
            mttd_samples.setdefault(label, {}).setdefault(w.kind, []).append(
                d_ms
            )
            if all(s.cleared is not None for s in node_spans):
                r_ms = (
                    max(s.cleared for s in node_spans) - w.start
                ) * 1000.0
                mttr_samples.setdefault(label, {}).setdefault(
                    w.kind, []
                ).append(r_ms)

    burn_s: dict[str, float] = {}
    for a in sorted(alerts, key=lambda s: (s.name, s.fired)):
        if a.alert_class != "slo_burn":
            continue
        t1 = a.cleared if a.cleared is not None else run_end
        burn_s[a.name] = burn_s.get(a.name, 0.0) + max(0.0, t1 - a.fired)
    burn: dict[str, dict] = {}
    over_budget = 0
    for slo in sorted(set(burn_s) | set(budget or {})):
        declared = None if budget is None else budget.get(slo)
        burned = round(burn_s.get(slo, 0.0), 6)
        within = None if declared is None else burned <= declared
        if within is False:
            over_budget += 1
        burn[slo] = {
            "burn_s": burned,
            "budget_s": declared,
            "within_budget": within,
        }

    health = {
        "incidents": len(rows),
        "detected": sum(1 for r in rows if r["alerts"]),
        "alerts_attributed": sum(r["alerts"] for r in rows),
        "alerts_unattributed": len(unattributed),
        "residual": sum(1 for r in rows if r["residual"]),
        "mttd": _fleet_percentiles(mttd_samples),
        "mttr": _fleet_percentiles(mttr_samples),
        "burn": burn,
        "burn_budget_ok": over_budget == 0,
        "ok": not unattributed and over_budget == 0,
    }
    return {
        "v": 1,
        "grace_s": grace,
        "incidents": rows,
        "unattributed": [
            {
                "class": a.alert_class,
                "name": a.name,
                "node": a.node,
                "fired": round(a.fired, 6),
                "cleared": (
                    round(a.cleared, 6) if a.cleared is not None else None
                ),
            }
            for a in unattributed
        ],
        "health": health,
    }


def report_ledger(
    report: dict,
    extra_windows: tuple[FaultWindow, ...] = (),
    budget: dict[str, float] | None = None,
) -> dict:
    """The one-call form the orchestrator (and offline tools replaying a
    report) use: extract windows + spans from the report and build."""
    return build_ledger(
        fault_windows_from_report(report, extra_windows),
        alert_spans_from_report(report),
        run_end=float(report.get("virtual_seconds") or 0.0),
        budget=budget,
    )


def worst_mttr_ms(ledger: dict) -> float:
    """Largest incident recovery time in ms (0.0 when nothing cleared)."""
    return round(
        max(
            (
                r["mttr_s"]
                for r in ledger.get("incidents", ())
                if r.get("mttr_s") is not None
            ),
            default=0.0,
        )
        * 1000.0,
        3,
    )


def record_metrics(ledger: dict) -> None:
    """Land the ledger in the `incident.*` namespace rows (the scenario
    delta surface — run_scenario folds these into `report['metrics']`)."""
    health = ledger["health"]
    _M_OPENED.inc(health["incidents"])
    _M_ATTRIBUTED.inc(health["alerts_attributed"])
    _M_UNATTRIBUTED.inc(health["alerts_unattributed"])
    for row in ledger["incidents"]:
        if row["mttd_s"] is not None:
            _M_MTTD.record(row["mttd_s"])
        if row["mttr_s"] is not None:
            _M_MTTR.record(row["mttr_s"])
    for b in health["burn"].values():
        _M_BURN.record(b["burn_s"])


def log_ledger(ledger: dict) -> None:
    """Emit the scrapeable surface (benchmark/logs.py's `+ INCIDENTS:`
    section greps these exact shapes): one line per incident, the
    one-line ledger summary, per-row burn-budget lines for declared
    rows, and the burn verdict."""
    health = ledger["health"]
    for row in ledger["incidents"]:
        log.info(
            "Incident %s: window %.3f-%ss nodes %s, %d alert(s), "
            "MTTD %s, MTTR %s%s",
            row["kind"],
            row["start"],
            "open" if row["end"] is None else f"{row['end']:.3f}",
            "fleet" if row["nodes"] is None else row["nodes"],
            row["alerts"],
            "-" if row["mttd_s"] is None else f"{row['mttd_s'] * 1e3:.1f} ms",
            "-" if row["mttr_s"] is None else f"{row['mttr_s'] * 1e3:.1f} ms",
            " RESIDUAL" if row["residual"] else "",
        )
    log.info(
        "Incident ledger: %d incident(s), %d alert(s) attributed, "
        "%d unattributed, %d residual, worst MTTR %.1f ms",
        health["incidents"],
        health["alerts_attributed"],
        health["alerts_unattributed"],
        health["residual"],
        worst_mttr_ms(ledger),
    )
    over = 0
    for slo, b in sorted(health["burn"].items()):
        if b["budget_s"] is None:
            continue
        if b["within_budget"] is False:
            over += 1
        log.info(
            "Burn budget %s: %.3f s burned of %.3f s budget (%s)",
            slo,
            b["burn_s"],
            b["budget_s"],
            "within" if b["within_budget"] else "OVER",
        )
    log.info(
        "Burn budget verdict: %s (%d SLO row(s) over budget)",
        "ok" if health["burn_budget_ok"] else "violated",
        over,
    )
