"""Causal tracing + flight recorder for cross-node commit-latency attribution.

PR 1's metrics are per-process AGGREGATES: they can say "commit latency
p99 regressed" but not "where did block B spend its time across the
committee" or "what was this node doing in the 5 s before the stall" —
the round-5 liveness bug took six ad-hoc instrumented reruns to
root-cause for exactly that reason. This module adds the three missing
pieces:

  * **Causal trace context** — `TraceContext(round, digest8, hop)`, a
    compact 18-byte token identifying one block's journey. It rides an
    optional TRAILER on the existing 4-byte-length network frames
    (`network/net.py`): the trailer lives INSIDE the framed payload,
    self-delimited by a magic suffix, so trailer-less frames (older
    peers, tracing disabled) parse unchanged and trailered frames are
    stripped before the codec sees them. The trace id is derivable from
    protocol content (round + block-digest prefix), so every layer can
    stamp events for a block WITHOUT threading a context object through
    the actor channels; the trailer's job is the frame-level receive
    stamp and the hop counter.

  * **Flight recorder** — a process-global fixed-size ring buffer of
    structured events (stage events, timer arms/fires, backpressure
    transitions, chaos fault injections). Recording is one deque.append
    under the GIL (no lock, O(1), oldest evicted by maxlen) and is gated
    on a module flag exactly like `HOTSTUFF_METRICS=0`: disabled-mode
    `event()` is a single global read and an early return. Dumps go to
    JSON on demand, on exit/SIGTERM (`node run --trace-out`), and
    automatically when the anomaly watchdog fires.

  * **Anomaly watchdog** — fires a recorder dump when the protocol looks
    wedged: a round stalled past N consecutive timeouts, a sustained
    egress cold-lane backpressure window, or a verify-throughput
    regression vs the run's own baseline. The dump then CONTAINS the
    events leading up to the anomaly — a replayable artifact instead of
    an instrumented rerun.

Event times use a pluggable clock (default `time.monotonic`); the chaos
runner points it at its virtual-time loop so recorded timelines match
the deterministic replay. Dumps carry a (mono, wall) anchor pair so
`tools/trace_report.py` can align rings from different processes.

Canonical stage vocabulary: the six per-block lifecycle stages
(`STAGES`) plus the auxiliary event kinds (`EVENT_KINDS`). Like the
metric namespace, this is the schema of record — the graftlint
`namespace` pass
fails any string-literal `tracing.event` kind that is not registered
here.

Dependency-free by design: stdlib + utils.metrics only (no jax, no
asyncio import at module level).
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import struct
import threading
import time
from collections import deque
from typing import Callable

from . import metrics

log = logging.getLogger("hotstuff.tracing")

__all__ = [
    "TraceContext",
    "FlightRecorder",
    "AnomalyWatchdog",
    "RECORDER",
    "WATCHDOG",
    "NODE_LABEL",
    "STAGES",
    "EVENT_KINDS",
    "TRAILER_MAGIC",
    "TRAILER_SIZE",
    "enabled",
    "enable",
    "set_clock",
    "event",
    "trace_id",
    "context_for",
    "note_received",
    "strip_trailer",
    "dump",
    "write_json",
    "reset",
]

# The six per-block lifecycle stages stitched into the commit-latency
# breakdown (ISSUE order: proposal -> payload-fetch -> verify -> vote ->
# QC-assembly -> commit).
STAGES: tuple[str, ...] = (
    "propose", "payload", "verify", "vote", "qc", "commit",
)

# Auxiliary event kinds the recorder accepts (everything `event()` may be
# called with; the lint enforces literals against this set).
EVENT_KINDS: frozenset[str] = frozenset(STAGES) | {
    "net.send",
    "net.recv",
    "net.probe",
    "timer.arm",
    "timer.fire",
    "timeout",
    "sync.request",
    "sync.retry",
    "payload.gossip",
    "payload.stored",
    "payload.served",
    "ingress.recv",
    "ingress.admit",
    "ingress.shed",
    "ingress.verify",
    "ingress.forward",
    "ingress.reject",
    "verify.batch",
    "agg.bundle",
    "agg.fallback",
    "backpressure.on",
    "backpressure.off",
    "chaos.fault",
    "chaos.crash",
    "chaos.restart",
    "watchdog.round_stall",
    "watchdog.verify_regression",
    "watchdog.backpressure",
    "watchdog.slo_burn",
    "slo.clear",
    "dump",
}

_M_EVENTS = metrics.counter("trace.events")
_M_DROPPED = metrics.counter("trace.dropped")
_M_DUMPS = metrics.counter("trace.dumps")
_M_TRIGGERS = metrics.counter("trace.watchdog_triggers")
_M_FRAMES_STRIPPED = metrics.counter("trace.frames_stripped")

_enabled = os.environ.get("HOTSTUFF_TRACE", "1") != "0"

# Pluggable clock: production uses the monotonic clock; the chaos
# orchestrator installs its virtual-time loop's `loop.time` so recorded
# timelines follow the deterministic replay.
_clock: Callable[[], float] = time.monotonic

# Which logical node is executing (an index in the chaos runner, a name
# in a real node process). Inherited by every task/thread spawned while
# set, so one in-process recorder can attribute events per node.
NODE_LABEL: contextvars.ContextVar[object | None] = contextvars.ContextVar(
    "trace-node-label", default=None
)


def enabled() -> bool:
    return _enabled


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def set_clock(fn: Callable[[], float] | None) -> Callable[[], float]:
    """Install a clock for event timestamps; returns the previous one.
    Pass None to restore the default monotonic clock."""
    global _clock
    prev, _clock = _clock, (fn or time.monotonic)
    return prev


# ---------------------------------------------------------------------------
# Trace context + frame trailer


def trace_id(round_: int, digest: bytes) -> str:
    """Canonical trace id for one block: round + 8-byte digest prefix.
    Derivable anywhere the block (or its QC / a vote on it) is in hand."""
    return f"r{round_}-{digest[:8].hex()}"


# Trailer layout (appended INSIDE the 4-byte-length frame):
#   [0x01 version][round u64 BE][digest prefix 8B][hop u8][4B magic]
# Detection keys on the magic suffix + version byte: a trailer-less frame
# whose payload happens to end with these 5 bytes misparses with
# probability ~2^-40 per frame — accepted (the trailer is observability,
# never a correctness dependency).
TRAILER_MAGIC = b"\x9c\x54\x52\x31"  # \x9c 'TR1'
_CTX = struct.Struct(">BQ8sB")
TRAILER_SIZE = _CTX.size + len(TRAILER_MAGIC)  # 22 bytes


class TraceContext:
    """Compact causal token: (round, block-digest prefix, hop counter)."""

    __slots__ = ("round", "digest8", "hop")

    def __init__(self, round_: int, digest8: bytes, hop: int = 0) -> None:
        self.round = round_
        self.digest8 = bytes(digest8[:8]).ljust(8, b"\0")
        self.hop = min(hop, 255)

    @property
    def trace_id(self) -> str:
        return f"r{self.round}-{self.digest8.hex()}"

    def encode(self) -> bytes:
        return _CTX.pack(1, self.round, self.digest8, self.hop)

    @staticmethod
    def decode(data: bytes) -> "TraceContext":
        ver, round_, digest8, hop = _CTX.unpack(data)
        if ver != 1:
            raise ValueError(f"unknown trace-context version {ver}")
        return TraceContext(round_, digest8, hop)

    def trailer(self) -> bytes:
        return self.encode() + TRAILER_MAGIC

    def __repr__(self) -> str:
        return f"TraceContext({self.trace_id}, hop={self.hop})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, TraceContext)
            and self.round == other.round
            and self.digest8 == other.digest8
            and self.hop == other.hop
        )


def strip_trailer(
    data: bytes, count: bool = True
) -> tuple[bytes, TraceContext | None]:
    """Split one framed payload into (codec bytes, trace context or None).
    Trailer-less frames pass through untouched, so trailer-enabled and
    trailer-less peers interoperate in both directions. `count=False`
    skips the inbound-frame counter (for send-side peeks — the chaos
    transport strips for its adversary policies and re-appends)."""
    if len(data) >= TRAILER_SIZE and data.endswith(TRAILER_MAGIC):
        try:
            ctx = TraceContext.decode(data[-TRAILER_SIZE:-len(TRAILER_MAGIC)])
        except (ValueError, struct.error):
            return data, None
        if count:
            _M_FRAMES_STRIPPED.inc()
        return data[:-TRAILER_SIZE], ctx
    return data, None


# Received-hop memory: trace_id -> hop of the last inbound frame carrying
# it, so a relayed message (vote for a received proposal) can extend the
# causal chain instead of restarting it. Bounded insertion-ordered dict.
_HOP_CAP = 1024
_hops: dict[str, int] = {}
_hops_lock = threading.Lock()


def note_received(ctx: TraceContext) -> None:
    """Record an inbound context (called by NetReceiver / the chaos
    transport after stripping a trailer)."""
    with _hops_lock:
        _hops[ctx.trace_id] = ctx.hop
        while len(_hops) > _HOP_CAP:
            _hops.pop(next(iter(_hops)))


def context_for(round_: int, digest: bytes) -> TraceContext:
    """Context for an OUTBOUND message about block (round, digest): hop
    extends the received chain when this node saw the block arrive, else
    starts at 0 (this node originated it)."""
    ctx = TraceContext(round_, digest)
    with _hops_lock:
        prev = _hops.get(ctx.trace_id)
    if prev is not None:
        ctx.hop = min(prev + 1, 255)
    return ctx


# ---------------------------------------------------------------------------
# Flight recorder


class FlightRecorder:
    """Fixed-size ring of structured events.

    Recording is a single `deque.append` (thread-safe under the GIL,
    maxlen evicts the oldest) — cheap enough for per-frame and per-stage
    stamping on the hot path. `dump()` snapshots the ring without
    stopping writers (a torn tail of one in-flight event is acceptable
    for a diagnostic artifact; a lock on the hot path is not)."""

    def __init__(self, capacity: int | None = None) -> None:
        if capacity is None:
            try:
                capacity = int(os.environ.get("HOTSTUFF_TRACE_RING", "16384"))
            except ValueError:
                capacity = 16384
        self.capacity = max(16, capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._count = 0  # total ever recorded (dropped = count - len)

    _USE_CTX = object()  # record(): default = read NODE_LABEL

    def record(
        self,
        kind: str,
        trace: str | None = None,
        dur: float | None = None,
        data: dict | None = None,
        label: object = _USE_CTX,
    ) -> None:
        if not _enabled:
            return
        self._count += 1
        _M_EVENTS.inc()
        if self._count > self.capacity:
            _M_DROPPED.inc()
        if label is self._USE_CTX:
            label = NODE_LABEL.get()
        self._ring.append((_clock(), label, kind, trace, dur, data))

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def dropped(self) -> int:
        return max(0, self._count - self.capacity)

    def events(self, node: object | None = None, limit: int | None = None) -> list[dict]:
        """Snapshot as dicts, optionally filtered to one node label and
        capped to the most recent `limit` events."""
        out = []
        for t, label, kind, trace, dur, data in list(self._ring):
            if node is not None and label != node:
                continue
            e: dict = {"t": round(t, 6), "kind": kind}
            if label is not None:
                e["node"] = label
            if trace is not None:
                e["trace"] = trace
            if dur is not None:
                e["dur"] = round(dur, 6)
            if data:
                e["data"] = data
            out.append(e)
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def dump(self, node: object | None = None) -> dict:
        """Full structured artifact. The (mono, wall) anchor pair lets
        `tools/trace_report.py` align rings dumped by different
        processes onto one wall-clock timeline."""
        _M_DUMPS.inc()
        return {
            "v": 1,
            "enabled": _enabled,
            "node": node if node is not None else NODE_LABEL.get(),
            "capacity": self.capacity,
            "recorded": self._count,
            "dropped": self.dropped,
            # graftlint: allow[determinism] dump-alignment stamp (merges per-process dumps onto one wall timeline)
            "anchor": {"mono": _clock(), "wall": time.time()},
            "events": self.events(node=node),
        }

    def write_json(self, path: str, node: object | None = None) -> None:
        with open(path, "w") as f:
            json.dump(self.dump(node=node), f, indent=2, sort_keys=True)
            f.write("\n")

    def reset(self) -> None:
        self._ring.clear()
        self._count = 0


RECORDER = FlightRecorder()


def event(
    kind: str,
    trace: str | None = None,
    dur: float | None = None,
    **data,
) -> None:
    """Record one event into the process flight recorder. Hot paths pass
    positional (kind, trace, dur) only — the kwargs dict is for cold
    sites. Disabled mode is a single global read + return."""
    if not _enabled:
        return
    RECORDER.record(kind, trace, dur, data or None)


def dump() -> dict:
    return RECORDER.dump()


def write_json(path: str) -> None:
    RECORDER.write_json(path)


# ---------------------------------------------------------------------------
# Anomaly watchdog


class AnomalyWatchdog:
    """Event-driven anomaly detector that triggers recorder dumps.

    Layers feed it observations (no polling thread — it must work
    unmodified under the chaos runner's virtual clock):

      * `note_timeout(round, consecutive)` — consensus pacemaker firings;
        `consecutive >= stall_timeouts` means the round is wedged beyond
        the ordinary crash-fault view-change (2 per rotation).
      * `note_backpressure(active)` — egress cold-lane backpressure
        transitions from the payload maker; active for longer than
        `backpressure_s` means gossip fan-out cannot reach a majority.
      * `note_verify(dur_s, n)` — per-flush verification cost from the
        BatchVerificationService; a sustained per-signature cost above
        `p99_factor` x the run's own baseline (the MEDIAN of the first
        BASELINE_SAMPLES flushes — cold-compile outliers must not poison
        it) is a verify regression (device fell back to host, relay
        degraded, ...).

    Each reason fires at most once per `cooldown_s`; firing records a
    `watchdog.<reason>` event and invokes every registered dump hook
    with (reason, detail). `node/main.py` installs a file-writing hook
    next to `--trace-out`; the chaos orchestrator captures dumps into
    its report.
    """

    # note_verify: samples to average into the baseline, and consecutive
    # regressed flushes required before firing (one slow flush is noise).
    BASELINE_SAMPLES = 32
    REGRESSION_STREAK = 8

    def __init__(
        self,
        stall_timeouts: int | None = None,
        backpressure_s: float | None = None,
        p99_factor: float | None = None,
        cooldown_s: float | None = None,
    ) -> None:
        env = os.environ.get
        self.stall_timeouts = stall_timeouts if stall_timeouts is not None else int(
            env("HOTSTUFF_TRACE_STALL_TIMEOUTS", "3")
        )
        self.backpressure_s = backpressure_s if backpressure_s is not None else float(
            env("HOTSTUFF_TRACE_BACKPRESSURE_S", "5")
        )
        self.p99_factor = p99_factor if p99_factor is not None else float(
            env("HOTSTUFF_TRACE_P99_FACTOR", "4")
        )
        self.cooldown_s = cooldown_s if cooldown_s is not None else float(
            env("HOTSTUFF_TRACE_COOLDOWN_S", "30")
        )
        self._hooks: list[Callable[[str, dict], None]] = []
        # Context hooks: callables returning extra dict sections merged
        # into every auto-dump (the telemetry plane registers one so each
        # <path>.watchdog-<reason>-<n>.json carries the last K metric
        # snapshots — the trajectory leading up to the trigger, not just
        # the event ring).
        self._context_hooks: list[Callable[[], dict]] = []
        self._last_fired: dict[str, float] = {}
        self._bp_since: float | None = None
        self._verify_samples: list[float] = []
        self._verify_baseline: float | None = None
        self._verify_streak = 0
        self.triggers: list[dict] = []

    # -- hooks ---------------------------------------------------------------

    def add_dump_hook(self, fn: Callable[[str, dict], None]) -> None:
        self._hooks.append(fn)

    def remove_dump_hook(self, fn: Callable[[str, dict], None]) -> None:
        try:
            self._hooks.remove(fn)
        except ValueError:
            pass

    def add_context_hook(self, fn: Callable[[], dict]) -> None:
        self._context_hooks.append(fn)

    def remove_context_hook(self, fn: Callable[[], dict]) -> None:
        try:
            self._context_hooks.remove(fn)
        except ValueError:
            pass

    def context(self) -> dict:
        """Merged context sections from every registered hook (dict-valued
        keys merge shallowly so several telemetry planes can each
        contribute under one 'telemetry' key); a failing hook is skipped —
        diagnostics must never take down the dump path."""
        out: dict = {}
        for fn in list(self._context_hooks):
            try:
                d = fn() or {}
            except Exception as e:
                log.warning("watchdog context hook failed: %r", e)
                continue
            for k, v in d.items():
                if isinstance(v, dict) and isinstance(out.get(k), dict):
                    out[k].update(v)
                else:
                    out[k] = v
        return out

    def set_auto_dump(self, path_prefix: str) -> Callable[[str, dict], None]:
        """Install (and return) a hook writing `<prefix>.watchdog-<reason>-<n>.json`
        per trigger."""
        seq = {"n": 0}

        def _write(reason: str, detail: dict) -> None:
            seq["n"] += 1
            path = f"{path_prefix}.watchdog-{reason}-{seq['n']}.json"
            try:
                d = RECORDER.dump()
                d["watchdog"] = {"reason": reason, **detail}
                ctx = self.context()
                if ctx:
                    # e.g. the telemetry plane's last K snapshots: the
                    # metric trajectory leading up to the trigger.
                    d["context"] = ctx
                with open(path, "w") as f:
                    json.dump(d, f, indent=2, sort_keys=True)
                    f.write("\n")
                log.warning("watchdog %s: flight recorder dumped to %s", reason, path)
            except OSError as e:
                log.warning("watchdog %s: dump failed: %r", reason, e)

        self.add_dump_hook(_write)
        return _write

    def _trigger(self, reason: str, **detail) -> None:
        now = _clock()
        last = self._last_fired.get(reason)
        if last is not None and now - last < self.cooldown_s:
            return
        self._last_fired[reason] = now
        _M_TRIGGERS.inc()
        RECORDER.record(f"watchdog.{reason}", None, None, detail or None)
        self.triggers.append({"t": round(now, 6), "reason": reason, **detail})
        log.warning("anomaly watchdog fired: %s %s", reason, detail)
        for hook in list(self._hooks):
            try:
                hook(reason, detail)
            except Exception as e:
                log.warning("watchdog hook failed: %r", e)

    # -- observations --------------------------------------------------------

    def note_timeout(self, round_: int, consecutive: int) -> None:
        if not _enabled:
            return
        if consecutive >= self.stall_timeouts:
            self._trigger("round_stall", round=round_, consecutive=consecutive)
        # A stall is also the moment to check whether backpressure has
        # been pinning the egress plane (the round-5 freeze signature:
        # stalled rounds WITH a saturated cold lane).
        if self._bp_since is not None:
            self.note_backpressure(True)

    def note_backpressure(self, active: bool) -> None:
        if not _enabled:
            return
        now = _clock()
        if active:
            if self._bp_since is None:
                self._bp_since = now
                RECORDER.record("backpressure.on", None, None, None)
            elif now - self._bp_since >= self.backpressure_s:
                self._trigger(
                    "backpressure",
                    sustained_s=round(now - self._bp_since, 3),
                )
        elif self._bp_since is not None:
            RECORDER.record(
                "backpressure.off", None, None,
                {"sustained_s": round(now - self._bp_since, 3)},
            )
            self._bp_since = None

    def note_slo_burn(
        self, slo: str, burn_short: float, burn_long: float
    ) -> None:
        """An SLO burn-rate alert from the telemetry plane
        (utils/telemetry.py): both evaluation windows are burning error
        budget past the configured factor. Fires the `slo_burn` reason
        (recorder event + auto-dump hooks) under the usual per-reason
        cooldown — the telemetry plane tracks per-SLO fired/cleared state
        itself, this is the dump trigger."""
        if not _enabled:
            return
        self._trigger(
            "slo_burn",
            slo=slo,
            burn_short=round(burn_short, 3),
            burn_long=round(burn_long, 3),
        )

    def note_handoff_violation(
        self, epoch: int, activation_round: int, trigger_round: int
    ) -> None:
        """An epoch-final handoff contract violation from the epoch
        manager (consensus/reconfig.py): a committed EpochChange's
        2-chain completion landed at/past its declared activation round,
        so gap rounds were certified by the old committee. Under the
        certification wall this requires a Byzantine quorum or a broken
        wall — fire the `handoff_violation` reason (recorder event +
        auto-dump hooks) so the run is diagnosed, not just counted."""
        if not _enabled:
            return
        self._trigger(
            "handoff_violation",
            epoch=epoch,
            activation_round=activation_round,
            trigger_round=trigger_round,
        )

    def note_verify(self, dur_s: float, n: int) -> None:
        if not _enabled or n <= 0:
            return
        per_sig = dur_s / n
        if self._verify_baseline is None:
            # Median, not mean: the first flushes include multi-second
            # XLA compiles on the device path — a mean baseline would sit
            # orders of magnitude above warm cost and the regression
            # trigger would never fire for exactly the runs it exists for.
            self._verify_samples.append(per_sig)
            if len(self._verify_samples) >= self.BASELINE_SAMPLES:
                ordered = sorted(self._verify_samples)
                self._verify_baseline = ordered[len(ordered) // 2]
                self._verify_samples = []
            return
        baseline = self._verify_baseline
        if baseline > 0 and per_sig > self.p99_factor * baseline:
            self._verify_streak += 1
            if self._verify_streak >= self.REGRESSION_STREAK:
                self._verify_streak = 0
                self._trigger(
                    "verify_regression",
                    per_sig_s=round(per_sig, 9),
                    baseline_s=round(baseline, 9),
                )
        else:
            self._verify_streak = 0

    def reset(self) -> None:
        self._last_fired.clear()
        self._context_hooks = []
        self._bp_since = None
        self._verify_samples = []
        self._verify_baseline = None
        self._verify_streak = 0
        self.triggers = []


WATCHDOG = AnomalyWatchdog()


def reset() -> None:
    """Clear recorder, hop memory, and watchdog state (test isolation)."""
    RECORDER.reset()
    WATCHDOG.reset()
    with _hops_lock:
        _hops.clear()
