"""Live telemetry plane: delta snapshots, SLO burn-rate alerts, and a
scrapeable per-node endpoint.

Everything observability has produced so far (metrics dumps, flight
recorder rings, chaos reports) is POST-MORTEM: a node exposes nothing
while it runs, and the SLOs attached to the scheduler's source classes
(crypto/scheduler.py `SourceClass.slo_s`) are advisory strings nothing
evaluates. This module closes both gaps:

  * **Delta-snapshot ring** — `TelemetryPlane.snapshot()` reads the
    process metrics registry and records DELTAS since the previous
    snapshot: counter/gauge movement, windowed histogram percentiles
    (computed from bucket-count deltas, so each snapshot's p50/p99
    describe that window's samples, not the whole run), and per-lane
    queueing stats from the owning service's `LaneStats` (fresh per run,
    per node — the per-node numbers a process-global histogram cannot
    give). Snapshots carry only the deterministic clock (`loop.time`
    under the chaos VirtualTimeLoop), so two same-seed chaos runs
    produce bit-identical rings.

  * **SLO burn-rate evaluator** — `SLOSpec` binds a latency objective
    ("99% of mempool-lane queueing under 500 ms") to a registered
    metrics-namespace histogram or a LaneStats lane. Each snapshot
    contributes (good, bad) events per SLO; the evaluator keeps TWO
    windows (short = reacts fast, long = filters blips — the standard
    multi-window burn-rate recipe) and fires when BOTH burn error budget
    faster than `burn_factor`x. Firing raises the `slo_burn`
    AnomalyWatchdog reason (auto-dump, cooldown — utils/tracing.py);
    the alert clears when the short window is back under budget. Fired/
    cleared transitions are logged ("SLO burn fired: ..." — scraped by
    benchmark/logs.py into the `+ TELEMETRY:` report section) and kept
    in `alerts` for reports and the dashboard.

  * **Scrape endpoint** — `TelemetryServer` answers framed JSON
    requests ({"cmd": "scrape"}) on the stack's 4-byte length framing
    (`network/net.py` FrameReader), serving the plane's dump: snapshot
    ring, alert history, active alerts, cumulative lane stats, and the
    device-occupancy timeline summary (ops/timeline.py) when one is
    attached. `node run --telemetry-port` and `bench.py
    --telemetry-port` expose it; `tools/telemetry_dash.py` polls N nodes
    live or reads the same shape out of a chaos report offline.

Registered telemetry planes also feed the watchdog's CONTEXT hooks: every
`<path>.watchdog-<reason>-<n>.json` auto-dump embeds the last
`dump_snapshots` ring entries, so the dump carries the metric trajectory
leading up to the trigger, not just the event ring.

Dependency-free by design: stdlib + utils.metrics/tracing (network.net
imported lazily inside the server/client) — no jax, importable everywhere
the chaos runner and the graftlint tool run (the import-boundary
pass pins it statically).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from collections import deque
from dataclasses import dataclass

from . import metrics, tracing

log = logging.getLogger("hotstuff.telemetry")

__all__ = [
    "SLOSpec",
    "VERIFY_E2E_SLO_S",
    "default_slos",
    "TelemetryConfig",
    "TelemetryPlane",
    "TelemetryServer",
    "PeerView",
    "peer_views",
    "infer_fleet_regions",
    "scrape",
    "scrape_sync",
    "serve_in_thread",
    "weighted_percentile",
    "merge_lane_summaries",
    "fleet_rollup",
]

_M_SNAPSHOTS = metrics.counter("telemetry.snapshots")
_M_FIRED = metrics.counter("telemetry.slo_burn_fired")
_M_CLEARED = metrics.counter("telemetry.slo_burn_cleared")
_M_SCRAPES = metrics.counter("telemetry.scrapes")
_M_PEER_VIEWS = metrics.counter("telemetry.peer_views")

# End-to-end verify-latency target for one device batch
# (verifier.e2e_s): a batch habitually slower than this is a degraded
# relay / host-fallback signature, the same class of anomaly the
# watchdog's verify_regression streak looks for — the SLO form makes it
# a budgeted, scrapeable objective instead of a streak heuristic.
VERIFY_E2E_SLO_S = float(os.environ.get("HOTSTUFF_VERIFY_E2E_SLO_S", "0.25"))


@dataclass(frozen=True)
class SLOSpec:
    """One latency objective the telemetry plane evaluates.

    `metric` MUST name a histogram row in the canonical metrics namespace
    (the graftlint `telemetry` pass enforces this, rc 1). With `lane`
    set, events
    come from the attached LaneStats lane instead (per-service, fresh per
    run — the scheduler lane SLOs); otherwise from the global histogram's
    bucket-count deltas (a delta bucket counts as violating when its
    LOWER edge is already past the threshold — conservative by one
    bucket). `objective` is the target fraction of samples under
    `threshold_s`; the error budget is its complement."""

    name: str
    metric: str
    threshold_s: float
    objective: float = 0.99
    lane: str | None = None


def default_slos() -> tuple[SLOSpec, ...]:
    """The evaluated SLO set of record: one lane SLO per registered
    scheduler source class (threshold = the class's published slo_s —
    PR 7's advisory strings, now enforced) plus the device verify-latency
    target. The graftlint `telemetry` pass fails the build if a source
    class is missing from this set."""
    from ..crypto.scheduler import SOURCE_CLASSES

    slos = [
        SLOSpec(
            name=f"lane.{name}",
            metric=f"scheduler.queue_{name}_s",
            threshold_s=cls.slo_s,
            objective=0.99,
            lane=name,
        )
        for name, cls in sorted(SOURCE_CLASSES.items())
    ]
    slos.append(
        SLOSpec(
            name="verify.e2e",
            metric="verifier.e2e_s",
            threshold_s=VERIFY_E2E_SLO_S,
            objective=0.99,
        )
    )
    # Epoch-final handoff contract (consensus/reconfig.py §5.5j). The
    # histogram's unit is ROUNDS, not seconds: every healthy handoff
    # records lag 0 (bucket lower edge 0 < threshold — never burns), a
    # violated handoff records >= 1 (lower edge 0.5 > threshold — burns
    # immediately), so a delayed-commit handoff fires the slo_burn
    # alert + auto-dump instead of only logging.
    slos.append(
        SLOSpec(
            name="reconfig.handoff",
            metric="reconfig.handoff_lag_rounds",
            threshold_s=0.4,
            objective=0.99,
        )
    )
    # Commit-proof serving (§5.5q): time from a proof query arriving to
    # the proof in the reply — for subscribe-until-commit queries this
    # spans the residual commit wait, so the target is the sub-second
    # finality-read contract, not a local lookup bound.
    slos.append(
        SLOSpec(
            name="proofs.serve",
            metric="proofs.serve_s",
            threshold_s=1.0,
            objective=0.99,
        )
    )
    return tuple(slos)


# Counter/gauge prefixes worth shipping in snapshots (everything here is a
# deterministic COUNT under the chaos virtual clock; wall-time-valued
# histograms are excluded unless explicitly configured).
_DEFAULT_PREFIXES = (
    "chaos.",
    "consensus.",
    "crypto.",
    "ingress.",
    "mempool.",
    "net.",
    "proofs.",
    "reconfig.",
    "scheduler.",
    "telemetry.",
    "timeline.",
    "trace.",
    "verifier.",
)


@dataclass
class TelemetryConfig:
    """Knobs for one plane.

    `histograms` lists the namespace histograms whose windowed
    percentiles ride in snapshots; the default covers the scheduler's
    virtual-time queue rows (deterministic under the chaos clock — a
    wall-time histogram in a snapshot would break bit-identical replay).
    Window sizes are in SNAPSHOTS: short reacts within
    `short_window * interval_s`, long filters blips."""

    interval_s: float = 5.0
    ring: int = 256
    short_window: int = 2
    long_window: int = 6
    burn_factor: float = 2.0
    dump_snapshots: int = 8  # last K embedded in watchdog auto-dumps
    counter_prefixes: tuple[str, ...] = _DEFAULT_PREFIXES
    histograms: tuple[str, ...] = (
        "scheduler.queue_consensus_s",
        "scheduler.queue_aggregate_s",
        "scheduler.queue_sync_s",
        "scheduler.queue_ingress_s",
        "scheduler.queue_mempool_s",
        "scheduler.bucket_size",
    )


def _delta_percentile(bounds: tuple, counts: list[int], q: float) -> float:
    """Interpolated percentile over DELTA bucket counts (no observed
    min/max for a window, so edges clamp to [0, last finite bound])."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    return metrics.bucket_percentile(
        bounds, counts, total, 0.0, float(bounds[-1]), q
    )


class _SloState:
    """Per-SLO evaluation window + alert latch."""

    __slots__ = ("spec", "window", "active")

    def __init__(self, spec: SLOSpec, long_window: int) -> None:
        self.spec = spec
        self.window: deque = deque(maxlen=max(1, long_window))
        self.active = False

    @property
    def warmed(self) -> bool:
        """True once the long window is FULL. Firing before that would
        judge burn_long over a handful of entries — a single bad snapshot
        right after plane start would satisfy both windows at once,
        exactly the blip the long window exists to filter."""
        return len(self.window) == self.window.maxlen

    @staticmethod
    def _burn(entries, budget: float) -> float:
        good = sum(g for g, _b in entries)
        bad = sum(b for _g, b in entries)
        total = good + bad
        if total <= 0:
            return 0.0  # no data = no burn (lets an idle lane clear)
        return (bad / total) / max(budget, 1e-9)

    def observe(self, good: int, bad: int, short_window: int) -> tuple[float, float]:
        self.window.append((good, bad))
        budget = 1.0 - self.spec.objective
        entries = list(self.window)
        return (
            self._burn(entries[-max(1, short_window):], budget),
            self._burn(entries, budget),
        )


class TelemetryPlane:
    """One node's live telemetry: snapshot ring + SLO evaluator.

    `lane_stats` is the owning BatchVerificationService's LaneStats (or a
    zero-arg callable resolving it — the chaos runner re-resolves across
    crash/restart); `timeline_fn` returns the device-occupancy summary
    (ops/timeline.py `TIMELINE.summary`) for dumps; `peers_fn` returns
    the node's per-peer observatory snapshot (`network/net.py
    peer_snapshot` — injected rather than imported, keeping utils/ free
    of a network dependency); `clock` defaults to `time.monotonic` and
    the chaos orchestrator passes its virtual `loop.time`."""

    def __init__(
        self,
        label: object | None = None,
        config: TelemetryConfig | None = None,
        slos: tuple[SLOSpec, ...] | None = None,
        lane_stats=None,
        timeline_fn=None,
        peers_fn=None,
        registry: metrics.Registry | None = None,
        clock=None,
    ) -> None:
        self.label = label
        self.config = config or TelemetryConfig()
        self.slos = tuple(slos if slos is not None else default_slos())
        self._lane_stats = lane_stats
        self._timeline_fn = timeline_fn
        self._peers_fn = peers_fn
        self._registry = registry or metrics.REGISTRY
        self._clock = clock or time.monotonic
        self._ring: deque = deque(maxlen=max(4, self.config.ring))
        self._seq = 0
        self._prev_counters: dict[str, float] = {}
        self._prev_buckets: dict[str, list[int]] = {}
        self._lane_cursor: dict[str, int] = {}
        self._lane_src = None  # the LaneStats the cursors index into
        self._slo_state = {
            spec.name: _SloState(spec, self.config.long_window)
            for spec in self.slos
        }
        self.alerts: list[dict] = []
        self._watchdog: tracing.AnomalyWatchdog | None = None
        self._context_hook = None
        # Baseline the delta state at plane BIRTH: the registry is
        # process-global and outlives the plane (tier-1 runs scenarios
        # back to back), so the first snapshot must not report the whole
        # process history as one giant delta — same-seed chaos runs would
        # otherwise differ in exactly that first entry.
        self._prime()

    def _prime(self) -> None:
        d = self._registry.dump(include_buckets=True)
        self._prev_counters = {
            name: v
            for name, v in d["counters"].items()
            if name.startswith(self.config.counter_prefixes)
        }
        self._prev_buckets = {
            name: list(row["buckets"]["counts"])
            for name, row in d["histograms"].items()
            if "buckets" in row
        }

    # -- watchdog context (auto-dumps embed the metric trajectory) -----------

    def attach_watchdog(
        self, watchdog: tracing.AnomalyWatchdog | None = None
    ) -> None:
        self.detach_watchdog()
        self._watchdog = watchdog or tracing.WATCHDOG

        def _ctx() -> dict:
            return {
                "telemetry": {
                    str(self.label): self.snapshots(
                        last=self.config.dump_snapshots
                    )
                }
            }

        self._context_hook = _ctx
        self._watchdog.add_context_hook(_ctx)

    def detach_watchdog(self) -> None:
        if self._watchdog is not None and self._context_hook is not None:
            self._watchdog.remove_context_hook(self._context_hook)
        self._watchdog = None
        self._context_hook = None

    # -- snapshotting --------------------------------------------------------

    def _resolve_lane_stats(self):
        ls = self._lane_stats
        return ls() if callable(ls) else ls

    def snapshot(self, now: float | None = None) -> dict:
        """Take one delta snapshot, append it to the ring, and evaluate
        every SLO. Deterministic: derives only from registry/LaneStats
        state and the injected clock."""
        now = self._clock() if now is None else now
        cfg = self.config
        d = self._registry.dump(include_buckets=True)
        snap: dict = {"seq": self._seq, "t": round(now, 6)}
        self._seq += 1

        counters = {}
        for name in sorted(d["counters"]):
            if not name.startswith(cfg.counter_prefixes):
                continue
            v = d["counters"][name]
            delta = v - self._prev_counters.get(name, 0)
            self._prev_counters[name] = v
            if delta:
                counters[name] = delta
        if counters:
            snap["counters"] = counters
        gauges = {
            name: round(v, 6)
            for name, v in sorted(d["gauges"].items())
            if v and name.startswith(cfg.counter_prefixes)
        }
        if gauges:
            snap["gauges"] = gauges

        # windowed histogram percentiles from bucket-count deltas
        hist_events: dict[str, tuple[int, int]] = {}  # metric -> (good, bad)
        hists = {}
        hist_rows = dict(d["histograms"])
        wanted = set(cfg.histograms) | {
            s.metric for s in self.slos if s.lane is None
        }
        for name in sorted(wanted):
            row = hist_rows.get(name)
            if row is None or "buckets" not in row:
                continue
            counts = row["buckets"]["counts"]
            bounds = tuple(
                b for b in row["buckets"]["le"] if not isinstance(b, str)
            )
            prev = self._prev_buckets.get(name)
            delta = [
                c - (prev[i] if prev and i < len(prev) else 0)
                for i, c in enumerate(counts)
            ]
            self._prev_buckets[name] = list(counts)
            total = sum(delta)
            spec = next(
                (s for s in self.slos if s.lane is None and s.metric == name),
                None,
            )
            if spec is not None:
                bad = sum(
                    c
                    for i, c in enumerate(delta)
                    if i > 0 and float(bounds[i - 1]) >= spec.threshold_s
                )
                hist_events[name] = (max(0, total - bad), bad)
            if total > 0 and name in cfg.histograms:
                hists[name] = {
                    "count": total,
                    "p50": round(_delta_percentile(bounds, delta, 0.50), 6),
                    "p99": round(_delta_percentile(bounds, delta, 0.99), 6),
                }
        if hists:
            snap["hist"] = hists

        # per-lane windows from the service-local LaneStats
        lane_events: dict[str, tuple[int, int]] = {}  # lane -> (good, bad)
        lane_thresholds = {
            s.lane: s.threshold_s for s in self.slos if s.lane is not None
        }
        ls = self._resolve_lane_stats()
        if ls is not None:
            if ls is not self._lane_src:
                # Fresh LaneStats (a chaos restart rebuilds the service):
                # stale cursors would hide every post-restart sample until
                # the new lists outgrew them — restart the windows at zero.
                self._lane_src = ls
                self._lane_cursor.clear()
            lanes = {}
            for lane in ls.lanes():
                # Cursor in MONOTONIC-total terms, not list positions:
                # LaneStats rotates its reservoir at CAP, so a position
                # cursor would freeze once the list stops growing — the
                # live lane SLOs would go permanently blind (and clear
                # active alerts via the no-data rule) after ~CAP verifies.
                total = ls.total(lane)
                cur = self._lane_cursor.get(lane, 0)
                if cur > total:  # same object, counters reset
                    cur = 0
                fresh = total - cur
                self._lane_cursor[lane] = total
                if fresh <= 0:
                    lane_events.setdefault(lane, (0, 0))
                    continue
                # More arrivals than the reservoir retains in one window:
                # judge the retained tail (the overflow is unknowable).
                new = ls.tail(lane, fresh)
                threshold = lane_thresholds.get(lane)
                bad = (
                    sum(1 for s in new if s > threshold)
                    if threshold is not None
                    else 0
                )
                lane_events[lane] = (len(new) - bad, bad)
                lanes[lane] = {
                    "count": len(new),
                    "p50_ms": round(metrics.percentile(new, 0.50) * 1e3, 3),
                    "p99_ms": round(metrics.percentile(new, 0.99) * 1e3, 3),
                    "bad": bad,
                }
            if lanes:
                snap["lanes"] = lanes

        self._evaluate(now, hist_events, lane_events, ls is not None)
        active = sorted(
            name for name, st in self._slo_state.items() if st.active
        )
        if active:
            snap["active"] = active
        self._ring.append(snap)
        _M_SNAPSHOTS.inc()
        if self._timeline_fn is not None:
            # One scrapeable line per snapshot (benchmark/logs.py folds
            # these into the report's `+ TELEMETRY:` section). Log-only:
            # the ring stays device-free so chaos rings (no timeline)
            # and device rings share one schema.
            try:
                dev = self._timeline_fn()
            except Exception:
                dev = None
            if dev and dev.get("chunks"):
                log.info(
                    "TELEMETRY device occupancy %.1f%% overlap headroom "
                    "%.1f%%",
                    dev["occupancy"] * 100.0,
                    dev["overlap_headroom"] * 100.0,
                )
        return snap

    def _evaluate(
        self,
        now: float,
        hist_events: dict[str, tuple[int, int]],
        lane_events: dict[str, tuple[int, int]],
        have_lane_stats: bool,
    ) -> None:
        cfg = self.config
        for spec in self.slos:
            if spec.lane is not None:
                if not have_lane_stats:
                    continue  # no lane source attached: nothing to judge
                good, bad = lane_events.get(spec.lane, (0, 0))
            else:
                good, bad = hist_events.get(spec.metric, (0, 0))
            state = self._slo_state[spec.name]
            burn_short, burn_long = state.observe(
                good, bad, cfg.short_window
            )
            if (
                not state.active
                and state.warmed
                and burn_short >= cfg.burn_factor
                and burn_long >= cfg.burn_factor
            ):
                state.active = True
                _M_FIRED.inc()
                self.alerts.append(
                    {
                        "slo": spec.name,
                        "event": "fired",
                        "t": round(now, 6),
                        "burn_short": round(burn_short, 3),
                        "burn_long": round(burn_long, 3),
                    }
                )
                log.warning(
                    "SLO burn fired: %s (burn %.1fx short / %.1fx long, "
                    "threshold %.3fs)",
                    spec.name,
                    burn_short,
                    burn_long,
                    spec.threshold_s,
                )
                (self._watchdog or tracing.WATCHDOG).note_slo_burn(
                    spec.name, burn_short, burn_long
                )
            elif state.active and burn_short < 1.0:
                state.active = False
                _M_CLEARED.inc()
                self.alerts.append(
                    {
                        "slo": spec.name,
                        "event": "cleared",
                        "t": round(now, 6),
                        "burn_short": round(burn_short, 3),
                        "burn_long": round(burn_long, 3),
                    }
                )
                log.warning("SLO burn cleared: %s", spec.name)
                tracing.event("slo.clear", None, None, slo=spec.name)

    async def run(self) -> None:
        """Periodic snapshot loop; spawn with actors.spawn so a chaos
        crash/teardown cancels it with the owning scope. Virtual-time
        safe: only `asyncio.sleep` + `loop.time`."""
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(self.config.interval_s)
            self.snapshot(loop.time())

    # -- read side -----------------------------------------------------------

    def snapshots(self, last: int | None = None) -> list[dict]:
        out = list(self._ring)
        if last is not None and len(out) > last:
            out = out[-last:]
        return out

    def active_alerts(self) -> list[str]:
        return sorted(n for n, st in self._slo_state.items() if st.active)

    def dump(self, last: int | None = None) -> dict:
        """The scrape payload / report embed. `commits` sums the
        consensus.commits deltas across the ring — accurate for a real
        one-node process; the chaos orchestrator overwrites it with the
        per-node truth (its registry is process-global across nodes)."""
        ls = self._resolve_lane_stats()
        snaps = self.snapshots(last)
        commits = sum(
            s.get("counters", {}).get("consensus.commits", 0) for s in snaps
        )
        return {
            "v": 1,
            "kind": "telemetry",
            "node": self.label,
            "interval_s": self.config.interval_s,
            # graftlint: allow[determinism] cross-process alignment stamp in scrape metadata; excluded from bit-identity checks
            "anchor": {"mono": self._clock(), "wall": time.time()},
            "snapshots": snaps,
            "alerts": list(self.alerts),
            "active_alerts": self.active_alerts(),
            "slos": [
                {
                    "name": s.name,
                    "metric": s.metric,
                    "threshold_s": s.threshold_s,
                    "objective": s.objective,
                    "lane": s.lane,
                }
                for s in self.slos
            ],
            "lanes": ls.summary() if ls is not None else {},
            "device": self._timeline_fn() if self._timeline_fn else None,
            "peers": self._peer_section(),
            "commits": commits,
        }

    def _peer_section(self) -> dict | None:
        if self._peers_fn is None:
            return None
        peers = self._peers_fn()
        if peers:
            _M_PEER_VIEWS.inc()
        return peers


# ---------------------------------------------------------------------------
# Fleet rollups: merge many nodes' telemetry into one cell record (the
# scenario-matrix runner's per-cell summary — tools/chaos_run.py --matrix).
#
# Cross-node percentile merge rule (documented because it is an
# approximation, not magic): true percentiles are not mergeable from
# per-node summaries, and per-node planes deliberately ship summaries,
# not sample rings (a 100-node cell would otherwise carry ~100x65k
# floats). Each node-lane summary (count, p50, p99, max) is therefore
# re-expanded into three weighted points — 50% of the count at p50, 49%
# at p99, the remainder at max — and the fleet percentile is the
# weighted nearest-rank over the pooled points. Exactness properties:
# the merged max is EXACT (max of maxes); the merged p99 is bounded
# above by the worst node's max and below by the best node's p50; and
# when every node saw the same distribution the merge reproduces that
# distribution's summary. Rollups additionally carry the worst NODE per
# lane, which needs no merge at all and is usually the number a
# regression hunt starts from.


def weighted_percentile(points: list[tuple[float, float]], q: float) -> float:
    """Nearest-rank percentile over (value, weight) points: the smallest
    value whose cumulative weight reaches q of the total. Degenerates to
    metrics.percentile when every weight is 1."""
    if not points:
        return 0.0
    total = sum(w for _v, w in points if w > 0)
    if total <= 0:
        return 0.0
    target = q * total
    cum = 0.0
    for v, w in sorted(points):
        if w <= 0:
            continue
        cum += w
        if cum >= target - 1e-12:
            return v
    return sorted(points)[-1][0]


def merge_lane_summaries(per_node: dict[str, dict]) -> dict[str, dict]:
    """{node: {lane: {count, p50_ms, p99_ms[, max_ms]}}} -> one merged
    summary per lane across the fleet (see the merge rule above), plus
    the worst node by p99 — {lane: {count, p50_ms, p99_ms, max_ms,
    worst_node, worst_node_p99_ms}}."""
    pooled: dict[str, list[tuple[float, float]]] = {}
    counts: dict[str, int] = {}
    worst: dict[str, tuple[float, str]] = {}  # lane -> (p99, node)
    for node, lanes in sorted(per_node.items()):
        for lane, s in (lanes or {}).items():
            count = int(s.get("count", 0))
            if count <= 0:
                continue
            p50 = float(s.get("p50_ms", 0.0))
            p99 = float(s.get("p99_ms", p50))
            mx = float(s.get("max_ms", p99))
            # Fractional weights on purpose: integer rounding would skew
            # the max share above 1% for small counts, dragging the
            # merged p99 of IDENTICAL per-node distributions up to max —
            # the fixed-point property the unit test pins.
            w50 = 0.50 * count
            w99 = 0.49 * count
            wmax = 0.01 * count
            pooled.setdefault(lane, []).extend(
                [(p50, w50), (p99, w99), (mx, wmax)]
            )
            counts[lane] = counts.get(lane, 0) + count
            if lane not in worst or p99 > worst[lane][0]:
                worst[lane] = (p99, str(node))
    out = {}
    for lane, points in pooled.items():
        out[lane] = {
            "count": counts[lane],
            "p50_ms": round(weighted_percentile(points, 0.50), 3),
            "p99_ms": round(weighted_percentile(points, 0.99), 3),
            "max_ms": round(max(v for v, w in points if w > 0), 3),
            "worst_node": worst[lane][1],
            "worst_node_p99_ms": round(worst[lane][0], 3),
        }
    return out


# ---------------------------------------------------------------------------
# Per-peer observatory views (the `peers` section of a telemetry dump,
# fed by network/net.py's PeerLink ledger through `peers_fn`).


@dataclass(frozen=True)
class PeerView:
    """One directed link's normalized observatory row — the shape the
    dashboard renders and a future region-aware LeaderElector consumes."""

    peer: str
    rtt_ewma_ms: float | None
    rtt_p50_ms: float | None
    frames_sent: int
    bytes_sent: int
    backoff_drops: int
    probes_sent: int
    pongs_received: int

    @staticmethod
    def from_snapshot(peer: str, snap: dict) -> "PeerView":
        return PeerView(
            peer=str(peer),
            rtt_ewma_ms=snap.get("rtt_ewma_ms"),
            rtt_p50_ms=snap.get("rtt_p50_ms"),
            frames_sent=int(snap.get("frames_sent") or 0),
            bytes_sent=int(snap.get("bytes_sent") or 0),
            backoff_drops=int(snap.get("backoff_drops") or 0),
            probes_sent=int(snap.get("probes_sent") or 0),
            pongs_received=int(snap.get("pongs_received") or 0),
        )


def peer_views(peers: dict[str, dict] | None) -> list[PeerView]:
    """A dump's `peers` section as sorted PeerView rows."""
    return [
        PeerView.from_snapshot(peer, snap or {})
        for peer, snap in sorted((peers or {}).items())
    ]


# Fleet region inference: two nodes share a region iff a measured RTT
# EWMA between them sits under this bound. The chaos WanMatrix separates
# intra-region (4 ms) from the closest inter-region RTT (62 ms) by more
# than a decade, so 30 ms recovers the seeded geometry exactly while
# tolerating per-frame jitter folded into the EWMAs.
REGION_RTT_THRESHOLD_MS = 30.0


def infer_fleet_regions(
    latency_ms: dict[str, dict[str, float]],
    threshold_ms: float = REGION_RTT_THRESHOLD_MS,
) -> dict[str, str]:
    """Partition nodes into RTT-derived regions: union-find over every
    measured link whose EWMA is under `threshold_ms` (either direction
    suffices — links are directed but latency is symmetric enough).
    Labels are synthetic (`rtt-0`, `rtt-1`, ... ordered by each group's
    smallest member), so callers compare PARTITIONS against ground
    truth, not label strings. Pure and deterministic."""
    nodes = sorted(
        set(latency_ms) | {b for m in latency_ms.values() for b in m}
    )
    parent = {n: n for n in nodes}

    def find(x: str) -> str:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for a in sorted(latency_ms):
        for b, rtt in sorted((latency_ms.get(a) or {}).items()):
            if rtt is not None and rtt <= threshold_ms:
                ra, rb = find(a), find(b)
                if ra != rb:
                    parent[max(ra, rb)] = min(ra, rb)
    groups: dict[str, list[str]] = {}
    for n in nodes:
        groups.setdefault(find(n), []).append(n)
    labels = {
        root: f"rtt-{k}"
        for k, root in enumerate(
            sorted(groups, key=lambda r: min(groups[r]))
        )
    }
    return {n: labels[find(n)] for n in nodes}


def peer_latency_map(peers: dict[str, dict]) -> dict[str, dict[str, float]]:
    """{node: {peer: link snapshot}} -> {node: {peer: RTT EWMA ms}},
    keeping only links with at least one closed probe loop."""
    out: dict[str, dict[str, float]] = {}
    for a, links in sorted((peers or {}).items()):
        row = {
            str(b): float(s["rtt_ewma_ms"])
            for b, s in sorted((links or {}).items())
            if isinstance(s, dict) and s.get("rtt_ewma_ms") is not None
        }
        if row:
            out[str(a)] = row
    return out


# Counter prefixes a matrix cell keeps from the scenario's metric deltas:
# the scale/health counters a regression diff is judged on, not the full
# delta dump (which stays in the per-scenario report).
_ROLLUP_COUNTER_PREFIXES = (
    "sync.", "reconfig.", "wan.", "chaos.", "agg.", "elect.", "incident.",
)


def fleet_rollup(report: dict) -> dict:
    """Distill one chaos report (ChaosOrchestrator._report shape) into
    the fleet-wide cell summary the scenario matrix commits: safety/
    liveness verdict, commit rate, cross-node lane-percentile merge,
    worst-node occupancy, alert totals, and the sync/epoch/wan counters.
    Pure function of the report, so offline tooling (telemetry_dash
    --matrix) reproduces the runner's numbers from the artifact alone."""
    span = float(report.get("virtual_seconds") or 0.0)
    # Per-node counts from the report's `commits` map when present: the
    # orchestrator builds it over EVERY node, so a fully-starved node
    # contributes its 0 to min_node. commit_times only lists nodes that
    # committed at least once — using it alone would report a healthy
    # floor while a node sat at zero.
    commits_map = report.get("commits")
    per_node_commits = {
        str(k): len(v)
        for k, v in (
            commits_map if commits_map else report.get("commit_times") or {}
        ).items()
    }
    total_commits = sum(per_node_commits.values())

    telem = report.get("telemetry") or {}
    # Lane summaries: prefer the telemetry dumps' cumulative LaneStats;
    # telemetry-less reports degrade to the scheduler section's
    # queue_delay (same {count, p50_ms, p99_ms, max_ms} shape).
    lane_src = (
        {label: dump.get("lanes") or {} for label, dump in telem.items()}
        if telem
        else {
            label: (s or {}).get("queue_delay") or {}
            for label, s in (report.get("scheduler") or {}).items()
        }
    )
    occupancies = {
        str(label): dump["device"]["occupancy"]
        for label, dump in telem.items()
        if isinstance(dump.get("device"), dict)
        and dump["device"].get("occupancy") is not None
    }
    worst_occ = min(occupancies.items(), key=lambda kv: kv[1], default=None)
    alerts_fired = sum(
        1
        for dump in telem.values()
        for a in dump.get("alerts") or ()
        if a.get("event") == "fired"
    )
    alerts_cleared = sum(
        1
        for dump in telem.values()
        for a in dump.get("alerts") or ()
        if a.get("event") == "cleared"
    )
    active = sorted(
        {
            f"{label}:{name}"
            for label, dump in telem.items()
            for name in dump.get("active_alerts") or ()
        }
    )
    metrics_delta = report.get("metrics") or {}
    # Fleet latency map (network observatory): prefer the report's
    # top-level `peers` section (present even without telemetry planes);
    # degrade to the per-dump `peers` embeds.
    peers = report.get("peers") or {
        str(label): dump.get("peers") or {} for label, dump in telem.items()
    }
    latency = peer_latency_map(peers)
    peer_rtt = None
    if latency:
        links = sum(len(row) for row in latency.values())
        # Region inference needs the FULL fleet mesh: with a partial
        # latency map (probe plane off on some nodes, or loops not yet
        # closed) the union-find only sees the measured nodes and its
        # region_count misleads — one sub-threshold link reads as "one
        # region". Honest answer: report the raw links/worst columns
        # always, the inference columns only at full coverage, and the
        # coverage fraction so dashboards can say WHY they're absent.
        n = int(report.get("nodes") or 0)
        expected_links = n * (n - 1)
        full_coverage = (
            n > 1 and len(latency) == n and links >= expected_links
        )
        peer_rtt = {
            "links": links,
            "coverage": (
                round(min(1.0, links / expected_links), 3)
                if expected_links
                else None
            ),
            "worst_ewma_ms": round(
                max(rtt for row in latency.values() for rtt in row.values()),
                3,
            ),
            "worst_cross_region_ewma_ms": None,
            "inferred_regions": None,
            "region_count": None,
        }
        if full_coverage:
            inferred = infer_fleet_regions(latency)
            cross = [
                rtt
                for a, row in latency.items()
                for b, rtt in row.items()
                if inferred.get(a) != inferred.get(b)
            ]
            peer_rtt["worst_cross_region_ewma_ms"] = (
                round(max(cross), 3) if cross else None
            )
            peer_rtt["inferred_regions"] = inferred
            peer_rtt["region_count"] = len(set(inferred.values()))
    # Election attribution (§5.5p): the elect.* counters accrue once per
    # node per committed round whenever a region map is wired, so the
    # per-commit averages divide fleet totals by fleet round-commits.
    # None when no elect.rounds moved (region-less run or old report) —
    # absence, not a zero claim.
    elect_rounds = int(metrics_delta.get("elect.rounds") or 0)
    election = None
    if elect_rounds:
        matches = int(metrics_delta.get("elect.leader_region_matches") or 0)
        hops = int(metrics_delta.get("elect.cross_region_hops") or 0)
        blind = int(metrics_delta.get("elect.cross_region_hops_blind") or 0)
        election = {
            "rounds": elect_rounds,
            "leader_region_matches": matches,
            "match_rate": round(matches / elect_rounds, 4),
            "cross_region_hops": hops,
            "hops_per_commit": round(hops / elect_rounds, 3),
            "cross_region_hops_blind": blind,
            "blind_hops_per_commit": round(blind / elect_rounds, 3),
        }
    return {
        "nodes": report.get("nodes"),
        "crypto_mode": report.get("crypto_mode", "exact"),
        "wan_regions": sorted(set((report.get("wan_regions") or {}).values())),
        "virtual_seconds": span,
        "verdict": {
            "ok": bool(report.get("ok")),
            "safety_violations": len(report.get("safety_violations") or ()),
            "liveness_violations": len(report.get("liveness_violations") or ()),
            "expectation_failures": len(
                report.get("expectation_failures") or ()
            ),
        },
        "commits": {
            "total": total_commits,
            "rate_per_s": round(total_commits / span, 3) if span > 0 else 0.0,
            "min_node": min(per_node_commits.values(), default=0),
            "max_node": max(per_node_commits.values(), default=0),
            # Certificate-plane payoff column (§5.5o): certificate bytes
            # per committed round, averaged fleet-wide. Both terms scale
            # with n, so a flat value across n = 4..128 is the O(1)
            # constant-size-certificate claim in one number; entry-list
            # fleets grow linearly here. The counter is maintained in
            # every crypto mode, so legacy and aggregate cells compare;
            # None = the report predates the counter (not "0 bytes").
            "bytes_per_committed_round": (
                round(
                    float(metrics_delta["agg.cert_bytes_committed"])
                    / total_commits,
                    1,
                )
                if total_commits and "agg.cert_bytes_committed" in metrics_delta
                else None
            ),
        },
        "lanes": merge_lane_summaries(lane_src),
        "occupancy": {
            "worst_node": worst_occ[0] if worst_occ else None,
            "worst": round(worst_occ[1], 6) if worst_occ else None,
        },
        "alerts": {
            "fired": alerts_fired,
            "cleared": alerts_cleared,
            "active": active,
        },
        "snapshots": sum(
            len(dump.get("snapshots") or ()) for dump in telem.values()
        ),
        "epoch_switches": sum(
            len(v) for v in (report.get("epoch_switches") or {}).values()
        ),
        "counters": {
            k: v
            for k, v in sorted(metrics_delta.items())
            if k.startswith(_ROLLUP_COUNTER_PREFIXES)
        },
        "peer_rtt": peer_rtt,
        "election": election,
        # Incident-ledger health verdict (utils/incidents.py §5.5r):
        # MTTD/MTTR percentiles per fault class, burn budget, and the
        # unattributed-alert count — the matrix cell's operations view.
        "health": report.get("health"),
        "fault_trace_truncated": bool(report.get("fault_trace_truncated")),
    }


# ---------------------------------------------------------------------------
# Scrape endpoint: framed JSON request/response on the stack's 4-byte
# length framing (network/net.py), one response per request frame.


class TelemetryServer:
    """Serves scrape requests for one plane — or for a STATIC dump dict
    (e.g. a node's telemetry section replayed out of a chaos report,
    which is how the dashboard's live-vs-offline equivalence is tested:
    the same dict serves both paths verbatim)."""

    def __init__(self, address: tuple[str, int], source) -> None:
        self._address = address
        self.source = source
        self._server: asyncio.AbstractServer | None = None

    @property
    def port(self) -> int:
        assert self._server is not None, "server not started"
        return self._server.sockets[0].getsockname()[1]

    async def start(self) -> int:
        """Bind and start serving; returns the bound port (0 in the
        requested address picks a free one — tests rely on this)."""
        self._server = await asyncio.start_server(
            self._handle, host=self._address[0], port=self._address[1]
        )
        log.info("telemetry scrape endpoint on %s:%d", self._address[0], self.port)
        return self.port

    def launch(self):
        """Spawn the accept loop as an actor task (node run / bench)."""
        from .actors import spawn

        return spawn(self._serve(), name="telemetry-server")

    async def _serve(self) -> None:
        if self._server is None:
            await self.start()
        async with self._server:
            await self._server.serve_forever()

    def _payload(self, last: int | None) -> dict:
        if isinstance(self.source, dict):
            return self.source
        return self.source.dump(last=last)

    async def _handle(self, reader, writer) -> None:
        from ..network.net import FrameReader, frame

        frames = FrameReader(reader)
        try:
            while True:
                data = await frames.next_frame()
                if data is None:
                    break
                try:
                    req = json.loads(data)
                    cmd = req.get("cmd")
                except Exception:
                    req, cmd = {}, None
                if cmd == "scrape":
                    _M_SCRAPES.inc()
                    last = req.get("last")
                    if last is None or (
                        isinstance(last, int)
                        and not isinstance(last, bool)
                        and last >= 0
                    ):
                        resp = self._payload(last)
                    else:
                        resp = {"error": "last must be a non-negative integer"}
                else:
                    resp = {"error": f"unknown cmd {cmd!r} (try 'scrape')"}
                body = json.dumps(resp, sort_keys=True).encode()
                writer.write(frame(body))
                await writer.drain()
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass


async def scrape(
    address: tuple[str, int], last: int | None = None, timeout: float = 5.0
) -> dict:
    """One scrape round-trip against a TelemetryServer."""
    from ..network.net import FrameReader, frame

    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(address[0], address[1]), timeout
    )
    try:
        req: dict = {"cmd": "scrape"}
        if last is not None:
            req["last"] = last
        writer.write(frame(json.dumps(req).encode()))
        await writer.drain()
        data = await asyncio.wait_for(FrameReader(reader).next_frame(), timeout)
        if data is None:
            raise ConnectionError("telemetry endpoint closed mid-scrape")
        return json.loads(data)
    finally:
        try:
            writer.close()
        except Exception:
            pass


def scrape_sync(
    address: tuple[str, int], last: int | None = None, timeout: float = 5.0
) -> dict:
    return asyncio.run(scrape(address, last=last, timeout=timeout))


def serve_in_thread(
    source,
    port: int = 0,
    host: str = "127.0.0.1",
    snapshot_interval_s: float | None = None,
) -> int:
    """Run a TelemetryServer on a daemon thread with its own event loop
    (the seam for synchronous hosts like bench.py). Optionally ticks the
    plane's snapshot loop at `snapshot_interval_s`. Returns the bound
    port; the thread dies with the process."""
    import threading

    started = threading.Event()
    box: dict = {}

    def _thread() -> None:
        async def main() -> None:
            server = TelemetryServer((host, port), source)
            box["port"] = await server.start()
            started.set()
            if snapshot_interval_s and isinstance(source, TelemetryPlane):
                source.config.interval_s = snapshot_interval_s
                # actors.spawn (not bare ensure_future): keeps a strong
                # reference, adopts the loop into any ambient SpawnScope,
                # and its done-callback already ERROR-logs a crashed
                # snapshot loop — the ring must not silently freeze while
                # scrapes keep serving stale rc-0 data.
                from .actors import spawn

                spawn(source.run(), name="telemetry-snapshots")
            async with server._server:
                await server._server.serve_forever()

        try:
            asyncio.run(main())
        except Exception as e:  # pragma: no cover - diagnostics only
            box["error"] = e
            started.set()

    threading.Thread(target=_thread, name="telemetry-server", daemon=True).start()
    if not started.wait(10) or "port" not in box:
        raise RuntimeError(f"telemetry server failed to start: {box.get('error')}")
    return box["port"]
