"""Actor/channel utilities: the framework's async substrate.

The reference is an actor-per-subsystem design on tokio: every component owns
an mpsc receiver and runs an infinite select! loop in its own task, with no
shared mutable state (18 tokio::spawn sites; SURVEY.md section 1). This module
provides the same discipline on asyncio: bounded channels, tracked spawns, and
a select-like multiplexer for (channel, timer) loops.
"""

from __future__ import annotations

import asyncio
import contextvars
import logging
from typing import Any, Awaitable, Coroutine, TypeVar

log = logging.getLogger("hotstuff.actors")

T = TypeVar("T")

# Default channel capacity, matching the reference's mpsc bounds (100-1000).
CHANNEL_CAPACITY = 1_000


def channel(capacity: int = CHANNEL_CAPACITY) -> asyncio.Queue:
    return asyncio.Queue(capacity)


_tasks: set[asyncio.Task] = set()

# Active SpawnScope, if any. A contextvar (not a global) so the scope
# PROPAGATES: a task spawned while a scope is active carries the scope in
# its context, and every task IT spawns later (per-peer net workers, sync
# waiters, verify dispatches) lands in the same scope — the transitive
# task tree of one in-process node, which is exactly what a chaos
# crash-restart must cancel.
_scope_var: contextvars.ContextVar["SpawnScope | None"] = contextvars.ContextVar(
    "hotstuff-spawn-scope", default=None
)


class SpawnScope:
    """Collects every task spawn()ed while the scope is active, including
    transitively (see _scope_var). Used by the chaos orchestrator to model
    a node crash as one cancel of the node's whole task tree."""

    __slots__ = ("name", "tasks", "_token")

    def __init__(self, name: str = "") -> None:
        self.name = name
        self.tasks: set[asyncio.Task] = set()
        self._token = None

    def __enter__(self) -> "SpawnScope":
        self._token = _scope_var.set(self)
        return self

    def __exit__(self, *exc) -> None:
        _scope_var.reset(self._token)
        self._token = None

    def adopt(self, task: asyncio.Task) -> None:
        self.tasks.add(task)
        task.add_done_callback(self.tasks.discard)

    def cancel(self) -> list[asyncio.Task]:
        """Cancel every live task in the scope; returns them so the caller
        can await the cancellations settling."""
        live = [t for t in self.tasks if not t.done()]
        for t in live:
            t.cancel()
        return live


def spawn(coro: Coroutine, name: str | None = None) -> asyncio.Task:
    """Spawn a long-lived actor task. Keeps a strong reference (asyncio only
    holds weak refs) and logs unexpected termination -- actors are expected to
    run forever, like the reference's spawned loops."""
    task = asyncio.get_running_loop().create_task(coro, name=name)
    _tasks.add(task)
    scope = _scope_var.get()
    if scope is not None:
        scope.adopt(task)

    def _done(t: asyncio.Task) -> None:
        _tasks.discard(t)
        if t.cancelled():
            return
        exc = t.exception()
        if exc is not None:
            log.error("actor %s crashed: %r", t.get_name(), exc, exc_info=exc)

    task.add_done_callback(_done)
    return task


class Selector:
    """Multiplexes many awaitable sources into one loop, like tokio::select!.

    Each source is re-armed after it yields, so no message is lost. Branches
    are (name, factory) where factory() returns a fresh awaitable.
    """

    # A ready branch that loses this many consecutive selections is served
    # regardless of priority. Priorities express TIE-BREAKS (who wins a
    # same-instant race), not precedence: without the bound, a flooded
    # higher-priority source (e.g. a peer spraying cheap SyncRequests)
    # starves the pacemaker branch indefinitely — strictly weaker liveness
    # than the reference's randomized select!, which serves any ready branch
    # with p >= 1/2 per iteration.
    STARVATION_BOUND = 8

    def __init__(self, starvation_bound: int = STARVATION_BOUND) -> None:
        self._factories: dict[str, Any] = {}
        self._pending: dict[str, asyncio.Task] = {}
        self._priority: dict[str, int] = {}
        self._last: str | None = None  # round-robin fairness cursor
        self._starvation_bound = starvation_bound
        self._deferred: dict[str, int] = {}  # consecutive ready-but-passed

    def add(self, name: str, factory, priority: int = 0) -> None:
        """Register a branch. Lower `priority` wins ties (same-instant
        readiness); rotation for fairness applies only WITHIN a priority
        class. Use a higher number for branches that must lose ties, e.g.
        a pacemaker timer that should not beat a proposal already queued
        (firing the timeout first would bump last_voted_round and withhold
        the vote for a block that arrived in time)."""
        self._factories[name] = factory
        self._priority[name] = priority

    def remove(self, name: str) -> None:
        self._factories.pop(name, None)
        self._priority.pop(name, None)
        self._deferred.pop(name, None)
        task = self._pending.pop(name, None)
        if task is not None:
            task.cancel()

    def ready(self, name: str) -> bool:
        """True iff `name`'s armed awaitable has already completed — i.e. a
        value is waiting to be returned by the next `next()` call. Lets a
        branch handler's inner fast-path loop yield to a higher-priority
        branch (the armed task consumes the queue item, so checking the
        queue's emptiness misses it)."""
        task = self._pending.get(name)
        return task is not None and task.done()

    async def next(self) -> tuple[str, Any]:
        """Wait for the first ready branch; returns (name, value)."""
        for name, factory in self._factories.items():
            if name not in self._pending:
                self._pending[name] = asyncio.ensure_future(factory())
        while True:
            done, _ = await asyncio.wait(
                self._pending.values(), return_when=asyncio.FIRST_COMPLETED
            )
            # Deterministic round-robin within each priority class: start
            # AFTER the branch served last, so a branch whose source is
            # continuously ready (e.g. a flooded tx channel) cannot starve
            # later-registered branches (tokio's select! randomizes for the
            # same reason; rotation keeps tests deterministic).
            names = sorted(
                self._factories, key=lambda n: self._priority.get(n, 0)
            )
            if self._last in names:
                prio = self._priority.get(self._last, 0)
                cls = [n for n in names if self._priority.get(n, 0) == prio]
                i = cls.index(self._last) + 1
                rotated = cls[i:] + cls[:i]
                it = iter(rotated)
                names = [
                    next(it) if self._priority.get(n, 0) == prio else n
                    for n in names
                ]
            ready = [
                n
                for n in names
                if (t := self._pending.get(n)) is not None and t.done()
            ]
            if not ready:
                continue
            winner = ready[0]
            # Bounded deferral: branches passed over while ready accumulate a
            # loss count; one that reaches the bound is served now. At most
            # one branch can cross the bound per call (counts reset on win).
            for n in ready[1:]:
                self._deferred[n] = self._deferred.get(n, 0) + 1
                if self._deferred[n] >= self._starvation_bound:
                    winner = n
            self._deferred.pop(winner, None)
            task = self._pending.pop(winner)
            self._last = winner
            return winner, task.result()

    def close(self) -> None:
        for task in self._pending.values():
            task.cancel()
        self._pending.clear()


class Timer:
    """Resettable timer (reference consensus/src/timer.rs:10-34): `wait()`
    resolves `delay_ms` after the most recent reset(). Deadline-based so that
    a wait() armed BEFORE a reset still honours the new deadline (an
    event-based version orphans pending waiters on reset, silently killing
    the pacemaker of any replica that processed a block)."""

    # Remainders below this count as due, in wait() AND expired() alike.
    # A remainder inside the event loop's clock resolution (~1 ns) makes
    # wait_for schedule a timeout the loop treats as ALREADY due: it fires
    # without the clock advancing, the recomputed remainder is unchanged,
    # and the waiter livelocks re-arming it (observed on the chaos
    # virtual-time loop, where nothing else nudges the clock). One
    # microsecond is far below any protocol-relevant delay.
    RESOLUTION_S = 1e-6

    def __init__(self, delay_ms: int) -> None:
        self._delay = delay_ms / 1000.0
        self._deadline = 0.0
        self._moved: asyncio.Event | None = None
        self.reset()

    def reset(self) -> None:
        from . import tracing

        if tracing.enabled():
            tracing.event("timer.arm", delay_ms=round(self._delay * 1000.0, 3))
        loop = asyncio.get_event_loop()
        self._deadline = loop.time() + self._delay
        # Wake pending waiters: an in-flight sleep targets the OLD deadline,
        # and if the new one is EARLIER (pacemaker backoff shrinking the
        # delay back to base) the waiter would silently oversleep by the
        # difference. Waiters re-check the fresh deadline and re-sleep.
        if self._moved is not None:
            moved, self._moved = self._moved, None
            moved.set()

    def set_delay_ms(self, delay_ms: float) -> None:
        """Change the delay applied by FUTURE reset() calls (pacemaker
        backoff); the current deadline is untouched."""
        self._delay = delay_ms / 1000.0

    @property
    def delay_ms(self) -> float:
        return self._delay * 1000.0

    def expired(self) -> bool:
        """True iff the CURRENT deadline has passed (within RESOLUTION_S —
        must agree with wait(), or a sub-resolution remainder spins the
        selector: wait() returns 'due' while expired() says 'stale').
        Consumers multiplexing wait() with message channels must re-check
        this when the timer branch wins: a completed wait() may predate a
        reset() that raced it (a stale expiry must not fire a timeout for
        the new round)."""
        return (
            asyncio.get_event_loop().time() >= self._deadline - self.RESOLUTION_S
        )

    async def wait(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            remaining = self._deadline - loop.time()
            if remaining <= self.RESOLUTION_S:
                from . import tracing

                tracing.event("timer.fire")
                return
            if self._moved is None:
                self._moved = asyncio.Event()
            moved = self._moved
            try:
                await asyncio.wait_for(moved.wait(), remaining)
            except asyncio.TimeoutError:
                pass  # deadline may have moved either way; loop re-checks
