"""Log formatting with millisecond UTC timestamps (reference enables ms
timestamps under the benchmark feature, node/src/main.rs:51-52). The line
format is the benchmark LogParser's contract:

    [2026-07-29T12:34:56.789Z INFO hotstuff.consensus] Committed B5(...)
"""

from __future__ import annotations

import logging
import sys
import time


class UtcMsFormatter(logging.Formatter):
    converter = time.gmtime

    def formatTime(self, record, datefmt=None):
        ct = self.converter(record.created)
        return f"{time.strftime('%Y-%m-%dT%H:%M:%S', ct)}.{int(record.msecs):03d}Z"


def setup_logging(verbosity: int = 2, stream=None) -> None:
    """-v count -> level, like env_logger (node/src/main.rs:43-53):
    0=ERROR, 1=WARNING, 2=INFO, 3+=DEBUG. Logs go to stderr."""
    level = [logging.ERROR, logging.WARNING, logging.INFO][min(verbosity, 2)]
    if verbosity >= 3:
        level = logging.DEBUG
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(
        UtcMsFormatter("[%(asctime)s %(levelname)s %(name)s] %(message)s")
    )
    root = logging.getLogger()
    root.handlers.clear()
    root.addHandler(handler)
    root.setLevel(level)
