"""Log formatting with millisecond UTC timestamps (reference enables ms
timestamps under the benchmark feature, node/src/main.rs:51-52). The line
format is the benchmark LogParser's contract:

    [2026-07-29T12:34:56.789Z INFO hotstuff.consensus] Committed B5(...)
"""

from __future__ import annotations

import logging
import sys
import time


class UtcMsFormatter(logging.Formatter):
    converter = time.gmtime

    def formatTime(self, record, datefmt=None):
        ct = self.converter(record.created)
        return f"{time.strftime('%Y-%m-%dT%H:%M:%S', ct)}.{int(record.msecs):03d}Z"


_LEVEL = logging.INFO  # last level chosen by setup_logging
_HANDLER: logging.Handler | None = None  # last handler installed by it


def _level_of(verbosity: int) -> int:
    level = [logging.ERROR, logging.WARNING, logging.INFO][min(verbosity, 2)]
    return logging.DEBUG if verbosity >= 3 else level


def setup_logging(verbosity: int = 2, stream=None) -> None:
    """-v count -> level, like env_logger (node/src/main.rs:43-53):
    0=ERROR, 1=WARNING, 2=INFO, 3+=DEBUG. Logs go to stderr.

    The installed handler (and thus the chosen stream) is remembered so
    `quiet_jax_logs` can re-assert it after a device plugin reconfigures
    the root logger mid-run."""
    global _LEVEL, _HANDLER
    level = _LEVEL = _level_of(verbosity)
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(
        UtcMsFormatter("[%(asctime)s %(levelname)s %(name)s] %(message)s")
    )
    _HANDLER = handler
    root = logging.getLogger()
    root.handlers.clear()
    root.addHandler(handler)
    root.setLevel(level)


def quiet_jax_logs(verbosity: int = 2) -> None:
    """Cap jax's internal loggers (compilation-cache tracing logs every key
    lookup at DEBUG, duplicated by jax's own stderr handler — tens of MB per
    benchmark run) and re-assert the root logging config: the TPU device
    plugin flips the root logger to DEBUG (and may swap handlers) during
    device init. Idempotent and re-callable: call AFTER `import jax`, and
    again after the first device dispatch."""
    level = logging.WARNING if verbosity < 3 else logging.DEBUG
    for name in ("jax", "jaxlib"):
        lg = logging.getLogger(name)
        lg.setLevel(level)
        lg.handlers.clear()  # drop jax's duplicate stderr handler
    for name in list(logging.root.manager.loggerDict):
        if name.startswith(("jax.", "jaxlib.")):
            lg = logging.getLogger(name)
            lg.setLevel(logging.NOTSET)  # inherit from the capped parent
            lg.handlers.clear()
            lg.propagate = True
    root = logging.getLogger()
    if _HANDLER is not None and _HANDLER not in root.handlers:
        # Device init dropped the handler setup_logging installed: restore
        # it (same instance, same stream) so the LogParser line contract
        # survives a mid-run logging reconfiguration.
        root.addHandler(_HANDLER)
    root.setLevel(_LEVEL)
