"""In-process metrics registry + stage tracing for the consensus/TPU hot paths.

Every performance claim so far came from ad-hoc timers (`tools/profile_e2e.py`
exists because a 2.1x device-vs-e2e gap was asserted before it was measured);
this module makes per-stage breakdowns a permanent, machine-readable artifact:

  * `counter(name)` / `gauge(name)` / `histogram(name)` — get-or-create
    metrics in a process-global registry. Counters are monotonic; histograms
    use FIXED bucket bounds (no per-sample storage) and derive p50/p95/p99
    by interpolation inside the owning bucket, so recording is O(log buckets)
    and memory is O(buckets) no matter how hot the path.
  * `span(hist)` context manager and `@timed(name)` decorator — stage
    tracing; a span records wall seconds into its histogram on exit.
  * `snapshot_json()` — one compact JSON object (no raw buckets) for the
    periodic `METRICS {json}` log line that `benchmark.logs.LogParser`
    scrapes; `dump()` / `write_json(path)` — the full structured artifact
    (`bench.py --metrics-out`, `node run --metrics-out`).
  * `start_periodic_emitter(interval_s)` — a daemon thread logging the
    snapshot line on `hotstuff.metrics` at INFO.

Thread-safety: every metric guards its state with its own lock — the
verifier's upload/dispatch threads, the BatchVerificationService worker
threads, and the asyncio actor loops all record concurrently.

Overhead: recording is gated on a module-level flag (`HOTSTUFF_METRICS=0`
disables it); when disabled, `inc`/`record`/`span` are a single global read
and an early return — no lock, no clock read.

The canonical metric namespace is registered eagerly at import
(`_DEFAULT_NAMESPACE` below, documented in COMPONENTS.md), so a `dump()`
always carries the full schema — zeros included — even in processes that
never exercise (or cannot import) a given layer. Layer modules re-request
the same names via get-or-create, which keeps handles and schema in sync.

Dependency-free by design: stdlib only, no jax, no package-internal imports.
"""

from __future__ import annotations

import functools
import json
import logging
import math
import os
import threading
import time
from bisect import bisect_left
from typing import Callable, Sequence

log = logging.getLogger("hotstuff.metrics")

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "TIME_BUCKETS_S",
    "SIZE_BUCKETS",
    "counter",
    "gauge",
    "histogram",
    "span",
    "timed",
    "enabled",
    "enable",
    "dump",
    "percentile",
    "bucket_percentile",
    "snapshot_json",
    "emit_snapshot",
    "write_json",
    "reset",
    "start_periodic_emitter",
]

# Wall-seconds buckets (1-2-5 series, 10 us .. 60 s): spans from sub-ms
# kernel dispatches up to multi-second cold compiles land in distinct rows.
TIME_BUCKETS_S: tuple[float, ...] = (
    1e-5, 2e-5, 5e-5, 1e-4, 2e-4, 5e-4, 1e-3, 2e-3, 5e-3,
    1e-2, 2e-2, 5e-2, 0.1, 0.2, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0,
)

# Power-of-two buckets for batch/queue sizes (1 .. 128k — the verifier's
# bucket widths are powers of two, so each width is its own row).
SIZE_BUCKETS: tuple[float, ...] = tuple(float(2**i) for i in range(18))


def percentile(values: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over raw samples, 0.0 on empty input.

    The ONE list-percentile definition (ceil nearest-rank): ingress
    loadgen, the scheduler's LaneStats, and tools/trace_report.py's
    local mirror all report a "p99" computed the same way, so the same
    samples never yield different percentiles in different reports.
    (Histogram.percentile interpolates over buckets — a different
    estimator for pre-binned data, not a duplicate of this.)"""
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = max(0, min(len(ordered) - 1, math.ceil(q * len(ordered)) - 1))
    return ordered[idx]


def bucket_percentile(
    bounds: Sequence[float],
    counts: Sequence[int],
    total: int,
    lo: float,
    hi: float,
    q: float,
) -> float:
    """Interpolated percentile over bucket counts (the ONE bucket
    estimator: Histogram.percentile feeds its observed min/max as the
    edge clamps; the telemetry plane's windowed delta percentiles have no
    observed range, so they pass [0, last finite bound]). `counts` has
    one extra overflow entry past `bounds`; `total` is sum(counts),
    passed in because Histogram reads it under its snapshot lock."""
    target = q * total
    cum = 0
    for i, c in enumerate(counts):
        if c and cum + c >= target:
            b_lo = float(bounds[i - 1]) if i > 0 else lo
            b_hi = float(bounds[i]) if i < len(bounds) else hi
            b_lo = max(b_lo, lo)  # clamp edges to the caller's range
            b_hi = max(min(b_hi, hi), b_lo)
            return b_lo + (b_hi - b_lo) * ((target - cum) / c)
        cum += c
    return hi

_enabled = os.environ.get("HOTSTUFF_METRICS", "1") != "0"

# Metric locks are RE-ENTRANT: the node's SIGTERM handler flushes a dump()
# on the interrupted main thread, which may be parked inside a record()'s
# critical section — a plain Lock would deadlock the exit path (a torn read
# of one in-flight sample is acceptable for a final snapshot; a hang is not).
_new_lock = threading.RLock


def enabled() -> bool:
    return _enabled


def enable(on: bool = True) -> None:
    """Flip recording globally (registration is always allowed)."""
    global _enabled
    _enabled = on


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0
        self._lock = _new_lock()

    def inc(self, n: int = 1) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Last-write-wins scalar (e.g. the current consensus round)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0.0
        self._lock = _new_lock()

    def set(self, v: float) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value = v

    def add(self, v: float) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value += v

    @property
    def value(self) -> float:
        return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0


class Histogram:
    """Fixed-bucket histogram with interpolated percentiles.

    `bounds` are the inclusive upper edges of the finite buckets; one
    overflow bucket catches everything above the last bound. Percentiles
    interpolate linearly inside the owning bucket (clamped to the observed
    min/max at the edges), so their error is bounded by the bucket width —
    the resolution contract callers pick via `buckets`.
    """

    __slots__ = ("name", "bounds", "_counts", "_count", "_sum", "_min", "_max", "_lock")

    def __init__(self, name: str, buckets: Sequence[float] = TIME_BUCKETS_S) -> None:
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(f"histogram {name}: buckets must be sorted and non-empty")
        self.name = name
        self.bounds = tuple(float(b) for b in buckets)
        self._counts = [0] * (len(self.bounds) + 1)  # +1 overflow
        self._count = 0
        self._sum = 0.0
        self._min = float("inf")
        self._max = float("-inf")
        self._lock = _new_lock()

    def record(self, v: float) -> None:
        if not _enabled:
            return
        i = bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def _snapshot(self) -> tuple[list[int], int, float, float, float]:
        """One locked copy of (bucket counts, count, sum, min, max)."""
        with self._lock:
            return (
                list(self._counts), self._count, self._sum, self._min, self._max
            )

    def _percentile_from(
        self, counts: list[int], total: int, lo_obs: float, hi_obs: float,
        q: float,
    ) -> float:
        return bucket_percentile(self.bounds, counts, total, lo_obs, hi_obs, q)

    def percentile(self, q: float) -> float:
        """q in [0, 1] -> interpolated value; 0.0 on an empty histogram."""
        counts, total, _s, lo_obs, hi_obs = self._snapshot()
        if total == 0:
            return 0.0
        return self._percentile_from(counts, total, lo_obs, hi_obs, q)

    def summary(self) -> dict:
        """All fields derive from ONE locked snapshot, so concurrent
        recording cannot yield an internally inconsistent summary (e.g.
        p95 < p50, or a count matching none of the percentile bases)."""
        counts, total, s, lo, hi = self._snapshot()
        if total == 0:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                    "mean": 0.0, "p50": 0.0, "p95": 0.0, "p99": 0.0}
        pct = lambda q: self._percentile_from(counts, total, lo, hi, q)
        return {
            "count": total,
            "sum": s,
            "min": lo,
            "max": hi,
            "mean": s / total,
            "p50": pct(0.50),
            "p95": pct(0.95),
            "p99": pct(0.99),
        }

    def buckets_dict(self) -> dict:
        with self._lock:
            counts = list(self._counts)
        return {"le": list(self.bounds) + ["+inf"], "counts": counts}

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._count = 0
            self._sum = 0.0
            self._min = float("inf")
            self._max = float("-inf")


class _Span:
    """Context manager timing one stage into a histogram (see `span`)."""

    __slots__ = ("_hist", "_t0")

    def __init__(self, hist: Histogram) -> None:
        self._hist = hist
        self._t0 = None

    def __enter__(self) -> "_Span":
        if _enabled:
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        if self._t0 is not None and _enabled:
            self._hist.record(time.perf_counter() - self._t0)
        self._t0 = None


class Registry:
    """Named metrics, get-or-create. One process-global default below."""

    def __init__(self) -> None:
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._lock = _new_lock()

    def _get_or_create(self, name: str, kind, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {kind.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter, lambda: Counter(name))

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge, lambda: Gauge(name))

    def histogram(
        self, name: str, buckets: Sequence[float] = TIME_BUCKETS_S
    ) -> Histogram:
        return self._get_or_create(name, Histogram, lambda: Histogram(name, buckets))

    def dump(self, include_buckets: bool = True) -> dict:
        """Full structured artifact (the `--metrics-out` JSON)."""
        counters, gauges, hists = {}, {}, {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if isinstance(m, Counter):
                counters[m.name] = m.value
            elif isinstance(m, Gauge):
                gauges[m.name] = m.value
            else:
                summary = m.summary()
                if include_buckets:
                    summary["buckets"] = m.buckets_dict()
                hists[m.name] = summary
        return {
            "v": 1,
            "enabled": _enabled,
            "counters": counters,
            "gauges": gauges,
            "histograms": hists,
        }

    def snapshot_json(self) -> str:
        """Compact one-line JSON (summaries only) for the METRICS log line."""
        return json.dumps(
            self.dump(include_buckets=False), separators=(",", ":"), sort_keys=True
        )

    def write_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.dump(), f, indent=2, sort_keys=True)
            f.write("\n")

    def reset(self) -> None:
        """Zero every metric; registrations are kept (test isolation)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m._reset()


REGISTRY = Registry()


def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name: str, buckets: Sequence[float] = TIME_BUCKETS_S) -> Histogram:
    return REGISTRY.histogram(name, buckets)


def span(hist: Histogram | str) -> _Span:
    """`with metrics.span(h): ...` — time the block into histogram `h`.
    Hot paths should pass a pre-created Histogram handle (a string does a
    registry lookup per call)."""
    if isinstance(hist, str):
        hist = REGISTRY.histogram(hist)
    return _Span(hist)


def timed(name: str, buckets: Sequence[float] = TIME_BUCKETS_S) -> Callable:
    """Decorator form of `span`: records each call's wall seconds."""
    h = REGISTRY.histogram(name, buckets)

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _enabled:
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                h.record(time.perf_counter() - t0)

        return wrapper

    return deco


def dump(include_buckets: bool = True) -> dict:
    return REGISTRY.dump(include_buckets)


def snapshot_json() -> str:
    return REGISTRY.snapshot_json()


def write_json(path: str) -> None:
    REGISTRY.write_json(path)


def reset() -> None:
    REGISTRY.reset()


def emit_snapshot() -> None:
    """Log one `METRICS {json}` line (the LogParser scraping contract)."""
    log.info("METRICS %s", snapshot_json())


_emitter_stop: threading.Event | None = None
_emitter_lock = threading.Lock()


def start_periodic_emitter(interval_s: float = 5.0) -> threading.Event | None:
    """Emit a snapshot line every `interval_s` from a daemon thread; returns
    the stop event (set() to halt), or None when interval <= 0 or an emitter
    is already running."""
    global _emitter_stop
    if interval_s <= 0:
        return None
    with _emitter_lock:
        if _emitter_stop is not None and not _emitter_stop.is_set():
            return None
        stop = _emitter_stop = threading.Event()

    def _loop() -> None:
        while not stop.wait(interval_s):
            if _enabled:
                emit_snapshot()

    threading.Thread(target=_loop, name="metrics-emitter", daemon=True).start()
    return stop


# --- canonical namespace ----------------------------------------------------
#
# (name, kind, buckets) — the schema of record, documented as the metric
# naming table in COMPONENTS.md. Registered eagerly so every dump carries
# the full schema with zeros for layers the process never exercised.

_DEFAULT_NAMESPACE: tuple[tuple[str, str, tuple[float, ...] | None], ...] = (
    # ops/ed25519.py + crypto/tpu_backend.py — verifier hot path
    ("verifier.stage_s", "histogram", None),
    ("verifier.upload_s", "histogram", None),
    ("verifier.dispatch_s", "histogram", None),
    ("verifier.readback_s", "histogram", None),
    ("verifier.e2e_s", "histogram", None),
    ("verifier.batch_size", "histogram", SIZE_BUCKETS),
    ("verifier.sigs", "counter", None),
    ("verifier.batches", "counter", None),
    ("verifier.chunks", "counter", None),
    ("verifier.device_hash_fallbacks", "counter", None),
    # committee-resident key precompute + verified-signature dedup
    ("verifier.decompressions", "counter", None),
    ("verifier.table_builds", "counter", None),
    ("verifier.pad_lanes", "counter", None),
    ("verifier.committee_batches", "counter", None),
    ("verifier.committee_sigs", "counter", None),
    ("verifier.committee_registrations", "counter", None),
    ("verifier.committee_misses", "counter", None),
    ("verifier.committee_size", "gauge", None),
    ("verifier.crossover_fallbacks", "counter", None),
    ("verifier.dedup_hits", "counter", None),
    ("verifier.dedup_misses", "counter", None),
    ("verifier.dedup_inserts", "counter", None),
    ("verifier.dedup_evictions", "counter", None),
    ("verifier.rejected_sigs", "counter", None),
    ("verifier.committee_rejected_sigs", "counter", None),
    # ops/bls.py — batched G1 public-key aggregation kernel (§5.5o).
    # host_fallbacks counts CommitteeTable aggregations that ran the exact
    # pure-python fold because jax was unavailable on the host.
    ("bls.table_builds", "counter", None),
    ("bls.aggregations", "counter", None),
    ("bls.points_aggregated", "counter", None),
    ("bls.host_fallbacks", "counter", None),
    ("crypto.tpu_batches", "counter", None),
    ("crypto.tpu_sigs", "counter", None),
    ("crypto.cpu_batches", "counter", None),
    ("crypto.cpu_sigs", "counter", None),
    ("crypto.batch_size", "histogram", SIZE_BUCKETS),
    # crypto/scheduler.py — continuous-batching device scheduler. One
    # queue-delay histogram PER REGISTERED SOURCE CLASS: the starvation
    # lint (the graftlint `scheduler` pass) fails if a class in
    # scheduler.SOURCE_CLASSES has no row here.
    ("scheduler.submitted", "counter", None),
    ("scheduler.dispatched_groups", "counter", None),
    ("scheduler.buckets", "counter", None),
    ("scheduler.critical_dispatches", "counter", None),
    ("scheduler.size_flushes", "counter", None),
    ("scheduler.grid_flushes", "counter", None),
    ("scheduler.deadline_flushes", "counter", None),
    ("scheduler.preempt_closes", "counter", None),
    ("scheduler.depth", "gauge", None),
    ("scheduler.bucket_size", "histogram", SIZE_BUCKETS),
    ("scheduler.queue_consensus_s", "histogram", None),
    ("scheduler.queue_aggregate_s", "histogram", None),
    ("scheduler.queue_sync_s", "histogram", None),
    ("scheduler.queue_ingress_s", "histogram", None),
    ("scheduler.queue_mempool_s", "histogram", None),
    # ops/pipeline.py — double-buffered async dispatch pipeline (§5.5i).
    # `pipeline.steals` is incremented by crypto/scheduler.py's cross-chip
    # work-stealing bulk dispatch; the rest by DispatchPipeline itself.
    ("pipeline.chunks", "counter", None),
    ("pipeline.depth", "gauge", None),
    ("pipeline.inflight", "gauge", None),
    ("pipeline.stalls", "counter", None),
    ("pipeline.stall_s", "histogram", None),
    ("pipeline.buffer_reuse", "counter", None),
    ("pipeline.buffer_allocs", "counter", None),
    ("pipeline.steals", "counter", None),
    # consensus/core.py + aggregator.py + synchronizer.py
    ("consensus.proposals", "counter", None),
    ("consensus.votes", "counter", None),
    ("consensus.commits", "counter", None),
    ("consensus.timeouts", "counter", None),
    ("consensus.qcs", "counter", None),
    ("consensus.tcs", "counter", None),
    ("consensus.sync_requests", "counter", None),
    ("consensus.sync_retries", "counter", None),
    ("consensus.sync_requests_served", "counter", None),
    ("consensus.sync_abandoned", "counter", None),
    ("consensus.sync_escalations", "counter", None),
    # consensus/synchronizer.py + core.py — batched catch-up range sync
    ("sync.range_requests", "counter", None),
    ("sync.range_served", "counter", None),
    ("sync.range_replies", "counter", None),
    ("sync.range_blocks", "counter", None),
    ("sync.parked_blocks", "counter", None),
    # consensus/reconfig.py — dynamic validator reconfiguration
    ("reconfig.epoch_switches", "counter", None),
    ("reconfig.proposed", "counter", None),
    ("reconfig.rejected", "counter", None),
    ("reconfig.late_applies", "counter", None),
    ("reconfig.epoch", "gauge", None),
    # consensus/reconfig.py + core.py — the epoch-final handoff (§5.5j):
    # wall-withheld certification acts, dead-fork pending drops, the
    # boundary-edge QC commit unlock, and the per-handoff lag histogram
    # (rounds the commit trigger landed past activation-1 — 0 on every
    # healthy handoff, >=1 exactly on a contract violation, which is
    # what the reconfig.handoff telemetry SLO keys on).
    ("reconfig.handoff_holds", "counter", None),
    ("reconfig.handoff_abandoned", "counter", None),
    ("reconfig.handoff_commits", "counter", None),
    ("reconfig.handoff_lag_rounds", "histogram", (0.5, 2.0, 8.0, 32.0)),
    # consensus/overlay.py — region-aware aggregation overlay (§5.5l).
    # vote_frames/timeout_frames count plane frames in BOTH modes (bundle
    # and legacy), so the timeout_storm matrix cells' frames-per-timeout
    # ratio is mode-comparable.
    ("agg.bundles_sent", "counter", None),
    ("agg.bundles_received", "counter", None),
    ("agg.entries_merged", "counter", None),
    ("agg.invalid_entries", "counter", None),
    ("agg.fallbacks", "counter", None),
    ("agg.vote_frames", "counter", None),
    ("agg.timeout_frames", "counter", None),
    # consensus/aggregator.py + core.py — constant-size certificate plane
    # (§5.5o). cert_bytes_committed counts wire bytes of EVERY committed
    # QC/TC (aggregate or entry-list, any crypto mode) so the fleet_rollup
    # bytes_per_committed_round column is mode-comparable across cells.
    ("agg.qcs_formed", "counter", None),
    ("agg.tcs_formed", "counter", None),
    ("agg.partials_merged", "counter", None),
    ("agg.partial_rejects", "counter", None),
    ("agg.cert_bytes_committed", "counter", None),
    # consensus/leader.py + core.py — region-aware election (§5.5p).
    # Counted per committed round whenever a region map is wired, in
    # EVERY elector mode; cross_region_hops_blind is the round-robin
    # counterfactual priced on the same rounds (in-artifact A/B).
    ("elect.rounds", "counter", None),
    ("elect.leader_region_matches", "counter", None),
    ("elect.cross_region_hops", "counter", None),
    ("elect.cross_region_hops_blind", "counter", None),
    ("consensus.round", "gauge", None),
    ("consensus.proposal_to_vote_s", "histogram", None),
    ("consensus.qc_form_s", "histogram", None),
    ("consensus.tc_form_s", "histogram", None),
    ("consensus.commit_latency_s", "histogram", None),
    # mempool/core.py
    ("mempool.payloads_own", "counter", None),
    ("mempool.payloads_other", "counter", None),
    ("mempool.payload_bytes", "counter", None),
    ("mempool.payload_requests_served", "counter", None),
    ("mempool.gossip_dropped", "counter", None),
    ("mempool.synthetic_skipped", "counter", None),
    ("mempool.requests_clamped", "counter", None),
    ("mempool.front_dropped", "counter", None),
    ("mempool.ingress_lane_txs", "counter", None),
    ("mempool.verify_batch_size", "histogram", SIZE_BUCKETS),
    # ingress/ — authenticated client plane with admission control
    ("ingress.received", "counter", None),
    ("ingress.admitted", "counter", None),
    ("ingress.shed", "counter", None),
    ("ingress.replays", "counter", None),
    ("ingress.malformed", "counter", None),
    ("ingress.verified_sigs", "counter", None),
    ("ingress.rejected_sigs", "counter", None),
    ("ingress.forwarded", "counter", None),
    ("ingress.lane_depth", "gauge", None),
    ("ingress.retry_after_ms", "histogram", SIZE_BUCKETS),
    ("ingress.verify_batch_size", "histogram", SIZE_BUCKETS),
    ("ingress.latency_s", "histogram", None),
    # proofs/ — commit-proof serving plane (registry + service)
    ("proofs.indexed", "counter", None),
    ("proofs.resolved", "counter", None),
    ("proofs.evicted", "counter", None),
    ("proofs.cert_mismatch", "counter", None),
    ("proofs.queries", "counter", None),
    ("proofs.served", "counter", None),
    ("proofs.unknown", "counter", None),
    ("proofs.subs_shed", "counter", None),
    ("proofs.malformed", "counter", None),
    ("proofs.registry_size", "gauge", None),
    ("proofs.serve_s", "histogram", None),
    ("proofs.proof_bytes", "histogram", SIZE_BUCKETS),
    # network/net.py
    ("net.bytes_sent", "counter", None),
    ("net.frames_sent", "counter", None),
    ("net.bytes_received", "counter", None),
    ("net.frames_received", "counter", None),
    ("net.send_failures", "counter", None),
    ("net.reconnects", "counter", None),
    ("net.dropped_full", "counter", None),
    ("net.decode_errors", "counter", None),
    ("net.backoff_seconds", "counter", None),
    ("net.backoff_drops", "counter", None),
    # network/net.py — per-peer link observatory roll-ups (the per-link
    # detail lives in the PeerLink ledger, not the registry)
    ("net.peer.links", "counter", None),
    ("net.peer.probes_sent", "counter", None),
    ("net.peer.pings_received", "counter", None),
    ("net.peer.pongs_received", "counter", None),
    ("net.peer.rtt_samples", "counter", None),
    # chaos/ — deterministic fault injection & invariant checking
    ("chaos.drops", "counter", None),
    ("chaos.delays", "counter", None),
    ("chaos.duplicates", "counter", None),
    ("chaos.reorders", "counter", None),
    ("chaos.partition_drops", "counter", None),
    ("chaos.unrouted", "counter", None),
    ("chaos.frames", "counter", None),
    ("chaos.forged_votes", "counter", None),
    ("chaos.forged_timeouts", "counter", None),
    ("chaos.equivocations", "counter", None),
    ("chaos.stale_replays", "counter", None),
    ("chaos.withheld_votes", "counter", None),
    ("chaos.crashes", "counter", None),
    ("chaos.restarts", "counter", None),
    ("chaos.late_boots", "counter", None),
    ("chaos.invariant_checks", "counter", None),
    ("chaos.invariant_violations", "counter", None),
    ("chaos.fault_trace_dropped", "counter", None),
    # chaos/trusted_crypto.py — keyed-hash stub signature scheme
    ("chaos.stub_signs", "counter", None),
    ("chaos.stub_verifies", "counter", None),
    ("chaos.stub_rejects", "counter", None),
    # chaos/trusted_crypto.py — aggregate analogue of the stub scheme
    # (TrustedAggScheme): XOR-combine partials, byte-exact recompute verify
    ("chaos.stub_agg_signs", "counter", None),
    ("chaos.stub_agg_verifies", "counter", None),
    ("chaos.stub_agg_rejects", "counter", None),
    # chaos/plan.py WanMatrix via chaos/transport.py — per-region RTT classes
    ("wan.frames", "counter", None),
    ("wan.cross_region_frames", "counter", None),
    # tools/chaos_run.py --matrix — scenario-matrix regression harness
    ("matrix.cells", "counter", None),
    ("matrix.cells_green", "counter", None),
    ("matrix.cells_red", "counter", None),
    ("matrix.regressions", "counter", None),
    # utils/tracing.py — causal tracing + flight recorder
    ("trace.events", "counter", None),
    ("trace.dropped", "counter", None),
    ("trace.dumps", "counter", None),
    ("trace.watchdog_triggers", "counter", None),
    ("trace.frames_tagged", "counter", None),
    ("trace.frames_stripped", "counter", None),
    # utils/telemetry.py — live telemetry plane (delta snapshots, SLO
    # burn-rate alerts, scrape endpoint)
    ("telemetry.snapshots", "counter", None),
    ("telemetry.slo_burn_fired", "counter", None),
    ("telemetry.slo_burn_cleared", "counter", None),
    ("telemetry.scrapes", "counter", None),
    ("telemetry.peer_views", "counter", None),
    # utils/incidents.py — run-level incident ledger (fault→alert→
    # recovery attribution, fleet MTTR accounting, burn budgets)
    ("incident.opened", "counter", None),
    ("incident.attributed", "counter", None),
    ("incident.unattributed", "counter", None),
    ("incident.mttd_s", "histogram", None),
    ("incident.mttr_s", "histogram", None),
    ("incident.budget_burn_s", "histogram", None),
    # ops/timeline.py — device-occupancy timeline
    ("timeline.intervals", "counter", None),
    ("timeline.dropped", "counter", None),
)


def register_defaults(registry: Registry | None = None) -> None:
    r = registry or REGISTRY
    for name, kind, buckets in _DEFAULT_NAMESPACE:
        if kind == "counter":
            r.counter(name)
        elif kind == "gauge":
            r.gauge(name)
        else:
            r.histogram(name, buckets or TIME_BUCKETS_S)


register_defaults()
