"""Compact deterministic binary codec for wire messages and storage.

Plays the role bincode plays in the reference (network/src/lib.rs:74,126):
a schema-less little-endian binary format driven by explicit per-type
encode/decode methods. Deterministic encoding matters because message digests
are computed over semantic content and signatures must round-trip exactly.
"""

from __future__ import annotations

import struct


class Writer:
    """Append-only byte sink with primitive writers."""

    __slots__ = ("_parts",)

    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def bytes(self) -> bytes:
        return b"".join(self._parts)

    def raw(self, b: bytes) -> None:
        self._parts.append(b)

    def u8(self, v: int) -> None:
        self._parts.append(struct.pack("<B", v))

    def u32(self, v: int) -> None:
        self._parts.append(struct.pack("<I", v))

    def u64(self, v: int) -> None:
        self._parts.append(struct.pack("<Q", v))

    def var_bytes(self, b: bytes) -> None:
        """Length-prefixed variable byte string."""
        self._parts.append(struct.pack("<I", len(b)))
        self._parts.append(b)

    def fixed(self, b: bytes, n: int) -> None:
        if len(b) != n:
            raise ValueError(f"expected {n} bytes, got {len(b)}")
        self._parts.append(b)

    def seq(self, items, write_one) -> None:
        self.u32(len(items))
        for it in items:
            write_one(self, it)


class Reader:
    """Cursor over an immutable byte buffer with primitive readers."""

    __slots__ = ("_buf", "_pos")

    def __init__(self, buf: bytes) -> None:
        self._buf = buf
        self._pos = 0

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._buf):
            raise SerdeError(
                f"buffer underrun: need {n} bytes at offset {self._pos}, have {len(self._buf)}"
            )
        out = self._buf[self._pos : self._pos + n]
        self._pos += n
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def var_bytes(self) -> bytes:
        n = self.u32()
        return self._take(n)

    def fixed(self, n: int) -> bytes:
        return self._take(n)

    def seq(self, read_one) -> list:
        n = self.u32()
        return [read_one(self) for _ in range(n)]

    def done(self) -> bool:
        return self._pos == len(self._buf)

    def expect_done(self) -> None:
        if not self.done():
            raise SerdeError(f"trailing garbage: {len(self._buf) - self._pos} bytes")


class SerdeError(Exception):
    """Malformed wire bytes (truncation, trailing data, bad tags)."""
