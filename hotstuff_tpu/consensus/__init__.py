from .config import Authority, Committee, Parameters
from .consensus import Consensus
from .messages import QC, TC, Block, LoopBack, Round, SyncRequest, Timeout, Vote

__all__ = [
    "Authority",
    "Committee",
    "Parameters",
    "Consensus",
    "QC",
    "TC",
    "Block",
    "LoopBack",
    "Round",
    "SyncRequest",
    "Timeout",
    "Vote",
]
