from .config import Authority, Committee, Parameters
from .consensus import Consensus
from .messages import (
    QC,
    TC,
    Block,
    LoopBack,
    Round,
    SyncRangeReply,
    SyncRangeRequest,
    SyncRequest,
    Timeout,
    Vote,
)
from .reconfig import EpochChange, EpochManager, EpochSchedule

__all__ = [
    "Authority",
    "Committee",
    "Parameters",
    "Consensus",
    "EpochChange",
    "EpochManager",
    "EpochSchedule",
    "QC",
    "TC",
    "Block",
    "LoopBack",
    "Round",
    "SyncRangeReply",
    "SyncRangeRequest",
    "SyncRequest",
    "Timeout",
    "Vote",
]
