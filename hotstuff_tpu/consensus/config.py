"""Consensus committee and parameters (reference consensus/src/config.rs).

Quorum math: with total stake N, quorum_threshold = 2N/3 + 1, so any two
quorums intersect in at least one honest authority when N = 3f+1
(consensus/src/config.rs:68-73).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import PublicKey
from ..network.net import Address


@dataclass(slots=True)
class Authority:
    stake: int
    address: Address


@dataclass(slots=True)
class Committee:
    """Voting authorities for one epoch (consensus/src/config.rs:31-88)."""

    authorities: dict[PublicKey, Authority]
    epoch: int = 1

    @staticmethod
    def new(info: list[tuple[PublicKey, int, Address]], epoch: int = 1) -> "Committee":
        return Committee(
            {name: Authority(stake, addr) for name, stake, addr in info}, epoch
        )

    def size(self) -> int:
        return len(self.authorities)

    def stake(self, name: PublicKey) -> int:
        auth = self.authorities.get(name)
        return auth.stake if auth else 0

    def total_votes(self) -> int:
        return sum(a.stake for a in self.authorities.values())

    def quorum_threshold(self) -> int:
        # 2N/3 + 1 (ensures any two quorums intersect in an honest node).
        return 2 * self.total_votes() // 3 + 1

    def address(self, name: PublicKey) -> Address | None:
        auth = self.authorities.get(name)
        return auth.address if auth else None

    def broadcast_addresses(self, myself: PublicKey) -> list[Address]:
        return [
            a.address for n, a in self.authorities.items() if n != myself
        ]

    def sorted_keys(self) -> list[PublicKey]:
        return sorted(self.authorities.keys())

    def to_json(self) -> dict:
        return {
            "epoch": self.epoch,
            "authorities": {
                name.encode_base64(): {
                    "stake": a.stake,
                    "address": f"{a.address[0]}:{a.address[1]}",
                }
                for name, a in self.authorities.items()
            },
        }

    @staticmethod
    def from_json(obj: dict) -> "Committee":
        auths = {}
        for name_b64, a in obj["authorities"].items():
            host, port = a["address"].rsplit(":", 1)
            auths[PublicKey.decode_base64(name_b64)] = Authority(
                a["stake"], (host, int(port))
            )
        return Committee(auths, obj.get("epoch", 1))


@dataclass(slots=True)
class Parameters:
    """Protocol tuning knobs with the reference defaults
    (consensus/src/config.rs:18-27)."""

    timeout_delay: int = 5_000  # ms before the pacemaker fires
    sync_retry_delay: int = 10_000  # ms between sync request retries
    max_payload_size: int = 500  # max bytes of payload digests per block
    min_block_delay: int = 100  # ms minimum spacing between blocks
    # Pacemaker exponential backoff (a liveness improvement over the
    # reference's fixed delay, consensus/src/timer.rs): each consecutive
    # local timeout multiplies the delay by `timeout_backoff` up to
    # `max_timeout_delay`; any QC that advances the round restores
    # `timeout_delay`. Under sustained overload a fixed pacemaker fires
    # storms of Timeout/TC work that compound the overload (246 timeouts in
    # the round-4 300 s saturation run); backoff lets the backlog drain.
    # 1.0 disables backoff (reference behavior).
    timeout_backoff: float = 2.0
    max_timeout_delay: int = 30_000  # ms cap for the backed-off delay
    # Region-aware aggregation overlay for the vote/timeout plane
    # (consensus/overlay.py). Default OFF: the all-to-all plane is the
    # committed-determinism baseline every pre-overlay scenario pins;
    # fleet-scale deployments (and the overlay chaos scenarios) opt in.
    aggregation_overlay: bool = False
    agg_fanout: int = 4  # tree arity AND the gossip-fallback peer count
    agg_hold_ms: int = 50  # interior merge window before forwarding up
    agg_fallback_ms: int = 500  # stalled-round bound before gossip fallback
    agg_max_forwards: int = 3  # upward re-forwards per (round, kind) key
    # Constant-size certificates (§5.5o): votes/timeouts carry aggregate
    # partials (one combined signature + committee bitmap) instead of
    # per-entry signature lists, and QC/TC wire forms become AggQC/AggTC.
    # Requires an installed aggsig scheme + key registry (the chaos
    # orchestrator wires both in trusted_crypto fleets). Default OFF:
    # legacy entry-list certificates are the committed-determinism
    # baseline, and mixed fleets interop by decoding both forms.
    aggregate_certs: bool = False
    agg_window: int = 8  # Handel score window: best-N partials kept per key
    # Network-observatory RTT probing (network/net.py peer ledger,
    # consensus/core.py probe ticker). 0 disables it — the default,
    # because probe frames share the chaos transport's per-link fault
    # streams with protocol traffic: enabling them shifts every
    # committed same-seed determinism pin. Scenarios that measure the
    # network (wan_observatory) opt in explicitly.
    probe_interval_ms: int = 0
    # Region-aware leader election (§5.5p, consensus/leader.py):
    # region-block rotation — the plurality WAN region's members lead
    # consecutively first, then the next region's, so the commit-critical
    # propose->certify pivot crosses regions only at region seams.
    # Default OFF: round-robin is the committed-determinism baseline;
    # the wan_election chaos cells and WAN deployments opt in. The
    # schedule stays a pure function of (round, committee, region map),
    # so flipping this on changes WHICH deterministic schedule runs,
    # never introduces nondeterminism.
    region_aware_election: bool = False
    # Leader-rooted vote collection (§5.5p): votes for round r flow to
    # round r's OWN leader (collector == leader's region head by
    # construction — under region-aware election the whole quorum path
    # stays inside the proposing region), and the finished certificate
    # rides ONE explicit handoff frame to round r+1's proposer. Default
    # OFF: the committed baseline roots the vote plane at the NEXT
    # leader, whose moving target pipelines the vote trip into the next
    # proposal broadcast — the wiring region placement cannot shorten.
    # The wan_election cells enable this in BOTH A/B arms so the only
    # varied bit is the election schedule itself.
    leader_collector: bool = False

    def log(self, log) -> None:
        # NOTE: these log entries are parsed by the benchmark LogParser.
        log.info("Timeout delay set to %s ms", self.timeout_delay)
        log.info("Sync retry delay set to %s ms", self.sync_retry_delay)
        log.info("Max payload size set to %s B", self.max_payload_size)
        log.info("Min block delay set to %s ms", self.min_block_delay)
        log.info("Timeout backoff set to %s", self.timeout_backoff)
        if self.probe_interval_ms:
            log.info("Probe interval set to %s ms", self.probe_interval_ms)
        if self.region_aware_election:
            log.info("Region-aware election enabled")
        if self.leader_collector:
            log.info("Leader-rooted vote collection enabled")

    def to_json(self) -> dict:
        return {
            "timeout_delay": self.timeout_delay,
            "sync_retry_delay": self.sync_retry_delay,
            "max_payload_size": self.max_payload_size,
            "min_block_delay": self.min_block_delay,
            "timeout_backoff": self.timeout_backoff,
            "max_timeout_delay": self.max_timeout_delay,
            "aggregation_overlay": self.aggregation_overlay,
            "agg_fanout": self.agg_fanout,
            "agg_hold_ms": self.agg_hold_ms,
            "agg_fallback_ms": self.agg_fallback_ms,
            "agg_max_forwards": self.agg_max_forwards,
            "aggregate_certs": self.aggregate_certs,
            "agg_window": self.agg_window,
            "probe_interval_ms": self.probe_interval_ms,
            "region_aware_election": self.region_aware_election,
            "leader_collector": self.leader_collector,
        }

    @staticmethod
    def from_json(obj: dict) -> "Parameters":
        p = Parameters()
        for k in vars(p) if not hasattr(Parameters, "__slots__") else Parameters.__slots__:
            if k in obj:
                setattr(p, k, obj[k])
        return p
