"""Leader election (reference consensus/src/leader.rs:16-20):
round-robin over the sorted authority keys."""

from __future__ import annotations

from ..crypto import PublicKey
from .config import Committee
from .messages import Round


class LeaderElector:
    def __init__(self, committee: Committee) -> None:
        self._keys: list[PublicKey] = committee.sorted_keys()

    def get_leader(self, round_: Round) -> PublicKey:
        return self._keys[round_ % len(self._keys)]
