"""Leader election (reference consensus/src/leader.rs:16-20).

Two electors share one seam (`get_leader(round) -> PublicKey`), selected
by `Parameters.region_aware_election` (consensus.py wiring):

  * `LeaderElector` — round-robin over the sorted authority keys — of
    the committee governing the round, so rotation crosses epoch
    boundaries with the committee (consensus/reconfig.py): a joined
    validator enters the rotation at its epoch's activation round and a
    departed one leaves it.

  * `RegionAwareElector` — region-block rotation (§5.5p): the rotation
    order groups members by WAN region — the plurality region first
    (most members; ties break on the smaller label, the same rule the
    aggregation overlay uses to place its timeout-plane collector) —
    and members lead CONSECUTIVELY within their region. Every member
    still leads exactly once per committee cycle (the identical
    fairness bound to round-robin, |committee| rounds) and every
    region's slot share equals its member share (quorum-weighted), but
    the commit-critical propose->certify pivot — round r's finished
    certificate reaching round r+1's proposer (a literal handoff frame
    under Parameters.leader_collector, which roots the vote tree at
    round r's own leader) — crosses regions only at
    the region-block seams: #regions pivots per cycle instead of
    ~(1 - sum(share^2)) of all rounds under interleaved round-robin.
    At n=64 over 4 balanced regions that is 4/64 vs ~48/64 cross-region
    pivots per committed round — the `elect.cross_region_hops` delta
    the wan_election matrix cells pin.

The region-aware schedule is a PURE function of (round, the committee
of that round, the frozen region map) — `elect_region_aware` — shared
verbatim by the elector and the chaos SafetyChecker's independent
derivation (chaos/invariants.py), so every honest node, a restarted
node, and the auditor compute bit-identical schedules. Nothing here may
read clocks, live RTTs, or any other mutable runtime state: measured
inputs are frozen ONCE at construction (see RegionAwareElector's
fallback order), never per round.
"""

from __future__ import annotations

from ..crypto import PublicKey
from .config import Committee
from .reconfig import Round, as_manager


class LeaderElector:
    def __init__(self, committee: Committee) -> None:
        # Committee or reconfig.EpochManager (per-epoch sorted keys are
        # cached inside the schedule — this resolves every round).
        self._epochs = as_manager(committee)

    def get_leader(self, round_: Round) -> PublicKey:
        keys = self._epochs.schedule.sorted_keys_for_round(round_)
        return keys[round_ % len(keys)]


def plurality_region(
    keys: list[PublicKey], region_of: dict[PublicKey, str]
) -> str:
    """The region label hosting the most of `keys` (unknown -> "");
    ties break on the smaller label — the overlay's collector-placement
    rule, so leader and timeout collector agree on "home" by
    construction."""
    counts: dict[str, int] = {}
    for pk in keys:
        label = region_of.get(pk, "")
        counts[label] = counts.get(label, 0) + 1
    return min(counts.items(), key=lambda kv: (-kv[1], kv[0]))[0]


def elect_region_aware(
    round_: Round, keys: list[PublicKey], region_of: dict[PublicKey, str]
) -> PublicKey:
    """The pure region-aware schedule rule. `keys` is the sorted
    committee of `round_`; `region_of` the frozen region map. The
    rotation order re-sorts the committee by (region size desc, region
    label, key) — plurality region first, members contiguous per region
    — and round r is led by position r mod |committee|. Degrades to
    plain round-robin when the map is empty or the committee spans a
    single region (a region-less fleet is bit-identical to the legacy
    elector)."""
    if not region_of:
        return keys[round_ % len(keys)]
    counts: dict[str, int] = {}
    for pk in keys:
        label = region_of.get(pk, "")
        counts[label] = counts.get(label, 0) + 1
    if len(counts) <= 1:
        return keys[round_ % len(keys)]
    ordered = sorted(
        keys,
        key=lambda pk: (
            -counts[region_of.get(pk, "")],
            region_of.get(pk, ""),
            pk,
        ),
    )
    return ordered[round_ % len(ordered)]


class RegionAwareElector(LeaderElector):
    """Latency-aware elector behind the same seam. Region-source
    fallback order, resolved ONCE at construction and frozen:

      1. `measured_rtts` — per-peer RTT EWMAs keyed by authority key
         (assembled by the caller from the network observatory's
         PeerViews, utils/telemetry.peer_views). Used only with FULL
         committee coverage (every genesis authority has at least one
         measured link), partitioned by utils/telemetry's RTT-class
         union-find — partial coverage would hand different nodes
         different maps and split the schedule.
      2. `region_of` — the seeded/overlay region map (the same map the
         aggregation overlay trees by; chaos wires the WanMatrix map
         here so every node shares it).
      3. Neither -> plain round-robin (LeaderElector semantics).
    """

    def __init__(
        self,
        committee: Committee,
        region_of: dict[PublicKey, str] | None = None,
        measured_rtts: dict[PublicKey, dict[PublicKey, float]] | None = None,
    ) -> None:
        super().__init__(committee)
        self._regions: dict[PublicKey, str] = dict(region_of or {})
        if measured_rtts:
            measured = self._regions_from_measurements(measured_rtts)
            if measured is not None:
                self._regions = measured

    def _regions_from_measurements(
        self, rtts: dict[PublicKey, dict[PublicKey, float]]
    ) -> dict[PublicKey, str] | None:
        # Lazy import: the elector stays dependency-light and the
        # telemetry module never becomes a consensus import requirement.
        from ..utils.telemetry import infer_fleet_regions

        genesis = self._epochs.schedule.sorted_keys_for_round(0)
        by_hex = {pk.data.hex(): pk for pk in genesis}
        latency: dict[str, dict[str, float]] = {}
        for a, row in sorted(rtts.items(), key=lambda kv: kv[0].data):
            cleaned = {
                b.data.hex(): float(v)
                for b, v in sorted(row.items(), key=lambda kv: kv[0].data)
                if v is not None
            }
            if cleaned:
                latency[a.data.hex()] = cleaned
        covered = set(latency) | {b for row in latency.values() for b in row}
        if not latency or not all(h in covered for h in by_hex):
            return None
        inferred = infer_fleet_regions(latency)
        return {
            by_hex[h]: label
            for h, label in sorted(inferred.items())
            if h in by_hex
        }

    @property
    def regions(self) -> dict[PublicKey, str]:
        """The frozen region map actually in effect (diagnostics)."""
        return dict(self._regions)

    def get_leader(self, round_: Round) -> PublicKey:
        keys = self._epochs.schedule.sorted_keys_for_round(round_)
        return elect_region_aware(round_, keys, self._regions)
