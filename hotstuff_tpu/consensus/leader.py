"""Leader election (reference consensus/src/leader.rs:16-20):
round-robin over the sorted authority keys — of the committee governing
the round, so rotation crosses epoch boundaries with the committee
(consensus/reconfig.py): a joined validator enters the rotation at its
epoch's activation round and a departed one leaves it."""

from __future__ import annotations

from ..crypto import PublicKey
from .config import Committee
from .reconfig import Round, as_manager


class LeaderElector:
    def __init__(self, committee: Committee) -> None:
        # Committee or reconfig.EpochManager (per-epoch sorted keys are
        # cached inside the schedule — this resolves every round).
        self._epochs = as_manager(committee)

    def get_leader(self, round_: Round) -> PublicKey:
        keys = self._epochs.schedule.sorted_keys_for_round(round_)
        return keys[round_ % len(keys)]
