"""Block-ancestry synchronizer (reference consensus/src/synchronizer.rs).

When a block's parent is missing locally, the synchronizer:
  1. requests the parent digest from ONE deterministically chosen peer
     (full-committee broadcast only after a retry — the fan-out
     escalation that tames retry storms; synchronizer.rs:56-65
     broadcasts immediately),
  2. spawns a waiter on store.notify_read(parent) that re-injects the
     blocked block into the core via LoopBack once the parent is stored
     (:104-107,68-76),
  3. re-sends stale requests every TIMER_ACCURACY ms, implementing a
     "perfect point-to-point link" over the fire-and-forget network
     (:79-93).

Catch-up extensions beyond the reference:

  * RANGE SYNC — when the blocked block sits more than
    RANGE_SYNC_THRESHOLD rounds past our committed round (a node joining
    from genesis, or returning after a long crash), a per-digest fetch
    would crawl: one block per request/retry cycle. Instead the
    synchronizer sends a SyncRangeRequest for the whole missing ancestor
    chain; the peer answers with up to MAX_RANGE_BATCH blocks oldest-
    first (consensus/messages.py), each verified through the normal
    proposal path, and the core chains the next batch eagerly
    (`continue_range`) until the target resolves.
  * UNVERIFIED PARKING — a proposal the node cannot validate yet
    (`fetch_unverified`): during an epoch reconfiguration a lagging node
    may receive blocks certified by a committee it has not learned
    (consensus/reconfig.py). The block is parked and RE-INJECTED RAW
    (not as LoopBack) once its parent arrives, so the core re-runs FULL
    validation with the epoch knowledge the synced ancestors installed.
    Nothing is trusted meanwhile: parked blocks only direct which
    ancestry to fetch.
  * CLEANUP — `cleanup(round_)` drops pending fetches and cancels
    waiters for branches at or below the committed round: an abandoned
    fork's entries used to live (and retry!) forever, since only a
    successful waiter popped them.

Sync traffic (requests, range requests) rides the network's URGENT
egress lane: it is the recovery path that un-stalls consensus and must
not queue behind bulk gossip (network/net.py NetSender lanes).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass

from ..crypto import Digest, PublicKey, sha512_32
from ..network.net import Address, NetMessage
from ..store import Store
from ..utils import metrics, tracing
from ..utils.actors import spawn
from ..utils.serde import Reader
from .messages import (
    MAX_RANGE_BATCH,
    Block,
    LoopBack,
    Round,
    SyncRangeRequest,
    SyncRequest,
    decode_stored_block,
    encode_consensus_message,
)
from .reconfig import as_manager

log = logging.getLogger("hotstuff.consensus")

TIMER_ACCURACY_MS = 5_000  # reference synchronizer.rs TIMER_ACCURACY

# Gap (blocked round - committed round) beyond which a per-digest fetch
# switches to batched range sync. Also the core's threshold for parking
# unverifiable far-ahead proposals (core.py CATCHUP path).
RANGE_SYNC_THRESHOLD = 8

# Bound on concurrently tracked blocked blocks: a Byzantine flood of
# fabricated far-future proposals must not grow the waiter set without
# limit (cleanup() reclaims abandoned entries as rounds commit).
WAITING_CAP = 1_024

# Serve-side bound on the ancestor walk answering one range request.
RANGE_WALK_CAP = 1_024

_M_SYNC_REQUESTS = metrics.counter("consensus.sync_requests")
_M_SYNC_RETRIES = metrics.counter("consensus.sync_retries")
_M_SYNC_ABANDONED = metrics.counter("consensus.sync_abandoned")
_M_SYNC_ESCALATIONS = metrics.counter("consensus.sync_escalations")
_M_RANGE_REQUESTS = metrics.counter("sync.range_requests")


@dataclass(slots=True)
class _Fetch:
    """State of one missing-parent fetch (keyed by the parent digest)."""

    ts: float  # last request instant (loop clock)
    round: Round  # round of the BLOCKED block (for cleanup)
    attempts: int = 0  # sends so far; >= 1 escalates to full broadcast
    ranged: bool = False  # batched range fetch instead of per-digest
    from_round: Round = 0  # floor sent with the last range request
    announced: bool = False  # "Range sync started" logged once


async def collect_range(
    store: Store,
    target: Digest,
    from_round: Round,
    cap: int = MAX_RANGE_BATCH,
    walk_cap: int = RANGE_WALK_CAP,
) -> list[Block]:
    """Serve-side walk: the ancestor chain ENDING at `target` (inclusive),
    truncated below at `from_round` (exclusive) and above at `cap`
    OLDEST blocks — the receiver must be able to verify each block
    against its already-stored parent, so a capped reply keeps the old
    end, not the new one. Returns [] when `target` is unknown."""
    chain: list[Block] = []
    digest = target
    for _ in range(walk_cap):
        raw = await store.read(digest.data)
        if raw is None:
            if not chain:
                return []  # unknown target: nothing to serve
            break
        block = decode_stored_block(raw)
        if block.round <= from_round:
            break
        chain.append(block)
        if block.qc.is_genesis():
            break
        digest = block.parent()
    chain.reverse()  # oldest first
    return chain[:cap]


class Synchronizer:
    def __init__(
        self,
        name: PublicKey,
        committee,  # Committee or reconfig.EpochManager
        store: Store,
        network_tx: asyncio.Queue,
        core_channel: asyncio.Queue,
        sync_retry_delay: int,
    ) -> None:
        self.name = name
        self.epochs = as_manager(committee)
        self.store = store
        self.network_tx = network_tx
        self.core_channel = core_channel
        self.sync_retry_delay = sync_retry_delay
        # parent digest -> fetch state (network request dedup/retry)
        self._pending: dict[Digest, _Fetch] = {}
        # blocked block digest -> (waiter task, blocked round): one waiter
        # per BLOCKED block — two different blocks may await the same
        # parent (reference synchronizer.rs:51 keys pending this way).
        self._waiting: dict[Digest, tuple[asyncio.Task, Round]] = {}
        self._committed_round: Round = 0
        self._retry_task = spawn(self._retry_loop(), name="consensus-sync-retry")

    @property
    def committee(self):
        return self.epochs.current()

    # -- commit-path bookkeeping --------------------------------------------

    def note_committed(self, round_: Round) -> None:
        self._committed_round = max(self._committed_round, round_)

    def cleanup(self, round_: Round) -> None:
        """Reclaim fetches for abandoned branches: a blocked block at or
        below the committed round can never commit (its round is taken),
        so its waiter task and retry entry are dead weight — and without
        this, `_pending` retries an unreachable digest forever (the
        pre-reconfig leak). Called from the core's commit path."""
        for blocked, (task, rnd) in list(self._waiting.items()):
            if rnd <= round_:
                task.cancel()
                del self._waiting[blocked]
                _M_SYNC_ABANDONED.inc()
        for digest, fetch in list(self._pending.items()):
            if fetch.round <= round_:
                del self._pending[digest]

    # -- fetch paths ---------------------------------------------------------

    async def get_parent_block(self, block: Block) -> Block | None:
        """Return the parent, or None after registering fetch + loopback
        (synchronizer.rs:131-145)."""
        if block.qc.is_genesis():
            return Block.genesis()
        parent = block.parent()
        raw = await self.store.read(parent.data)
        if raw is not None:
            return decode_stored_block(raw)
        await self._register(parent, block, reverify=False)
        return None

    async def get_ancestors(self, block: Block) -> tuple[Block, Block] | None:
        """(b0, b1) = grandparent, parent -- the 2-chain needed for the commit
        rule (synchronizer.rs:147-161)."""
        b1 = await self.get_parent_block(block)
        if b1 is None:
            return None
        b0 = await self.get_parent_block(b1)
        if b0 is None:
            # Parent present but grandparent missing: extremely rare (parent
            # was stored only after ITS ancestry check); waiter handles it.
            return None
        return b0, b1

    async def fetch_unverified(self, block: Block) -> bool:
        """Catch-up parking for a proposal that FAILED validation while
        sitting far past our round (possibly certified by an epoch we
        have not learned — see module docstring). Registers a range
        fetch for its claimed ancestry and arranges the RAW block's
        re-injection (full revalidation) once the parent arrives.
        Returns False when the parked set is at capacity (caller should
        drop the block and let retries recover)."""
        blocked = block.digest()
        if blocked not in self._waiting and len(self._waiting) >= WAITING_CAP:
            return False
        await self._register(block.parent(), block, reverify=True)
        return True

    async def fetch_certified(self, digest: Digest, round_: Round) -> bool:
        """Fetch a block we only know as a CERTIFICATE reference (a
        Timeout's embedded high_qc hash) that our store lacks.

        Two callers, one mechanism (consensus/core._handle_timeout):

        * stale-epoch BOOTSTRAP — a joiner admitted at an epoch boundary
          (or a node that missed several boundaries) may be unable to
          verify ANY live traffic, while the committee needs that very
          node for quorum, so no proposals flow and the proposal-parking
          seam (`fetch_unverified`) never fires; the unverifiable
          timeouts' high_qc still names a chain position to fetch;
        * certified-gap CLOSURE — a verified timeout's high_qc certifies
          a block we never received (the node ran ahead of its floor by
          adopting certificates during a stall); nothing else would ever
          deliver the block, since proposals reference it only as
          ancestry of FUTURE rounds that cannot form while the committee
          waits for this node.

        Nothing is trusted from the container — the digest only directs
        which ancestry to fetch, and every served block re-runs full
        validation. Per-digest for small gaps, batched range sync past
        RANGE_SYNC_THRESHOLD. Bounded: digest dedup, WAITING_CAP, and a
        one-range-pipeline gate (one catch-up at a time)."""
        gap = round_ - self._committed_round
        if gap <= 0:
            return False
        if digest in self._pending or digest in self._waiting:
            return False
        ranged = gap > RANGE_SYNC_THRESHOLD
        if ranged and any(f.ranged for f in self._pending.values()):
            return False  # an active pipeline is already closing the gap
        if len(self._waiting) >= WAITING_CAP:
            return False
        if await self.store.read(digest.data) is not None:
            return False
        fetch = _Fetch(
            ts=asyncio.get_running_loop().time(), round=round_, ranged=ranged
        )
        self._pending[digest] = fetch
        self._waiting[digest] = (
            spawn(
                self._certified_waiter(digest),
                name=f"sync-certified-{digest.short()}",
            ),
            round_,
        )
        await self._send(digest, fetch)
        return True

    async def _certified_waiter(self, digest: Digest) -> None:
        # No re-injection: the range replay already ran every block
        # (including the target) through the full proposal path — this
        # waiter only reclaims the fetch/waiting entries on arrival.
        await self.store.notify_read(digest.data)
        self._pending.pop(digest, None)
        self._waiting.pop(digest, None)

    async def _register(
        self, parent: Digest, block: Block, reverify: bool
    ) -> None:
        blocked = block.digest()
        if blocked not in self._waiting:
            if len(self._waiting) >= WAITING_CAP:
                log.warning(
                    "sync waiter set at capacity (%d); dropping %s",
                    WAITING_CAP,
                    block,
                )
                return
            self._waiting[blocked] = (
                spawn(
                    self._waiter(parent, block, reverify),
                    name=f"sync-wait-{parent.short()}",
                ),
                block.round,
            )
        if parent not in self._pending:
            # Loop clock, not time.monotonic(): identical on a production
            # loop, but under the chaos runner's virtual-time loop the
            # retry schedule must follow VIRTUAL time or dropped sync
            # requests would never be re-sent (wall time barely moves).
            gap = block.round - self._committed_round
            fetch = _Fetch(
                ts=asyncio.get_running_loop().time(),
                round=block.round,
                ranged=gap > RANGE_SYNC_THRESHOLD,
            )
            self._pending[parent] = fetch
            if fetch.ranged and any(
                f.ranged and f.round <= fetch.round
                for f in self._pending.values()
                if f is not fetch
            ):
                # Suppress a ranged send while a DEEPER (or equal) range
                # pipeline is active: during catch-up every live proposal
                # suspends on a DIFFERENT parent, and firing a
                # SyncRangeRequest per round would fan out near-identical
                # 64-block batches (the chains share ancestry). The entry
                # is registered but not sent: as the active pipeline
                # closes the gap, the waiter cascade resolves these; the
                # retry timer covers the residue if the active fetch
                # dies. A fetch BELOW every active one always sends — it
                # is the connecting fetch when a gap exceeds the serve
                # walk cap and a batch arrives detached from the
                # committed floor (its blocks suspend on an ancestor the
                # batch did not reach).
                return
            await self._send(parent, fetch)

    async def continue_range(self, target: Digest) -> None:
        """Eager batch chaining: the core processed a range reply that
        advanced the committed floor but the target is still missing —
        request the next batch immediately instead of waiting out the
        retry timer. No-progress replies deliberately fall through to the
        timer (a peer serving junk must not drive a hot request loop)."""
        fetch = self._pending.get(target)
        if fetch is None or not fetch.ranged:
            return
        if self._committed_round <= fetch.from_round:
            return  # no forward progress since the last request
        fetch.ts = asyncio.get_running_loop().time()
        # The deterministic first-choice peer just served a good batch:
        # keep the continuation on it instead of escalating to broadcast
        # (retries still escalate via the timer if it goes quiet).
        fetch.attempts = 0
        await self._send(target, fetch)

    async def _waiter(self, digest: Digest, blocked: Block, reverify: bool) -> None:
        await self.store.notify_read(digest.data)
        self._pending.pop(digest, None)
        self._waiting.pop(blocked.digest(), None)
        # Parked-unverified blocks re-enter as RAW proposals so the core
        # re-runs leader/signature/epoch validation with the ancestors
        # (and any committed epoch switches) now in place; ordinary
        # suspended blocks were already validated and LoopBack straight
        # into ordering.
        await self.core_channel.put(blocked if reverify else LoopBack(blocked))

    # -- request fan-out -----------------------------------------------------

    def _peers(self, digest: Digest, attempts: int) -> list[Address]:
        """Escalating fan-out: the first request goes to ONE peer chosen
        as a pure function of (digest, own key) — deterministic under
        chaos replay, uniformly spread across the committee — and only a
        retry escalates to the full broadcast. The old always-broadcast
        behaviour turned every missing digest into n-1 frames per retry
        tick across the whole committee (the retry-storm satellite)."""
        addrs = sorted(self.epochs.current().broadcast_addresses(self.name))
        if not addrs:
            return []
        if attempts == 0:
            i = int.from_bytes(
                sha512_32(digest.data + self.name.data)[:8], "little"
            ) % len(addrs)
            return [addrs[i]]
        return addrs

    async def _send(self, digest: Digest, fetch: _Fetch) -> None:
        addrs = self._peers(digest, fetch.attempts)
        if not addrs:
            return
        if fetch.attempts == 1:
            _M_SYNC_ESCALATIONS.inc()
        if fetch.ranged:
            _M_RANGE_REQUESTS.inc()
            fetch.from_round = self._committed_round
            if not fetch.announced:
                fetch.announced = True
                # NOTE: parsed by the benchmark LogParser (catch-up lag).
                log.info(
                    "Range sync started for %s: %d rounds behind",
                    digest.short(),
                    max(fetch.round - self._committed_round, 0),
                )
            if tracing.enabled():
                tracing.event(
                    "sync.request", digest=digest.short(), range=True,
                    from_round=fetch.from_round,
                )
            msg = SyncRangeRequest(digest, fetch.from_round, self.name)
        else:
            _M_SYNC_REQUESTS.inc()
            if tracing.enabled():
                tracing.event("sync.request", digest=digest.short())
            msg = SyncRequest(digest, self.name)
        fetch.attempts += 1
        data = encode_consensus_message(msg)
        # Urgent lane: recovery traffic must not queue behind bulk gossip.
        await self.network_tx.put(NetMessage(data, addrs, urgent=True))

    async def _retry_loop(self) -> None:
        while True:
            await asyncio.sleep(TIMER_ACCURACY_MS / 1000.0)
            await self._retry_pass(asyncio.get_running_loop().time())

    async def _retry_pass(self, now: float) -> None:
        """One sweep over the pending fetches (factored out of the loop
        for the frame-count regression tests). Re-sends any fetch whose
        last request is older than sync_retry_delay, escalating the
        fan-out (see `_peers`); `ts` resets so consecutive retries are
        spaced by the full retry delay, not the timer tick."""
        for digest, fetch in list(self._pending.items()):
            if (now - fetch.ts) * 1000.0 >= self.sync_retry_delay:
                log.debug("retrying sync request for %s", digest.short())
                _M_SYNC_RETRIES.inc()
                if tracing.enabled():
                    tracing.event("sync.retry", digest=digest.short())
                fetch.ts = now
                await self._send(digest, fetch)
