"""Block-ancestry synchronizer (reference consensus/src/synchronizer.rs).

When a block's parent is missing locally, the synchronizer:
  1. broadcasts a SyncRequest for the parent digest (synchronizer.rs:56-65),
  2. spawns a waiter on store.notify_read(parent) that re-injects the blocked
     block into the core via LoopBack once the parent is stored (:104-107,68-76),
  3. re-broadcasts stale requests every TIMER_ACCURACY ms, implementing a
     "perfect point-to-point link" over the fire-and-forget network (:79-93).
"""

from __future__ import annotations

import asyncio
import logging

from ..crypto import Digest, PublicKey
from ..network.net import NetMessage
from ..store import Store
from ..utils import metrics, tracing
from ..utils.actors import spawn
from .config import Committee
from .messages import (
    Block,
    LoopBack,
    SyncRequest,
    encode_consensus_message,
)

log = logging.getLogger("hotstuff.consensus")

TIMER_ACCURACY_MS = 5_000  # reference synchronizer.rs TIMER_ACCURACY

_M_SYNC_REQUESTS = metrics.counter("consensus.sync_requests")
_M_SYNC_RETRIES = metrics.counter("consensus.sync_retries")


class Synchronizer:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        store: Store,
        network_tx: asyncio.Queue,
        core_channel: asyncio.Queue,
        sync_retry_delay: int,
    ) -> None:
        self.name = name
        self.committee = committee
        self.store = store
        self.network_tx = network_tx
        self.core_channel = core_channel
        self.sync_retry_delay = sync_retry_delay
        # parent digest -> first-request timestamp (network request dedup/retry)
        self._pending: dict[Digest, float] = {}
        # blocked block digest -> waiter (one waiter per BLOCKED block: two
        # different blocks may await the same parent, reference
        # synchronizer.rs:51 keys pending by the blocked block)
        self._waiting: dict[Digest, asyncio.Task] = {}
        self._retry_task = spawn(self._retry_loop(), name="consensus-sync-retry")

    async def get_parent_block(self, block: Block) -> Block | None:
        """Return the parent, or None after registering fetch + loopback
        (synchronizer.rs:131-145)."""
        if block.qc.is_genesis():
            return Block.genesis()
        parent = block.parent()
        raw = await self.store.read(parent.data)
        if raw is not None:
            from ..utils.serde import Reader

            return Block.decode(Reader(raw))
        blocked = block.digest()
        if blocked not in self._waiting:
            self._waiting[blocked] = spawn(
                self._waiter(parent, block), name=f"sync-wait-{parent.short()}"
            )
        if parent not in self._pending:
            # Loop clock, not time.monotonic(): identical on a production
            # loop, but under the chaos runner's virtual-time loop the
            # retry schedule must follow VIRTUAL time or dropped sync
            # requests would never be re-broadcast (wall time barely moves).
            self._pending[parent] = asyncio.get_running_loop().time()
            await self._request(parent)
        return None

    async def get_ancestors(self, block: Block) -> tuple[Block, Block] | None:
        """(b0, b1) = grandparent, parent -- the 2-chain needed for the commit
        rule (synchronizer.rs:147-161)."""
        b1 = await self.get_parent_block(block)
        if b1 is None:
            return None
        b0 = await self.get_parent_block(b1)
        if b0 is None:
            # Parent present but grandparent missing: extremely rare (parent
            # was stored only after ITS ancestry check); waiter handles it.
            return None
        return b0, b1

    async def _waiter(self, digest: Digest, blocked: Block) -> None:
        await self.store.notify_read(digest.data)
        self._pending.pop(digest, None)
        self._waiting.pop(blocked.digest(), None)
        await self.core_channel.put(LoopBack(blocked))

    async def _request(self, digest: Digest) -> None:
        _M_SYNC_REQUESTS.inc()
        if tracing.enabled():
            tracing.event("sync.request", digest=digest.short())
        data = encode_consensus_message(SyncRequest(digest, self.name))
        addrs = self.committee.broadcast_addresses(self.name)
        await self.network_tx.put(NetMessage(data, addrs))

    async def _retry_loop(self) -> None:
        while True:
            await asyncio.sleep(TIMER_ACCURACY_MS / 1000.0)
            now = asyncio.get_running_loop().time()
            for digest, ts in list(self._pending.items()):
                if (now - ts) * 1000.0 >= self.sync_retry_delay:
                    log.debug("retrying sync request for %s", digest.short())
                    _M_SYNC_RETRIES.inc()
                    if tracing.enabled():
                        tracing.event("sync.retry", digest=digest.short())
                    await self._request(digest)
