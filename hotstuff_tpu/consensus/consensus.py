"""Consensus subsystem launcher (reference consensus/src/consensus.rs:20-105):
wires the net receiver/sender, leader elector, mempool driver, synchronizer,
and spawns the core state-machine actor.
"""

from __future__ import annotations

import asyncio
import logging

from ..crypto import PublicKey, SignatureService
from ..network import NetReceiver, NetSender
from ..network.net import Address
from ..store import Store
from ..utils.actors import channel, spawn
from .config import Committee, Parameters
from .core import Core
from .leader import LeaderElector, RegionAwareElector
from .mempool_driver import MempoolDriver
from .messages import decode_consensus_message
from .reconfig import EpochManager, as_manager
from .synchronizer import Synchronizer

log = logging.getLogger("hotstuff.consensus")


class Consensus:
    @staticmethod
    def run(
        name: PublicKey,
        committee: Committee,
        parameters: Parameters,
        store: Store,
        signature_service: SignatureService,
        mempool_channel: asyncio.Queue,
        commit_channel: asyncio.Queue,
        core_channel: asyncio.Queue | None = None,
        verification_service=None,
        epoch_manager: EpochManager | None = None,
        listen_address: Address | None = None,
        overlay_regions: dict[PublicKey, str] | None = None,
        agg_signer=None,
        proof_registry=None,
    ) -> Core:
        """Boot the consensus plane; returns the Core (its actor task is
        spawned). The committee addresses are this plane's listen ports.
        `core_channel` may be supplied by the composition root so other
        subsystems (the mempool payload synchronizer) can LoopBack blocks
        into the core (node/src/node.rs:34-89 channel wiring).

        `epoch_manager` (reconfig.py) is shared by the core, leader
        elector, aggregator and synchronizer, so a committed epoch change
        moves them to the successor committee atomically; one is built
        from the genesis committee when not supplied. `listen_address`
        covers a node that is NOT in the genesis committee — a validator
        expecting to JOIN at a later epoch boundary still needs a bound
        port to catch up and participate from. `overlay_regions` maps
        authority keys to WAN region labels for the aggregation overlay's
        region-aware tree (consensus/overlay.py); only consulted when
        Parameters.aggregation_overlay is on. `agg_signer` is this
        node's aggregate-scheme signing handle (crypto/aggsig.AggSigner);
        required — together with Parameters.aggregate_certs — for the
        node to EMIT aggregate votes/timeouts (§5.5o); inbound aggregate
        certificates are understood regardless. `proof_registry`
        (proofs/registry.py) receives every committed block with its
        certifying certificate, feeding the commit-proof serving plane
        (§5.5q)."""
        # NOTE: boot-time config echo; parsed by the benchmark harness.
        parameters.log(log)

        if core_channel is None:
            core_channel = channel()
        network_tx = channel()

        epochs = epoch_manager if epoch_manager is not None else as_manager(committee)
        address = committee.address(name) or listen_address
        assert address is not None, (
            "node must be in the committee or supply listen_address"
        )
        NetReceiver(
            ("0.0.0.0", address[1]),
            core_channel,
            decode=decode_consensus_message,
            name="consensus-receiver",
        )
        NetSender(network_tx, name="consensus-sender")

        # Elector seam (§5.5p): region-aware placement consumes the SAME
        # region map the aggregation overlay trees by, so the vote-plane
        # collector (overlay roots the tree at get_leader(round+1)) and
        # the leader co-locate by construction.
        leader_elector = (
            RegionAwareElector(epochs, region_of=overlay_regions)
            if parameters.region_aware_election
            else LeaderElector(epochs)
        )
        mempool_driver = MempoolDriver(mempool_channel)
        synchronizer = Synchronizer(
            name,
            epochs,
            store,
            network_tx,
            core_channel,
            parameters.sync_retry_delay,
        )
        core = Core(
            name,
            epochs,
            parameters,
            signature_service,
            store,
            leader_elector,
            mempool_driver,
            synchronizer,
            core_channel,
            network_tx,
            commit_channel,
            verification_service=verification_service,
            overlay_regions=overlay_regions,
            agg_signer=agg_signer,
            proof_registry=proof_registry,
        )
        spawn(core.run(), name="consensus-core")
        log.info(
            "Consensus node %s successfully booted on %s", name.short(), address
        )
        return core
