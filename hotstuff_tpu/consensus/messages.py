"""Consensus wire messages: Block, Vote, QC, Timeout, TC.

Capability parity with reference consensus/src/messages.rs:
  * Block{qc, tc?, author, round, payload: [Digest], signature}  (:22-76)
  * Vote{hash, round, author, signature}                         (:120-146)
  * QC{hash, round, votes: [(pk, sig)]} + quorum verify_batch    (:150-226)
  * Timeout{high_qc, round, author, signature}                   (:230-265)
  * TC{round, votes: [(pk, sig, high_qc_round)]}                 (:270-315)

Every signed artifact commits to a domain-separated SHA-512/32 digest of its
semantic content. A Vote signs the SAME digest a QC later verifies, so 2f+1
Vote signatures aggregate directly into a QC whose batch verification is the
TPU hot path (QC.verify -> Signature.verify_batch).

Aggregate certificate plane (§5.5o): AggQC/AggTC are the constant-size
forms — ONE aggregatable signature (crypto/aggsig seam) plus a fixed
64-byte committee bitmap instead of a per-author entry list, signing the
SAME `_vote_digest`/`_timeout_digest` preimages as the legacy forms, so
the cert FORM is a transport choice and never a new trust domain. Bit i
of a bitmap is member i of `_committee_at(committee, round).sorted_keys()`
— epoch-resolved, so a bitmap is meaningless outside its own round's
committee. Legacy entry-list forms still decode everywhere (mixed-fleet
interop); aggregate-carrying frames ride NEW envelope tags, which old
peers drop at `unknown consensus tag` — the same graceful-degradation
path Ping/Pong established.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from ..crypto import Digest, PublicKey, SecretKey, Signature, aggsig, sha512_32
from ..utils.serde import Reader, SerdeError, Writer
from .config import Committee
from .errors import (
    AuthorityReuseError,
    InvalidSignatureError,
    QCRequiresQuorumError,
    TCRequiresQuorumError,
    UnknownAuthorityError,
    ensure,
)
from .reconfig import EpochChange

Round = int  # u64

# Upper bound on blocks per SyncRangeReply: bounds the serve-side store
# walk, the reply frame size, and what a receiver will decode from an
# unauthenticated peer (the blocks themselves are self-verifying).
MAX_RANGE_BATCH = 64


def _committee_at(committee, round_: Round) -> Committee:
    """Resolve the committee governing `round_`. Verification paths accept
    either a bare Committee (static, the pre-reconfig behaviour) or an
    epoch resolver (reconfig.EpochManager / EpochSchedule): with dynamic
    reconfiguration, a certificate's quorum is judged against the
    committee of the certificate's OWN epoch — a boundary block's
    embedded QC may belong to the epoch before the block's."""
    resolver = getattr(committee, "committee_for_round", None)
    return committee if resolver is None else resolver(round_)


def _vote_digest(hash_: Digest, round_: Round) -> Digest:
    """Digest signed by a Vote and verified by a QC (must coincide)."""
    return Digest(sha512_32(b"HSVOTE" + hash_.data + struct.pack("<Q", round_)))


def _timeout_digest(round_: Round, high_qc_round: Round) -> Digest:
    """Digest signed by a Timeout and verified by a TC (must coincide)."""
    return Digest(
        sha512_32(b"HSTMO" + struct.pack("<QQ", round_, high_qc_round))
    )


def _encode_votes(w: Writer, votes: list[tuple[PublicKey, Signature]]) -> None:
    w.seq(
        votes,
        lambda wr, v: (wr.fixed(v[0].data, 32), wr.fixed(v[1].data, 64)),
    )


def _decode_votes(r: Reader) -> list[tuple[PublicKey, Signature]]:
    return r.seq(lambda rd: (PublicKey(rd.fixed(32)), Signature(rd.fixed(64))))


@dataclass(frozen=True, slots=True)
class QC:
    """Quorum certificate: 2f+1 vote signatures over one block digest
    (consensus/src/messages.rs:150-226)."""

    hash: Digest
    round: Round
    votes: tuple[tuple[PublicKey, Signature], ...]

    @staticmethod
    def genesis() -> "QC":
        return QC(Digest.zero(), 0, ())

    def is_genesis(self) -> bool:
        """Full equality with QC.genesis(): a forged round-0 QC with a
        non-zero hash must NOT bypass verification (the reference compares
        against QC::genesis() exactly, consensus/src/messages.rs)."""
        return self == QC.genesis()

    def signed_digest(self) -> Digest:
        return _vote_digest(self.hash, self.round)

    def check_quorum(self, committee: Committee) -> None:
        """Structural checks only: authority uniqueness, known stake, 2f+1
        weight (messages.rs:180-196) — against the committee of THIS QC's
        round/epoch (`_committee_at`). Signature checks are separate so the
        async path can batch them through the verification service."""
        committee = _committee_at(committee, self.round)
        weight = 0
        used: set[PublicKey] = set()
        for name, _ in self.votes:
            ensure(name not in used, AuthorityReuseError(name))
            stake = committee.stake(name)
            ensure(stake > 0, UnknownAuthorityError(name))
            used.add(name)
            weight += stake
        ensure(weight >= committee.quorum_threshold(), QCRequiresQuorumError())

    def verify(self, committee: Committee) -> None:
        """Quorum + uniqueness checks, then BATCH signature verification --
        the per-block crypto hot spot (messages.rs:180-198). Raises on failure."""
        self.check_quorum(committee)
        ok = Signature.verify_batch(self.signed_digest(), list(self.votes))
        ensure(ok, InvalidSignatureError("QC batch verification failed"))

    def signed_items(self) -> tuple[list[bytes], list[tuple[PublicKey, Signature]]]:
        """(messages, (pk, sig)) triples for batched service verification."""
        d = self.signed_digest().data
        return [d] * len(self.votes), list(self.votes)

    async def verify_async(
        self, committee: Committee, service, trace: str | None = None
    ) -> None:
        """verify() with the signature batch routed through the
        BatchVerificationService (off-loop, coalesced with other pending
        requests) instead of a synchronous backend call in the actor loop.
        Tagged `committee=True`: every vote is signed by a registered
        validator key, so the batch rides the committee-resident kernel
        (and dedup-cached votes skip the backend entirely). `trace` tags
        the service group with the block's trace id (utils/tracing.py)."""
        self.check_quorum(committee)
        msgs, pairs = self.signed_items()
        mask = await service.verify_group(
            msgs, pairs, urgent=True, committee=True, trace=trace,
            source="consensus"
        )
        ensure(all(mask), InvalidSignatureError("QC batch verification failed"))

    def encode(self, w: Writer) -> None:
        w.fixed(self.hash.data, 32)
        w.u64(self.round)
        _encode_votes(w, list(self.votes))

    @staticmethod
    def decode(r: Reader) -> "QC":
        return QC(Digest(r.fixed(32)), r.u64(), tuple(_decode_votes(r)))

    def __str__(self) -> str:
        return f"QC(B{self.round}({self.hash.short()}), {len(self.votes)} votes)"


@dataclass(frozen=True, slots=True)
class TC:
    """Timeout certificate: 2f+1 timeout signatures for one round; each vote
    carries the author's high_qc round (consensus/src/messages.rs:270-315)."""

    round: Round
    votes: tuple[tuple[PublicKey, Signature, Round], ...]

    def high_qc_rounds(self) -> list[Round]:
        return [r for _, _, r in self.votes]

    def check_quorum(self, committee: Committee) -> None:
        committee = _committee_at(committee, self.round)
        weight = 0
        used: set[PublicKey] = set()
        for name, _, _ in self.votes:
            ensure(name not in used, AuthorityReuseError(name))
            stake = committee.stake(name)
            ensure(stake > 0, UnknownAuthorityError(name))
            used.add(name)
            weight += stake
        ensure(weight >= committee.quorum_threshold(), TCRequiresQuorumError())

    def signed_items(self) -> tuple[list[bytes], list[tuple[PublicKey, Signature]]]:
        # Distinct messages (each binds its own high_qc_round): verify_batch_alt.
        msgs = [_timeout_digest(self.round, hr).data for _, _, hr in self.votes]
        pairs = [(pk, sig) for pk, sig, _ in self.votes]
        return msgs, pairs

    def verify(self, committee: Committee) -> None:
        self.check_quorum(committee)
        msgs, pairs = self.signed_items()
        ok = Signature.verify_batch_alt(msgs, pairs)
        ensure(ok, InvalidSignatureError("TC batch verification failed"))

    async def verify_async(
        self, committee: Committee, service, trace: str | None = None
    ) -> None:
        self.check_quorum(committee)
        msgs, pairs = self.signed_items()
        mask = await service.verify_group(
            msgs, pairs, urgent=True, committee=True, trace=trace,
            source="consensus"
        )
        ensure(all(mask), InvalidSignatureError("TC batch verification failed"))

    def encode(self, w: Writer) -> None:
        w.u64(self.round)
        w.seq(
            list(self.votes),
            lambda wr, v: (
                wr.fixed(v[0].data, 32),
                wr.fixed(v[1].data, 64),
                wr.u64(v[2]),
            ),
        )

    @staticmethod
    def decode(r: Reader) -> "TC":
        round_ = r.u64()
        votes = r.seq(
            lambda rd: (PublicKey(rd.fixed(32)), Signature(rd.fixed(64)), rd.u64())
        )
        return TC(round_, tuple(votes))

    def __str__(self) -> str:
        return f"TC(round {self.round}, {len(self.votes)} votes)"


def _resolve_agg_keys(members: list[PublicKey]) -> list[bytes]:
    """Committee identity -> aggregate public key, via the aggsig
    registry (certificates carry no keys — that is the O(1) point). A
    member without a registered aggregate key fails verification: the
    registry is the proof-of-possession boundary."""
    pks: list[bytes] = []
    for member in members:
        agg_pk = aggsig.agg_key_of(member.data)
        ensure(
            agg_pk is not None,
            InvalidSignatureError(f"no aggregate key registered for {member}"),
        )
        pks.append(agg_pk)
    return pks


def _bitmap_members(bitmap: int, committee: Committee) -> list[PublicKey]:
    try:
        return aggsig.members_of(bitmap, committee.sorted_keys())
    except ValueError as exc:
        raise UnknownAuthorityError(f"aggregate bitmap: {exc}") from None


def _encode_bitmap(w: Writer, bitmap: int) -> None:
    w.fixed(aggsig.bitmap_to_bytes(bitmap), aggsig.AGG_BITMAP_BYTES)


def _decode_bitmap(r: Reader) -> int:
    return aggsig.bitmap_from_bytes(r.fixed(aggsig.AGG_BITMAP_BYTES))


@dataclass(frozen=True, slots=True)
class AggQC:
    """Constant-size quorum certificate: ONE aggregate signature over
    `_vote_digest(hash, round)` plus the bitmap of signing members.
    Duck-type-compatible with QC everywhere the core reads certificates
    (.hash/.round/.is_genesis()/check_quorum/verify) — genesis itself
    stays the legacy QC.genesis() sentinel."""

    hash: Digest
    round: Round
    bitmap: int
    agg_sig: bytes

    def is_genesis(self) -> bool:
        return False

    def signed_digest(self) -> Digest:
        return _vote_digest(self.hash, self.round)

    def signers(self) -> int:
        return self.bitmap.bit_count()

    def check_quorum(self, committee: Committee) -> None:
        """Structural checks: bitmap within the round's committee,
        2f+1 stake. Uniqueness is free — a bitmap cannot name a member
        twice."""
        committee = _committee_at(committee, self.round)
        members = _bitmap_members(self.bitmap, committee)
        weight = sum(committee.stake(m) for m in members)
        ensure(weight >= committee.quorum_threshold(), QCRequiresQuorumError())

    def verify(self, committee: Committee) -> None:
        self.check_quorum(committee)
        own = _committee_at(committee, self.round)
        pks = _resolve_agg_keys(_bitmap_members(self.bitmap, own))
        ok = aggsig.active_agg_scheme().verify(
            pks, self.signed_digest().data, self.agg_sig
        )
        ensure(ok, InvalidSignatureError("aggregate QC verification failed"))

    async def verify_async(
        self, committee: Committee, service, trace: str | None = None
    ) -> None:
        """Aggregate verification is ONE combine-and-compare (stub) or
        one multi-pairing (exact) — there is no per-entry batch to
        coalesce, so it runs inline rather than through the
        verification service."""
        self.verify(committee)

    def encode(self, w: Writer) -> None:
        w.fixed(self.hash.data, 32)
        w.u64(self.round)
        _encode_bitmap(w, self.bitmap)
        w.var_bytes(self.agg_sig)

    @staticmethod
    def decode(r: Reader) -> "AggQC":
        return AggQC(
            Digest(r.fixed(32)), r.u64(), _decode_bitmap(r), r.var_bytes()
        )

    def __str__(self) -> str:
        return f"AggQC(B{self.round}({self.hash.short()}), {self.signers()} signers)"


@dataclass(frozen=True, slots=True)
class AggTC:
    """Constant-size timeout certificate: ONE aggregate signature
    spanning one signing GROUP per distinct high-qc round (members in
    group (hqr, bitmap) signed `_timeout_digest(round, hqr)`). Groups
    must be bitmap-disjoint; quorum is their combined stake. Group
    count is bounded by distinct hqr values among 2f+1 signers, so the
    certificate is O(#distinct hqrs) — in practice a handful — never
    O(n)."""

    round: Round
    groups: tuple[tuple[Round, int], ...]  # (high_qc_round, bitmap)
    agg_sig: bytes

    def high_qc_rounds(self) -> list[Round]:
        return [hqr for hqr, _ in self.groups]

    def signers(self) -> int:
        return sum(bm.bit_count() for _, bm in self.groups)

    def check_quorum(self, committee: Committee) -> None:
        committee = _committee_at(committee, self.round)
        weight = 0
        seen = 0
        for _, bm in self.groups:
            overlap = bm & seen
            if overlap:
                idx = (overlap & -overlap).bit_length() - 1
                raise AuthorityReuseError(committee.sorted_keys()[idx])
            seen |= bm
            weight += sum(
                committee.stake(m) for m in _bitmap_members(bm, committee)
            )
        ensure(weight >= committee.quorum_threshold(), TCRequiresQuorumError())

    def verify(self, committee: Committee) -> None:
        self.check_quorum(committee)
        own = _committee_at(committee, self.round)
        groups = [
            (
                _resolve_agg_keys(_bitmap_members(bm, own)),
                _timeout_digest(self.round, hqr).data,
            )
            for hqr, bm in self.groups
        ]
        ok = aggsig.active_agg_scheme().verify_groups(groups, self.agg_sig)
        ensure(ok, InvalidSignatureError("aggregate TC verification failed"))

    async def verify_async(
        self, committee: Committee, service, trace: str | None = None
    ) -> None:
        self.verify(committee)

    def encode(self, w: Writer) -> None:
        w.u64(self.round)
        w.seq(
            list(self.groups),
            lambda wr, g: (wr.u64(g[0]), _encode_bitmap(wr, g[1])),
        )
        w.var_bytes(self.agg_sig)

    @staticmethod
    def decode(r: Reader) -> "AggTC":
        round_ = r.u64()
        groups = tuple(r.seq(lambda rd: (rd.u64(), _decode_bitmap(rd))))
        if len(groups) > aggsig.MAX_AGG_COMMITTEE:
            raise SerdeError(f"aggregate TC over group cap: {len(groups)}")
        return AggTC(round_, groups, r.var_bytes())

    def __str__(self) -> str:
        return (
            f"AggTC(round {self.round}, {len(self.groups)} groups, "
            f"{self.signers()} signers)"
        )


# Versioned certificate codec: aggregate-carrying containers (v2 blocks,
# stored blobs, agg timeout bundles) prefix each certificate with one
# version byte so either form round-trips.
def encode_any_qc(w: Writer, qc) -> None:
    if isinstance(qc, AggQC):
        w.u8(1)
    else:
        w.u8(0)
    qc.encode(w)


def decode_any_qc(r: Reader):
    return AggQC.decode(r) if r.u8() else QC.decode(r)


def encode_any_tc(w: Writer, tc) -> None:
    if isinstance(tc, AggTC):
        w.u8(1)
    else:
        w.u8(0)
    tc.encode(w)


def decode_any_tc(r: Reader):
    return AggTC.decode(r) if r.u8() else TC.decode(r)


@dataclass(frozen=True, slots=True)
class Block:
    """A proposal: orders payload DIGESTS only (32 B each); payload bytes are
    disseminated by the mempool plane (consensus/src/messages.rs:22-117).

    Certificates may be legacy (QC/TC) or aggregate (AggQC/AggTC) forms;
    the block DIGEST commits to (qc.hash, qc.round) only, so it is
    independent of the certificate form — certificates are self-verifying
    and the form is a transport choice (module docstring)."""

    qc: QC | AggQC
    tc: TC | AggTC | None
    author: PublicKey
    round: Round
    payload: tuple[Digest, ...]
    signature: Signature
    # Optional committee-succession payload (consensus/reconfig.py): the
    # block digest commits to it, and the new committee activates only
    # once THIS block is 2-chain committed (the epoch-commit rule). A
    # carrying block is an EPOCH-FINAL POSITION: honest nodes that
    # admitted it refuse to certify rounds at or past the declared
    # activation until the commit lands, so the old committee certifies
    # through the boundary minus one and the successor owns everything
    # after — no certificate in the committed chain can ever be judged
    # by the wrong epoch's committee (§5.5j).
    reconfig: EpochChange | None = None
    # digest cache: read on every vote/store/commit/sync touch
    _digest: Digest | None = field(
        default=None, init=False, repr=False, compare=False
    )

    @staticmethod
    def genesis() -> "Block":
        return Block(
            QC.genesis(),
            None,
            PublicKey(bytes(32)),
            0,
            (),
            Signature(bytes(64)),
        )

    def is_genesis(self) -> bool:
        return self.round == 0

    def digest(self) -> Digest:
        if self._digest is None:
            object.__setattr__(
                self,
                "_digest",
                Block.make_digest(
                    self.author, self.round, self.payload, self.qc, self.reconfig
                ),
            )
        return self._digest

    def parent(self) -> Digest:
        return self.qc.hash

    @staticmethod
    def make_digest(
        author: PublicKey,
        round_: Round,
        payload: list[Digest],
        qc: QC | AggQC,
        reconfig: EpochChange | None = None,
    ) -> Digest:
        # graftlint: allow[wire-schema] proofs/messages.py recomputes this SAME artifact (CommitProof.block_digest) by design — one preimage, two sites
        h = b"HSBLOCK" + author.data + struct.pack("<Q", round_)
        for d in payload:
            h += d.data
        h += qc.hash.data + struct.pack("<Q", qc.round)
        if reconfig is not None:
            # Committed-to ONLY when present: reconfig-free blocks keep the
            # historical preimage byte-for-byte, and a relay can neither
            # strip nor alter a carried change without breaking the
            # author's signature over this digest.
            h += b"HSEPOCH" + reconfig.digest().data
        return Digest(sha512_32(h))

    @staticmethod
    def new_from_key(
        qc: QC,
        tc: TC | None,
        author: PublicKey,
        round_: Round,
        payload: list[Digest],
        secret: SecretKey,
        reconfig: EpochChange | None = None,
    ) -> "Block":
        """Sync constructor bypassing the SignatureService, as the reference
        test fixtures do (consensus/src/tests/common.rs:44-61)."""
        digest = Block.make_digest(author, round_, payload, qc, reconfig)
        return Block(
            qc, tc, author, round_, tuple(payload),
            Signature.new(digest, secret), reconfig,
        )

    def verify(self, committee: Committee) -> None:
        """Ingress checks (consensus/src/messages.rs:55-76): known author with
        stake, author signature, embedded QC, embedded TC, carried epoch
        change. Author stake resolves against the committee of THIS
        block's round; the certificates resolve against their own rounds
        inside their check_quorum."""
        own = _committee_at(committee, self.round)
        ensure(own.stake(self.author) > 0, UnknownAuthorityError(self.author))
        ok = self.signature.verify(self.digest(), self.author)
        ensure(ok, InvalidSignatureError(f"bad block signature B{self.round}"))
        if not self.qc.is_genesis():
            self.qc.verify(committee)
        if self.tc is not None:
            self.tc.verify(committee)
        if self.reconfig is not None:
            ensure(
                own.stake(self.reconfig.author) > 0,
                UnknownAuthorityError(self.reconfig.author),
            )
            ok = self.reconfig.signature.verify(
                self.reconfig.digest(), self.reconfig.author
            )
            ensure(
                ok, InvalidSignatureError(f"bad epoch-change signature B{self.round}")
            )

    async def verify_async(
        self, committee: Committee, service, trace: str | None = None
    ) -> None:
        """verify() with ALL signature checks (author + embedded QC + embedded
        TC + carried epoch change) submitted as ONE group to the
        BatchVerificationService: a single coalesced backend dispatch per
        block instead of synchronous calls in the consensus actor loop."""
        own = _committee_at(committee, self.round)
        ensure(own.stake(self.author) > 0, UnknownAuthorityError(self.author))
        msgs: list[bytes] = [self.digest().data]
        pairs: list[tuple[PublicKey, Signature]] = [(self.author, self.signature)]
        qc_lo = qc_hi = tc_lo = tc_hi = len(msgs)
        if isinstance(self.qc, AggQC):
            # One combine-and-compare (or one multi-pairing): no entry
            # batch to coalesce through the service — verified inline.
            self.qc.verify(committee)
        elif not self.qc.is_genesis():
            self.qc.check_quorum(committee)
            m, p = self.qc.signed_items()
            qc_lo, qc_hi = len(msgs), len(msgs) + len(m)
            msgs += m
            pairs += p
        if isinstance(self.tc, AggTC):
            self.tc.verify(committee)
        elif self.tc is not None:
            self.tc.check_quorum(committee)
            m, p = self.tc.signed_items()
            tc_lo, tc_hi = len(msgs), len(msgs) + len(m)
            msgs += m
            pairs += p
        ec_lo = len(msgs)
        if self.reconfig is not None:
            # The change must be signed by a CURRENT (block-round) epoch
            # authority; the successor committee governs nothing until the
            # carrying block commits and the activation round arrives.
            ensure(
                own.stake(self.reconfig.author) > 0,
                UnknownAuthorityError(self.reconfig.author),
            )
            msgs.append(self.reconfig.digest().data)
            pairs.append((self.reconfig.author, self.reconfig.signature))
        mask = await service.verify_group(
            msgs, pairs, urgent=True, committee=True, trace=trace,
            source="consensus"
        )
        ensure(mask[0], InvalidSignatureError(f"bad block signature B{self.round}"))
        ensure(
            all(mask[qc_lo:qc_hi]),
            InvalidSignatureError("QC batch verification failed"),
        )
        ensure(
            all(mask[tc_lo:tc_hi]),
            InvalidSignatureError("TC batch verification failed"),
        )
        ensure(
            all(mask[ec_lo:]),
            InvalidSignatureError(f"bad epoch-change signature B{self.round}"),
        )

    def has_agg_certs(self) -> bool:
        return isinstance(self.qc, AggQC) or isinstance(self.tc, AggTC)

    def encode(self, w: Writer) -> None:
        """LEGACY wire layout — byte-identical to every committed
        artifact. Blocks carrying aggregate certificates must use
        encode_v2 (the envelope and store helpers route on
        has_agg_certs)."""
        if self.has_agg_certs():
            raise TypeError(
                "aggregate-certificate block needs the v2 encoding"
            )
        self.qc.encode(w)
        if self.tc is None:
            w.u8(0)
        else:
            w.u8(1)
            self.tc.encode(w)
        w.fixed(self.author.data, 32)
        w.u64(self.round)
        w.seq(list(self.payload), lambda wr, d: wr.fixed(d.data, 32))
        w.fixed(self.signature.data, 64)
        if self.reconfig is None:
            w.u8(0)
        else:
            w.u8(1)
            self.reconfig.encode(w)

    @staticmethod
    def decode(r: Reader) -> "Block":
        qc = QC.decode(r)
        tc = TC.decode(r) if r.u8() else None
        author = PublicKey(r.fixed(32))
        round_ = r.u64()
        payload = tuple(r.seq(lambda rd: Digest(rd.fixed(32))))
        sig = Signature(r.fixed(64))
        reconfig = EpochChange.decode(r) if r.u8() else None
        return Block(qc, tc, author, round_, payload, sig, reconfig)

    def encode_v2(self, w: Writer) -> None:
        """Same field order as the legacy layout with each certificate
        behind a one-byte version prefix (encode_any_qc/tc) — the form
        aggregate-carrying frames and store blobs use."""
        encode_any_qc(w, self.qc)
        if self.tc is None:
            w.u8(0)
        else:
            w.u8(1)
            encode_any_tc(w, self.tc)
        w.fixed(self.author.data, 32)
        w.u64(self.round)
        w.seq(list(self.payload), lambda wr, d: wr.fixed(d.data, 32))
        w.fixed(self.signature.data, 64)
        if self.reconfig is None:
            w.u8(0)
        else:
            w.u8(1)
            self.reconfig.encode(w)

    @staticmethod
    def decode_v2(r: Reader) -> "Block":
        qc = decode_any_qc(r)
        tc = decode_any_tc(r) if r.u8() else None
        author = PublicKey(r.fixed(32))
        round_ = r.u64()
        payload = tuple(r.seq(lambda rd: Digest(rd.fixed(32))))
        sig = Signature(r.fixed(64))
        reconfig = EpochChange.decode(r) if r.u8() else None
        return Block(qc, tc, author, round_, payload, sig, reconfig)

    def size(self) -> int:
        w = Writer()
        if self.has_agg_certs():
            self.encode_v2(w)
        else:
            self.encode(w)
        return len(w.bytes())

    def certificate_bytes(self) -> int:
        """Encoded size of the certificates this block carries (QC plus
        TC if any) — the quantity the `bytes_per_committed_round` matrix
        column accounts per commit. Uses each certificate's own wire
        encoding, so legacy forms report O(96·quorum) and aggregate
        forms report a committee-size-independent constant."""
        w = Writer()
        self.qc.encode(w)
        if self.tc is not None:
            self.tc.encode(w)
        return len(w.bytes())

    def __str__(self) -> str:
        return f"B{self.round}({self.digest().short()})"


def _encode_any_block(w: Writer, block: Block) -> None:
    if block.has_agg_certs():
        w.u8(1)
        block.encode_v2(w)
    else:
        w.u8(0)
        block.encode(w)


def _decode_any_block(r: Reader) -> Block:
    return Block.decode_v2(r) if r.u8() else Block.decode(r)


def encode_stored_block(block: Block) -> bytes:
    """Store-blob form: one version byte then the matching block layout.
    Every store read/write goes through this pair so a store can hold
    legacy and aggregate-certificate blocks side by side (stores are
    per-run; no cross-version migration concern)."""
    w = Writer()
    _encode_any_block(w, block)
    return w.bytes()


def decode_stored_block(data: bytes) -> Block:
    r = Reader(data)
    block = _decode_any_block(r)
    r.expect_done()
    return block


@dataclass(frozen=True, slots=True)
class Vote:
    """A vote on a block, sent to the NEXT leader
    (consensus/src/messages.rs:120-146)."""

    hash: Digest
    round: Round
    author: PublicKey
    signature: Signature

    @staticmethod
    def new_from_key(
        hash_: Digest, round_: Round, author: PublicKey, secret: SecretKey
    ) -> "Vote":
        return Vote(hash_, round_, author, Signature.new(_vote_digest(hash_, round_), secret))

    def signed_digest(self) -> Digest:
        return _vote_digest(self.hash, self.round)

    def verify(self, committee: Committee) -> None:
        committee = _committee_at(committee, self.round)
        ensure(committee.stake(self.author) > 0, UnknownAuthorityError(self.author))
        ok = self.signature.verify(self.signed_digest(), self.author)
        ensure(ok, InvalidSignatureError(f"bad vote signature V{self.round}"))

    async def verify_async(
        self, committee: Committee, service, trace: str | None = None
    ) -> None:
        committee = _committee_at(committee, self.round)
        ensure(committee.stake(self.author) > 0, UnknownAuthorityError(self.author))
        ok = await service.verify(
            self.signed_digest().data, self.author, self.signature,
            committee=True, trace=trace,
        )
        ensure(ok, InvalidSignatureError(f"bad vote signature V{self.round}"))

    def encode(self, w: Writer) -> None:
        w.fixed(self.hash.data, 32)
        w.u64(self.round)
        w.fixed(self.author.data, 32)
        w.fixed(self.signature.data, 64)

    @staticmethod
    def decode(r: Reader) -> "Vote":
        return Vote(
            Digest(r.fixed(32)), r.u64(), PublicKey(r.fixed(32)), Signature(r.fixed(64))
        )

    def __str__(self) -> str:
        return f"V{self.round}({self.hash.short()})"


@dataclass(frozen=True, slots=True)
class Timeout:
    """Signed claim that a round timed out, carrying the author's highest QC
    (consensus/src/messages.rs:230-265)."""

    high_qc: QC
    round: Round
    author: PublicKey
    signature: Signature

    @staticmethod
    def new_from_key(
        high_qc: QC, round_: Round, author: PublicKey, secret: SecretKey
    ) -> "Timeout":
        digest = _timeout_digest(round_, high_qc.round)
        return Timeout(high_qc, round_, author, Signature.new(digest, secret))

    def signed_digest(self) -> Digest:
        return _timeout_digest(self.round, self.high_qc.round)

    def verify(self, committee: Committee) -> None:
        own = _committee_at(committee, self.round)
        ensure(own.stake(self.author) > 0, UnknownAuthorityError(self.author))
        ok = self.signature.verify(self.signed_digest(), self.author)
        ensure(ok, InvalidSignatureError(f"bad timeout signature T{self.round}"))
        if not self.high_qc.is_genesis():
            self.high_qc.verify(committee)

    async def verify_async(
        self, committee: Committee, service, trace: str | None = None
    ) -> None:
        """Timeout signature + embedded high_qc votes as one service group."""
        own = _committee_at(committee, self.round)
        ensure(own.stake(self.author) > 0, UnknownAuthorityError(self.author))
        msgs: list[bytes] = [self.signed_digest().data]
        pairs: list[tuple[PublicKey, Signature]] = [(self.author, self.signature)]
        if not self.high_qc.is_genesis():
            self.high_qc.check_quorum(committee)
            m, p = self.high_qc.signed_items()
            msgs += m
            pairs += p
        mask = await service.verify_group(
            msgs, pairs, urgent=True, committee=True, trace=trace,
            source="consensus"
        )
        ensure(mask[0], InvalidSignatureError(f"bad timeout signature T{self.round}"))
        ensure(
            all(mask[1:]),
            InvalidSignatureError("QC batch verification failed"),
        )

    def encode(self, w: Writer) -> None:
        self.high_qc.encode(w)
        w.u64(self.round)
        w.fixed(self.author.data, 32)
        w.fixed(self.signature.data, 64)

    @staticmethod
    def decode(r: Reader) -> "Timeout":
        return Timeout(
            QC.decode(r), r.u64(), PublicKey(r.fixed(32)), Signature(r.fixed(64))
        )

    def __str__(self) -> str:
        return f"T{self.round}(high_qc round {self.high_qc.round})"


# ---------------------------------------------------------------------------
# Wire envelope (the reference's ConsensusMessage enum, consensus/src/core.rs).

TAG_PROPOSE = 0
TAG_VOTE = 1
TAG_TIMEOUT = 2
TAG_TC = 3
TAG_SYNC_REQUEST = 4
TAG_SYNC_RANGE_REQUEST = 5
TAG_SYNC_RANGE_REPLY = 6
# Aggregation-overlay partial-quorum bundles (consensus/overlay.py).
TAG_VOTE_BUNDLE = 7
TAG_TIMEOUT_BUNDLE = 8
# Network-observatory RTT probes (network/net.py peer ledger). Probe
# frames ride the normal consensus framing, so a peer that predates them
# hits `unknown consensus tag` in decode, counts one net.decode_errors,
# and drops the frame — the graceful-degradation path for mixed fleets.
TAG_PING = 9
TAG_PONG = 10
# Aggregate certificate plane (§5.5o): only frames that actually carry
# an aggregate form use these tags — a mixed fleet keeps full interop on
# the legacy tags, and aggregate frames degrade at old peers exactly
# like Ping/Pong (unknown tag, one decode_errors count, frame dropped).
TAG_PROPOSE_V2 = 11
TAG_AGG_VOTE_BUNDLE = 12
TAG_AGG_TIMEOUT_BUNDLE = 13
TAG_AGG_TC = 14
TAG_SYNC_RANGE_REPLY_V2 = 15

# Defensive cap on entries per partial bundle: an unauthenticated peer
# must not make a receiver decode (and batch-verify) an unbounded entry
# list per frame. Real bundles carry at most one committee's worth.
MAX_BUNDLE_ENTRIES = 4096


def encode_consensus_message(msg) -> bytes:
    w = Writer()
    if isinstance(msg, Block):
        if msg.has_agg_certs():
            w.u8(TAG_PROPOSE_V2)
            msg.encode_v2(w)
        else:
            w.u8(TAG_PROPOSE)
            msg.encode(w)
    elif isinstance(msg, Vote):
        w.u8(TAG_VOTE)
        msg.encode(w)
    elif isinstance(msg, Timeout):
        w.u8(TAG_TIMEOUT)
        msg.encode(w)
    elif isinstance(msg, TC):
        w.u8(TAG_TC)
        msg.encode(w)
    elif isinstance(msg, AggTC):
        w.u8(TAG_AGG_TC)
        msg.encode(w)
    elif isinstance(msg, SyncRequest):
        w.u8(TAG_SYNC_REQUEST)
        w.fixed(msg.digest.data, 32)
        w.fixed(msg.requester.data, 32)
    elif isinstance(msg, SyncRangeRequest):
        w.u8(TAG_SYNC_RANGE_REQUEST)
        w.fixed(msg.target.data, 32)
        w.u64(msg.from_round)
        w.fixed(msg.requester.data, 32)
    elif isinstance(msg, SyncRangeReply):
        if len(msg.blocks) > MAX_RANGE_BATCH:
            raise ValueError(f"range reply over batch cap: {len(msg.blocks)}")
        if any(b.has_agg_certs() for b in msg.blocks):
            w.u8(TAG_SYNC_RANGE_REPLY_V2)
            w.fixed(msg.target.data, 32)
            w.seq(list(msg.blocks), _encode_any_block)
        else:
            w.u8(TAG_SYNC_RANGE_REPLY)
            w.fixed(msg.target.data, 32)
            w.seq(list(msg.blocks), lambda wr, b: b.encode(wr))
    elif isinstance(msg, VoteBundle):
        if len(msg.votes) > MAX_BUNDLE_ENTRIES:
            raise ValueError(f"vote bundle over entry cap: {len(msg.votes)}")
        w.u8(TAG_VOTE_BUNDLE)
        w.u64(msg.round)
        w.fixed(msg.hash.data, 32)
        _encode_votes(w, list(msg.votes))
    elif isinstance(msg, TimeoutBundle):
        if len(msg.timeouts) > MAX_BUNDLE_ENTRIES:
            raise ValueError(
                f"timeout bundle over entry cap: {len(msg.timeouts)}"
            )
        w.u8(TAG_TIMEOUT_BUNDLE)
        w.u64(msg.round)
        msg.high_qc.encode(w)
        w.seq(
            list(msg.timeouts),
            lambda wr, v: (
                wr.fixed(v[0].data, 32),
                wr.fixed(v[1].data, 64),
                wr.u64(v[2]),
            ),
        )
    elif isinstance(msg, AggVoteBundle):
        w.u8(TAG_AGG_VOTE_BUNDLE)
        w.u64(msg.round)
        w.fixed(msg.hash.data, 32)
        _encode_bitmap(w, msg.bitmap)
        w.var_bytes(msg.agg_sig)
        w.u8(min(msg.depth, 255))
    elif isinstance(msg, AggTimeoutBundle):
        w.u8(TAG_AGG_TIMEOUT_BUNDLE)
        w.u64(msg.round)
        encode_any_qc(w, msg.high_qc)
        w.seq(
            list(msg.groups),
            lambda wr, g: (wr.u64(g[0]), _encode_bitmap(wr, g[1])),
        )
        w.var_bytes(msg.agg_sig)
        w.u8(min(msg.depth, 255))
    elif isinstance(msg, Ping):
        w.u8(TAG_PING)
        w.fixed(msg.origin.data, 32)
        w.u64(msg.seq)
        w.u64(msg.sent_at_us)
    elif isinstance(msg, Pong):
        w.u8(TAG_PONG)
        w.fixed(msg.origin.data, 32)
        w.fixed(msg.responder.data, 32)
        w.u64(msg.seq)
        w.u64(msg.sent_at_us)
    else:
        raise TypeError(f"not a consensus message: {msg!r}")
    return w.bytes()


def decode_consensus_message(data: bytes):
    r = Reader(data)
    tag = r.u8()
    if tag == TAG_PROPOSE:
        out = Block.decode(r)
    elif tag == TAG_VOTE:
        out = Vote.decode(r)
    elif tag == TAG_TIMEOUT:
        out = Timeout.decode(r)
    elif tag == TAG_TC:
        out = TC.decode(r)
    elif tag == TAG_SYNC_REQUEST:
        out = SyncRequest(Digest(r.fixed(32)), PublicKey(r.fixed(32)))
    elif tag == TAG_SYNC_RANGE_REQUEST:
        out = SyncRangeRequest(
            Digest(r.fixed(32)), r.u64(), PublicKey(r.fixed(32))
        )
    elif tag == TAG_SYNC_RANGE_REPLY:
        target = Digest(r.fixed(32))
        blocks = tuple(r.seq(Block.decode))
        if len(blocks) > MAX_RANGE_BATCH:
            # Defensive cap BEFORE anything downstream trusts the batch:
            # an unauthenticated peer must not make us buffer an
            # arbitrarily long chain segment per frame.
            raise SerdeError(f"range reply over batch cap: {len(blocks)}")
        out = SyncRangeReply(target, blocks)
    elif tag == TAG_VOTE_BUNDLE:
        round_ = r.u64()
        hash_ = Digest(r.fixed(32))
        votes = tuple(_decode_votes(r))
        if len(votes) > MAX_BUNDLE_ENTRIES:
            raise SerdeError(f"vote bundle over entry cap: {len(votes)}")
        out = VoteBundle(round_, hash_, votes)
    elif tag == TAG_TIMEOUT_BUNDLE:
        round_ = r.u64()
        high_qc = QC.decode(r)
        timeouts = tuple(
            r.seq(
                lambda rd: (
                    PublicKey(rd.fixed(32)),
                    Signature(rd.fixed(64)),
                    rd.u64(),
                )
            )
        )
        if len(timeouts) > MAX_BUNDLE_ENTRIES:
            raise SerdeError(f"timeout bundle over entry cap: {len(timeouts)}")
        out = TimeoutBundle(round_, high_qc, timeouts)
    elif tag == TAG_PROPOSE_V2:
        out = Block.decode_v2(r)
    elif tag == TAG_AGG_TC:
        out = AggTC.decode(r)
    elif tag == TAG_SYNC_RANGE_REPLY_V2:
        target = Digest(r.fixed(32))
        blocks = tuple(r.seq(_decode_any_block))
        if len(blocks) > MAX_RANGE_BATCH:
            raise SerdeError(f"range reply over batch cap: {len(blocks)}")
        out = SyncRangeReply(target, blocks)
    elif tag == TAG_AGG_VOTE_BUNDLE:
        out = AggVoteBundle(
            r.u64(), Digest(r.fixed(32)), _decode_bitmap(r),
            r.var_bytes(), r.u8(),
        )
    elif tag == TAG_AGG_TIMEOUT_BUNDLE:
        round_ = r.u64()
        high_qc = decode_any_qc(r)
        groups = tuple(r.seq(lambda rd: (rd.u64(), _decode_bitmap(rd))))
        if len(groups) > aggsig.MAX_AGG_COMMITTEE:
            raise SerdeError(
                f"aggregate timeout bundle over group cap: {len(groups)}"
            )
        out = AggTimeoutBundle(round_, high_qc, groups, r.var_bytes(), r.u8())
    elif tag == TAG_PING:
        out = Ping(PublicKey(r.fixed(32)), r.u64(), r.u64())
    elif tag == TAG_PONG:
        out = Pong(
            PublicKey(r.fixed(32)), PublicKey(r.fixed(32)), r.u64(), r.u64()
        )
    else:
        raise SerdeError(f"unknown consensus tag {tag}")
    r.expect_done()
    return out


@dataclass(frozen=True, slots=True)
class SyncRequest:
    """Ask peers to re-send a missing block (consensus/src/core.rs:418-436)."""

    digest: Digest
    requester: PublicKey


@dataclass(frozen=True, slots=True)
class SyncRangeRequest:
    """Batched catch-up fetch: ask for the ancestor chain of `target`
    down to (exclusive) `from_round` — the requester's committed round,
    below which the chains must coincide. The serving peer walks its
    store back from `target` and answers with ONE SyncRangeReply of up
    to MAX_RANGE_BATCH blocks, OLDEST first, so the receiver can verify
    and commit progressively (each block's parent precedes it)."""

    target: Digest
    from_round: Round
    requester: PublicKey


@dataclass(frozen=True, slots=True)
class SyncRangeReply:
    """Ancestor batch for a SyncRangeRequest (oldest-first, capped).
    Unauthenticated as a message — each carried block is independently
    verified through the normal proposal path, with QC quorums judged
    against the committee of the QC's own epoch."""

    target: Digest
    blocks: tuple[Block, ...]


@dataclass(frozen=True, slots=True)
class VoteBundle:
    """Aggregation-overlay partial quorum for one (round, block digest):
    a mergeable set of individually signed votes (consensus/overlay.py).
    Unauthenticated as a CONTAINER — each (author, signature) entry is
    batch-verified against `_vote_digest(hash, round)` by the receiver
    before it merges, and an invalid entry is dropped alone (it cannot
    poison the rest of the bundle)."""

    round: Round
    hash: Digest
    votes: tuple[tuple[PublicKey, Signature], ...]

    def signed_digest(self) -> Digest:
        return _vote_digest(self.hash, self.round)

    def __str__(self) -> str:
        return f"VB{self.round}({self.hash.short()}, {len(self.votes)} votes)"


@dataclass(frozen=True, slots=True)
class TimeoutBundle:
    """Aggregation-overlay partial quorum for one timed-out round: a
    mergeable set of (author, signature, high_qc_round) timeout entries
    plus the highest QC any merged author reported (ONE certificate per
    bundle instead of one per timeout — the storm-shrinking payload).
    Entries verify individually against `_timeout_digest(round, hqr)`;
    the carried high_qc is quorum-checked and batch-verified before
    adoption, exactly like a Timeout's."""

    round: Round
    high_qc: QC
    timeouts: tuple[tuple[PublicKey, Signature, Round], ...]

    def __str__(self) -> str:
        return (
            f"TB{self.round}(high_qc round {self.high_qc.round}, "
            f"{len(self.timeouts)} timeouts)"
        )


@dataclass(frozen=True, slots=True)
class AggVoteBundle:
    """Handel-style PARTIAL aggregate for one (round, block digest): an
    aggregate signature over `_vote_digest(hash, round)` covering the
    bitmap's members. A single node's vote is the singleton-bitmap case;
    interior overlay nodes merge bitmap-DISJOINT partials by one
    combine() plus a bitmap OR — gossip carries aggregates, never entry
    lists. Verification is ATOMIC: the partial verifies as a whole or is
    dropped as a whole (there is no per-entry salvage in an aggregate —
    Handel's atomic-partial rule), so a forged member poisons only the
    partial it rides in, and only until the sender's next window.
    `depth` is telemetry-only (merge-tree height for the CERTS scrape):
    it never participates in verification."""

    round: Round
    hash: Digest
    bitmap: int
    agg_sig: bytes
    depth: int = 0

    def signed_digest(self) -> Digest:
        return _vote_digest(self.hash, self.round)

    def signers(self) -> int:
        return self.bitmap.bit_count()

    def __str__(self) -> str:
        return (
            f"AVB{self.round}({self.hash.short()}, {self.signers()} signers, "
            f"depth {self.depth})"
        )


@dataclass(frozen=True, slots=True)
class AggTimeoutBundle:
    """Handel-style partial aggregate for one timed-out round: one
    aggregate signature spanning `groups` (one (high_qc_round, bitmap)
    group per distinct claimed hqr, AggTC-shaped), plus the highest QC
    the contributing members could back their claims with. Atomicity
    replaces the legacy `filter_backed` per-entry salvage: a bundle
    whose max claimed hqr exceeds its carried certificate's round is
    rejected WHOLE (an honest sender never produces one), so the
    TC-poisoning guard holds without per-entry signatures to fall back
    on."""

    round: Round
    high_qc: QC | AggQC
    groups: tuple[tuple[Round, int], ...]
    agg_sig: bytes
    depth: int = 0

    def signers(self) -> int:
        return sum(bm.bit_count() for _, bm in self.groups)

    def __str__(self) -> str:
        return (
            f"ATB{self.round}(high_qc round {self.high_qc.round}, "
            f"{len(self.groups)} groups, {self.signers()} signers)"
        )


@dataclass(frozen=True, slots=True)
class Ping:
    """RTT probe (network observatory): `origin` broadcasts one Ping per
    probe interval; every receiver answers a Pong directly to the origin.
    Timestamps are MICROSECONDS of the ORIGIN's loop clock (`loop.time()`
    — the virtual clock under chaos, so measured RTTs replay
    bit-identically); the responder echoes them opaquely, never
    interprets them. Unsigned by design: a probe carries no protocol
    authority, and a forged one costs its victim exactly one reply
    frame. The origin key is carried in-frame because the receive path
    does not authenticate frame senders."""

    origin: PublicKey
    seq: int
    sent_at_us: int

    def __str__(self) -> str:
        return f"Ping(seq {self.seq})"


@dataclass(frozen=True, slots=True)
class Pong:
    """Echo of a Ping, addressed back to its origin. `responder`
    identifies the measured peer; `sent_at_us` is the origin's own
    send stamp echoed back, so RTT = now - sent_at_us needs no clock
    agreement between the two nodes."""

    origin: PublicKey
    responder: PublicKey
    seq: int
    sent_at_us: int

    def __str__(self) -> str:
        return f"Pong(seq {self.seq})"


@dataclass(frozen=True, slots=True)
class LoopBack:
    """Internal-only: re-inject a block whose dependencies arrived
    (consensus/src/synchronizer.rs:68-76). Never serialized."""

    block: Block
