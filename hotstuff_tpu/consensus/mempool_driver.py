"""Consensus-side proxy to the mempool (reference consensus/src/mempool.rs).

ConsensusMempoolMessage variants (mempool.rs:16-20):
  * Get(max, reply)        -> payload digests for a new block
  * Verify(block, reply)   -> payload availability: Accept / Reject / Wait
  * Cleanup(b0, b1, block) -> drop state for committed/ordered payloads

On Wait the mempool synchronizer fetches missing payloads and loops the block
back to the consensus core when they arrive, so `verify` simply returns False
and the core drops the block for now (consensus/src/mempool.rs:41-60).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from enum import Enum

from ..utils.actors import channel
from .messages import Block


class PayloadStatus(Enum):
    ACCEPT = "accept"
    REJECT = "reject"
    WAIT = "wait"


@dataclass(slots=True)
class MempoolGet:
    max_size: int
    reply: asyncio.Future


@dataclass(slots=True)
class MempoolVerify:
    block: Block
    reply: asyncio.Future


@dataclass(slots=True)
class MempoolCleanup:
    b0: Block
    b1: Block
    block: Block


class MempoolDriver:
    def __init__(self, mempool_channel: asyncio.Queue) -> None:
        self._tx = mempool_channel

    async def get(self, max_size: int) -> list:
        fut = asyncio.get_running_loop().create_future()
        await self._tx.put(MempoolGet(max_size, fut))
        return await fut

    async def verify(self, block: Block) -> bool:
        """True iff all payloads are locally available (Accept). Reject raises;
        Wait returns False after the mempool registered a fetch+loopback."""
        if not block.payload:
            return True
        fut = asyncio.get_running_loop().create_future()
        await self._tx.put(MempoolVerify(block, fut))
        status = await fut
        if status == PayloadStatus.REJECT:
            from .errors import MalformedBlockError

            raise MalformedBlockError(f"invalid payload in {block}")
        return status == PayloadStatus.ACCEPT

    async def cleanup(self, b0: Block, b1: Block, block: Block) -> None:
        await self._tx.put(MempoolCleanup(b0, b1, block))
