"""Region-aware aggregation overlay for the vote/timeout plane.

The all-to-all control plane is the measured blocker on the road to
100-1000-node committees: a stalled round costs O(n²) timeout frames
(every node re-broadcasts its Timeout to every peer at pacemaker pace —
the 64-node lossy@seed2 storm in CHAOS_MATRIX_r01). Handel
(arXiv:1906.05132) and aggregated-signature gossip BFT (arXiv:1911.04698)
show the fix: aggregate partial quorums along a tree so each node ships
ONE frame up instead of n-1 frames out.

Pieces:

  * `AggregationTree` — the pure derivation. For (epoch committee, round,
    kind) the tree is a deterministic function every honest node computes
    identically: members are permuted by a round-keyed hash (load
    rotates across rounds), grouped by WAN region, each region forms a
    `fanout`-ary heap rooted at its region head, and region heads make
    ONE cross-region hop to the round's collector. The collector is the
    next round's leader for the vote plane (it needs the QC to propose)
    and a plurality-region member for the timeout plane (region-aware
    placement — ROADMAP item 5 residue (c): the TC can form anywhere and
    is broadcast, so the root belongs where most of the committee is
    cheap to reach). Epoch boundaries rotate the tree automatically:
    membership resolves per round through the EpochManager schedule.

  * `OverlayRouter` — a node's runtime: per-(round, kind) merge state,
    hold timers (an interior node briefly waits to merge its children's
    partials into one upward frame), bounded re-forwards, and the
    GOSSIP FALLBACK: if the round has not advanced `agg_fallback_ms`
    after this node shipped its own entry, it gossips its merged partial
    to `agg_fanout` deterministic peers — a crashed aggregator degrades
    to bounded fan-out instead of silence.

Partial bundles (`consensus/messages.py` VoteBundle / TimeoutBundle) are
UNAUTHENTICATED containers like SyncRangeReply: every carried entry is an
individually signed vote/timeout, batch-verified by the receiver through
the BatchVerificationService on the scheduler's dedicated `aggregate`
lane (crypto/scheduler.py — priority between consensus and sync) before
it is merged. An invalid entry is dropped and counted
(`agg.invalid_entries`) WITHOUT poisoning the rest of the bundle, so a
Byzantine aggregator can waste one lie per frame but cannot suppress the
honest entries it relays — and withholding entirely is what the fallback
bounds. A bundle's carried high_qc is quorum-checked and batch-verified
before adoption, like a Timeout's.

Frame accounting: `agg.vote_frames` / `agg.timeout_frames` count every
vote-/timeout-plane frame SENT (bundles here, unicast votes and broadcast
timeouts on the legacy path in core.py), so frames-per-timeout is
computable in both modes — the committed `timeout_storm` vs
`timeout_storm_legacy` matrix cells are exactly that ratio, O(fanout)
vs O(n).

Determinism: no wall-clock reads, hold/fallback timers ride the event
loop (virtual under chaos), and the tree is a pure hash of
(round, kind, committee) — a same-seed chaos replay reproduces identical
bundle traffic bit for bit.
"""

from __future__ import annotations

import asyncio
import logging
import struct

from ..crypto import Digest, PublicKey, aggsig, sha512_32
from ..utils import metrics, tracing
from ..utils.actors import spawn
from .aggregator import AggPartialSet, _merge_timeout_payload
from .errors import ConsensusError
from .messages import (
    QC,
    AggQC,
    AggTimeoutBundle,
    AggVoteBundle,
    Round,
    TimeoutBundle,
    VoteBundle,
)

log = logging.getLogger("hotstuff.consensus")

KIND_VOTE = 0
KIND_TIMEOUT = 1

_M_BUNDLES_SENT = metrics.counter("agg.bundles_sent")
_M_BUNDLES_RECEIVED = metrics.counter("agg.bundles_received")
_M_ENTRIES_MERGED = metrics.counter("agg.entries_merged")
_M_INVALID = metrics.counter("agg.invalid_entries")
_M_FALLBACKS = metrics.counter("agg.fallbacks")
_M_VOTE_FRAMES = metrics.counter("agg.vote_frames")
_M_TIMEOUT_FRAMES = metrics.counter("agg.timeout_frames")

# How many (round, kind) trees the router memoizes: the active round plus
# a little slack for late traffic (trees are cheap to rebuild; the cache
# only bounds repeated derivation inside one round's message burst).
_TREE_CACHE = 8


def note_plane_frames(kind: int, n: int) -> None:
    """Count `n` vote-/timeout-plane frames sent. Called by the router
    for bundle traffic and by core.py for the legacy unicast/broadcast
    paths, so the storm metric is mode-independent."""
    if n <= 0:
        return
    (_M_VOTE_FRAMES if kind == KIND_VOTE else _M_TIMEOUT_FRAMES).inc(n)


class AggregationTree:
    """Deterministic region-aware aggregation tree for one (round, kind).

    Derivation rule (documented in COMPONENTS.md §5.5l):
      1. `seed = sha512_32("HSAGGTREE" || round || kind)`; members sort
         by `sha512_32(seed || pk)` — a per-round permutation, so
         interior/aggregator duty rotates with the round.
      2. Members group by WAN region (unknown region -> "").
      3. The collector is `collector` when given (vote plane: the next
         leader), else the first permuted member of the PLURALITY region
         (most members; ties break on the smaller region label).
      4. Each region's permuted members form a `fanout`-ary heap:
         `parent(list[j]) = list[(j-1)//fanout]`; the region head is
         `list[0]` (the collector, in its own region).
      5. Region heads make ONE cross-region hop to the collector; every
         other edge is intra-region.
    """

    __slots__ = (
        "round", "kind", "fanout", "collector", "order",
        "_parent", "_children", "_region", "_subtree",
    )

    def __init__(
        self,
        members: list[PublicKey],
        region_of: dict[PublicKey, str],
        round_: Round,
        kind: int,
        fanout: int,
        collector: PublicKey | None = None,
    ) -> None:
        if not members:
            raise ValueError("aggregation tree needs at least one member")
        self.round = round_
        self.kind = kind
        self.fanout = max(1, fanout)
        seed = sha512_32(b"HSAGGTREE" + struct.pack("<QB", round_, kind))
        self.order = sorted(members, key=lambda pk: sha512_32(seed + pk.data))
        self._region = {pk: region_of.get(pk, "") for pk in self.order}
        by_region: dict[str, list[PublicKey]] = {}
        for pk in self.order:
            by_region.setdefault(self._region[pk], []).append(pk)
        if collector is None:
            # Plurality-region placement (timeout plane): the region with
            # the most members wins, ties break on the smaller label, and
            # the collector is its first permuted member — the subtree's
            # plurality region hosts the root (ROADMAP 5 residue (c)).
            plurality = min(
                by_region.items(), key=lambda kv: (-len(kv[1]), kv[0])
            )[0]
            collector = by_region[plurality][0]
        # A vote-plane collector outside this round's committee (the next
        # epoch's leader at a boundary) owns no intra-region subtree:
        # every region head simply hops to it.
        self.collector = collector
        self._parent: dict[PublicKey, PublicKey | None] = {}
        self._children: dict[PublicKey, list[PublicKey]] = {}
        for _region, group in sorted(by_region.items()):
            if collector in group:
                group = [collector] + [pk for pk in group if pk != collector]
            for j, pk in enumerate(group):
                if j == 0:
                    self._parent[pk] = None if pk == collector else collector
                else:
                    self._parent[pk] = group[(j - 1) // self.fanout]
        self._parent[collector] = None
        for pk, parent in self._parent.items():
            if parent is not None:
                self._children.setdefault(parent, []).append(pk)
        # Subtree sizes precomputed bottom-up (reverse BFS from the
        # collector): subtree_size is read on EVERY merge, and a per-call
        # recursive walk would cost O(subtree) per inbound bundle.
        bfs = [collector]
        i = 0
        while i < len(bfs):
            bfs.extend(self._children.get(bfs[i], ()))
            i += 1
        self._subtree: dict[PublicKey, int] = {}
        for pk in reversed(bfs):
            self._subtree[pk] = 1 + sum(
                self._subtree[c] for c in self._children.get(pk, ())
            )

    def parent(self, pk: PublicKey) -> PublicKey | None:
        return self._parent.get(pk)

    def children(self, pk: PublicKey) -> list[PublicKey]:
        return self._children.get(pk, [])

    def subtree_size(self, pk: PublicKey) -> int:
        """Members in pk's subtree, pk included (the coverage target an
        interior node forwards at without waiting out its hold timer)."""
        return self._subtree.get(pk, 1)

    def fallback_peers(self, pk: PublicKey, k: int) -> list[PublicKey]:
        """The k members after pk in permuted order (cyclic, self
        excluded): the bounded gossip set a fallback degrades to."""
        others = [m for m in self.order if m != pk]
        if not others:
            return []
        try:
            start = self.order.index(pk)
        except ValueError:
            start = 0
        rotated = self.order[start + 1 :] + self.order[: start + 1]
        return [m for m in rotated if m != pk][:k]

    def cross_region_edges(self) -> int:
        """Count of tree edges whose endpoints sit in different regions —
        by construction at most one per region (head -> collector)."""
        return sum(
            1
            for pk, parent in self._parent.items()
            if parent is not None
            and self._region.get(pk) != self._region.get(parent)
        )

    def depth(self, pk: PublicKey) -> int:
        d, cur = 0, pk
        while True:
            parent = self._parent.get(cur)
            if parent is None:
                return d
            d, cur = d + 1, parent


class _Pending:
    """Merge state for one (round, kind[, digest]) key. Legacy mode
    accumulates per-author entries; aggregate mode (Parameters.
    aggregate_certs) accumulates bitmap-disjoint partials in a Handel
    AggPartialSet instead — `agg_set` is created on first aggregate
    merge and the two never mix under one key."""

    __slots__ = (
        "entries", "best_qc", "forwards", "hold_task", "fallback_task",
        "agg_set",
    )

    def __init__(self) -> None:
        self.entries: dict[PublicKey, tuple] = {}
        self.best_qc: QC | None = None  # best carried cert (QC or AggQC)
        self.forwards = 0
        self.hold_task: asyncio.Task | None = None
        self.fallback_task: asyncio.Task | None = None
        self.agg_set: AggPartialSet | None = None

    def cancel_hold(self) -> None:
        if self.hold_task is not None and not self.hold_task.done():
            self.hold_task.cancel()
        self.hold_task = None

    def cancel(self) -> None:
        self.cancel_hold()
        if self.fallback_task is not None and not self.fallback_task.done():
            self.fallback_task.cancel()
        self.fallback_task = None


class OverlayRouter:
    """A node's overlay runtime. Owned by the consensus Core (which does
    the verification and certificate assembly); the router owns tree
    derivation, merge state, hold/fallback timers, and bundle egress.

    Always constructed — `enabled` (Parameters.aggregation_overlay)
    gates only whether this node's OWN votes/timeouts ride the tree;
    inbound bundles merge and count either way, so a mixed fleet
    degrades gracefully."""

    def __init__(self, core, region_of: dict[PublicKey, str] | None = None) -> None:
        self.core = core
        self.enabled = bool(core.parameters.aggregation_overlay)
        self.region_of = dict(region_of or {})
        p = core.parameters
        self.fanout = p.agg_fanout
        self.hold_s = p.agg_hold_ms / 1000.0
        self.fallback_s = p.agg_fallback_ms / 1000.0
        self.max_forwards = p.agg_max_forwards
        # Aggregate-certificate mode: partials are one signature + bitmap
        # and interior merges are combine()+OR — never entry lists.
        self.agg = bool(p.aggregate_certs)
        self.window = p.agg_window
        self._trees: dict[tuple[Round, int], AggregationTree] = {}
        self._state: dict[tuple, _Pending] = {}

    # -- tree derivation -----------------------------------------------------

    def tree(self, round_: Round, kind: int) -> AggregationTree:
        key = (round_, kind)
        t = self._trees.get(key)
        if t is None:
            epochs = self.core.epochs
            members = epochs.schedule.sorted_keys_for_round(round_)
            # Vote-plane root: the NEXT leader needs the QC to propose —
            # the baseline roots the tree there. Leader-collector mode
            # (§5.5p) roots it at the CURRENT leader instead (collector
            # == leader's region head by construction); the certificate
            # then rides one explicit handoff frame to the next proposer
            # (core._handoff_qc).
            collector = (
                self.core.leader_elector.get_leader(
                    round_
                    if self.core.parameters.leader_collector
                    else round_ + 1
                )
                if kind == KIND_VOTE
                else None
            )
            t = AggregationTree(
                members, self.region_of, round_, kind, self.fanout, collector
            )
            if len(self._trees) >= _TREE_CACHE:
                # Evict the entry FARTHEST from the requested round, not
                # the lowest: a staked peer signing entries for far-future
                # rounds could otherwise pin the cache with junk trees
                # while the ACTIVE round's tree gets evicted per bundle.
                farthest = max(
                    self._trees, key=lambda k: abs(k[0] - round_)
                )
                del self._trees[farthest]
            self._trees[key] = t
        return t

    # -- merge state ---------------------------------------------------------

    @staticmethod
    def vote_key(round_: Round, hash_: Digest) -> tuple:
        return (KIND_VOTE, round_, hash_)

    @staticmethod
    def timeout_key(round_: Round) -> tuple:
        return (KIND_TIMEOUT, round_)

    def _pending(self, key: tuple) -> _Pending:
        # Parity note: like the Aggregator's maker maps (aggregator.py),
        # a Byzantine peer holding real stake can sign future-round
        # entries and grow this map ahead of the round; cleanup() bounds
        # it on every round advance, same as the reference's aggregator.
        st = self._state.get(key)
        if st is None:
            st = self._state[key] = _Pending()
        return st

    def fresh(self, key: tuple, entries) -> list:
        """Entries whose author this key has not merged yet — the dedup
        applied BEFORE verification so redelivered bundles cost nothing."""
        seen = self._pending(key).entries
        out, dup = [], set()
        for entry in entries:
            if entry[0] not in seen and entry[0] not in dup:
                dup.add(entry[0])
                out.append(entry)
        return out

    def merge(self, key: tuple, entries, high_qc: QC | None = None) -> list:
        """Merge VERIFIED entries; returns the genuinely new ones. Keeps
        the highest-round high_qc seen for timeout keys (the one the
        forwarded bundle carries up)."""
        st = self._pending(key)
        new = []
        for entry in entries:
            if entry[0] not in st.entries:
                st.entries[entry[0]] = entry
                new.append(entry)
        if new:
            _M_ENTRIES_MERGED.inc(len(new))
        if high_qc is not None and not high_qc.is_genesis():
            if st.best_qc is None or high_qc.round > st.best_qc.round:
                st.best_qc = high_qc
        return new

    def note_invalid(self, n: int) -> None:
        if n > 0:
            _M_INVALID.inc(n)

    # -- aggregate merges (Parameters.aggregate_certs) -----------------------

    def merge_agg_vote(
        self, key: tuple, bitmap: int, agg_sig: bytes, depth: int
    ) -> None:
        """Merge one VERIFIED vote partial: Handel windowed insert —
        combine() + bitmap OR against every disjoint entry."""
        st = self._pending(key)
        if st.agg_set is None:
            st.agg_set = AggPartialSet(
                aggsig.active_agg_scheme().combine, self.window
            )
        st.agg_set.add(bitmap, agg_sig, depth)
        _M_ENTRIES_MERGED.inc(bitmap.bit_count())

    def merge_agg_timeout(
        self,
        key: tuple,
        groups: tuple[tuple[Round, int], ...],
        agg_sig: bytes,
        depth: int,
        carried_cert=None,
    ) -> None:
        """Merge one VERIFIED timeout partial. Keeps the highest-round
        carried certificate: every accepted partial's claims were backed
        by its own carried cert, so the max over contributors backs the
        merged bundle's claims too (the atomic analogue of
        filter_backed's invariant)."""
        st = self._pending(key)
        if st.agg_set is None:
            st.agg_set = AggPartialSet(_merge_timeout_payload, self.window)
        coverage = 0
        for _, bm in groups:
            coverage |= bm
        st.agg_set.add(
            coverage,
            (tuple(sorted(groups)), agg_sig, aggsig.active_agg_scheme()),
            depth,
        )
        _M_ENTRIES_MERGED.inc(coverage.bit_count())
        if carried_cert is not None and not carried_cert.is_genesis():
            if st.best_qc is None or carried_cert.round > st.best_qc.round:
                st.best_qc = carried_cert

    def covered(self, key: tuple) -> int:
        """Members this key's merged state covers — entry count in legacy
        mode, best-packing popcount in aggregate mode (the forward-policy
        quantity)."""
        st = self._pending(key)
        if st.agg_set is not None:
            best = st.agg_set.best()
            return best[0].bit_count() if best else 0
        return len(st.entries)

    def quorum_certificate(self, key: tuple, committee) -> QC | AggQC | None:
        """The complete certificate this vote key's merged state can
        assemble, or None below quorum stake. The leader-collector
        quorum watch (§5.5p): under Parameters.leader_collector the
        NEXT leader is an ordinary interior node of the round's tree —
        the collector is the round's own leader — so it cannot sink
        partials into an aggregator without starving the collector's
        subtree. Instead it assembles straight from merged state the
        moment coverage reaches quorum, which the collector's explicit
        handoff frame (core._handoff_qc, a whole-QC bundle) delivers in
        one merge. Entries here are already verified (only verified
        partials merge), so the check is structural stake arithmetic."""
        st = self._state.get(key)
        if st is None or key[0] != KIND_VOTE:
            return None
        if st.agg_set is not None:
            best = st.agg_set.best()
            if best is None:
                return None
            bitmap, sig, _depth = best
            qc: QC | AggQC = AggQC(key[2], key[1], bitmap, sig)
        elif st.entries:
            qc = QC(key[2], key[1], tuple(st.entries.values()))
        else:
            return None
        try:
            qc.check_quorum(committee)
        except ConsensusError:
            return None
        return qc

    # -- egress --------------------------------------------------------------

    def _bundle(self, key: tuple):
        st = self._pending(key)
        if st.agg_set is not None:
            best = st.agg_set.best()
            if best is None:
                return None
            if key[0] == KIND_VOTE:
                bitmap, sig, depth = best
                return AggVoteBundle(key[1], key[2], bitmap, sig, depth)
            _, payload, depth = best
            groups, sig, _ = payload
            return AggTimeoutBundle(
                key[1], st.best_qc or QC.genesis(), groups, sig, depth
            )
        entries = tuple(st.entries.values())
        if key[0] == KIND_VOTE:
            return VoteBundle(key[1], key[2], entries)
        return TimeoutBundle(key[1], st.best_qc or QC.genesis(), entries)

    async def _send(self, key: tuple, to: PublicKey, urgent: bool) -> None:
        bundle = self._bundle(key)
        if bundle is None or not bundle_weight(bundle):
            return
        _M_BUNDLES_SENT.inc()
        note_plane_frames(key[0], 1)
        tracing.RECORDER.record(
            "agg.bundle",
            None,
            None,
            {
                "round": key[1],
                "kind": "vote" if key[0] == KIND_VOTE else "timeout",
                "entries": bundle_weight(bundle),
            },
        )
        await self.core._transmit(bundle, to, urgent=urgent)

    async def on_own_vote(self, vote) -> None:
        """This node's vote enters the tree (never called when this node
        is the collector — the core feeds its own aggregator directly)."""
        key = self.vote_key(vote.round, vote.hash)
        self.merge(key, [(vote.author, vote.signature)])
        self._arm_fallback(key)
        await self.after_merge(key)

    async def on_own_timeout(self, timeout) -> None:
        key = self.timeout_key(timeout.round)
        self.merge(
            key,
            [(timeout.author, timeout.signature, timeout.high_qc.round)],
            high_qc=timeout.high_qc,
        )
        self._arm_fallback(key)
        await self.after_merge(key)

    async def on_own_vote_agg(self, bundle: AggVoteBundle) -> None:
        """This node's own singleton vote partial enters the tree."""
        key = self.vote_key(bundle.round, bundle.hash)
        self.merge_agg_vote(key, bundle.bitmap, bundle.agg_sig, bundle.depth)
        self._arm_fallback(key)
        await self.after_merge(key)

    async def on_own_timeout_agg(self, bundle: AggTimeoutBundle) -> None:
        key = self.timeout_key(bundle.round)
        self.merge_agg_timeout(
            key, bundle.groups, bundle.agg_sig, bundle.depth,
            carried_cert=bundle.high_qc,
        )
        self._arm_fallback(key)
        await self.after_merge(key)

    async def after_merge(self, key: tuple) -> None:
        """Forward policy after any merge: ship immediately once this
        node's whole subtree is covered (nothing left to wait for), else
        arm the hold timer so nearby children coalesce into one frame."""
        if not self.enabled:
            return
        round_ = key[1]
        if self.core.round > round_:
            return
        st = self._pending(key)
        if st.forwards >= self.max_forwards:
            return  # _forward would no-op: don't churn hold tasks
        tree = self.tree(round_, key[0])
        if tree.parent(self.core.name) is None:
            return  # collector: the core's aggregator is the sink
        if self.covered(key) >= tree.subtree_size(self.core.name):
            st.cancel_hold()
            await self._forward(key)
        elif st.hold_task is None or st.hold_task.done():
            st.hold_task = spawn(self._hold(key), name="agg-hold")

    async def _forward(self, key: tuple) -> None:
        st = self._pending(key)
        if self.core.round > key[1] or st.forwards >= self.max_forwards:
            return
        tree = self.tree(key[1], key[0])
        parent = tree.parent(self.core.name)
        if parent is None:
            return
        st.forwards += 1
        await self._send(key, parent, urgent=key[0] == KIND_TIMEOUT)

    async def _hold(self, key: tuple) -> None:
        try:
            await asyncio.sleep(self.hold_s)
        except asyncio.CancelledError:
            return
        st = self._state.get(key)
        if st is not None:
            st.hold_task = None
        await self._forward(key)

    def _arm_fallback(self, key: tuple) -> None:
        """(Re-)arm the gossip fallback each time this node contributes
        its OWN entry: if the round is still stalled `agg_fallback_ms`
        later (dead parent, dead collector, partition), the merged
        partial gossips to `fanout` deterministic peers — bounded
        fan-out instead of silence."""
        if not self.enabled:
            return
        st = self._pending(key)
        if st.fallback_task is not None and not st.fallback_task.done():
            return
        st.fallback_task = spawn(self._fallback(key), name="agg-fallback")

    async def _fallback(self, key: tuple) -> None:
        try:
            await asyncio.sleep(self.fallback_s)
        except asyncio.CancelledError:
            return
        st = self._state.get(key)
        if st is not None:
            st.fallback_task = None
        if self.core.round > key[1]:
            return  # the round advanced: the tree worked
        tree = self.tree(key[1], key[0])
        peers = tree.fallback_peers(self.core.name, self.fanout)
        if not peers:
            return
        st = self._pending(key)
        _M_FALLBACKS.inc()
        note_plane_frames(key[0], len(peers))
        _M_BUNDLES_SENT.inc(len(peers))
        covered = self.covered(key)
        tracing.RECORDER.record(
            "agg.fallback",
            None,
            None,
            {"round": key[1], "peers": len(peers), "entries": covered},
        )
        # NOTE: parsed by the benchmark LogParser (+ AGG section).
        log.info(
            "Agg fallback round %s: %s entries to %s peers",
            key[1],
            covered,
            len(peers),
        )
        bundle = self._bundle(key)
        if bundle is None:
            return
        for peer in peers:
            await self.core._transmit(bundle, peer, urgent=key[0] == KIND_TIMEOUT)

    def note_received(self) -> None:
        _M_BUNDLES_RECEIVED.inc()

    # -- lifecycle -----------------------------------------------------------

    def cleanup(self, round_: Round) -> None:
        """Drop merge state and trees for rounds below `round_` (called
        beside Aggregator.cleanup on every round advance)."""
        for key in [k for k in self._state if k[1] < round_]:
            self._state.pop(key).cancel()
        for key in [k for k in self._trees if k[0] < round_ - 1]:
            del self._trees[key]


def bundle_entries(bundle) -> tuple:
    """The entry tuple of either bundle kind (votes or timeouts)."""
    return bundle.votes if isinstance(bundle, VoteBundle) else bundle.timeouts


def bundle_weight(bundle) -> int:
    """Members a bundle speaks for: entry count for legacy bundles,
    bitmap popcount for aggregate partials."""
    if isinstance(bundle, (AggVoteBundle, AggTimeoutBundle)):
        return bundle.signers()
    return len(bundle_entries(bundle))


def filter_backed(entries, backed_round: Round) -> tuple[list, int]:
    """Timeout entries whose high_qc_round CLAIM is backed by the
    bundle's carried QC: claim <= the verified carried QC's round
    (genesis claims, hqr 0, are self-backing). Returns (accepted,
    rejected_count).

    This is the bundle-path equivalent of what the legacy Timeout plane
    gets for free: `Timeout.verify` binds the signed hqr to the carried
    high_qc AND verifies that QC, so a TC's `high_qc_rounds()` only ever
    names rounds a real QC exists for. A bundle carries ONE best QC for
    many entries, so the binding must be explicit — otherwise a staked
    Byzantine author could sign an entry with an absurd hqr, and any TC
    including it would fail every future proposal's justification check
    (`block.qc.round >= max(tc.high_qc_rounds())`): permanent liveness
    loss. Honest bundles always pass: the merge keeps the MAX-round
    carried QC, so every honestly merged entry's claim stays covered."""
    ok = [e for e in entries if e[2] <= backed_round]
    return ok, len(entries) - len(ok)
