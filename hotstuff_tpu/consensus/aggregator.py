"""Vote/timeout aggregation into QCs/TCs (reference consensus/src/aggregator.rs).

QCMaker/TCMaker accumulate stake-weighted signatures, reject duplicate
authors, and fire EXACTLY ONCE when the quorum threshold is reached
(aggregator.rs:74-94,113-138). The Aggregator keys makers per (round, digest)
and drops state for old rounds on cleanup (aggregator.rs:52-70).

This accumulate-then-batch-verify structure is precisely the seam the TPU
backend exploits: a full QC's signatures are verified as one vmapped batch.
"""

from __future__ import annotations

import time

from ..crypto import Digest, PublicKey, Signature
from ..utils import metrics, tracing
from .config import Committee
from .errors import UnknownAuthorityError, ensure
from .messages import (
    QC,
    TC,
    Round,
    Timeout,
    Vote,
    _timeout_digest,
    _vote_digest,
)
from .reconfig import as_manager

# qc_form_s / tc_form_s: first vote (or timeout) appended -> quorum fired —
# the vote->QC leg of the proposal->vote->QC->commit latency chain.
_M_QCS = metrics.counter("consensus.qcs")
_M_TCS = metrics.counter("consensus.tcs")
_M_QC_FORM = metrics.histogram("consensus.qc_form_s")
_M_TC_FORM = metrics.histogram("consensus.tc_form_s")


class QCMaker:
    """Accumulates votes for one (block digest, round) into a QC."""

    def __init__(self) -> None:
        self.weight = 0
        self.votes: list[tuple[PublicKey, Signature]] = []
        self.used: set[PublicKey] = set()
        self._first_at: float | None = None

    def append(self, vote: Vote, committee: Committee) -> QC | None:
        return self.add(
            vote.author, vote.signature, vote.round, vote.hash, committee
        )

    def add(
        self,
        author: PublicKey,
        signature: Signature,
        round_: Round,
        hash_: Digest,
        committee: Committee,
    ) -> QC | None:
        """Entry-level accumulation: the shape partial bundles arrive in
        (consensus/overlay.py) — a Vote is just one entry."""
        if author in self.used:
            return None  # redelivery (retries rebroadcast); not Byzantine
        stake = committee.stake(author)
        ensure(stake > 0, UnknownAuthorityError(author))
        if self._first_at is None:
            self._first_at = time.perf_counter()
        self.used.add(author)
        self.votes.append((author, signature))
        self.weight += stake
        if self.weight >= committee.quorum_threshold():
            self.weight = 0  # fire exactly once (aggregator.rs:88)
            _M_QCS.inc()
            form_s = time.perf_counter() - self._first_at
            _M_QC_FORM.record(form_s)
            if tracing.enabled():
                tracing.event(
                    "qc",
                    tracing.trace_id(round_, hash_.data),
                    form_s,
                    votes=len(self.votes),
                )
            return QC(hash_, round_, tuple(self.votes))
        return None


class TCMaker:
    """Accumulates timeouts for one round into a TC."""

    def __init__(self) -> None:
        self.weight = 0
        self.votes: list[tuple[PublicKey, Signature, Round]] = []
        self.used: set[PublicKey] = set()
        self._first_at: float | None = None

    def append(self, timeout: Timeout, committee: Committee) -> TC | None:
        return self.add(
            timeout.author,
            timeout.signature,
            timeout.high_qc.round,
            timeout.round,
            committee,
        )

    def add(
        self,
        author: PublicKey,
        signature: Signature,
        high_qc_round: Round,
        round_: Round,
        committee: Committee,
    ) -> TC | None:
        """Entry-level accumulation for partial timeout bundles: only the
        (author, signature, high_qc_round) triple is needed to weigh and
        assemble the TC — the full high_qc rides the bundle once, not
        once per author (consensus/overlay.py)."""
        if author in self.used:
            return None  # redelivery (nodes re-timeout the same round)
        stake = committee.stake(author)
        ensure(stake > 0, UnknownAuthorityError(author))
        if self._first_at is None:
            self._first_at = time.perf_counter()
        self.used.add(author)
        self.votes.append((author, signature, high_qc_round))
        self.weight += stake
        if self.weight >= committee.quorum_threshold():
            self.weight = 0
            _M_TCS.inc()
            _M_TC_FORM.record(time.perf_counter() - self._first_at)
            return TC(round_, tuple(self.votes))
        return None


class Aggregator:
    def __init__(self, committee: Committee, verification_service=None) -> None:
        # Committee or reconfig.EpochManager: stake weights and quorum
        # thresholds resolve against the committee of the VOTE's round, so
        # a QC forming across an epoch boundary counts the right epoch's
        # validators on each side.
        self.epochs = as_manager(committee)
        self.votes_aggregators: dict[tuple[Round, Digest], QCMaker] = {}
        self.timeouts_aggregators: dict[Round, TCMaker] = {}
        # Votes/timeouts reaching the aggregator were already verified by
        # the core; seeding their triples into the service's dedup cache
        # means the QC/TC assembled from them re-verifies ZERO signatures
        # (each signature is otherwise checked 2-3x over its lifetime).
        self.verification_service = verification_service

    @property
    def committee(self) -> Committee:
        return self.epochs.current()

    def _seed(self, digest: Digest, author: PublicKey, sig: Signature) -> None:
        svc = self.verification_service
        if svc is not None and hasattr(svc, "seed_verified"):
            svc.seed_verified(digest.data, author, sig)

    def add_vote(self, vote: Vote) -> QC | None:
        """May raise ConsensusError on Byzantine input (duplicate author).
        Parity note: like the reference (its aggregator.rs:29-30 TODO), a
        bad node could grow this map; cleanup() bounds it per round
        advance."""
        key = (vote.round, vote.hash)
        maker = self.votes_aggregators.setdefault(key, QCMaker())
        qc = maker.append(vote, self.epochs.committee_for_round(vote.round))
        self._seed(vote.signed_digest(), vote.author, vote.signature)
        return qc

    def add_timeout(self, timeout: Timeout) -> TC | None:
        maker = self.timeouts_aggregators.setdefault(timeout.round, TCMaker())
        tc = maker.append(timeout, self.epochs.committee_for_round(timeout.round))
        self._seed(
            timeout.signed_digest(), timeout.author, timeout.signature
        )
        return tc

    # -- partial-bundle entries (consensus/overlay.py) -----------------------

    def add_vote_entry(
        self, round_: Round, hash_: Digest, author: PublicKey, sig: Signature
    ) -> QC | None:
        """One verified vote entry from a partial bundle: same maker (and
        exactly-once quorum firing) as a full Vote for the same key."""
        maker = self.votes_aggregators.setdefault((round_, hash_), QCMaker())
        qc = maker.add(
            author, sig, round_, hash_, self.epochs.committee_for_round(round_)
        )
        self._seed(_vote_digest(hash_, round_), author, sig)
        return qc

    def add_timeout_entry(
        self, round_: Round, author: PublicKey, sig: Signature, high_qc_round: Round
    ) -> TC | None:
        """One verified timeout entry from a partial bundle."""
        maker = self.timeouts_aggregators.setdefault(round_, TCMaker())
        tc = maker.add(
            author,
            sig,
            high_qc_round,
            round_,
            self.epochs.committee_for_round(round_),
        )
        self._seed(_timeout_digest(round_, high_qc_round), author, sig)
        return tc

    def cleanup(self, round_: Round) -> None:
        self.votes_aggregators = {
            k: v for k, v in self.votes_aggregators.items() if k[0] >= round_
        }
        self.timeouts_aggregators = {
            k: v for k, v in self.timeouts_aggregators.items() if k >= round_
        }
