"""Vote/timeout aggregation into QCs/TCs (reference consensus/src/aggregator.rs).

QCMaker/TCMaker accumulate stake-weighted signatures, reject duplicate
authors, and fire EXACTLY ONCE when the quorum threshold is reached
(aggregator.rs:74-94,113-138). The Aggregator keys makers per (round, digest)
and drops state for old rounds on cleanup (aggregator.rs:52-70).

This accumulate-then-batch-verify structure is precisely the seam the TPU
backend exploits: a full QC's signatures are verified as one vmapped batch.
"""

from __future__ import annotations

import time

from ..crypto import Digest, PublicKey, Signature, aggsig
from ..utils import metrics, tracing
from .config import Committee
from .errors import UnknownAuthorityError, ensure
from .messages import (
    QC,
    TC,
    AggQC,
    AggTC,
    AggVoteBundle,
    Round,
    Timeout,
    Vote,
    _timeout_digest,
    _vote_digest,
)
from .reconfig import as_manager

# qc_form_s / tc_form_s: first vote (or timeout) appended -> quorum fired —
# the vote->QC leg of the proposal->vote->QC->commit latency chain.
_M_QCS = metrics.counter("consensus.qcs")
_M_TCS = metrics.counter("consensus.tcs")
_M_QC_FORM = metrics.histogram("consensus.qc_form_s")
_M_TC_FORM = metrics.histogram("consensus.tc_form_s")
# Aggregate certificate plane: certificates formed from Handel partial
# sets, and partial merges performed while packing them.
_M_AGG_QCS = metrics.counter("agg.qcs_formed")
_M_AGG_TCS = metrics.counter("agg.tcs_formed")
_M_AGG_MERGES = metrics.counter("agg.partials_merged")


class QCMaker:
    """Accumulates votes for one (block digest, round) into a QC."""

    def __init__(self) -> None:
        self.weight = 0
        self.votes: list[tuple[PublicKey, Signature]] = []
        self.used: set[PublicKey] = set()
        self._first_at: float | None = None

    def append(self, vote: Vote, committee: Committee) -> QC | None:
        return self.add(
            vote.author, vote.signature, vote.round, vote.hash, committee
        )

    def add(
        self,
        author: PublicKey,
        signature: Signature,
        round_: Round,
        hash_: Digest,
        committee: Committee,
    ) -> QC | None:
        """Entry-level accumulation: the shape partial bundles arrive in
        (consensus/overlay.py) — a Vote is just one entry."""
        if author in self.used:
            return None  # redelivery (retries rebroadcast); not Byzantine
        stake = committee.stake(author)
        ensure(stake > 0, UnknownAuthorityError(author))
        if self._first_at is None:
            self._first_at = time.perf_counter()
        self.used.add(author)
        self.votes.append((author, signature))
        self.weight += stake
        if self.weight >= committee.quorum_threshold():
            self.weight = 0  # fire exactly once (aggregator.rs:88)
            _M_QCS.inc()
            form_s = time.perf_counter() - self._first_at
            _M_QC_FORM.record(form_s)
            if tracing.enabled():
                tracing.event(
                    "qc",
                    tracing.trace_id(round_, hash_.data),
                    form_s,
                    votes=len(self.votes),
                )
            return QC(hash_, round_, tuple(self.votes))
        return None


class TCMaker:
    """Accumulates timeouts for one round into a TC."""

    def __init__(self) -> None:
        self.weight = 0
        self.votes: list[tuple[PublicKey, Signature, Round]] = []
        self.used: set[PublicKey] = set()
        self._first_at: float | None = None

    def append(self, timeout: Timeout, committee: Committee) -> TC | None:
        return self.add(
            timeout.author,
            timeout.signature,
            timeout.high_qc.round,
            timeout.round,
            committee,
        )

    def add(
        self,
        author: PublicKey,
        signature: Signature,
        high_qc_round: Round,
        round_: Round,
        committee: Committee,
    ) -> TC | None:
        """Entry-level accumulation for partial timeout bundles: only the
        (author, signature, high_qc_round) triple is needed to weigh and
        assemble the TC — the full high_qc rides the bundle once, not
        once per author (consensus/overlay.py)."""
        if author in self.used:
            return None  # redelivery (nodes re-timeout the same round)
        stake = committee.stake(author)
        ensure(stake > 0, UnknownAuthorityError(author))
        if self._first_at is None:
            self._first_at = time.perf_counter()
        self.used.add(author)
        self.votes.append((author, signature, high_qc_round))
        self.weight += stake
        if self.weight >= committee.quorum_threshold():
            self.weight = 0
            _M_TCS.inc()
            _M_TC_FORM.record(time.perf_counter() - self._first_at)
            return TC(round_, tuple(self.votes))
        return None


class Aggregator:
    def __init__(self, committee: Committee, verification_service=None) -> None:
        # Committee or reconfig.EpochManager: stake weights and quorum
        # thresholds resolve against the committee of the VOTE's round, so
        # a QC forming across an epoch boundary counts the right epoch's
        # validators on each side.
        self.epochs = as_manager(committee)
        self.votes_aggregators: dict[tuple[Round, Digest], QCMaker] = {}
        self.timeouts_aggregators: dict[Round, TCMaker] = {}
        # Votes/timeouts reaching the aggregator were already verified by
        # the core; seeding their triples into the service's dedup cache
        # means the QC/TC assembled from them re-verifies ZERO signatures
        # (each signature is otherwise checked 2-3x over its lifetime).
        self.verification_service = verification_service

    @property
    def committee(self) -> Committee:
        return self.epochs.current()

    def _seed(self, digest: Digest, author: PublicKey, sig: Signature) -> None:
        svc = self.verification_service
        if svc is not None and hasattr(svc, "seed_verified"):
            svc.seed_verified(digest.data, author, sig)

    def add_vote(self, vote: Vote) -> QC | None:
        """May raise ConsensusError on Byzantine input (duplicate author).
        Parity note: like the reference (its aggregator.rs:29-30 TODO), a
        bad node could grow this map; cleanup() bounds it per round
        advance."""
        key = (vote.round, vote.hash)
        maker = self.votes_aggregators.setdefault(key, QCMaker())
        qc = maker.append(vote, self.epochs.committee_for_round(vote.round))
        self._seed(vote.signed_digest(), vote.author, vote.signature)
        return qc

    def add_timeout(self, timeout: Timeout) -> TC | None:
        maker = self.timeouts_aggregators.setdefault(timeout.round, TCMaker())
        tc = maker.append(timeout, self.epochs.committee_for_round(timeout.round))
        self._seed(
            timeout.signed_digest(), timeout.author, timeout.signature
        )
        return tc

    # -- partial-bundle entries (consensus/overlay.py) -----------------------

    def add_vote_entry(
        self, round_: Round, hash_: Digest, author: PublicKey, sig: Signature
    ) -> QC | None:
        """One verified vote entry from a partial bundle: same maker (and
        exactly-once quorum firing) as a full Vote for the same key."""
        maker = self.votes_aggregators.setdefault((round_, hash_), QCMaker())
        qc = maker.add(
            author, sig, round_, hash_, self.epochs.committee_for_round(round_)
        )
        self._seed(_vote_digest(hash_, round_), author, sig)
        return qc

    def add_timeout_entry(
        self, round_: Round, author: PublicKey, sig: Signature, high_qc_round: Round
    ) -> TC | None:
        """One verified timeout entry from a partial bundle."""
        maker = self.timeouts_aggregators.setdefault(round_, TCMaker())
        tc = maker.add(
            author,
            sig,
            high_qc_round,
            round_,
            self.epochs.committee_for_round(round_),
        )
        self._seed(_timeout_digest(round_, high_qc_round), author, sig)
        return tc

    def cleanup(self, round_: Round) -> None:
        self.votes_aggregators = {
            k: v for k, v in self.votes_aggregators.items() if k[0] >= round_
        }
        self.timeouts_aggregators = {
            k: v for k, v in self.timeouts_aggregators.items() if k >= round_
        }


# ---------------------------------------------------------------------------
# Aggregate certificate plane (§5.5o): Handel-style partial sets.


class AggPartialSet:
    """Windowed, scored set of VERIFIED partials for one aggregation key
    (Handel, arXiv:1906.05132 §4, collapsed to the parts this plane
    needs): each entry is (coverage bitmap, opaque payload, depth).

    * Scoring: an incoming partial whose coverage is a SUBSET of an
      existing entry scores zero and is dropped — it can never extend
      the best packing.
    * Merging: on every insert, one greedy best-first pass combines the
      newcomer with every bitmap-DISJOINT entry (`merge` is the scheme's
      public combine — point add / stub XOR — plus the payload-specific
      bookkeeping); both the raw partial and the merged packing are
      retained so later arrivals can pack differently.
    * Windowing: entries are kept best-coverage-first and truncated to
      `window` — bounded state per key no matter what an adversary
      floods (unverified junk never reaches this set at all: partials
      verify atomically BEFORE insertion).

    Determinism: ordering is (coverage desc, bitmap asc) — pure
    functions of the entries, so same-seed fleets pack identically."""

    __slots__ = ("window", "entries", "_merge")

    def __init__(self, merge, window: int = 8) -> None:
        self._merge = merge
        self.window = max(1, int(window))
        self.entries: list[tuple[int, object, int]] = []

    def add(self, bitmap: int, payload, depth: int) -> None:
        for bm, _, _ in self.entries:
            if bitmap | bm == bm:
                return  # subset: score 0
        merged_bm, merged_payload, merged_depth = bitmap, payload, depth
        merged = False
        for bm, pl, dp in self.entries:
            if not merged_bm & bm:
                merged_bm |= bm
                merged_payload = self._merge(merged_payload, pl)
                merged_depth = max(merged_depth, dp) + 1
                merged = True
                _M_AGG_MERGES.inc()
        self.entries.append((bitmap, payload, depth))
        if merged:
            self.entries.append((merged_bm, merged_payload, merged_depth))
        self.entries.sort(key=lambda e: (-e[0].bit_count(), e[0]))
        del self.entries[self.window:]

    def best(self) -> tuple[int, object, int] | None:
        return self.entries[0] if self.entries else None


def _bitmap_stake(bitmap: int, committee: Committee) -> int:
    keys = committee.sorted_keys()
    return sum(
        committee.stake(keys[i])
        for i in range(bitmap.bit_length())
        if bitmap >> i & 1
    )


class AggQCMaker:
    """Packs verified vote partials for one (round, digest) into an
    AggQC; fires exactly once, like QCMaker."""

    def __init__(self, scheme, window: int) -> None:
        self.partials = AggPartialSet(scheme.combine, window)
        self.done = False

    def add(
        self,
        bitmap: int,
        agg_sig: bytes,
        depth: int,
        hash_: Digest,
        round_: Round,
        committee: Committee,
    ) -> AggQC | None:
        if self.done:
            return None
        self.partials.add(bitmap, agg_sig, depth)
        best = self.partials.best()
        if best is None:
            return None
        bm, sig, _ = best
        if _bitmap_stake(bm, committee) >= committee.quorum_threshold():
            self.done = True
            _M_QCS.inc()
            _M_AGG_QCS.inc()
            return AggQC(hash_, round_, bm, sig)
        return None


def _merge_timeout_payload(a, b):
    """Payloads are ((hqr, bitmap) groups sorted by hqr, agg_sig): union
    same-hqr groups bitwise, keep the combined signature alongside."""
    groups_a, sig_a, scheme = a
    groups_b, sig_b, _ = b
    merged: dict[Round, int] = dict(groups_a)
    for hqr, bm in groups_b:
        merged[hqr] = merged.get(hqr, 0) | bm
    return (tuple(sorted(merged.items())), scheme.combine(sig_a, sig_b), scheme)


class AggTCMaker:
    """Packs verified timeout partials for one round into an AggTC."""

    def __init__(self, scheme, window: int) -> None:
        self.partials = AggPartialSet(_merge_timeout_payload, window)
        self.done = False
        self._scheme = scheme

    def add(
        self,
        groups: tuple[tuple[Round, int], ...],
        agg_sig: bytes,
        depth: int,
        round_: Round,
        committee: Committee,
    ) -> AggTC | None:
        if self.done:
            return None
        coverage = 0
        for _, bm in groups:
            coverage |= bm
        self.partials.add(
            coverage,
            (tuple(sorted(groups)), agg_sig, self._scheme),
            depth,
        )
        best = self.partials.best()
        if best is None:
            return None
        bm, payload, _ = best
        if _bitmap_stake(bm, committee) >= committee.quorum_threshold():
            self.done = True
            _M_TCS.inc()
            _M_AGG_TCS.inc()
            best_groups, sig, _ = payload
            return AggTC(round_, best_groups, sig)
        return None


class AggCertAggregator:
    """Aggregate-plane sibling of Aggregator: per-(round, digest) vote
    makers and per-round timeout makers over Handel partial sets. The
    caller (core / overlay router) verifies every partial atomically
    BEFORE it reaches this state — nothing here re-checks signatures."""

    def __init__(self, committee, window: int = 8) -> None:
        self.epochs = as_manager(committee)
        self.window = window
        self.vote_makers: dict[tuple[Round, Digest], AggQCMaker] = {}
        self.timeout_makers: dict[Round, AggTCMaker] = {}

    def add_vote_partial(self, bundle: AggVoteBundle) -> AggQC | None:
        key = (bundle.round, bundle.hash)
        maker = self.vote_makers.get(key)
        if maker is None:
            maker = AggQCMaker(aggsig.active_agg_scheme(), self.window)
            self.vote_makers[key] = maker
        return maker.add(
            bundle.bitmap,
            bundle.agg_sig,
            bundle.depth,
            bundle.hash,
            bundle.round,
            self.epochs.committee_for_round(bundle.round),
        )

    def add_timeout_partial(
        self,
        round_: Round,
        groups: tuple[tuple[Round, int], ...],
        agg_sig: bytes,
        depth: int,
    ) -> AggTC | None:
        maker = self.timeout_makers.get(round_)
        if maker is None:
            maker = AggTCMaker(aggsig.active_agg_scheme(), self.window)
            self.timeout_makers[round_] = maker
        return maker.add(
            groups,
            agg_sig,
            depth,
            round_,
            self.epochs.committee_for_round(round_),
        )

    def cleanup(self, round_: Round) -> None:
        self.vote_makers = {
            k: v for k, v in self.vote_makers.items() if k[0] >= round_
        }
        self.timeout_makers = {
            k: v for k, v in self.timeout_makers.items() if k >= round_
        }
